"""Version shim layer.

The reference supports several Spark releases from ONE plugin jar by
ServiceLoader-discovering a version-matched provider of the
version-sensitive APIs (sql-plugin/.../SparkShims.scala:38-71 trait;
ShimLoader.scala:26-60 provider matching; shims/spark300, spark301,
spark310 modules). The version axis for a TPU framework is the JAX /
jaxlib / libtpu release train: sharding constructors, tree utilities and
donation/compilation options move between releases. Same design:

- ``TpuShims``: the trait — every version-sensitive operation the rest of
  the framework is allowed to touch goes through here.
- ``ShimServiceProvider`` subclasses: one per supported release range,
  each declaring ``matches(version)`` (SparkShimServiceProvider's
  VERSIONNAMES match) and building its shims.
- ``ShimLoader.get_shims()``: picks the first provider matching the
  running jax version, caches it; ``SPARK_RAPIDS_TPU_SHIM`` forces one by
  name (the reference's version-override test hook,
  RapidsConf SHIMS_PROVIDER_OVERRIDE analogue).
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple


class TpuShims:
    """Version-sensitive API surface (the SparkShims trait analogue)."""

    version_name: str = "base"

    # --- tree utilities ----------------------------------------------------
    def tree_map(self, fn, *trees):
        raise NotImplementedError

    def tree_leaves(self, tree):
        raise NotImplementedError

    # --- meshes & shardings ------------------------------------------------
    def make_mesh(self, axis_shapes: Sequence[int],
                  axis_names: Sequence[str], devices=None):
        """Build a Mesh over the given (possibly virtual) device grid."""
        raise NotImplementedError

    def named_sharding(self, mesh, *spec):
        raise NotImplementedError

    def replicated_sharding(self, mesh):
        raise NotImplementedError

    # --- compilation -------------------------------------------------------
    def jit(self, fn, *, static_argnums=(), donate_argnums=(),
            out_shardings=None):
        raise NotImplementedError

    def device_put(self, value, sharding=None):
        raise NotImplementedError

    # --- introspection -----------------------------------------------------
    def devices(self) -> List:
        raise NotImplementedError

    def default_backend(self) -> str:
        raise NotImplementedError


class _ModernJaxShims(TpuShims):
    """jax >= 0.4.26: jax.tree.*, jax.sharding.*, jax.make_mesh available."""

    version_name = "jax-modern"

    def __init__(self):
        import jax
        self._jax = jax

    def tree_map(self, fn, *trees):
        return self._jax.tree.map(fn, *trees)

    def tree_leaves(self, tree):
        return self._jax.tree.leaves(tree)

    def make_mesh(self, axis_shapes, axis_names, devices=None):
        import numpy as np
        from jax.sharding import Mesh
        devs = list(devices if devices is not None else self._jax.devices())
        n = 1
        for a in axis_shapes:
            n *= a
        grid = np.asarray(devs[:n]).reshape(tuple(axis_shapes))
        return Mesh(grid, tuple(axis_names))

    def named_sharding(self, mesh, *spec):
        from jax.sharding import NamedSharding, PartitionSpec
        return NamedSharding(mesh, PartitionSpec(*spec))

    def replicated_sharding(self, mesh):
        from jax.sharding import NamedSharding, PartitionSpec
        return NamedSharding(mesh, PartitionSpec())

    def jit(self, fn, *, static_argnums=(), donate_argnums=(),
            out_shardings=None):
        kw = {}
        if out_shardings is not None:
            kw["out_shardings"] = out_shardings
        return self._jax.jit(fn, static_argnums=static_argnums,
                             donate_argnums=donate_argnums, **kw)

    def device_put(self, value, sharding=None):
        return (self._jax.device_put(value, sharding)
                if sharding is not None else self._jax.device_put(value))

    def devices(self):
        return list(self._jax.devices())

    def default_backend(self) -> str:
        return self._jax.default_backend()


class _LegacyJaxShims(_ModernJaxShims):
    """jax < 0.4.26: no jax.tree namespace — tree_util spellings."""

    version_name = "jax-legacy"

    def tree_map(self, fn, *trees):
        return self._jax.tree_util.tree_map(fn, *trees)

    def tree_leaves(self, tree):
        return self._jax.tree_util.tree_leaves(tree)


class ShimServiceProvider:
    """One per supported release range (SparkShimServiceProvider)."""

    name: str = "?"

    def matches(self, version: Tuple[int, ...]) -> bool:
        raise NotImplementedError

    def build(self) -> TpuShims:
        raise NotImplementedError


class ModernJaxProvider(ShimServiceProvider):
    name = "jax-modern"

    def matches(self, version):
        return version >= (0, 4, 26)

    def build(self):
        return _ModernJaxShims()


class LegacyJaxProvider(ShimServiceProvider):
    name = "jax-legacy"

    def matches(self, version):
        return (0, 4, 0) <= version < (0, 4, 26)

    def build(self):
        return _LegacyJaxShims()


class ShimLoader:
    """Pick the provider matching the running jax (ShimLoader.scala:26-60:
    iterate registered providers, first VERSIONNAMES match wins)."""

    _PROVIDERS: List[ShimServiceProvider] = [
        ModernJaxProvider(), LegacyJaxProvider(),
    ]
    _cached: Optional[TpuShims] = None

    @staticmethod
    def parse_version(text: str) -> Tuple[int, ...]:
        parts = []
        for p in text.split(".")[:3]:
            digits = "".join(ch for ch in p if ch.isdigit())
            parts.append(int(digits) if digits else 0)
        return tuple(parts)

    @classmethod
    def register(cls, provider: ShimServiceProvider) -> None:
        cls._PROVIDERS.insert(0, provider)
        cls._cached = None

    @classmethod
    def get_shims(cls) -> TpuShims:
        if cls._cached is not None:
            return cls._cached
        override = os.environ.get("SPARK_RAPIDS_TPU_SHIM")
        if override:
            for p in cls._PROVIDERS:
                if p.name == override:
                    cls._cached = p.build()
                    return cls._cached
            raise RuntimeError(f"no shim provider named {override!r} "
                               f"(have {[p.name for p in cls._PROVIDERS]})")
        import jax
        version = cls.parse_version(jax.__version__)
        for p in cls._PROVIDERS:
            if p.matches(version):
                cls._cached = p.build()
                return cls._cached
        raise RuntimeError(
            f"no shim provider matches jax {jax.__version__}; supported: "
            f"{[p.name for p in cls._PROVIDERS]}")
