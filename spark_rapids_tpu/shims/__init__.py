from spark_rapids_tpu.shims.loader import ShimLoader, TpuShims  # noqa: F401
