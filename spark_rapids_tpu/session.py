"""Session and DataFrame front-end.

Plays the role of SparkSession + the plugin bootstrap: building a session
installs the TPU override rules exactly the way
``spark.plugins=com.nvidia.spark.SQLPlugin`` installs ColumnarOverrideRules
(reference: Plugin.scala:36-54, SQLPlugin.scala:28-31). `explain` and the
`spark.rapids.*` conf surface match the reference's user API (L7).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Union

import pandas as pd

from spark_rapids_tpu.config.conf import TpuConf
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.exec.base import ExecContext
from spark_rapids_tpu.sql import plan as lp
from spark_rapids_tpu.sql.functions import Column, SortOrder, _c, _expr, col as col_fn
from spark_rapids_tpu.sql.planner import Planner
from spark_rapids_tpu.sql.sources import CsvSource, InMemorySource, ParquetSource


class OrderedSet:
    """Insertion-ordered set (dict-backed) so size sweeps can evict
    oldest-first — an arbitrary ``set.pop()`` could drop a hot entry or,
    worse, re-enable a blocklisted speculation key."""

    def __init__(self):
        self._d: dict = {}

    def add(self, k) -> None:
        self._d[k] = True

    def __contains__(self, k) -> bool:
        return k in self._d

    def __len__(self) -> int:
        return len(self._d)

    def pop_oldest(self) -> None:
        del self._d[next(iter(self._d))]


class LruDict(dict):
    """dict whose reads move the key to the end, so the size sweep's
    oldest-first eviction approximates LRU instead of FIFO (a stable hot
    query set inserted early must outlive churned dead keys)."""

    def get(self, k, default=None):
        try:
            return self[k]
        except KeyError:
            return default

    def __getitem__(self, k):
        v = super().__getitem__(k)
        if next(reversed(self)) != k:
            super().__delitem__(k)
            super().__setitem__(k, v)
        return v


class TpuSparkSession:
    _active: Optional["TpuSparkSession"] = None
    _lock = threading.Lock()

    def __init__(self, conf: TpuConf):
        self.conf = conf
        self._base_settings = dict(conf._settings)
        from spark_rapids_tpu.memory.device import TpuDeviceManager
        from spark_rapids_tpu.memory.semaphore import TpuSemaphore
        from spark_rapids_tpu.memory.spill import (
            BufferCatalog, MemoryEventHandler,
        )
        self.device_manager = TpuDeviceManager.get(conf)
        self.semaphore = TpuSemaphore.get(conf.concurrent_tpu_tasks)
        # persistent-compile-cache hit/miss counters (obs/compilecache.py):
        # registered once per process so first-run warmup attribution is
        # first-class in profile reports
        from spark_rapids_tpu.obs import compilecache
        compilecache.install()
        # cross-process shared compile cache + AOT pre-warm from history
        # (ROADMAP item 3): configured at session START so the pre-warm
        # pass overlaps everything the first query does, and jax's
        # persistent cache points at the shared dir before any compile
        compilecache.SHARED.configure_from_conf(conf)
        from spark_rapids_tpu.serving import prewarm as _prewarm
        _prewarm.maybe_start_from_conf(conf)
        # spillable-buffer runtime wired into execution: cached scan
        # batches register here and over-budget allocations spill them
        # device->host->disk (reference: GpuShuffleEnv.initStorage,
        # GpuShuffleEnv.scala:51-72 + DeviceMemoryEventHandler.scala:65-89)
        self.buffer_catalog = BufferCatalog(
            conf.host_spill_storage_size,
            device_manager=self.device_manager)
        self.memory_event_handler = MemoryEventHandler(
            self.buffer_catalog.device_store)
        self.device_manager.register_oom_handler(self.memory_event_handler)
        # test hook: captured executed physical plans
        # (reference: ExecutionPlanCaptureCallback, Plugin.scala:144-233)
        self.captured_plans: List = []
        self.capture_plans = False
        # device-resident scan batches (spark.rapids.sql.cacheDeviceScans)
        self.device_scan_cache: dict = {}
        # encoded-page cache for the deviceDecode scan path
        # (spark.rapids.sql.scan.pageCache.*): hot tables re-decode from
        # cached encoded pages instead of re-reading + re-uploading
        from spark_rapids_tpu.memory.spill import EncodedPageCache
        self.page_cache = EncodedPageCache(
            int(conf.get("spark.rapids.sql.scan.pageCache.maxBytes",
                         256 << 20) or 0),
            int(conf.get("spark.rapids.sql.scan.pageCache.deviceMaxBytes",
                         64 << 20) or 0)) \
            if conf.get_bool("spark.rapids.sql.scan.pageCache.enabled",
                             True) else None
        # device mesh for distributed execution (None = single-device);
        # when set, TpuShuffleExchangeExec exchanges over it with an ICI
        # all_to_all instead of collapsing locally (parallel/distributed.py)
        self.mesh = None
        # accelerated shuffle manager (spark.rapids.shuffle.transport.
        # enabled): lazily built; shares the session catalog so shuffle
        # buffers are spillable (RapidsShuffleInternalManager.scala:74-178)
        self._shuffle_env = None
        self._shuffle_id_counter = 0
        self._active_shuffles: List[int] = []
        # catalog ids of per-query transient spillables (exchange buckets,
        # broadcast tables): consumed entries remove themselves; leftovers
        # (short-circuited limits, errors) release at query end
        self._transient_bids: set = set()
        # adaptive statistics: aggregate signature -> last observed
        # partial-pass reduction ratio (groups/rows); known-poor reducers
        # skip their partial pass from batch 0 on later executions
        self.agg_ratio_cache: LruDict = LruDict()
        # adaptive capacity speculation (spark.rapids.sql.adaptiveCapacity
        # .enabled): structural-plan-fingerprint -> last observed join
        # expansion sizes; later executions skip the per-join capacity
        # sync and verify in one deferred fetch (exec/tpujoin.py,
        # _verify_speculation). capacity_spec_reruns counts verification
        # misses (each one transparently re-executed without speculation).
        self.capacity_cache: LruDict = LruDict()
        self.capacity_spec_reruns = 0
        self.capacity_spec_hits = 0
        # speculation keys that failed verification and must not retry
        # ("nocache|" prefix: dense grouping keys — chronically-stale
        # stats would otherwise re-execute every run). Insertion-ordered
        # set (dict keys) so the size sweep evicts oldest-first — an
        # arbitrary set.pop() could re-enable a known-bad speculation.
        self.capacity_spec_blocklist: OrderedSet = OrderedSet()
        # plan fingerprints that have executed once: dense grouping only
        # engages from the second execution (first-run scan stats cannot
        # cover the upload yet — they record as batches stream)
        self.dense_plans_seen: OrderedSet = OrderedSet()
        # scan-derived integer column bounds: column name -> (min, max),
        # unioned across every scanned batch carrying that name. ADVISORY
        # (the role of the reference's cuDF column min/max the join build
        # reads): the dense-key join fast path sizes its direct-index
        # table from these and VERIFIES them on device, falling back to
        # the exact sort probe on mismatch — correctness never depends on
        # this registry (exec/tpujoin.py).
        self.column_stats: dict = {}
        # rename provenance: alias -> {source column names} recorded by
        # rename-only projections, so stats resolve through `.alias(...)`
        self.column_aliases: dict = {}
        # observability state of the last executed query (obs/)
        self.last_query_metrics: dict = {}
        self.last_node_times: dict = {}
        self.last_plan = None
        self.last_profile = None
        # adaptive-execution record of the last AQE query: stage count,
        # rule decisions, final plan tree (sql/adaptive/executor.py);
        # bench.py --aqe-sweep archives it per query
        self.last_aqe: Optional[dict] = None
        # tenant/job-group tag (set_job_group): flows into every event,
        # the tenant.* metric labels, and live progress records — the
        # per-tenant accounting substrate the serving layer reads.
        # Thread-scoped: the scheduler's workers each run a different
        # tenant's job concurrently (_set_thread_job_group); a plain
        # set_job_group also updates the session-wide default so the
        # single-threaded API keeps its exact pre-serving behavior.
        self._job_group_default: tuple = (None, "")
        self._job_group_tls = threading.local()
        # serving-layer state: cross-query plan/result caches and the
        # AQE exchange-reuse cache (serving/caches.py), created lazily on
        # first use so sessions that never serve pay nothing
        self._serving_caches = None
        self._serving_lock = threading.Lock()
        # per-executing-thread ExecContext scope: register/release of
        # per-query resources (transient spillables, shuffle ids) routes
        # to the OWNING query's context so concurrent queries cannot
        # free each other's buffers
        self._exec_scope = threading.local()
        self._shuffle_lock = threading.Lock()
        # SIGUSR1 -> flight-recorder + thread-stack + progress dump into
        # the event log (obs/monitor.py; main-thread sessions only)
        if conf.get_bool("spark.rapids.tpu.ui.signalDiagnostics", True):
            from spark_rapids_tpu.obs.monitor import (
                install_signal_diagnostics,
            )
            install_signal_diagnostics()

    def clear_device_cache(self) -> None:
        for _source, parts in self.device_scan_cache.values():
            for entries in parts.values():
                for _fname, bid in entries:
                    self.buffer_catalog.remove(bid)
        self.device_scan_cache.clear()

    def _make_transport(self, executor_id: str):
        kind = self.conf.get("spark.rapids.shuffle.transport.class",
                             "inprocess")
        if kind == "socket":
            from spark_rapids_tpu.shuffle.socket_transport import (
                SocketTransport,
            )
            return SocketTransport(executor_id)
        if kind == "inprocess":
            from spark_rapids_tpu.shuffle.transport import InProcessTransport
            return InProcessTransport(executor_id)
        # SPI: dotted path "module:Class" taking (executor_id)
        import importlib
        mod, _, cls = kind.partition(":")
        return getattr(importlib.import_module(mod), cls)(executor_id)

    @property
    def shuffle_envs(self):
        """The executor pool for the accelerated shuffle manager. With
        spark.rapids.shuffle.executors > 1, map tasks stripe across the
        pool and cross-executor fetches ride the configured transport
        (socket = real TCP loopback) through serializer -> server ->
        client -> received catalog — the reference's multi-executor UCX
        flow (RapidsShuffleInternalManager.scala:74-362) in one process."""
        if self._shuffle_env is None:
            from spark_rapids_tpu.shuffle.manager import ShuffleEnv
            bsize = int(self.conf.get(
                "spark.rapids.shuffle.bounceBuffers.size", 4 << 20))
            bcount = int(self.conf.get(
                "spark.rapids.shuffle.bounceBuffers.count", 16))
            nexec = int(self.conf.get("spark.rapids.shuffle.executors", 1))
            self._shuffle_env = [
                ShuffleEnv(f"local-exec-{i}",
                           self._make_transport(f"local-exec-{i}"),
                           bounce_buffer_size=bsize,
                           bounce_buffer_count=bcount,
                           buffer_catalog=self.buffer_catalog)
                for i in range(max(1, nexec))]
        return self._shuffle_env

    @property
    def shuffle_env(self):
        return self.shuffle_envs[0]

    def _current_ctx(self):
        """The ExecContext of the query executing on THIS thread (set by
        ``_execute``); None outside a query. Per-query resource tracking
        (transients, shuffle ids) routes here so concurrent queries each
        release exactly their own."""
        return getattr(self._exec_scope, "ctx", None)

    def next_shuffle_id(self) -> int:
        with self._shuffle_lock:
            self._shuffle_id_counter += 1
            sid = self._shuffle_id_counter
            self._active_shuffles.append(sid)
        ctx = self._current_ctx()
        if ctx is not None:
            ctx.active_shuffles.append(sid)
        return sid

    def release_active_shuffles(self, ctx=None) -> None:
        """Unregister every shuffle a query registered (the reference's
        unregisterShuffle path). With a context, only that query's
        shuffles; without one (session.stop), everything outstanding."""
        if ctx is None:
            ctx = self._current_ctx()
        if self._shuffle_env is None:
            if ctx is not None:
                ctx.active_shuffles.clear()
            return
        with self._shuffle_lock:
            if ctx is not None:
                sids, ctx.active_shuffles = list(ctx.active_shuffles), []
                self._active_shuffles = [
                    s for s in self._active_shuffles if s not in set(sids)]
            else:
                sids, self._active_shuffles = self._active_shuffles, []
        for env in self._shuffle_env:
            for sid in sids:
                env.shuffle_catalog.remove_shuffle(sid)

    def register_transient(self, bid: int) -> int:
        ctx = self._current_ctx()
        if ctx is not None:
            ctx.transient_bids.add(bid)
        else:
            self._transient_bids.add(bid)
        return bid

    def add_transient_batch(self, batch, priority: int) -> int:
        """Register a per-query spillable in the catalog AND the transient
        set in one step — the pairing is load-bearing (an add_batch alone
        would pin the buffer in the catalog past query end)."""
        return self.register_transient(
            self.buffer_catalog.add_batch(batch, priority))

    def consume_transient(self, bid: int) -> None:
        ctx = self._current_ctx()
        if ctx is not None:
            ctx.transient_bids.discard(bid)
        self._transient_bids.discard(bid)
        self.buffer_catalog.remove(bid)

    def release_transient_buffers(self, ctx=None) -> None:
        """Free per-query spillables a short-circuited (or failed) query
        never consumed. With a context, only that query's; the session-
        level set (registrations outside any query) drains too when no
        other query is executing them."""
        if ctx is None:
            ctx = self._current_ctx()
        if ctx is not None:
            bids, ctx.transient_bids = set(ctx.transient_bids), set()
        else:
            bids, self._transient_bids = set(self._transient_bids), set()
        for bid in bids:
            self.buffer_catalog.remove(bid)

    def set_mesh(self, n_devices: Optional[int]) -> None:
        """Configure an n-device data-parallel mesh for distributed
        exchanges (the session-level analogue of enabling the reference's
        RapidsShuffleManager, GpuShuffleEnv.scala:27-136). ``None`` returns
        to single-device execution."""
        if n_devices is None:
            self.mesh = None
            return
        from spark_rapids_tpu.parallel.distributed import data_parallel_mesh
        self.mesh = data_parallel_mesh(n_devices)

    # --- builder -----------------------------------------------------------
    class Builder:
        def __init__(self):
            self._conf: Dict[str, object] = {}
            self._name = "spark-rapids-tpu"

        def app_name(self, name: str) -> "TpuSparkSession.Builder":
            self._name = name
            return self

        def config(self, key: str, value) -> "TpuSparkSession.Builder":
            self._conf[key] = value
            return self

        def get_or_create(self) -> "TpuSparkSession":
            with TpuSparkSession._lock:
                if TpuSparkSession._active is None:
                    TpuSparkSession._active = TpuSparkSession(
                        TpuConf(self._conf))
                else:
                    for k, v in self._conf.items():
                        TpuSparkSession._active.conf.set(k, v)
                return TpuSparkSession._active

    @staticmethod
    def builder() -> "TpuSparkSession.Builder":
        return TpuSparkSession.Builder()

    @staticmethod
    def active() -> "TpuSparkSession":
        s = TpuSparkSession._active
        if s is None:
            s = TpuSparkSession.builder().get_or_create()
        return s

    def stop(self) -> None:
        """Tear the session down (SparkSession.stop parity): release
        cached/spilled buffers, detach the memory event handler from the
        process-wide device manager (a later session registers its own),
        and clear the singleton."""
        self.clear_device_cache()
        self.clear_serving_caches()
        from spark_rapids_tpu.serving import prewarm as _prewarm
        _prewarm.cancel_active()
        self.release_active_shuffles()
        if self._shuffle_env is not None:
            for env in self._shuffle_env:
                env.close()
            self._shuffle_env = None
        self.device_manager.unregister_oom_handler(self.memory_event_handler)
        self.buffer_catalog.close()
        with TpuSparkSession._lock:
            if TpuSparkSession._active is self:
                TpuSparkSession._active = None

    # --- tenancy -----------------------------------------------------------
    def set_job_group(self, tenant, description: str = "") -> None:
        """Tag subsequent queries with a tenant/job-group id (the
        SparkContext.setJobGroup analogue). The tag flows into every
        event the journal records for those queries, the ``tenant.*``
        counters in the process-wide metrics registry (rendered live at
        ``/metrics`` and aggregated at ``/api/tenants``), and the live
        query-progress records. ``set_job_group(None)`` clears it."""
        group = (str(tenant) if tenant else None,
                 str(description or ""))
        self._job_group_default = group
        self._job_group_tls.value = group

    def clear_job_group(self) -> None:
        self.set_job_group(None)

    def _set_thread_job_group(self, tenant, description: str = "") -> None:
        """Tag THIS THREAD's queries only (the serving workers' form:
        each worker runs a different tenant's job concurrently, and a
        session-wide tag would cross-attribute them)."""
        self._job_group_tls.value = (str(tenant) if tenant else None,
                                     str(description or ""))

    @property
    def _job_group(self) -> tuple:
        return getattr(self._job_group_tls, "value",
                       self._job_group_default)

    # --- serving ------------------------------------------------------------
    def _serving(self):
        """The session's serving-cache bundle (serving/caches.py), or
        None when every serving cache is disabled — the legacy planning
        path then runs with zero extra work per query."""
        conf = self.conf
        from spark_rapids_tpu.serving import caches as sc
        if not (conf.get_bool(sc.PLAN_CACHE_ENABLED, True)
                or conf.get_bool(sc.RESULT_CACHE_ENABLED, False)):
            return None
        return self._serving_bundle()

    def _serving_bundle(self):
        if self._serving_caches is None:
            with self._serving_lock:
                if self._serving_caches is None:
                    from spark_rapids_tpu.serving.caches import (
                        ServingCaches,
                    )
                    self._serving_caches = ServingCaches()
        return self._serving_caches

    def serving_scheduler(self, **kwargs):
        """Build an admission scheduler over this session
        (serving/scheduler.py): submit/status/cancel with per-tenant
        weighted-fair lanes, bounded-queue load-shed, per-query
        deadlines and tenant HBM quotas. The caller owns its lifecycle
        (``close()``)."""
        from spark_rapids_tpu.serving.scheduler import QueryScheduler
        return QueryScheduler(self, **kwargs)

    def clear_serving_caches(self) -> None:
        if self._serving_caches is not None:
            self._serving_caches.clear()

    @staticmethod
    def _count_rows(outs) -> int:
        try:
            return sum(len(df) for df in outs) if outs else 0
        except TypeError:
            return 0

    def _note_tenant(self, tenant, status: str, wall_s: float,
                     rows: int = 0) -> None:
        """Per-tenant accounting, once per query end (success or
        failure): the counters /api/tenants aggregates and a Prometheus
        scrape sees as srt_tenant_* series."""
        from spark_rapids_tpu.obs.metrics import REGISTRY
        t = tenant or "default"
        REGISTRY.counter("tenant.queries", tenant=t, status=status).add(1)
        REGISTRY.counter("tenant.wallSeconds", tenant=t).add(
            round(wall_s, 6))
        if rows:
            REGISTRY.counter("tenant.rowsReturned", tenant=t).add(rows)

    # --- conf --------------------------------------------------------------
    def set_conf(self, key: str, value) -> None:
        self.conf.set(key, value)

    def get_conf(self, key: str, default=None):
        return self.conf.get(key, default)

    def reset_conf(self) -> None:
        self.conf._settings = dict(self._base_settings)

    # --- data --------------------------------------------------------------
    def create_dataframe(self, df: pd.DataFrame,
                         num_partitions: int = 1) -> "DataFrame":
        return DataFrame(self, lp.LogicalScan(InMemorySource(df,
                                                             num_partitions)))

    def range(self, start: int, end: Optional[int] = None, step: int = 1,
              num_partitions: int = 2) -> "DataFrame":
        if end is None:
            start, end = 0, start
        return DataFrame(self, lp.LogicalRange(start, end, step,
                                               num_partitions))

    @property
    def read(self) -> "DataFrameReader":
        return DataFrameReader(self)

    # --- execution ---------------------------------------------------------
    def _execute(self, logical: lp.LogicalPlan):
        """logical -> CPU physical -> TPU overrides -> run; returns
        (final physical plan, list of output pandas DataFrames)."""
        import time

        from spark_rapids_tpu.obs import events as obs_events
        from spark_rapids_tpu.obs import metrics as obs_metrics
        from spark_rapids_tpu.obs.trace import TRACER

        conf = self.conf
        ctx = ExecContext(conf, self)
        # gather-free execution flags (docs/gatherfree.md): per-value hash
        # tables, exchange-boundary dictionary merge, codes-on-the-wire
        from spark_rapids_tpu.columnar import dictionary as _dictionary
        _dictionary.configure_from_conf(conf)
        # per-query tracer window: configure from conf, clear so an
        # exported file holds exactly this query (a speculation re-run is
        # part of the same query and keeps its spans)
        trace_path = str(conf.get("spark.rapids.tpu.trace.path", "") or "")
        trace_on = (conf.get_bool("spark.rapids.tpu.trace.enabled", False)
                    or bool(trace_path))
        TRACER.configure(trace_on, conf.get_bool(
            "spark.rapids.tpu.trace.jaxAnnotations", False))
        if trace_on:
            TRACER.clear()
        # reset NOW, not on the success path: a failed query must not
        # leave the previous query's profile/metrics masquerading as "the
        # last executed query" in a post-mortem
        self.last_query_metrics = {}
        self.last_node_times = {}
        self.last_plan = None
        self.last_profile = None
        self.last_aqe = None
        # process-wide registry snapshot: the profile reports this query's
        # DELTA of spill/fetch/compile activity
        global_before = (obs_metrics.REGISTRY.values()
                         if ctx.metrics_enabled else None)
        # truncation counters snapshot: the profile's observability
        # section reports this query's DELTA, not the process totals.
        # The 5th element is the compile-ledger seq watermark: the
        # profile's ``compiles`` section covers entries recorded after
        # it; the 6th is the sync-ledger watermark feeding the profile's
        # ``syncs`` section + occupancy estimate
        from spark_rapids_tpu.obs.compileledger import LEDGER as _LEDGER
        from spark_rapids_tpu.obs.syncledger import SYNC_LEDGER as _SYNCS
        obs_before = (TRACER.dropped, obs_events.EVENTS.dropped,
                      obs_events.EVENTS.rotations,
                      obs_events.EVENTS.rotate_failures,
                      _LEDGER.seq, _SYNCS.seq) \
            if ctx.metrics_enabled else None
        if ctx.metrics_enabled:
            # the scan pipeline's peak gauge is state, not flow: reset it
            # per query so the profile's queueDepthPeak is THIS query's
            # peak, not the process's all-time high (obs/profile.py)
            obs_metrics.REGISTRY.gauge("scan.prefetch.queueDepthPeak") \
                .set(0)
        t_query0 = time.perf_counter()
        # durable event journal (obs/events.py): the query window opens
        # HERE so planning failures are on record too; the failure path
        # below dumps the always-on flight recorder into the log
        obs_events.EVENTS.configure_from_conf(conf)
        # compile ledger (obs/compileledger.py): per-cause attribution of
        # every backend compile this query triggers
        from spark_rapids_tpu.obs.compileledger import LEDGER
        LEDGER.configure_from_conf(conf)
        # host-sync ledger (obs/syncledger.py): per-site attribution of
        # every device<->host blocking point, plus the opt-in transfer-
        # guard coverage audit (spark.rapids.tpu.debug.transferGuard)
        from spark_rapids_tpu.obs import syncledger as _syncledger
        _SYNCS.configure_from_conf(conf)
        _guard_mode = str(conf.get(
            "spark.rapids.tpu.debug.transferGuard", "off") or "off")
        _syncledger.set_guard_mode(
            _guard_mode if _guard_mode in ("log", "disallow") else None)
        # zero-warm-up layer: coarse secondary-dimension shape buckets
        # (one compile serves a dimension range), the cross-process
        # shared compile cache (one compile per CLUSTER) and the AOT
        # pre-warm pass (history compiles before traffic). All three
        # default off/empty = byte-identical engine behavior.
        from spark_rapids_tpu.obs import compilecache as _compilecache
        from spark_rapids_tpu.serving import prewarm as _prewarm
        from spark_rapids_tpu.utils import kernelcache as _kernelcache
        _kernelcache.configure_shape_buckets_from_conf(conf)
        _compilecache.SHARED.configure_from_conf(conf)
        _prewarm.maybe_start_from_conf(conf)
        # live monitoring service (obs/monitor.py): starts/stops the
        # embedded HTTP server on conf change and keeps the progress
        # tracker's single hot-path flag in lockstep. Off (the default)
        # this is two conf reads and ctx.progress stays None.
        from spark_rapids_tpu.obs import monitor as obs_monitor
        from spark_rapids_tpu.obs.progress import PROGRESS
        obs_monitor.maybe_serve(conf)
        tenant, job_desc = self._job_group
        qid = obs_events.EVENTS.query_start(
            tenant=tenant,
            confFingerprint=obs_events.conf_fingerprint(conf._settings))
        qp = None
        if PROGRESS.enabled:
            qp = PROGRESS.begin(qid, tenant=tenant, description=job_desc)
            ctx.progress = qp
        # per-thread execution scope: register/release of per-query
        # resources (transients, shuffle ids) resolves to THIS context
        # while the query runs on this thread
        self._exec_scope.ctx = ctx
        try:
            # transfer-guard audit: untracked device->host transfers
            # outside any sync_scope are logged (or raise) while the
            # query body runs; sync scopes re-enter "allow"
            with _syncledger.guard_context(_guard_mode):
                plan, outs, ctx = self._plan_and_run(
                    logical, ctx, conf, obs_metrics, global_before,
                    t_query0, trace_on, trace_path, obs_before)
        except BaseException as e:
            wall_s = round(time.perf_counter() - t_query0, 6)
            err = f"{type(e).__name__}: {e}"[:300]
            # cooperative cancellation / deadline: a first-class terminal
            # state, not a failure — the dedicated journal event carries
            # the flight-recorder tail + compile-ledger tail so a killed
            # query still leaves its last moments on record
            from spark_rapids_tpu.serving.cancellation import (
                QueryCancelled, QueryTimeout,
            )
            if isinstance(e, QueryTimeout):
                status, kind = "timeout", "queryTimeout"
            elif isinstance(e, QueryCancelled):
                status, kind = "cancelled", "queryCancelled"
            else:
                status, kind = "failed", None
            if kind is not None:
                extra = {}
                if status == "timeout" and ctx.cancel is not None:
                    extra["deadlineSeconds"] = ctx.cancel.deadline_s
                obs_events.EVENTS.emit(
                    kind, reason=err, wall_s=wall_s,
                    events=obs_events.EVENTS.flight_events(),
                    compiles=_LEDGER.tail(), syncs=_SYNCS.tail(),
                    **extra)
            obs_events.EVENTS.query_end(
                status=status, flight_dump=kind is None, error=err,
                wall_s=wall_s)
            self._note_tenant(tenant, status, wall_s)
            if qp is not None:
                PROGRESS.finish(qp, status, error=err)
            raise
        finally:
            self._exec_scope.ctx = None
            _syncledger.set_guard_mode(None)
        wall_s = round(time.perf_counter() - t_query0, 6)
        rows_out = self._count_rows(outs)
        obs_events.EVENTS.query_end(
            status="success", wall_s=wall_s, rowsReturned=rows_out,
            **self._coverage_fields(plan, ctx))
        self._note_tenant(tenant, "success", wall_s, rows_out)
        if qp is not None:
            PROGRESS.finish(qp, "success")
        self._sweep_adaptive_caches()
        return plan, outs

    def _plan_and_run(self, logical, ctx, conf, obs_metrics, global_before,
                      t_query0, trace_on, trace_path, obs_before=None):
        """The planning + execution body of ``_execute``, factored out so
        the event journal's failure path wraps it in one place. Returns
        (plan, outputs, final ExecContext) — a speculation re-run swaps
        in a fresh context, and the coverage event reads the one that
        actually executed."""
        import time

        from spark_rapids_tpu.obs import events as obs_events
        from spark_rapids_tpu.obs.trace import TRACER
        from spark_rapids_tpu.sql.overrides import (
            TpuOverrides, TransitionOverrides, assert_is_on_tpu,
        )

        # record rename provenance (alias -> source names) from the
        # LOGICAL plan — physical projections can fuse away, but the
        # logical tree always carries `.alias(...)` / USING-join renames.
        # Advisory input to the dense-key join's stats resolution; bounds
        # are device-verified there, so staleness only loosens them.
        self._note_rename_aliases(logical)
        # column pruning (narrowing projects above filters / semi-anti
        # build sides), then projection pushdown: mark file scans with the
        # query's referenced column subset before planning (sql/pushdown.py)
        from spark_rapids_tpu.sql.pushdown import (
            annotate_scan_pruning, prune_filter_columns,
        )
        logical = prune_filter_columns(logical)
        annotate_scan_pruning(logical)
        planner = Planner(conf)
        # tiny-query overhead-floor fast path: single-partition planning
        # + semaphore/shrink-sync/bookkeeping elision (docs/gatherfree.md);
        # mesh execution keeps the general plan (data is born distributed)
        if getattr(self, "mesh", None) is None:
            planner.note_input_size(logical)
        ctx.small_query = planner.small_query
        ctx.small_query_keep_sem = planner.small_query_keep_sem
        if isinstance(logical, lp.LogicalLimit):
            # root-position limit plans as one CollectLimit operator
            cpu_plan = planner.plan_collect_limit(logical)
        else:
            cpu_plan = planner.plan(logical)
        # adaptive query execution (sql/adaptive/): cut the plan into
        # stages at hash-exchange boundaries, materialize map sides,
        # re-optimize the remainder from the observed sizes. Off (the
        # default) — and on a mesh, and for stage-less plans — the
        # legacy single-shot path below runs byte-identically.
        if (conf.get_bool("spark.rapids.sql.adaptive.enabled", False)
                and getattr(self, "mesh", None) is None):
            from spark_rapids_tpu.sql.adaptive.executor import (
                has_adaptive_stages,
            )
            if has_adaptive_stages(cpu_plan):
                return self._run_adaptive(cpu_plan, ctx, conf,
                                          obs_metrics, global_before,
                                          t_query0, trace_on, trace_path,
                                          obs_before)
        # cross-query serving caches (serving/caches.py), keyed by
        # (plan digest, conf fingerprint, source data versions):
        #   * result cache (opt-in): identical dashboard-style query ->
        #     answer straight from the cached host frames, zero execution;
        #   * plan cache (on by default): repeat submission skips the
        #     tag+convert rewrite entirely — zero re-planning, and the
        #     identical operator signatures keep every kernel-cache key
        #     warm (timed_compiles stays 0).
        caches = self._serving()
        cache_key = caches.key_for(cpu_plan, conf, logical) \
            if caches is not None else None
        tenant = self._job_group[0]
        if cache_key is not None:
            hit = caches.result_cache.get(cache_key, conf, tenant)
            if hit is not None:
                plan, outs = hit
                obs_events.EVENTS.emit(
                    "resultCacheHit", planDigest=cache_key[0],
                    rows=self._count_rows(outs))
                if self.capture_plans:
                    self.captured_plans.append(plan)
                self._finish_query(plan, ctx, conf, obs_metrics,
                                   global_before, t_query0, trace_on,
                                   trace_path, obs_before)
                return plan, outs, ctx
        plan = caches.plan_cache.get(cache_key, conf, tenant) \
            if cache_key is not None else None
        plan_cache_hit = plan is not None
        overrides = None
        if plan_cache_hit:
            pass  # tag+convert skipped: the rewrite was cached
        elif conf.sql_enabled:
            overrides = TpuOverrides(conf)
            plan = overrides.apply(cpu_plan)
            plan = TransitionOverrides(conf).apply(plan)
            if (getattr(self, "mesh", None) is None and conf.get_bool(
                    "spark.rapids.sql.agg.fuseCountDistinct", True)):
                from spark_rapids_tpu.exec.aggfuse import (
                    fuse_count_distinct,
                )
                plan = fuse_count_distinct(plan)
            if conf.get_bool("spark.rapids.sql.reuseSubtrees.enabled",
                             True):
                from spark_rapids_tpu.exec.reuse import (
                    reuse_common_subtrees,
                )
                plan = reuse_common_subtrees(plan)
        else:
            plan = cpu_plan
        if conf.test_enabled and not plan_cache_hit:
            assert_is_on_tpu(plan, conf)
        if cache_key is not None and not plan_cache_hit:
            caches.plan_cache.put(cache_key, plan, conf)
        if self.capture_plans:
            self.captured_plans.append(plan)
        # durable plan facts: structural digest + operator coverage + the
        # tree itself (tools/history_server.py renders plan pages from
        # the log alone), and one cpuFallback event per tagged-off
        # operator with the tag pass's will-not-work reasons (the
        # explain-why-not record the qualification tool ranks by impact)
        if plan_cache_hit:
            obs_events.EVENTS.emit(
                "planCacheHit", planDigest=obs_events.plan_digest(plan))
        obs_events.EVENTS.emit(
            "queryPlan", planDigest=obs_events.plan_digest(plan),
            planCacheHit=plan_cache_hit,
            planTree=plan.tree_string()[:20000],
            **self._coverage_fields(plan))
        if ctx.progress is not None:
            ctx.progress.set_plan(plan)
        if overrides is not None:
            for meta in overrides.fallback_metas():
                obs_events.EVENTS.emit(
                    "cpuFallback", op=meta.plan.name,
                    describe=meta.plan.describe()[:200],
                    reasons=list(meta.reasons))
        # final output to host
        outs: List[pd.DataFrame] = []
        if ctx.speculate and any(
                type(n).__name__ in ("TpuWriteExec", "CpuWriteExec")
                for n in plan.walk()):
            # writes commit files DURING the drain; a speculation miss
            # detected after it would have committed truncated output and
            # the re-execution would collide with the committed path.
            # Capacity syncs stay exact under write commands.
            ctx.speculate = False
        try:
            with TRACER.span("Query", speculative=bool(ctx.speculate)):
                outs = self._drain(plan, ctx, conf)
            if ctx.spec_pending and not self._verify_speculation(ctx):
                # a speculated capacity did not cover its actual size:
                # the speculative output may be truncated. Re-execute the
                # same physical plan without speculation (the cache
                # entries that missed were dropped above, so the next
                # execution re-learns them with the exact sync).
                self.capacity_spec_reruns += 1
                # ratios learned from a misspeculated run may be garbage
                # (a dense-group miss collapses group counts)
                for sig in ctx.ratio_writes:
                    self.agg_ratio_cache.pop(sig, None)
                self.release_active_shuffles(ctx)
                self.release_transient_buffers(ctx)
                prev_progress = ctx.progress
                small = ctx.small_query
                keep_sem = ctx.small_query_keep_sem
                ctx = ExecContext(conf, self, speculate=False)
                ctx.progress = prev_progress  # same query, same record
                ctx.small_query = small
                ctx.small_query_keep_sem = keep_sem
                # re-point this thread's execution scope at the fresh
                # context so the re-run's registrations release with IT
                self._exec_scope.ctx = ctx
                with TRACER.span("Query", speculative=False,
                                 rerun=True):
                    outs = self._drain(plan, ctx, conf)
        finally:
            self.release_active_shuffles(ctx)
            self.release_transient_buffers(ctx)
        if cache_key is not None:
            # opt-in result cache: remember (plan, outputs) for identical
            # dashboard-style re-submissions (deterministic reads only)
            caches.result_cache.maybe_put(cache_key, cpu_plan, plan,
                                          outs, conf, tenant)
        self._finish_query(plan, ctx, conf, obs_metrics, global_before,
                           t_query0, trace_on, trace_path, obs_before)
        return plan, outs, ctx

    def _run_adaptive(self, cpu_plan, ctx, conf, obs_metrics,
                      global_before, t_query0, trace_on, trace_path,
                      obs_before):
        """Adaptive branch of ``_plan_and_run``: the executor owns
        per-stage conversion + materialization + re-planning; this wraps
        it with the same event/metrics/profile bookkeeping as the legacy
        path. Capacity speculation is off — AQE's stage barriers are the
        syncs speculation avoids, and a speculative re-execution would
        invalidate the statistics its own re-planning consumed."""
        from spark_rapids_tpu.obs import events as obs_events
        from spark_rapids_tpu.obs.trace import TRACER
        from spark_rapids_tpu.sql.adaptive.executor import AdaptiveExecutor

        ctx.speculate = False
        adaptive = AdaptiveExecutor(self, conf, ctx)
        # static-shape digest FIRST, so a query that dies mid-stage still
        # leaves a plan record next to its flight-recorder dump (the
        # legacy path emits queryPlan before the drain); no coverage
        # census — the plan is unconverted at this point
        obs_events.EVENTS.emit(
            "queryPlan", planDigest=obs_events.plan_digest(cpu_plan),
            adaptive=True, phase="static")
        if ctx.progress is not None:
            # the static shape now; the executor re-sets the tree as
            # runtime re-planning evolves it and reports stage progress
            ctx.progress.set_plan(cpu_plan)
        try:
            with TRACER.span("Query", adaptive=True):
                plan, outs = adaptive.execute(cpu_plan)
        finally:
            self.release_active_shuffles()
            self.release_transient_buffers()
        if self.capture_plans:
            self.captured_plans.append(plan)
        # the digest is of the runtime-re-planned FINAL plan: it differs
        # from the static shape exactly when an AQE rule fired
        obs_events.EVENTS.emit(
            "queryPlan", planDigest=obs_events.plan_digest(plan),
            planTree=plan.tree_string()[:20000],
            adaptive=True, phase="final", aqeStages=len(adaptive.stages),
            aqeDecisions=len(adaptive.decisions),
            **self._coverage_fields(plan))
        if ctx.progress is not None:
            ctx.progress.set_plan(plan)
        self._finish_query(plan, ctx, conf, obs_metrics, global_before,
                           t_query0, trace_on, trace_path, obs_before)
        return plan, outs, ctx

    def _finish_query(self, plan, ctx, conf, obs_metrics, global_before,
                      t_query0, trace_on, trace_path, obs_before):
        """Shared post-run bookkeeping of both execution paths:
        per-operator SQL metrics of the last executed query (the
        reference surfaces these in the Spark UI, GpuExec.scala:61-67),
        the memory runtime's counters, the profile report and the trace
        export."""
        import time

        from spark_rapids_tpu.obs.trace import TRACER
        if ctx.metrics_enabled:
            cat = self.buffer_catalog
            mem = {
                "allocatedBytes": self.device_manager.allocated,
                "spillCount": self.memory_event_handler.spill_count,
                "deviceStoreBytes": cat.device_store.total_size,
                "hostStoreBytes": cat.host_store.total_size,
                "diskStoreBytes": cat.disk_store.total_size,
            }
            for k, v in mem.items():
                ctx.registry.gauge(k, op="memory").set(v)
            # per-tier resident bytes into the process-wide registry
            cat.publish_metrics()
        self.last_query_metrics = ctx.metrics
        self.last_node_times = ctx.node_times  # profiler (syncEachOp)
        self.last_plan = plan
        self.last_profile = None
        if ctx.metrics_enabled:
            from spark_rapids_tpu.obs.profile import build_profile
            delta = obs_metrics.registry_delta(
                global_before, obs_metrics.REGISTRY.values())
            self.last_profile = build_profile(
                plan, ctx, delta,
                wall_s=time.perf_counter() - t_query0,
                obs_before=obs_before)
        if trace_on and trace_path:
            TRACER.export_chrome(trace_path)

    # --- observability ------------------------------------------------------
    def _coverage_fields(self, plan, ctx=None) -> dict:
        """TPU-vs-CPU operator census of a converted plan (transitions
        excluded — they are the boundary, not a side), plus — given the
        executed context — observed per-CPU-operator inclusive seconds,
        the qualification tool's estimated fallback time impact."""
        tpu = cpu = 0
        cpu_time: dict = {}
        for node in plan.walk():
            if node.name in ("HostToDeviceExec", "DeviceToHostExec"):
                continue
            if node.columnar_output or getattr(node, "columnar_input",
                                               False):
                tpu += 1
                continue
            cpu += 1
            if ctx is not None:
                st = ctx.node_stats.get(id(node))
                if st is not None:
                    d = node.describe()[:200]
                    cpu_time[d] = round(
                        cpu_time.get(d, 0.0) + st["time"], 6)
        total = tpu + cpu
        out = {"tpuOps": tpu, "cpuOps": cpu,
               "coveragePct": round(100.0 * tpu / total, 2)
               if total else 100.0}
        if cpu_time:
            out["cpuOpTime"] = cpu_time
        return out

    def dump_flight_recorder(self) -> List[dict]:
        """Snapshot the always-on flight recorder (obs/events.py): the
        last N events — and spans, while tracing is on — regardless of
        whether the event log is enabled. Also writes the snapshot into
        the journal as a ``flightRecorder`` event when it is."""
        from spark_rapids_tpu.obs.events import EVENTS
        # one snapshot serves both the journal and the caller — a second
        # flight_events() here could diverge under concurrent emitters
        return EVENTS.dump_flight(reason="manual")["events"]

    def profile_report(self) -> str:
        """Human-readable profile of the last executed query: plan tree
        annotated with inclusive/exclusive time, rows, batches, plus the
        query's spill/fetch/compile-cache activity (obs/profile.py).
        Empty string when no profiled query has run (metrics disabled)."""
        return "" if self.last_profile is None else \
            self.last_profile.render()

    def profile_json(self) -> Optional[dict]:
        """Machine shape of the last query's profile (None when no
        profiled query has run). Consumed by tools/trace_summary.py and
        archived per query by bench.py."""
        return None if self.last_profile is None else \
            self.last_profile.to_json()

    # adaptive-state size cap: fingerprints embed per-upload data uids,
    # so a workload that keeps creating DataFrames mints fresh keys every
    # query and the dicts would grow for the session's lifetime
    # (ADVICE r4 #4). The LruDict caches touch keys on read, so
    # oldest-first half-eviction approximates LRU; the ordered sets evict
    # oldest-first (never arbitrary — a random blocklist eviction would
    # re-enable a known-bad speculation).
    ADAPTIVE_CACHE_CAP = 4096

    def _sweep_adaptive_caches(self) -> None:
        cap = self.ADAPTIVE_CACHE_CAP
        for d in (self.capacity_cache, self.agg_ratio_cache,
                  self.column_stats, self.column_aliases):
            if len(d) > cap:
                for k in list(d.keys())[:len(d) - cap // 2]:
                    del d[k]
        for s in (self.capacity_spec_blocklist, self.dense_plans_seen):
            if len(s) > cap:
                while len(s) > cap // 2:
                    s.pop_oldest()

    def _verify_speculation(self, ctx) -> bool:
        """ONE deferred fetch validating every capacity the query
        speculated (exec/tpujoin.py). A covered speculation is EXACT —
        capacities only pad — so success means the speculative output
        stands; any shortfall (or a dense-probe ok-flag gone false) drops
        the offending cache entry and returns False, and _execute
        re-runs the plan without speculation. Surviving entries are
        refreshed with the actual sizes so the cache follows data drift
        while it stays inside the buckets."""
        import jax
        flat = []
        for _key, totals_d, _caps, oks_d, _exact in ctx.spec_pending:
            flat.extend(totals_d)
            flat.extend(oks_d)
        if flat:
            from spark_rapids_tpu.obs.syncledger import sync_scope
            with sync_scope("speculation.verify",
                            detail=f"arrays={len(flat)}"):
                fetched = jax.device_get(flat)
        else:
            fetched = []
        pos = 0
        all_good = True
        for key, totals_d, caps, oks_d, exact in ctx.spec_pending:
            sizes = fetched[pos:pos + len(totals_d)]
            pos += len(totals_d)
            oks = fetched[pos:pos + len(oks_d)]
            pos += len(oks_d)
            good = all(bool(o) for o in oks)
            if good and exact is not None:
                # exchange-shrink speculation: the cached row counts were
                # used as EXACT host metadata (batch._host_rows), so any
                # drift — not just overflow — invalidates
                good = all(int(a) == int(e) for a, e in zip(sizes, exact))
            elif good:
                # join-expansion speculation: capacities only pad, so the
                # entry stands while the actual sizes stay covered.
                # Verify the CONSUMED prefix (a short-circuiting parent —
                # CollectLimit — may abandon the emission loop early;
                # batches never expanded cannot have truncated anything)
                for cap, sz in zip(caps, sizes):
                    sz = [int(x) for x in sz]
                    if cap is None:  # speculated-empty batch
                        if sz[0] != 0:
                            good = False
                            break
                        continue
                    out_cap, s_caps, b_caps = cap
                    cchars = list(s_caps) + list(b_caps)
                    if (sz[0] > out_cap or len(sz) - 1 != len(cchars)
                            or any(c > cc
                                   for c, cc in zip(sz[1:], cchars))):
                        good = False
                        break
            if good:
                ent = self.capacity_cache.get(key)
                if (exact is None and ent is not None
                        and len(sizes) == ent.get("n")):
                    ent["sizes"] = [[int(x) for x in s] for s in sizes]
            else:
                self.capacity_cache.pop(key, None)
                if key.startswith("nocache|"):
                    self.capacity_spec_blocklist.add(key)
                all_good = False
        return all_good

    def _note_rename_aliases(self, logical) -> None:
        from spark_rapids_tpu.sql.exprs.core import Alias, Col
        amap = self.column_aliases
        stack = [logical]
        while stack:
            node = stack.pop()
            stack.extend(node.children)
            if isinstance(node, lp.LogicalProject):
                for out_name, e in node.exprs:
                    while isinstance(e, Alias):
                        e = e.children[0]
                    if isinstance(e, Col) and e.name != out_name:
                        amap.setdefault(out_name, set()).add(e.name)

    def _drain(self, plan, ctx, conf) -> List[pd.DataFrame]:
        outs: List[pd.DataFrame] = []
        if plan.columnar_output:
            # drain every partition's device batches first, then convert
            # with to_pandas_many: TWO device->host round trips for the
            # whole result set instead of two per output partition
            from spark_rapids_tpu.columnar.batch import DeviceBatch
            final = plan
            batches: List[DeviceBatch] = []
            for part in final.executed_partitions(ctx):
                try:
                    batches.extend(part())
                finally:
                    if self.semaphore is not None:
                        self.semaphore.release()
            # result fetch under a "Collect" scope: the fused-fetch
            # pack/slice kernels it compiles attribute to "Collect" in
            # the ledger, and the device->host seconds land as a
            # Collect/fetchTime SQL metric. Deliberately NOT charged to
            # the root node's breakdown (node_id=None): the fetch runs
            # AFTER the root's pull window, and folding it in would
            # break the device+transfer+dispatch == exclusive invariant
            # of the per-operator rows (obs/profile.py)
            import time as _time

            from spark_rapids_tpu.obs import compileledger
            from spark_rapids_tpu.obs.syncledger import sync_scope
            with compileledger.op_context("Collect", None, None):
                _t0 = _time.perf_counter()
                with sync_scope("collect.fetch",
                                detail=f"batches={len(batches)}"):
                    outs = DeviceBatch.to_pandas_many(
                        batches, fused_fetch_bytes=int(conf.get(
                            "spark.rapids.sql.collect.fusedFetchBytes",
                            4 << 20)))
                if ctx.metrics_enabled:
                    ctx.metric_add("Collect", "fetchTime",
                                   _time.perf_counter() - _t0)
        else:
            for part in plan.executed_partitions(ctx):
                for df in part():
                    outs.append(df)
        return outs


class DataFrameWriter:
    """df.write.mode("overwrite").parquet(path) — the DataFrameWriter
    surface over LogicalWrite (reference: GpuDataWritingCommandExec path)."""

    def __init__(self, df: "DataFrame"):
        self._df = df
        self._mode = "error"
        self._partition_cols: List[str] = []

    def mode(self, m: str) -> "DataFrameWriter":
        m = {"errorifexists": "error"}.get(m, m)
        assert m in ("error", "overwrite"), m
        self._mode = m
        return self

    def partition_by(self, *cols: str) -> "DataFrameWriter":
        """Hive-style dynamic partitioning: one key=value directory level
        per column (reference: GpuInsertIntoHadoopFsRelationCommand's
        dynamic-partition write path)."""
        missing = [c for c in cols if c not in self._df.schema.names]
        if missing:
            raise ValueError(f"partition_by columns not in schema: {missing}")
        self._partition_cols = list(cols)
        return self

    partitionBy = partition_by

    def _run(self, path: str, fmt: str) -> None:
        plan = lp.LogicalWrite(self._df._plan, path, fmt, self._mode,
                               self._partition_cols)
        self._df.session._execute(plan)

    def parquet(self, path: str) -> None:
        self._run(path, "parquet")

    def csv(self, path: str) -> None:
        self._run(path, "csv")

    def orc(self, path: str) -> None:
        self._run(path, "orc")


class DataFrameReader:
    def __init__(self, session: TpuSparkSession):
        self.session = session
        self._schema: Optional[Schema] = None
        self._options: Dict[str, str] = {}

    def schema(self, schema: Schema) -> "DataFrameReader":
        self._schema = schema
        return self

    def option(self, key: str, value) -> "DataFrameReader":
        self._options[key] = value
        return self

    def parquet(self, *paths: str) -> "DataFrame":
        return DataFrame(self.session,
                         lp.LogicalScan(ParquetSource(list(paths))))

    def csv(self, *paths: str) -> "DataFrame":
        header = str(self._options.get("header", "true")).lower() == "true"
        return DataFrame(self.session,
                         lp.LogicalScan(CsvSource(list(paths),
                                                  schema=self._schema,
                                                  header=header)))

    def orc(self, *paths: str) -> "DataFrame":
        from spark_rapids_tpu.sql.sources import OrcSource
        return DataFrame(self.session,
                         lp.LogicalScan(OrcSource(list(paths))))


class GroupedData:
    def __init__(self, df: "DataFrame", grouping_cols: Sequence):
        self.df = df
        self.grouping = grouping_cols

    def agg(self, *agg_cols: Column) -> "DataFrame":
        from spark_rapids_tpu.sql.exprs.core import Alias, Col
        schema = self.df._plan.schema()
        child = self.df._plan
        grouping = []
        computed = []   # non-column keys get pre-projected (Spark's shape)
        for i, g in enumerate(self.grouping):
            e = _c(g)
            name = e.sql_name(schema)
            base = e.children[0] if isinstance(e, Alias) else e
            if not isinstance(base, Col):
                # a computed key aliased to an EXISTING column name would
                # collide with its passthrough twin in the pre-projection
                # and name-binding would silently group on the raw column;
                # project under an internal name, output the user's alias
                iname = f"__grp{i}" if name in schema.names else name
                computed.append((iname, e))
                e = Col(iname)
            grouping.append((name, e))
        if computed:
            passthrough = [(n, col_fn(n).expr) for n in schema.names]
            child = lp.LogicalProject(child, passthrough + computed)
        result_exprs = []
        for c in agg_cols:
            e = _expr(c)
            result_exprs.append((e.sql_name(schema), e))
        from spark_rapids_tpu.sql.exprs.aggregates import find_aggregates
        if any(getattr(fn, "is_distinct", False)
               for _, e in result_exprs for fn in find_aggregates(e)):
            return self._agg_with_distinct(child, grouping, schema,
                                           result_exprs)
        # key results reference the aggregate's OUTPUT names (finalize
        # resolves Col against grouping names), not the pre-projection's
        # internal names
        results = [(n, Col(n)) for n, _ in grouping] + result_exprs
        return DataFrame(self.df.session,
                         lp.LogicalAggregate(child, grouping, results))

    def _agg_with_distinct(self, child, grouping, schema, result_exprs):
        """count(DISTINCT d) rewrite: aggregate twice.

        Level 1 groups by keys+d, reducing every non-distinct aggregate to
        its update intermediates; level 2 groups by the keys, merging the
        intermediates and counting the now-unique d values. Same plan shape
        Spark produces for a single distinct column set (the reference
        falls back to CPU for the multi-distinct cases it can't split this
        way, aggregate.scala:40-225)."""
        from spark_rapids_tpu.sql.exprs import aggregates as am
        from spark_rapids_tpu.sql.exprs.core import Col
        fns, seen = [], set()
        for _, e in result_exprs:
            for fn in am.find_aggregates(e):
                if id(fn) not in seen:
                    seen.add(id(fn))
                    fns.append(fn)
        dist = [fn for fn in fns if getattr(fn, "is_distinct", False)]
        dist_names = {fn.children[0].sql_name(schema) for fn in dist}
        if len(dist_names) > 1:
            raise NotImplementedError(
                "multiple DISTINCT aggregate column sets in one aggregation "
                f"are not supported: {sorted(dist_names)}")
        # the grouping machinery keys columns by name: materialize d as
        # __dist so both aggregation levels can refer to it uniformly
        names = child.schema().names
        child = lp.LogicalProject(
            child, [(n, col_fn(n).expr) for n in names]
            + [("__dist", dist[0].children[0])])
        l1_grouping = list(grouping) + [("__dist", Col("__dist"))]

        # reduction kind -> aggregate constructor, shared by the level-1
        # (update) and level-2 (merge) tables; count_valid only appears on
        # the update side (its merge kind is 'sum')
        kind_ctor = {
            "sum": am.Sum, "min": am.Min, "max": am.Max, "any": am.Max,
            "first": lambda e: am.First(e, False),
            "first_valid": lambda e: am.First(e, True),
            "last": lambda e: am.Last(e, False),
            "last_valid": lambda e: am.Last(e, True),
        }

        def level1_fn(kind, child_expr):
            if kind == "count_valid":
                return am.Count(child_expr)
            return kind_ctor[kind](child_expr)

        def merge_fn(kind, ref):
            return kind_ctor[kind](ref)

        l1_results = [(n, Col(n)) for n, _ in l1_grouping]
        fn_level2 = {}
        pi = 0
        for fn in fns:
            if getattr(fn, "is_distinct", False):
                # d is unique per level-2 group now; counting its non-NULL
                # occurrences is exactly count(DISTINCT d)
                fn_level2[id(fn)] = am.Count(Col("__dist"))
                continue
            refs = []
            for (ukind, cidx), mkind in zip(fn.update_ops(), fn.merge_ops()):
                pname = f"__p{pi}"
                pi += 1
                l1_results.append((pname, level1_fn(ukind, fn.children[cidx])))
                refs.append(merge_fn(mkind, Col(pname)))
            fn_level2[id(fn)] = fn.finalize(refs, schema)
        level1 = lp.LogicalAggregate(child, l1_grouping, l1_results)

        def rewrite(e):
            if isinstance(e, am.AggregateFunction):
                return fn_level2[id(e)]
            return e.map_children(rewrite)

        l2_grouping = [(n, col_fn(n).expr) for n, _ in grouping]
        l2_results = list(l2_grouping) + [(n, rewrite(e))
                                          for n, e in result_exprs]
        return DataFrame(self.df.session,
                         lp.LogicalAggregate(level1, l2_grouping, l2_results))

    def count(self) -> "DataFrame":
        from spark_rapids_tpu.sql import functions as F
        return self.agg(F.count("*").alias("count"))


class RollupData:
    """rollup/cube grouping: an Expand producing one projection per
    grouping set (null-ed out keys + a grouping id), then a regular
    aggregate over keys+gid (Spark's Expand+Aggregate lowering)."""

    def __init__(self, df: "DataFrame", grouping_cols: Sequence,
                 kind: str):
        self.df = df
        self.grouping = grouping_cols
        self.kind = kind  # 'rollup' | 'cube'

    def _grouping_sets(self, nkeys: int):
        if self.kind == "rollup":
            return [list(range(k)) for k in range(nkeys, -1, -1)]
        import itertools
        sets = []
        for r in range(nkeys, -1, -1):
            sets.extend(list(c) for c in
                        itertools.combinations(range(nkeys), r))
        return sets

    def agg(self, *agg_cols: Column) -> "DataFrame":
        from spark_rapids_tpu.sql.exprs.core import Literal
        schema = self.df._plan.schema()
        keys = [(_c(g).sql_name(schema), _c(g)) for g in self.grouping]
        key_dtypes = [e.dtype(schema) for _, e in keys]
        key_names = {n for n, _ in keys}
        # non-key child columns pass through; key columns are re-emitted
        # per grouping set (nulled when rolled up) to avoid name collisions
        base = [(n, col_fn(n).expr) for n in schema.names
                if n not in key_names]
        projections = []
        for gid, kept in enumerate(self._grouping_sets(len(keys))):
            proj = list(base)
            for j, (name, e) in enumerate(keys):
                if j in kept:
                    proj.append((name, e))
                else:
                    proj.append((name, Literal(None, key_dtypes[j])))
            proj.append(("_gid", Literal(gid)))
            projections.append(proj)
        expand = lp.LogicalExpand(self.df._plan, projections)
        grouping = [(n, col_fn(n).expr) for n, _ in keys]
        grouping.append(("_gid", col_fn("_gid").expr))
        results = [(n, col_fn(n).expr) for n, _ in keys]
        for c in agg_cols:
            e = _expr(c)
            results.append((e.sql_name(schema), e))
        return DataFrame(self.df.session,
                         lp.LogicalAggregate(expand, grouping, results))


class DataFrame:
    def __init__(self, session: TpuSparkSession, plan: lp.LogicalPlan):
        self.session = session
        self._plan = plan

    # --- schema ------------------------------------------------------------
    @property
    def schema(self) -> Schema:
        return self._plan.schema()

    @property
    def columns(self) -> List[str]:
        return list(self.schema.names)

    def __getitem__(self, name: str) -> Column:
        return col_fn(name)

    # --- transformations ---------------------------------------------------
    def select(self, *cols) -> "DataFrame":
        from spark_rapids_tpu.sql.exprs.core import Col
        from spark_rapids_tpu.sql.window import WindowExpression
        schema = self.schema
        exprs = []
        for c in cols:
            e = _c(c)
            exprs.append((e.sql_name(schema), e))
        # window expressions in a projection: append the windowed columns
        # first (Spark's WindowExec shape), then project over them
        win_items = []

        def extract(e):
            if isinstance(e, WindowExpression):
                name = f"__w{len(win_items)}"
                win_items.append((name, e))
                return Col(name)
            return e.map_children(extract)

        exprs = [(n, extract(e)) for n, e in exprs]
        child = self._plan
        if win_items:
            child = lp.LogicalWindow(child, win_items)
        return DataFrame(self.session, lp.LogicalProject(child, exprs))

    def with_column(self, name: str, c: Column) -> "DataFrame":
        from spark_rapids_tpu.sql.window import WindowExpression
        from spark_rapids_tpu.sql.exprs.generators import ExplodeSplit
        e = _expr(c)
        if isinstance(e, ExplodeSplit):
            if name in self.schema.names:
                raise ValueError(f"generated column {name!r} would shadow "
                                 "an existing column")
            if e.with_pos and "pos" in self.schema.names:
                raise ValueError("posexplode's 'pos' column would shadow an "
                                 "existing column; rename it first")
            return DataFrame(self.session, lp.LogicalGenerate(
                self._plan, e.split.children[0], e.split.delim, name,
                e.with_pos))
        if isinstance(e, WindowExpression):
            # window columns append to the child (Spark's WindowExec shape)
            out = DataFrame(self.session,
                            lp.LogicalWindow(self._plan, [(name, e)]))
            if name in self.schema.names:
                raise ValueError(f"window column {name!r} would shadow an "
                                 "existing column")
            return out
        schema = self.schema
        exprs = [(n, col_fn(n).expr) for n in schema.names if n != name]
        exprs.append((name, e))
        return DataFrame(self.session, lp.LogicalProject(self._plan, exprs))

    withColumn = with_column

    def filter(self, condition: Column) -> "DataFrame":
        return DataFrame(self.session,
                         lp.LogicalFilter(self._plan, _expr(condition)))

    where = filter

    def group_by(self, *cols) -> GroupedData:
        return GroupedData(self, cols)

    groupBy = group_by

    def rollup(self, *cols) -> "RollupData":
        return RollupData(self, cols, "rollup")

    def cube(self, *cols) -> "RollupData":
        return RollupData(self, cols, "cube")

    def agg(self, *agg_cols: Column) -> "DataFrame":
        return GroupedData(self, []).agg(*agg_cols)

    def order_by(self, *cols) -> "DataFrame":
        orders = []
        for c in cols:
            if isinstance(c, SortOrder):
                orders.append(c)
            elif isinstance(c, str):
                orders.append(SortOrder(col_fn(c).expr))
            else:
                orders.append(SortOrder(_expr(c)))
        return DataFrame(self.session, lp.LogicalSort(self._plan, orders))

    orderBy = order_by
    sort = order_by

    def limit(self, n: int) -> "DataFrame":
        return DataFrame(self.session, lp.LogicalLimit(self._plan, n))

    def union(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(self.session,
                         lp.LogicalUnion([self._plan, other._plan]))

    def join(self, other: "DataFrame", on=None, how: str = "inner",
             left_on=None, right_on=None) -> "DataFrame":
        """Equi-join. ``on`` names columns present on both sides;
        ``left_on``/``right_on`` pair differently-named keys positionally
        (the TPC-H shape: l_orderkey = o_orderkey)."""
        how = {"outer": "full", "full_outer": "full", "left_outer": "left",
               "right_outer": "right", "semi": "leftsemi",
               "anti": "leftanti"}.get(how, how)

        def keyify(spec):
            if isinstance(spec, str):
                spec = [spec]
            return [col_fn(c).expr if isinstance(c, str) else _expr(c)
                    for c in spec]
        if left_on is not None or right_on is not None:
            assert left_on is not None and right_on is not None
            lkeys = keyify(left_on)
            rkeys = keyify(right_on)
            assert len(lkeys) == len(rkeys), "left_on/right_on length mismatch"
        elif on is None:
            lkeys, rkeys = [], []
            how = "cross"
        elif isinstance(on, Column):
            # arbitrary boolean condition -> nested-loop join (reference:
            # GpuBroadcastNestedLoopJoinExec, disabled on device by default)
            return DataFrame(self.session,
                             lp.LogicalJoin(self._plan, other._plan, how,
                                            [], [], condition=_expr(on)))
        elif isinstance(on, (str, list, tuple)):
            # Spark USING-column semantics: one output column per key name
            names = [on] if isinstance(on, str) else list(on)
            if how in ("leftsemi", "leftanti"):
                lkeys, rkeys = keyify(names), keyify(names)
            else:
                return self._join_using(other, names, how)
        else:
            raise TypeError("join on must be a column name, list of names, "
                            "or a boolean Column condition")
        return DataFrame(self.session,
                         lp.LogicalJoin(self._plan, other._plan, how,
                                        lkeys, rkeys))

    def _join_using(self, other: "DataFrame", names, how: str) -> "DataFrame":
        """join(on=[k]) merges each key into ONE output column: rename the
        right side's keys, join positionally, then re-emit a single key
        column (the left value, the right for right joins, coalesce for
        full — matching Spark's USING resolution)."""
        from spark_rapids_tpu.sql.exprs.conditional import Coalesce
        shared = (set(self.schema.names) & set(other.schema.names)) \
            - set(names)
        if shared:
            raise ValueError(
                "join(on=...) with non-key columns present on both sides is "
                f"ambiguous: {sorted(shared)}; alias or drop them first")
        rmap = {n: f"__rk_{n}" for n in names}
        right = other.select(*[
            col_fn(n).alias(rmap[n]) if n in rmap else col_fn(n)
            for n in other.schema.names])
        joined = DataFrame(self.session, lp.LogicalJoin(
            self._plan, right._plan, how,
            [col_fn(n).expr for n in names],
            [col_fn(rmap[n]).expr for n in names]))
        out = []
        for n in names:
            if how == "right":
                out.append(col_fn(rmap[n]).alias(n))
            elif how == "full":
                out.append(Column(Coalesce([col_fn(n).expr,
                                            col_fn(rmap[n]).expr])).alias(n))
            else:
                out.append(col_fn(n))
        out += [col_fn(n) for n in self.schema.names if n not in names]
        out += [col_fn(n) for n in other.schema.names if n not in names]
        return joined.select(*out)

    def drop(self, *names: str) -> "DataFrame":
        dropped = set(names)
        return self.select(*[n for n in self.schema.names
                             if n not in dropped])

    def with_column_renamed(self, old: str, new: str) -> "DataFrame":
        return self.select(*[
            col_fn(n).alias(new) if n == old else col_fn(n)
            for n in self.schema.names])

    withColumnRenamed = with_column_renamed

    @property
    def write(self) -> "DataFrameWriter":
        return DataFrameWriter(self)

    def distinct(self) -> "DataFrame":
        """Deduplicate rows (planned as a group-by over every column)."""
        exprs = [(n, col_fn(n).expr) for n in self.schema.names]
        return DataFrame(self.session,
                         lp.LogicalAggregate(self._plan, exprs, [
                             (n, col_fn(n).expr) for n in self.schema.names]))

    def repartition(self, n: int) -> "DataFrame":
        return DataFrame(self.session, lp.LogicalRepartition(self._plan, n))

    def coalesce(self, n: int) -> "DataFrame":
        return DataFrame(self.session, lp.LogicalCoalesce(self._plan, n))

    # --- actions -----------------------------------------------------------
    def collect(self) -> pd.DataFrame:
        _, outs = self.session._execute(self._plan)
        # null-mask-preserving concat: partition frames can mix masked
        # and plain dtypes across partitions (exec/cpu.py)
        from spark_rapids_tpu.exec.cpu import concat_host_frames
        return concat_host_frames(outs, self.schema)

    toPandas = collect

    def count_rows(self) -> int:
        return int(len(self.collect()))

    def explain(self, mode: str = "ALL") -> str:
        """Print the physical plan with TPU tag annotations (the reference's
        hallmark spark.rapids.sql.explain feature)."""
        from spark_rapids_tpu.sql.overrides import TpuOverrides, TransitionOverrides
        conf = self.session.conf.copy()
        cpu_plan = Planner(conf).plan(self._plan)
        overrides = TpuOverrides(conf)
        overrides.apply(cpu_plan)
        text = overrides.explain_text()
        print(text)
        return text
