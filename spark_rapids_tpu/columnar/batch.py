"""Device-resident columnar batches and the host (pandas) twin.

``DeviceBatch`` is the TPU analogue of a Spark ``ColumnarBatch`` of
``GpuColumnVector``s; ``HostBatch`` is the twin used on the CPU side after a
``DeviceToHost`` transition (reference: RapidsHostColumnVector.java).

Capacity bucketing: batches are padded to a bucketed capacity (default
power-of-two) so that the set of XLA programs compiled for any query is
bounded by O(#operators x log(max batch rows)) rather than one per distinct
row count. This replaces cuDF's fully-dynamic shapes (SURVEY.md section 7
hard-part 1/3).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from spark_rapids_tpu.columnar import dtype as dtypes
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.columnar.dtype import DType
from spark_rapids_tpu.obs.syncledger import sync_scope

def _host_nbytes(tree) -> int:
    """Bytes landed by a completed device->host fetch (numpy leaves)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        total += getattr(leaf, "nbytes", 0) or 0
    return total



MIN_CAPACITY = 8


def _start_host_copies_tree(tree) -> None:
    """Issue async device->host copies for every array leaf before a
    blocking ``jax.device_get``: without copies in flight, a multi-array
    fetch serializes one ~40-100ms tunnel round trip PER ARRAY; with
    them the whole tree lands in about one round trip plus transfer
    time. Best-effort — a backend without the method just skips."""
    for leaf in jax.tree_util.tree_leaves(tree):
        copy = getattr(leaf, "copy_to_host_async", None)
        if copy is None:
            continue
        try:
            copy()
        except Exception:  # noqa: BLE001 — prefetch is advisory only
            return


def bucket_capacity(n: int, growth: float = 2.0, minimum: int = MIN_CAPACITY) -> int:
    """Smallest capacity bucket >= n. growth=2.0 -> power-of-two buckets.
    growth <= 1 cannot make progress (it would loop forever)."""
    assert growth > 1.0, f"bucket growth must exceed 1.0, got {growth}"
    cap = minimum
    while cap < n:
        cap = int(np.ceil(cap * growth))
    return cap


class Schema:
    """Ordered (name, dtype) pairs."""

    def __init__(self, names: Sequence[str], dtypes_: Sequence[DType]):
        assert len(names) == len(dtypes_)
        self.names: Tuple[str, ...] = tuple(names)
        self.dtypes: Tuple[DType, ...] = tuple(dtypes_)

    def __len__(self) -> int:
        return len(self.names)

    def __eq__(self, other) -> bool:
        return (isinstance(other, Schema) and self.names == other.names
                and self.dtypes == other.dtypes)

    def __hash__(self) -> int:
        return hash((self.names, self.dtypes))

    def __repr__(self) -> str:
        cols = ", ".join(f"{n}: {d}" for n, d in zip(self.names, self.dtypes))
        return f"Schema({cols})"

    def index_of(self, name: str) -> int:
        return self.names.index(name)

    def dtype_of(self, name: str) -> DType:
        return self.dtypes[self.index_of(name)]

    @staticmethod
    def from_pandas(df: pd.DataFrame) -> "Schema":
        names, dts = [], []
        for i, name in enumerate(df.columns):
            names.append(str(name))
            dts.append(_pandas_col_dtype(df.iloc[:, i]))
        return Schema(names, dts)


@jax.tree_util.register_pytree_node_class
class DeviceBatch:
    """Columns + a device scalar row count; static capacity.

    ``num_rows`` is an int32 *device scalar* so it can flow through traced
    code (a filter's output count is data, not shape). ``num_rows_host()``
    syncs it to the host when operator orchestration needs the value.
    """

    def __init__(self, schema: Schema, columns: List[DeviceColumn],
                 num_rows: jnp.ndarray):
        self.schema = schema
        self.columns = columns
        self.num_rows = num_rows
        self._host_rows: Optional[int] = None

    def tree_flatten(self):
        return (self.columns, self.num_rows), self.schema

    @classmethod
    def tree_unflatten(cls, schema, children):
        columns, num_rows = children
        return cls(schema, list(columns), num_rows)

    @property
    def capacity(self) -> int:
        return self.columns[0].capacity if self.columns else 0

    @property
    def num_cols(self) -> int:
        return len(self.columns)

    def column(self, name: str) -> DeviceColumn:
        return self.columns[self.schema.index_of(name)]

    def num_rows_host(self) -> int:
        if self._host_rows is None:
            # fallback sync site: a named call-site scope (if any) wins
            # via sync_scope reentrancy, so this only attributes scalar
            # count fetches nobody wrapped explicitly
            with sync_scope("batch.rowCount", nbytes=4):
                self._host_rows = int(self.num_rows)
        return self._host_rows

    def num_rows_hint(self) -> int:
        """Row-count upper bound WITHOUT a device sync: the exact count if
        already fetched, else the capacity. Scalar device->host fetches
        cost a full round trip (~hundreds of ms on tunneled attachments),
        so control-flow that only needs an estimate must use this."""
        return self._host_rows if self._host_rows is not None \
            else self.capacity

    def row_mask(self) -> jnp.ndarray:
        """bool (capacity,): True for live rows (the leading num_rows)."""
        return jnp.arange(self.capacity, dtype=jnp.int32) < self.num_rows

    def device_memory_size(self) -> int:
        """Bytes of device storage held by this batch."""
        total = 0
        for leaf in jax.tree_util.tree_leaves(self):
            total += leaf.size * leaf.dtype.itemsize
        return total

    def __repr__(self) -> str:
        return (f"DeviceBatch(rows~{self._host_rows}, capacity={self.capacity}, "
                f"schema={self.schema})")

    # --- conversion --------------------------------------------------------
    @staticmethod
    def from_pandas(df: pd.DataFrame, capacity: Optional[int] = None,
                    schema: Optional[Schema] = None,
                    dict_encode: bool = True,
                    dict_state: Optional[dict] = None,
                    dict_numerics: bool = True,
                    blocked_chars: int = 0,
                    device=None) -> "DeviceBatch":
        """Host -> device transition (reference: GpuRowToColumnarExec /
        HostColumnarToGpu, GpuRowToColumnarExec.scala:45-502).

        ``dict_encode``: probe each column for low cardinality and attach a
        host-computed dictionary (codes + static values) — the aggregation
        fast path's direct slot addressing rides it (see
        DeviceColumn.dict_codes). ``dict_state``: a mutable per-scan
        registry making every batch of one scan share one dictionary (see
        host_dict_encode_stateful). ``blocked_chars``: when > 0, string
        columns that did NOT dictionary-encode and whose longest row fits
        the given byte stride upload as fixed-stride char SLABS (+lens)
        instead of packed chars+offsets — row movement then rides 2-D
        lane-contiguous row gathers and packed chars only materialize if
        an operator genuinely reads them (spark.rapids.sql.dict.
        blockedChars)."""
        from spark_rapids_tpu.columnar.column import (
            host_dict_encode_stateful, np_build_slab, slab_stride_for,
        )
        if schema is None:
            schema = Schema.from_pandas(df)
        n = len(df)
        cap = capacity if capacity is not None else bucket_capacity(n)
        # per-column factorize hints precomputed by the scan pipeline's
        # decode workers (sources._attach_dict_hints), keyed by column
        # name; only trusted when the frame was not re-chunked since
        hints = getattr(df, "attrs", None)
        hints = hints.get("srt_dict_fact") if hints else None
        # build every column's device-layout buffers host-side, then ship
        # the whole batch in ONE device_put (per-buffer uploads each pay a
        # round trip on remote attachments)
        host_bufs = []
        dict_metas = []
        slab_metas = []
        # positional iteration: join outputs may carry duplicate column names
        for i, dt in enumerate(schema.dtypes):
            values, validity = _pandas_to_numpy(df.iloc[:, i], dt)
            bufs = DeviceColumn.build_host_buffers(values, validity, dt, cap)
            fact = hints.get(str(df.columns[i])) if hints else None
            if fact is not None and len(fact[0]) != n:
                fact = None
            # ``dict_numerics=False`` (file-scan uploads): only string
            # columns are dictionary-probed — the numeric probe+encode is
            # an element-wise pass per column per batch on the upload hot
            # path, and integer grouping keys ride the dense-key path
            # (spark.rapids.sql.agg.denseKeys) instead of dictionaries
            enc = host_dict_encode_stateful(values, validity, dt, cap,
                                            dict_state, i, fact=fact) \
                if dict_encode and (dict_numerics or dt.is_string) else None
            if enc is not None and dt.is_string:
                # only pay the slab scan when a dictionary was actually
                # built (high-cardinality columns already bailed at the
                # probe): NUL-bearing data must not be dictionary-encoded
                # (see string_host_buffers_have_nul)
                from spark_rapids_tpu.columnar.column import (
                    string_host_buffers_have_nul,
                )
                if string_host_buffers_have_nul(bufs, n):
                    enc = None
                    if dict_state is not None:
                        dict_state[i] = False  # close for the whole scan
            if enc is not None:
                codes, vals = enc
                bufs = bufs + (codes,)
                dict_metas.append(vals)
                slab_metas.append(0)
            else:
                dict_metas.append(None)
                stride = 0
                if blocked_chars > 0 and dt.is_string:
                    chars_b, _v, offs_b = bufs[0], bufs[1], bufs[2]
                    max_len = int((offs_b[1:n + 1] - offs_b[:n]).max()) \
                        if n else 0
                    stride = slab_stride_for(max_len, blocked_chars)
                    if stride and dict_state is not None:
                        # per-scan stride registry (the slab twin of the
                        # dictionary registry): LATER batches pad to the
                        # widest stride seen so far. A later batch can
                        # still WIDEN the stride (one new program shape),
                        # but strides are pow2-bucketed so churn is
                        # bounded at log2(maxStride/8) widenings per
                        # column per scan
                        prev = int(dict_state.get(("slab", i), 0) or 0)
                        if prev < 0:
                            stride = 0  # column exceeded maxStride earlier
                        else:
                            stride = max(stride, prev)
                            dict_state[("slab", i)] = stride
                    if not stride and dict_state is not None \
                            and dt.is_string and blocked_chars > 0:
                        dict_state[("slab", i)] = -1
                    if stride:
                        words, lens = np_build_slab(chars_b, offs_b, cap,
                                                    stride)
                        bufs = (words, bufs[1], lens)
                slab_metas.append(stride)
            host_bufs.append(bufs)
        # ``device``: explicit placement for sharded scans (mesh execution
        # uploads partition i to mesh device i so data is born distributed)
        dev = jax.device_put((host_bufs, np.asarray(n, np.int32)),
                             device=device)
        dev_bufs, num_rows = dev
        cols = []
        for dt, bufs, dvals, stride in zip(schema.dtypes, dev_bufs,
                                           dict_metas, slab_metas):
            if dvals is not None:
                cols.append(DeviceColumn(dt, *bufs[:-1], dict_codes=bufs[-1],
                                         dict_values=dvals))
            elif stride:
                words, vpad, lens = bufs
                cols.append(DeviceColumn(dt, None, vpad, slab64=words,
                                         lens=lens))
            else:
                cols.append(DeviceColumn(dt, *bufs))
        batch = DeviceBatch(schema, cols, num_rows)
        batch._host_rows = n
        return batch

    def to_pandas(self) -> pd.DataFrame:
        """Device -> host transition (reference: GpuColumnarToRowExec).
        All column buffers (and the row count) ride one batched
        ``jax.device_get`` — per-buffer fetches pay a full round trip each
        on remote attachments (~hundreds of ms)."""
        return DeviceBatch.to_pandas_many([self])[0]

    @staticmethod
    def to_pandas_many(batches: Sequence["DeviceBatch"],
                       fused_fetch_bytes: int = 4 << 20) -> List[pd.DataFrame]:
        """Convert many batches with at most TWO total device->host round
        trips (row counts, then every batch's buffers) — the whole-query
        output fetch of collect() rides this, so the sync count is
        independent of the partition count. When the padded buffers fit
        under ``fused_fetch_bytes`` the counts and full-capacity buffers
        ride ONE round trip instead (and no per-length device slice
        programs need compiling); each round trip costs ~100-250 ms on a
        tunneled attachment, which dominates small-result collects."""
        import jax
        if not batches:
            return []
        need = [b for b in batches if b._host_rows is None]
        total_padded = sum(b.device_memory_size() for b in batches)
        if total_padded <= fused_fetch_bytes:
            # mesh results live on several devices; one jitted pack
            # cannot span them — the multi-array fused fetch handles that
            devs = set()
            for b in batches:
                devs |= getattr(b.num_rows, "devices", set)() \
                    if callable(getattr(b.num_rows, "devices", None)) \
                    else set()
            if len(devs) <= 1:
                return DeviceBatch._to_pandas_packed(batches)
            if need:
                return DeviceBatch._to_pandas_fused(batches)
        if need:
            with sync_scope("batch.fetch", detail="rowCounts",
                            nbytes=4 * len(need)):
                counts = jax.device_get([b.num_rows for b in need])
            for b, c in zip(need, counts):
                b._host_rows = int(c)
        all_views = [[col.device_views(b._host_rows) for col in b.columns]
                     for b in batches]
        _start_host_copies_tree(all_views)
        with sync_scope("batch.fetch", detail="buffers") as sc:
            host = jax.device_get(all_views)
            sc.add_bytes(_host_nbytes(host))
        out: List[pd.DataFrame] = []
        for b, host_cols in zip(batches, host):
            n = b._host_rows
            series: List[pd.Series] = []
            for dt, col, parts in zip(b.schema.dtypes, b.columns, host_cols):
                values, validity = col.numpy_from_host(parts, n)
                series.append(_numpy_to_pandas(values, validity, dt)
                              .reset_index(drop=True))
            if not series:
                out.append(pd.DataFrame(index=range(n)))
                continue
            # positional construction: join outputs may carry duplicate
            # column names (both sides keep their key column, like Spark)
            df = pd.concat(series, axis=1)
            df.columns = list(b.schema.names)
            out.append(df)
        return out

    @staticmethod
    def _to_pandas_packed(batches: Sequence["DeviceBatch"]) -> List[pd.DataFrame]:
        """ONE device buffer for the whole result set: a jitted kernel
        concatenates every batch's row count + column buffers into a
        single uint8 slab, fetched with a single device_get. Even a
        batched multi-array fetch pays per-ARRAY costs on the tunneled
        attachment (~25-40ms each after async overlap); a small query's
        ~10-50 output arrays made the fetch the whole query floor. The
        slab layout is derived host-side from the same static structure
        the kernel packs, then sliced into numpy views."""
        import jax
        from spark_rapids_tpu.utils.kernelcache import cached_jit

        # (static) pack plan: mirrors the kernel's segment order. float64
        # data cannot be packed (no f64 bitcast on this stack — see
        # ops/floatbits.py; arithmetic bit extraction is not value-exact
        # for -0.0/NaN/denormals) so it rides as SIDE arrays in the same
        # fetch; everything else lands in one uint8 slab.
        plan = []  # per batch: list of (field, np_dtype, count)
        sig_parts = []
        for b in batches:
            fields = [("rows", np.dtype(np.int32), 1)]
            for col in b.columns:
                if col.dtype.is_string and col.has_slab:
                    cap = int(col.validity.shape[0])
                    w = int(col._slab64.shape[1])
                    fields.append(("slab", np.dtype(np.uint64), cap * w))
                    fields.append(("lens", np.dtype(np.int32), cap))
                    fields.append(("validity", np.dtype(np.uint8), cap))
                elif col.dtype.is_string and col.is_lazy:
                    cap = int(col.validity.shape[0])
                    fields.append(("codes", np.dtype(np.int32), cap))
                    fields.append(("validity", np.dtype(np.uint8), cap))
                elif col.dtype.is_string:
                    cap = int(col.validity.shape[0])
                    fields.append(("chars", np.dtype(np.uint8),
                                   int(col.data.shape[0])))
                    fields.append(("offsets", np.dtype(np.int32), cap + 1))
                    fields.append(("validity", np.dtype(np.uint8), cap))
                else:
                    cap = int(col.validity.shape[0])
                    dt = np.dtype(col.data.dtype)
                    if dt == np.dtype(np.bool_):
                        dt = np.dtype(np.uint8)
                    if dt == np.dtype(np.float64):
                        fields.append(("side", dt, cap))
                    else:
                        fields.append(("data", dt, cap))
                    fields.append(("validity", np.dtype(np.uint8), cap))
            plan.append(fields)
            sig_parts.append(";".join(f"{f}:{d}:{c}" for f, d, c in fields))
        sig = "packfetch|" + "|".join(sig_parts)

        def build():
            def to_bytes(arr):
                if arr.dtype == jnp.bool_:
                    return arr.astype(jnp.uint8)
                if arr.dtype == jnp.uint8:
                    return arr
                if arr.dtype.itemsize == 8:
                    # 64-bit ints: split into u32 words (the x64-rewrite
                    # pass rejects a direct 64->8 bitcast), then to bytes
                    u = arr.astype(jnp.uint64)
                    lo = (u & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
                    hi = (u >> jnp.uint64(32)).astype(jnp.uint32)
                    arr = jnp.stack([lo, hi], axis=-1).reshape(-1)
                return jax.lax.bitcast_convert_type(
                    arr, jnp.uint8).reshape(-1)

            def pack(bs):
                segs = []
                sides = []
                for b in bs:
                    segs.append(to_bytes(
                        b.num_rows.astype(jnp.int32).reshape(1)))
                    for col in b.columns:
                        if col.dtype.is_string and col.has_slab:
                            segs.append(to_bytes(
                                col._slab64.reshape(-1)))
                            segs.append(to_bytes(
                                col._lens.astype(jnp.int32)))
                            segs.append(col.validity.astype(jnp.uint8))
                        elif col.dtype.is_string and col.is_lazy:
                            segs.append(to_bytes(
                                col.dict_codes.astype(jnp.int32)))
                            segs.append(col.validity.astype(jnp.uint8))
                        elif col.dtype.is_string:
                            segs.append(col.data)
                            segs.append(to_bytes(
                                col.offsets.astype(jnp.int32)))
                            segs.append(col.validity.astype(jnp.uint8))
                        else:
                            if col.data.dtype == jnp.float64:
                                sides.append(col.data)
                            else:
                                segs.append(to_bytes(col.data))
                            segs.append(col.validity.astype(jnp.uint8))
                return jnp.concatenate(segs), sides
            return jax.jit(pack)

        slab_d, sides_d = cached_jit(sig, build)(list(batches))
        _start_host_copies_tree((slab_d, sides_d))
        with sync_scope("batch.fetch", detail="packed") as sc:
            slab, sides = jax.device_get((slab_d, sides_d))
            sc.add_bytes(_host_nbytes((slab, sides)))
        slab = np.asarray(slab)
        sides = [np.asarray(sd) for sd in sides]
        side_i = 0

        out: List[pd.DataFrame] = []
        off = 0

        def take(dt: np.dtype, count: int):
            nonlocal off
            nb = dt.itemsize * count
            arr = slab[off:off + nb].view(dt)
            off += nb
            return arr

        for b, fields in zip(batches, plan):
            it = iter(fields)
            _f, dt, c = next(it)
            n = int(take(dt, c)[0])
            b._host_rows = n
            series: List[pd.Series] = []
            for col, cdt in zip(b.columns, b.schema.dtypes):
                if cdt.is_string and col.has_slab:
                    w = int(col._slab64.shape[1])
                    # NB: do not name this ``slab`` — that is the outer
                    # fetched byte buffer take() slices from
                    slab_col = take(*next(it)[1:]).reshape(-1, w)
                    lens = take(*next(it)[1:])
                    validity = take(*next(it)[1:]).astype(bool)
                    trimmed = (validity[:n], lens[:n], slab_col[:n])
                elif cdt.is_string and col.is_lazy:
                    codes = take(*next(it)[1:])
                    validity = take(*next(it)[1:]).astype(bool)
                    trimmed = (validity[:n], codes[:n])
                elif cdt.is_string:
                    chars = take(*next(it)[1:])
                    offsets = take(*next(it)[1:])
                    validity = take(*next(it)[1:]).astype(bool)
                    trimmed = (validity[:n], offsets[:n + 1], chars)
                else:
                    field, fdt, fcount = next(it)
                    if field == "side":
                        data = sides[side_i]
                        side_i += 1
                    else:
                        data = take(fdt, fcount)
                    validity = take(*next(it)[1:]).astype(bool)
                    if cdt.np_dtype == np.bool_:
                        data = data.astype(bool)
                    trimmed = (data[:n], validity[:n])
                values, validity = col.numpy_from_host(trimmed, n)
                series.append(_numpy_to_pandas(values, validity, cdt)
                              .reset_index(drop=True))
            if not series:
                out.append(pd.DataFrame(index=range(n)))
                continue
            df = pd.concat(series, axis=1)
            df.columns = list(b.schema.names)
            out.append(df)
        return out

    @staticmethod
    def _to_pandas_fused(batches: Sequence["DeviceBatch"]) -> List[pd.DataFrame]:
        """One device_get of (num_rows + full-capacity buffers) for every
        batch, trimmed to the fetched row counts host-side."""
        import jax

        def views(c):
            # lazy (codes-only) string columns ship codes+validity and
            # decode through their static dictionary on the host —
            # touching .data here would materialize the worst-case char
            # slab on device and ship it over the tunnel. Slab columns
            # ship words+lens and unpack host-side.
            if c.dtype.is_string and c.has_slab:
                return (c.validity, c._lens, c._slab64)
            if c.dtype.is_string and c.is_lazy:
                return (c.validity, c.dict_codes)
            if c.dtype.is_string:
                return (c.data, c.validity, c.offsets)
            return (c.data, c.validity)

        payload = [(b.num_rows, [views(c) for c in b.columns])
                   for b in batches]
        _start_host_copies_tree(payload)
        with sync_scope("batch.fetch", detail="fused") as sc:
            host = jax.device_get(payload)
            sc.add_bytes(_host_nbytes(host))
        out: List[pd.DataFrame] = []
        for b, (count, host_cols) in zip(batches, host):
            n = int(count)
            b._host_rows = n
            series: List[pd.Series] = []
            for dt, col, parts in zip(b.schema.dtypes, b.columns, host_cols):
                if dt.is_string and col.has_slab:
                    validity, lens, slab = (np.asarray(p) for p in parts)
                    trimmed = (validity[:n], lens[:n], slab[:n])
                elif dt.is_string and col.is_lazy:
                    validity, codes = (np.asarray(p) for p in parts)
                    trimmed = (validity[:n], codes[:n])
                elif dt.is_string:
                    chars, validity, offsets = (np.asarray(p) for p in parts)
                    trimmed = (validity[:n], offsets[:n + 1], chars)
                else:
                    data, validity = (np.asarray(p) for p in parts)
                    trimmed = (data[:n], validity[:n])
                values, validity = col.numpy_from_host(trimmed, n)
                series.append(_numpy_to_pandas(values, validity, dt)
                              .reset_index(drop=True))
            if not series:
                out.append(pd.DataFrame(index=range(n)))
                continue
            df = pd.concat(series, axis=1)
            df.columns = list(b.schema.names)
            out.append(df)
        return out

    @staticmethod
    def empty(schema: Schema, capacity: int = MIN_CAPACITY) -> "DeviceBatch":
        cols = []
        for dt in schema.dtypes:
            cols.append(DeviceColumn.from_numpy(
                np.empty(0, dtype=object if dt.is_string else dt.np_dtype),
                None, dt, capacity))
        return DeviceBatch(schema, cols, jnp.asarray(0, dtype=jnp.int32))


# ---------------------------------------------------------------------------
# pandas <-> numpy(+mask) helpers
# ---------------------------------------------------------------------------

def _pandas_col_dtype(s: pd.Series) -> DType:
    dt = s.dtype
    name = str(dt)
    mapping = {
        "boolean": dtypes.BOOL, "bool": dtypes.BOOL,
        "Int8": dtypes.INT8, "int8": dtypes.INT8,
        "Int16": dtypes.INT16, "int16": dtypes.INT16,
        "Int32": dtypes.INT32, "int32": dtypes.INT32,
        "Int64": dtypes.INT64, "int64": dtypes.INT64,
        "Float32": dtypes.FLOAT32, "float32": dtypes.FLOAT32,
        "Float64": dtypes.FLOAT64, "float64": dtypes.FLOAT64,
    }
    if name in mapping:
        return mapping[name]
    if name.startswith("datetime64"):
        # NOTE: logical dates also land here (host convention: dates ride
        # as datetime64 -> micros); the srt_logical_dtype attrs marker
        # tells date-aware consumers (Cast to string) without changing the
        # micros unpack every datetime consumer assumes
        return dtypes.TIMESTAMP_US
    if name in ("object", "str", "string"):
        return dtypes.STRING
    raise TypeError(f"unsupported pandas dtype: {name}")


def _pandas_to_numpy(s: pd.Series, dt: DType) -> Tuple[np.ndarray, np.ndarray]:
    """Null discipline: numpy-backed numeric/bool columns cannot represent
    missing (float NaN is a *value*, like SQL NaN, not NULL) so they are
    all-valid; nullable extension dtypes (Int64/Float64/boolean) use their
    mask; datetime64 NaT and object-column None are NULL."""
    if (not dt.is_string and isinstance(s.dtype, np.dtype)
            and s.dtype.kind in "biuf"):
        validity = np.ones(len(s), dtype=np.bool_)
        return s.to_numpy(dtype=dt.np_dtype), validity
    validity = (~s.isna()).to_numpy(dtype=np.bool_)
    if dt.is_string:
        vals = s.to_numpy(dtype=object)
        if not validity.all():
            vals = vals.copy()
            vals[~validity] = None  # replace NaN placeholders with None
        return vals, validity
    if dt == dtypes.DATE32:
        if str(s.dtype).startswith("datetime64") or str(s.dtype) == "object":
            vals = pd.to_datetime(s).to_numpy(dtype="datetime64[D]")
            return vals.astype(np.int64).astype(np.int32), validity
        return s.to_numpy(dtype=np.int32, na_value=0), validity
    if dt == dtypes.TIMESTAMP_US:
        if str(s.dtype).startswith("datetime64"):
            # already datetime64: unit-cast directly — pd.to_datetime on
            # an existing datetime column pays a should_cache element
            # sweep per batch, pure overhead on the scan upload hot path
            out = s.to_numpy(dtype="datetime64[us]").astype(np.int64)
            if not validity.all():
                out = np.where(validity, out, 0)
            return out, validity
        if str(s.dtype) == "object":
            vals = pd.to_datetime(s).to_numpy(dtype="datetime64[us]")
            out = vals.astype(np.int64)
            out = np.where(validity, out, 0)
            return out, validity
        return s.to_numpy(dtype=np.int64, na_value=0), validity
    fill = dtypes.null_fill_value(dt)
    return s.to_numpy(dtype=dt.np_dtype, na_value=fill), validity


def _numpy_to_pandas(values: np.ndarray, validity: np.ndarray,
                     dt: DType) -> pd.Series:
    has_nulls = not bool(validity.all()) if len(validity) else False
    if dt.is_string:
        s = pd.Series(values, dtype="str")
        return s
    if dt == dtypes.DATE32:
        out = values.astype("datetime64[D]").astype("datetime64[s]")
        s = pd.Series(out)
        if has_nulls:
            s = s.mask(~validity)
        # pandas cannot hold datetime64[D]; mark the logical date type so
        # host dtype dispatch (series_dtype) does not read it as timestamp
        s.attrs["srt_logical_dtype"] = "date32"
        return s
    if dt == dtypes.TIMESTAMP_US:
        out = values.astype("datetime64[us]")
        s = pd.Series(out)
        if has_nulls:
            s = s.mask(~validity)
        return s
    if has_nulls:
        s = pd.Series(values, dtype=dt.pandas_nullable)
        return s.mask(~validity)
    # keep plain numpy dtype when no nulls: fast path and exact CPU parity
    return pd.Series(values)
