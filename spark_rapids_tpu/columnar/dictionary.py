"""First-class dictionary-column support: merge semantics + value tables.

The gather-free execution mode (docs/gatherfree.md) carries
dictionary-encoded string columns end-to-end as int32 codes and only ever
touches char space through the STATIC dictionary — a host tuple riding
jit cache keys as pytree aux data. Everything per-VALUE is therefore
computable host-side once per dictionary and baked into traces as
constants:

  * ``union_dictionaries``: the exchange-boundary merge — union of the
    input dictionaries in canonical sorted order plus one O(cardinality)
    int32 remap table per input. The same stateful-remap shape the scan
    path already uses (column.host_dict_encode_stateful), applied between
    batches instead of between a batch and a scan registry.
  * ``value_prefix_chunk_tables``: the 64-byte big-endian prefix images +
    length key of every dictionary value — bit-identical to
    ops/sortops._string_prefix_chunks on the decoded column, so
    sort/join/range-partition operands of dictionary columns are ONE tiny
    table gather per image instead of 64 char gathers per row.
  * ``value_hash_tables``: the two polynomial hashes of every value —
    bit-identical to ops/hashing.string_poly_hashes on the decoded
    column, so exchange partitioning and join tiebreaks of dictionary
    columns are a table gather instead of a char-scanning segment hash.

Rollback: spark.rapids.sql.dict.enabled=false disables dictionary
encoding at upload, so none of these paths can engage (legacy
chars+offsets execution everywhere).
"""

from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

import numpy as np

_M64 = (1 << 64) - 1
_PREFIX_CHUNKS = 8  # keep in sync with ops/sortops.STRING_PREFIX_CHUNKS


# module flags configured per query from conf (session._execute).
# ``hash_values`` gates a VALUE-IDENTICAL path (per-value hash tables vs
# char scans), so a kernel traced under the other setting is never a
# correctness hazard. ``merge_exchange`` changes output REPRESENTATION
# (codes vs chars), so its consumers bake it into their kernel-cache
# signatures (exec/tpu._concat_device). ``wire`` picks the shuffle frame
# format (shuffle/wire.py) — both ends of the in-process transport read
# the same flag.
#
# Scope: these are PROCESS-wide, like the session conf they mirror —
# concurrent queries of one session share one conf, so they agree by
# construction. Under concurrent serving a mid-flight set_conf can flip
# a flag between two queries' kernels; every reachable combination is
# CORRECT (codes and chars are value-equal, v1 and v2 frames both
# deserialize) — only which representation ran is affected, same
# semantics as every other session-global conf.
_FLAGS = {"hash_values": True, "merge_exchange": True, "wire": True}


def configure_from_conf(conf) -> None:
    _FLAGS["hash_values"] = conf.get_bool(
        "spark.rapids.sql.dict.hashValues", True)
    _FLAGS["merge_exchange"] = conf.get_bool(
        "spark.rapids.sql.dict.mergeOnExchange", True)
    _FLAGS["wire"] = conf.get_bool("spark.rapids.sql.dict.wire", True)


def hash_values_enabled() -> bool:
    return _FLAGS["hash_values"]


def merge_exchange_enabled() -> bool:
    return _FLAGS["merge_exchange"]


def wire_enabled() -> bool:
    return _FLAGS["wire"]


def _value_bytes(v) -> bytes:
    if isinstance(v, str):
        return v.encode("utf-8")
    return str(v).encode("utf-8")


@functools.lru_cache(maxsize=512)
def value_prefix_chunk_tables(dict_values: tuple) -> Tuple[np.ndarray, ...]:
    """(card + 1,) uint64 tables, one per prefix-chunk image plus the
    trailing length key — entry ``card`` is the NULL/padding sentinel
    (all-zero images, length 0, exactly what an empty-extent invalid row
    produces on the char path)."""
    card = len(dict_values)
    out = [np.zeros(card + 1, np.uint64) for _ in range(_PREFIX_CHUNKS + 1)]
    for i, v in enumerate(dict_values):
        raw = _value_bytes(v)
        for c in range(_PREFIX_CHUNKS):
            img = 0
            for b in range(8):
                pos = c * 8 + b
                byte = raw[pos] if pos < len(raw) else 0
                img = ((img << 8) | byte) & _M64
            out[c][i] = img
        out[_PREFIX_CHUNKS][i] = len(raw)
    return tuple(out)


@functools.lru_cache(maxsize=512)
def value_hash_tables(dict_values: tuple) -> Tuple[np.ndarray, np.ndarray]:
    """(h1, h2) uint64 tables of shape (card + 1,): the two independent
    polynomial hashes of each dictionary value, bit-identical to
    ops/hashing.string_poly_hashes over the decoded rows. Entry ``card``
    (NULL) carries the NULL_HASH sentinel — the same value the char path
    assigns every invalid row."""
    from spark_rapids_tpu.ops.hashing import (
        NULL_HASH, P1, P2, SALT1, SALT2, np_splitmix64,
    )
    card = len(dict_values)
    acc1 = np.zeros(card + 1, np.uint64)
    acc2 = np.zeros(card + 1, np.uint64)
    lens = np.zeros(card + 1, np.uint64)
    for i, v in enumerate(dict_values):
        raw = _value_bytes(v)
        a1 = a2 = 0
        for b in raw:
            a1 = (a1 * P1 + b) & _M64
            a2 = (a2 * P2 + b) & _M64
        acc1[i], acc2[i], lens[i] = a1, a2, len(raw)
    h1 = np_splitmix64(acc1 + np.uint64(SALT1) + lens)
    h2 = np_splitmix64(acc2 + np.uint64(SALT2) + lens)
    h1[card] = NULL_HASH
    h2[card] = NULL_HASH
    return h1, h2


def union_dictionaries(dicts: Sequence[tuple]
                       ) -> Tuple[tuple, List[np.ndarray]]:
    """Union the value sets in canonical sorted order (the same order
    host_dict_encode establishes, so identical value SETS keep producing
    identical — compile-key-stable — dictionaries) and build one int32
    remap table per input: ``remap[old_code] -> new_code`` with the NULL
    sentinel (old card) mapping to the union's NULL sentinel (union
    card)."""
    seen = set()
    union: list = []
    for d in dicts:
        for v in d:
            if v not in seen:
                seen.add(v)
                union.append(v)
    union.sort()
    pos = {v: i for i, v in enumerate(union)}
    ucard = len(union)
    remaps = []
    for d in dicts:
        r = np.empty(len(d) + 1, np.int32)
        for i, v in enumerate(d):
            r[i] = pos[v]
        r[len(d)] = ucard
        remaps.append(r)
    return tuple(union), remaps


# bounded memo of union results keyed by the input dictionary tuples —
# exchanges re-concat the same per-scan dictionaries every execution
@functools.lru_cache(maxsize=256)
def _union_cached(dict_tuple_of_tuples: tuple):
    vals, remaps = union_dictionaries(list(dict_tuple_of_tuples))
    return vals, tuple(r.tobytes() for r in remaps), \
        tuple(len(r) for r in remaps)


def union_dictionaries_cached(dicts: Sequence[tuple]
                              ) -> Tuple[tuple, List[np.ndarray]]:
    vals, blobs, lens = _union_cached(tuple(dicts))
    return vals, [np.frombuffer(b, np.int32).copy() for b in blobs]


def mergeable(parts) -> bool:
    """True when every column in ``parts`` carries a dictionary (possibly
    different ones) — the precondition for the union+remap merge."""
    return all(p.dict_values is not None and p.dict_codes is not None
               for p in parts)
