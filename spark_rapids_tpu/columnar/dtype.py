"""SQL data-type system with mappings to numpy/jax/pyarrow.

Mirrors the type gate and Spark<->cuDF dtype mapping of the reference
(GpuOverrides.scala:383-395 supported-type set; GpuColumnVector.java:134-199
mapping). Supported: bool, int8/16/32/64, float32/64, date (int32 days),
timestamp (int64 microseconds, UTC), string.

On device:
  * fixed-width types are one jnp array of the physical dtype plus a validity
    mask (True = valid);
  * strings are (offsets int32[n+1], chars uint8[char_capacity]) plus
    validity, the same offsets+chars layout cuDF uses — it is also the natural
    layout for XLA segment ops.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import numpy as np
import pyarrow as pa


@dataclass(frozen=True)
class DType:
    name: str
    np_dtype: Optional[np.dtype]  # physical numpy/jax dtype (None for string)
    pa_type: Any                  # pyarrow logical type
    pandas_nullable: str          # pandas extension dtype name for the host path

    def __repr__(self) -> str:
        return self.name

    @property
    def is_string(self) -> bool:
        return self.name == "string"

    @property
    def is_floating(self) -> bool:
        return self.name in ("float32", "float64")

    @property
    def is_integral(self) -> bool:
        return self.name in ("int8", "int16", "int32", "int64")

    @property
    def is_numeric(self) -> bool:
        return self.is_floating or self.is_integral

    @property
    def is_datetime(self) -> bool:
        return self.name in ("date32", "timestamp_us")

    @property
    def itemsize(self) -> int:
        return 1 if self.is_string else self.np_dtype.itemsize


BOOL = DType("bool", np.dtype(np.bool_), pa.bool_(), "boolean")
INT8 = DType("int8", np.dtype(np.int8), pa.int8(), "Int8")
INT16 = DType("int16", np.dtype(np.int16), pa.int16(), "Int16")
INT32 = DType("int32", np.dtype(np.int32), pa.int32(), "Int32")
INT64 = DType("int64", np.dtype(np.int64), pa.int64(), "Int64")
FLOAT32 = DType("float32", np.dtype(np.float32), pa.float32(), "Float32")
FLOAT64 = DType("float64", np.dtype(np.float64), pa.float64(), "Float64")
# days since unix epoch
DATE32 = DType("date32", np.dtype(np.int32), pa.date32(), "object")
# microseconds since unix epoch, UTC only (reference supports UTC timestamps
# only, GpuOverrides.scala:389-393)
TIMESTAMP_US = DType("timestamp_us", np.dtype(np.int64), pa.timestamp("us"), "object")
STRING = DType("string", None, pa.string(), "str")

ALL_DTYPES = [BOOL, INT8, INT16, INT32, INT64, FLOAT32, FLOAT64, DATE32,
              TIMESTAMP_US, STRING]
_BY_NAME = {d.name: d for d in ALL_DTYPES}


def by_name(name: str) -> DType:
    return _BY_NAME[name]


def from_arrow(t: pa.DataType) -> DType:
    if pa.types.is_boolean(t): return BOOL
    if pa.types.is_int8(t): return INT8
    if pa.types.is_int16(t): return INT16
    if pa.types.is_int32(t): return INT32
    if pa.types.is_int64(t): return INT64
    if pa.types.is_float32(t): return FLOAT32
    if pa.types.is_float64(t): return FLOAT64
    if pa.types.is_date32(t): return DATE32
    if pa.types.is_timestamp(t): return TIMESTAMP_US
    if pa.types.is_string(t) or pa.types.is_large_string(t): return STRING
    if pa.types.is_decimal(t):
        raise TypeError("decimal is not supported (the reference also lacks "
                        "decimal support at v0)")
    raise TypeError(f"unsupported arrow type: {t}")


def from_numpy(dt: np.dtype) -> DType:
    dt = np.dtype(dt)
    if dt == np.bool_: return BOOL
    if dt == np.int8: return INT8
    if dt == np.int16: return INT16
    if dt == np.int32: return INT32
    if dt == np.int64: return INT64
    if dt == np.float32: return FLOAT32
    if dt == np.float64: return FLOAT64
    if dt.kind == "M":  # datetime64
        if dt == np.dtype("datetime64[D]"):
            return DATE32
        return TIMESTAMP_US
    if dt.kind in ("U", "S", "O"):
        return STRING
    raise TypeError(f"unsupported numpy dtype: {dt}")


def common_type(a: DType, b: DType) -> DType:
    """Numeric type promotion following Spark's binary-op coercion."""
    if a == b:
        return a
    order = [INT8, INT16, INT32, INT64, FLOAT32, FLOAT64]
    if a in order and b in order:
        return order[max(order.index(a), order.index(b))]
    if BOOL in (a, b):
        other = b if a == BOOL else a
        if other in order:
            return other
    if {a, b} == {DATE32, TIMESTAMP_US}:
        return TIMESTAMP_US  # Spark widens date to timestamp
    raise TypeError(f"no common type for {a} and {b}")


def null_fill_value(d: DType):
    """Canonical value stored in invalid slots so device math is deterministic."""
    if d == BOOL:
        return False
    if d.is_floating:
        return 0.0
    return 0
