"""Device-resident column vectors.

The TPU analogue of the reference's ``GpuColumnVector``
(sql-plugin/src/main/java/com/nvidia/spark/rapids/GpuColumnVector.java:41-199):
a column whose storage is XLA device buffers (jax arrays) rather than cuDF
device memory. Registered as a jax pytree so whole batches can flow through
``jax.jit``-traced operator stages.

Shape discipline (the core TPU-first design decision): every column has a
static ``capacity`` (padded to a bucketed size, see batch.py) while the number
of *valid leading rows* is carried as data (the batch's ``num_rows`` scalar).
This keeps every XLA program shape-static while allowing dynamic result sizes
(filters, joins) without recompilation — the mitigation SURVEY.md section 7
"hard parts" items 1 and 3 call for.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtype as dtypes
from spark_rapids_tpu.columnar.dtype import DType


@jax.tree_util.register_pytree_node_class
class DeviceColumn:
    """One column on the device.

    Fixed-width: ``data`` has shape (capacity,) with physical dtype.
    String: ``data`` is uint8 chars of shape (char_capacity,), ``offsets`` is
    int32 of shape (capacity + 1,). Invalid/padding rows have empty extents.
    ``validity`` is bool (capacity,), True = valid. Padding rows are invalid.
    """

    def __init__(self, dtype: DType, data: Optional[jnp.ndarray],
                 validity: jnp.ndarray,
                 offsets: Optional[jnp.ndarray] = None,
                 prefix8: Optional[jnp.ndarray] = None,
                 dict_codes: Optional[jnp.ndarray] = None,
                 dict_values: Optional[tuple] = None,
                 slab64: Optional[jnp.ndarray] = None,
                 lens: Optional[jnp.ndarray] = None):
        self.dtype = dtype
        # codes-only (lazy) string column: ``data=None`` with a dictionary
        # present. Chars/offsets materialize from the static dictionary on
        # first access (the .data/.offsets properties) — pipeline stages
        # that never read chars (concat, joins on other keys, dict-coded
        # grouping/sorting/predicates) move ONLY the int32 codes, which
        # measured ~2x cheaper than even the dict-rebuild char gather at
        # fact-table scale. The TPU answer to cuDF keeping dictionary
        # columns encoded end-to-end.
        #
        # slab (blocked-chars) string column: ``data=None`` with a
        # fixed-stride uint64 slab present. ``slab64`` is (capacity,
        # stride/8) with row i's bytes packed value-wise (byte j at bit
        # 8*(j%8) of word j//8) and ZERO past the row's length; ``lens``
        # is int32 (capacity,). Row movement is then a 2-D lane-
        # contiguous row gather (the stacked-gather form, 4-6x cheaper
        # than the 1-D char-index gather on TPU) and sort/group/hash
        # images derive densely from the words. Packed chars+offsets
        # materialize lazily only when an operator actually reads them.
        assert data is not None or (dtype.is_string
                                    and (dict_values is not None
                                         or slab64 is not None)), dtype
        self._data = data
        self._slab64 = slab64
        self._lens = lens
        self.validity = validity
        self._offsets = offsets
        # optional per-row big-endian image of the first 8 bytes (uint64,
        # (capacity,)): computed host-side at upload for scanned string
        # columns and propagated through gathers, it lets grouping/sorting
        # read key bytes without per-row char gathers (which lower to
        # seconds-per-million-rows scalar loops on TPU). Derived string
        # columns may carry None. Lazy (codes-only) columns derive it
        # from the dictionary on access.
        self._prefix8 = prefix8
        # optional host-computed dictionary encoding (low-cardinality
        # columns): ``dict_codes`` int32 (capacity,) with values in
        # [0, card], where card = len(dict_values) encodes NULL (and row
        # padding); ``dict_values`` is a STATIC tuple of python values in
        # canonical sorted order. Being pytree aux data, the dictionary is
        # a compile-time constant — the aggregation fast path uses it for
        # direct slot addressing and rebuilds group-key outputs from host
        # constants with zero device char reads (the TPU answer to cuDF's
        # dictionary columns the reference leans on for strings).
        self.dict_codes = dict_codes
        self.dict_values = dict_values

    # --- lazy chars (codes-only / slab string columns) --------------------
    @property
    def data(self):
        if self._data is None:
            if self._slab64 is not None:
                self._materialize_from_slab()
            else:
                self._materialize_chars()
        return self._data

    @property
    def offsets(self):
        if self._offsets is None and self._data is None \
                and self.dtype.is_string:
            if self._slab64 is not None:
                self._materialize_from_slab()
            else:
                self._materialize_chars()
        return self._offsets

    @property
    def prefix8(self):
        if (self._prefix8 is None and self.dtype.is_string
                and self.has_slab):
            # big-endian image of the first 8 bytes == byte-reversed word
            # 0 of the slab (0-padded past the end by the slab invariant)
            # — a dense op, no char gathers
            self._prefix8 = _bswap64(self._slab64[:, 0])
            return self._prefix8
        if (self._prefix8 is None and self.dtype.is_string
                and self.dict_values is not None
                and self.dict_codes is not None):
            # row-space derivation from the static dictionary — cheap (one
            # tiny-table gather), no char materialization needed
            import numpy as np
            card = len(self.dict_values)
            imgs = np.asarray(
                [int.from_bytes(v.encode("utf-8")[:8].ljust(8, b"\0"),
                                "big") for v in self.dict_values] + [0],
                np.uint64)
            self._prefix8 = jnp.where(
                self.validity,
                jnp.asarray(imgs)[jnp.clip(self.dict_codes, 0, card)],
                jnp.uint64(0))
        return self._prefix8

    @prefix8.setter
    def prefix8(self, v) -> None:
        self._prefix8 = v

    @property
    def is_lazy(self) -> bool:
        """True while chars/offsets are unmaterialized (codes-only or
        slab-backed)."""
        return self._data is None

    @property
    def has_slab(self) -> bool:
        """True for a slab-backed (blocked-chars) string column whose
        packed chars have not been materialized."""
        return self._slab64 is not None and self._data is None

    @property
    def char_stride(self) -> int:
        """Static per-row byte stride of the slab layout."""
        assert self._slab64 is not None
        return int(self._slab64.shape[1]) * 8

    def lens_(self) -> jnp.ndarray:
        """Per-row byte lengths (int32) WITHOUT materializing a lazy
        column: slab columns carry them, dictionary columns derive them
        from the static dictionary, packed columns diff their offsets."""
        if self._slab64 is not None and self._lens is not None:
            return self._lens
        if self.is_lazy:
            _dc, _ds, dlens = self.dict_tables()
            card = len(self.dict_values)
            lens = jnp.asarray(dlens)[jnp.clip(self.dict_codes, 0, card)]
            return jnp.where(self.validity, lens, 0).astype(jnp.int32)
        return (self.offsets[1:] - self.offsets[:-1]).astype(jnp.int32)

    def _materialize_from_slab(self) -> None:
        """Rebuild packed chars+offsets from the fixed-stride slab. The
        flat slab is the gather source, so this is the ONLY remaining
        1-D char gather on the blocked path — paid solely by operators
        that genuinely need the packed layout (byte-level string
        expressions), never by row movement, sorting, grouping, hashing
        or the result fetch."""
        cap, w = int(self._slab64.shape[0]), int(self._slab64.shape[1])
        stride = w * 8
        lens = jnp.where(self.validity, self._lens, 0).astype(jnp.int32)
        offsets = jnp.concatenate([
            jnp.zeros((1,), jnp.int32), jnp.cumsum(lens).astype(jnp.int32)])
        total = offsets[cap]
        char_cap = _char_bucket(cap * stride)
        # value-semantics byte expansion (endian-independent): byte j of
        # a row is (word[j//8] >> 8*(j%8)) & 0xFF
        shifts = (jnp.uint64(8) * jnp.arange(8, dtype=jnp.uint64))
        flat = ((self._slab64[:, :, None] >> shifts[None, None, :])
                & jnp.uint64(0xFF)).astype(jnp.uint8).reshape(cap * stride)
        from spark_rapids_tpu.ops.rowops import rank_of_iota
        k = jnp.arange(char_cap, dtype=jnp.int32)
        out_row = jnp.clip(rank_of_iota(offsets, char_cap) - 1, 0, cap - 1)
        src = out_row * stride + (k - offsets[out_row])
        chars = flat[jnp.clip(src, 0, cap * stride - 1)]
        self._data = jnp.where(k < total, chars, 0).astype(jnp.uint8)
        self._offsets = offsets

    def dict_tables(self):
        """Host constants of the static dictionary: (chars u8, starts
        int32 (card+1,), lens int32 (card+1,)) — trailing entry is the
        NULL sentinel (empty)."""
        import numpy as np
        vals_b = [v.encode("utf-8") for v in self.dict_values]
        dchars = np.frombuffer(b"".join(vals_b) or b"\0", np.uint8)
        dlens = np.asarray([len(v) for v in vals_b] + [0], np.int32)
        dstarts = np.concatenate([[0], np.cumsum(dlens[:-1])]).astype(
            np.int32)
        return dchars, dstarts, dlens

    def _materialize_chars(self) -> None:
        """Rebuild chars+offsets from dictionary codes (jnp ops: works
        eagerly or inside a consumer's trace). Char capacity is the
        static worst case capacity*maxlen, bucketed."""
        assert self.dict_values is not None and self.dict_codes is not None
        dchars, dstarts, dlens = self.dict_tables()
        card = len(self.dict_values)
        cap = int(self.validity.shape[0])
        code_c = jnp.clip(self.dict_codes, 0, card)
        lens = jnp.where(self.validity, jnp.asarray(dlens)[code_c], 0)
        offsets = jnp.concatenate([
            jnp.zeros((1,), jnp.int32),
            jnp.cumsum(lens).astype(jnp.int32)])
        max_len = max((len(v.encode("utf-8")) for v in self.dict_values),
                      default=1)
        char_cap = _char_bucket(cap * max_len)
        from spark_rapids_tpu.ops.rowops import rank_of_iota
        k = jnp.arange(char_cap, dtype=jnp.int32)
        out_row = jnp.clip(rank_of_iota(offsets, char_cap) - 1, 0, cap - 1)
        src = (jnp.asarray(dstarts)[code_c[out_row]]
               + (k - offsets[out_row]))
        chars = jnp.asarray(dchars)[jnp.clip(src, 0, dchars.shape[0] - 1)]
        total = offsets[cap]
        self._data = jnp.where(k < total, chars, 0).astype(jnp.uint8)
        self._offsets = offsets

    # --- pytree protocol ---------------------------------------------------
    def tree_flatten(self):
        if self.has_slab:
            # slab layout: validity + slab words + lens are the whole
            # payload; packed chars materialize on the other side on
            # demand (a column that already materialized packed chars
            # flattens as packed below — the slab is dropped, its cost
            # has been paid)
            return ((self.validity, self._slab64, self._lens),
                    ("slab", self.dtype))
        lazy = self._data is None
        if lazy:
            # codes-only: validity + codes are the whole payload; chars
            # materialize on the other side on demand
            return ((self.validity, self.dict_codes),
                    (self.dtype, False, self.dict_values, True))
        leaves = [self._data, self.validity]
        if self.dtype.is_string:
            leaves.append(self._offsets)
        has_prefix = self.dtype.is_string and self._prefix8 is not None
        if has_prefix:
            leaves.append(self._prefix8)
        if self.dict_values is not None:
            leaves.append(self.dict_codes)
        return tuple(leaves), (self.dtype, has_prefix, self.dict_values,
                               False)

    @classmethod
    def tree_unflatten(cls, aux, children):
        if isinstance(aux, tuple) and len(aux) == 2 and aux[0] == "slab":
            validity, slab64, lens = children
            return cls(aux[1], None, validity, slab64=slab64, lens=lens)
        if isinstance(aux, tuple):
            if len(aux) == 4:
                dtype, has_prefix, dict_values, lazy = aux
            elif len(aux) == 3:
                (dtype, has_prefix, dict_values), lazy = aux, False
            else:
                (dtype, has_prefix), dict_values, lazy = aux, None, False
        else:
            dtype, has_prefix, dict_values, lazy = aux, False, None, False
        it = list(children)
        if lazy:
            validity, dict_codes = it
            return cls(dtype, None, validity, dict_codes=dict_codes,
                       dict_values=dict_values)
        data, validity = it[0], it[1]
        pos = 2
        offsets = prefix8 = dict_codes = None
        if dtype.is_string:
            offsets = it[pos]
            pos += 1
        if has_prefix:
            prefix8 = it[pos]
            pos += 1
        if dict_values is not None:
            dict_codes = it[pos]
        return cls(dtype, data, validity, offsets, prefix8,
                   dict_codes, dict_values)

    @property
    def dict_card(self) -> int:
        """Number of real dictionary values (code == dict_card is NULL)."""
        assert self.dict_values is not None
        return len(self.dict_values)

    # --- properties --------------------------------------------------------
    @property
    def capacity(self) -> int:
        # validity is (capacity,) for every kind — and reading it never
        # triggers lazy char materialization
        return int(self.validity.shape[0])

    @property
    def char_capacity(self) -> int:
        assert self.dtype.is_string
        return int(self.data.shape[0])

    def __repr__(self) -> str:
        return f"DeviceColumn({self.dtype}, capacity={self.capacity})"

    # --- construction ------------------------------------------------------
    @staticmethod
    def from_numpy(values: np.ndarray, validity: Optional[np.ndarray],
                   dtype: DType, capacity: int,
                   char_capacity: Optional[int] = None) -> "DeviceColumn":
        """Build a device column from host data, padding to ``capacity``.

        The host-side build-then-upload mirrors the reference's
        ``GpuColumnarBatchBuilder`` (GpuColumnVector.java:43-132).
        """
        bufs = DeviceColumn.build_host_buffers(values, validity, dtype,
                                               capacity, char_capacity)
        return DeviceColumn(dtype, *[jnp.asarray(b) for b in bufs])

    @staticmethod
    def build_host_buffers(values: np.ndarray,
                           validity: Optional[np.ndarray],
                           dtype: DType, capacity: int,
                           char_capacity: Optional[int] = None):
        """Device-layout numpy buffers (constructor order), upload-ready —
        kept separate from the upload so a whole batch's buffers can ride
        ONE jax.device_put (per-buffer uploads each pay a round trip on
        remote attachments)."""
        n = len(values)
        assert n <= capacity, (n, capacity)
        if validity is None:
            validity = np.ones(n, dtype=np.bool_)
        vpad = np.zeros(capacity, dtype=np.bool_)
        vpad[:n] = validity

        if dtype.is_string:
            # vectorized offsets+chars extraction via arrow (C-speed); the
            # arrow StringArray layout is exactly our device layout
            import pyarrow as pa
            arr = pa.array(np.asarray(values, dtype=object), type=pa.string(),
                           mask=~validity[:n] if n else None,
                           from_pandas=True)
            src_off = np.frombuffer(arr.buffers()[1], dtype=np.int32,
                                    count=n + 1) if n else np.zeros(1, np.int32)
            offsets = np.zeros(capacity + 1, dtype=np.int32)
            offsets[:n + 1] = src_off - src_off[0]
            total = int(offsets[n])
            offsets[n + 1:] = total
            if char_capacity is None:
                char_capacity = _char_bucket(total)
            assert total <= char_capacity, (total, char_capacity)
            chars = np.zeros(char_capacity, dtype=np.uint8)
            if total:
                data_buf = arr.buffers()[2]
                chars[:total] = np.frombuffer(
                    data_buf, dtype=np.uint8,
                    count=total, offset=src_off[0])
            prefix8 = _np_prefix8(chars, offsets, capacity)
            return (chars, vpad, offsets, prefix8)

        fill = dtypes.null_fill_value(dtype)
        vals = np.asarray(values, dtype=dtype.np_dtype)
        dpad = np.empty(capacity, dtype=dtype.np_dtype)
        dpad[:n] = vals
        dpad[n:] = fill
        # canonicalize nulls to the fill value so device math is
        # deterministic; the all-valid scan hot path skips the rewrite
        # (np.full + np.where paid two extra full-column passes here)
        v = validity[:n]
        if not v.all():
            np.copyto(dpad[:n], np.asarray(fill, dtype=dtype.np_dtype),
                      where=~v)
        return (dpad, vpad)

    # --- host access -------------------------------------------------------
    def device_views(self, num_rows: int):
        """The device arrays a host copy needs (leading-rows slices).
        Kept lazy so a whole batch's views can ride ONE jax.device_get —
        per-buffer fetches each pay a full round trip on remote
        attachments. Codes-only columns ship just codes+validity and
        decode through the static dictionary on the host; slab columns
        ship the fixed-stride words + lens and unpack host-side (numpy) —
        neither ever runs a device char gather for the fetch."""
        if self.has_slab:
            return (self.validity[:num_rows], self._lens[:num_rows],
                    self._slab64[:num_rows])
        if self._data is None and self.dtype.is_string:
            return (self.validity[:num_rows], self.dict_codes[:num_rows])
        if self.dtype.is_string:
            return (self.validity[:num_rows], self.offsets[:num_rows + 1],
                    self.data)
        return (self.data[:num_rows], self.validity[:num_rows])

    def to_numpy(self, num_rows: int) -> Tuple[np.ndarray, np.ndarray]:
        """Copy the leading ``num_rows`` to host. Returns (values, validity).
        String columns return an object array of python str (None if null)."""
        import jax
        return self.numpy_from_host(
            jax.device_get(self.device_views(num_rows)), num_rows)

    def numpy_from_host(self, host_parts,
                        num_rows: int) -> Tuple[np.ndarray, np.ndarray]:
        """Finish a host copy from already-fetched device_views buffers."""
        if self.has_slab:
            validity, lens, slab = (np.asarray(p) for p in host_parts)
            chars, offsets = np_slab_to_packed(slab, lens, validity)
            return self.numpy_from_host_packed(chars, offsets, validity,
                                               num_rows)
        if self._data is None and self.dtype.is_string:
            validity, codes = (np.asarray(p) for p in host_parts)
            card = len(self.dict_values)
            table = np.asarray(list(self.dict_values) + [None],
                               dtype=object)
            out = table[np.clip(codes, 0, card)]
            out[~validity] = None
            return out, validity
        if self.dtype.is_string:
            validity, offsets, chars = (np.asarray(p) for p in host_parts)
            return self.numpy_from_host_packed(chars, offsets, validity,
                                               num_rows)
        data, validity = (np.asarray(p) for p in host_parts)
        return data, validity

    def numpy_from_host_packed(self, chars, offsets, validity,
                               num_rows: int):
        """Packed chars+offsets -> python strings (the shared tail of the
        packed and slab host-decode paths)."""
        import pyarrow as pa
        offsets = np.ascontiguousarray(offsets)
        chars = np.ascontiguousarray(chars)
        null_count = int(num_rows - validity.sum())
        vbuf = (pa.py_buffer(np.packbits(validity, bitorder="little"))
                if null_count else None)
        arr = pa.StringArray.from_buffers(
            num_rows, pa.py_buffer(offsets), pa.py_buffer(chars),
            vbuf, null_count)
        try:
            out = arr.to_numpy(zero_copy_only=False)
        except Exception:
            # byte-oriented device kernels (substring on multi-byte
            # UTF-8) can produce invalid UTF-8; decode leniently
            out = np.empty(num_rows, dtype=object)
            for i in range(num_rows):
                if validity[i]:
                    out[i] = bytes(
                        chars[offsets[i]:offsets[i + 1]]).decode(
                            "utf-8", errors="replace")
                else:
                    out[i] = None
        return out, validity


def _bswap64(x: jnp.ndarray) -> jnp.ndarray:
    """Byte-reverse uint64 values (value semantics, endian-independent):
    turns a little-ordered slab word into the big-endian order-preserving
    image the sort/group kernels compare."""
    out = jnp.zeros(x.shape, jnp.uint64)
    for b in range(8):
        byte = (x >> (jnp.uint64(8) * jnp.uint64(b))) & jnp.uint64(0xFF)
        out = out | (byte << (jnp.uint64(8) * jnp.uint64(7 - b)))
    return out


def slab_stride_for(max_len: int, max_stride: int) -> int:
    """Power-of-two per-row byte stride (>= 8) for the blocked char-slab
    layout, or 0 when the column's longest row exceeds ``max_stride``."""
    stride = 8
    while stride < max_len:
        stride <<= 1
    return stride if stride <= max_stride else 0


def np_build_slab(chars: np.ndarray, offsets: np.ndarray, capacity: int,
                  stride: int) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side packed -> fixed-stride slab conversion (upload path):
    (slab uint64 (capacity, stride/8), lens int32 (capacity,)). Bytes
    past each row's length are ZERO — the slab invariant every dense
    image derivation relies on. Word packing is value-based (byte j at
    bit 8*(j%8)), matching the device-side extraction exactly."""
    lens = (offsets[1:] - offsets[:-1]).astype(np.int64)
    starts = offsets[:-1].astype(np.int64)
    nc = max(len(chars), 1)
    j = np.arange(stride)
    idx = np.clip(starts[:, None] + j[None, :], 0, nc - 1)
    mask = j[None, :] < lens[:, None]
    bytes_ = np.where(mask, chars[idx], 0).astype(np.uint64)
    shifts = np.uint64(8) * np.arange(8, dtype=np.uint64)
    words = (bytes_.reshape(capacity, stride // 8, 8)
             << shifts[None, None, :]).sum(axis=2, dtype=np.uint64)
    return words, lens.astype(np.int32)


def np_slab_to_packed(slab: np.ndarray, lens: np.ndarray,
                      validity: np.ndarray
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side slab -> packed chars+offsets (the result-fetch decode):
    pure vectorized numpy, no device work at all."""
    n, w = slab.shape
    stride = w * 8
    lens = np.clip(np.asarray(lens, np.int64), 0, stride)
    shifts = np.uint64(8) * np.arange(8, dtype=np.uint64)
    bytes_ = ((slab[:, :, None] >> shifts[None, None, :])
              & np.uint64(0xFF)).astype(np.uint8).reshape(n, stride)
    mask = np.arange(stride)[None, :] < lens[:, None]
    chars = np.ascontiguousarray(bytes_[mask])
    offsets = np.zeros(n + 1, np.int32)
    offsets[1:] = np.cumsum(lens).astype(np.int32)
    return chars, offsets


def _np_prefix8(chars: np.ndarray, offsets: np.ndarray,
                capacity: int) -> np.ndarray:
    """Big-endian uint64 image of each row's first 8 bytes (0-padded past
    the end), vectorized on the host — the order-preserving prefix the
    device sort/group kernels would otherwise re-derive with per-row char
    gathers (see DeviceColumn.prefix8)."""
    lens = (offsets[1:] - offsets[:-1]).astype(np.int64)
    starts = offsets[:-1].astype(np.int64)
    nc = max(len(chars), 1)
    idx = starts[:, None] + np.arange(8)[None, :]
    in_row = np.arange(8)[None, :] < lens[:, None]
    b = np.where(in_row, chars[np.clip(idx, 0, nc - 1)], 0).astype(np.uint64)
    shifts = np.uint64(8) * np.arange(7, -1, -1, dtype=np.uint64)
    return (b << shifts[None, :]).sum(axis=1, dtype=np.uint64)


# host dictionary encoding, applied at upload. Cardinality cap keeps the
# static dictionaries small enough to ride jit cache keys; the sample
# probe keeps the cost near-zero for high-cardinality columns (factorize
# of a 750k-row column costs ~50-100 ms — only paid when the sample says
# the column is plausibly low-cardinality).
DICT_MAX_CARD = 256
_DICT_PROBE = 4096
# small-TABLE dictionary pre-seeding (exec/tpu.py TpuScanExec): a scan
# of a small in-memory table seeds its per-scan dictionary registry from
# the WHOLE column, so even all-distinct strings (a dimension table's
# natural key) encode — joins fan such columns out into fact-scale
# batches where dictionary codes make grouping/join images one u64
# operand instead of prefix-chunks+hashes, and results fetch as codes.
# The limits gate on the full table size, never on a chunk's length
# (host_dict_encode's own probe keeps protecting fact-scale uploads).
DICT_SMALL_TABLE_ROWS = 1 << 15
DICT_MAX_CARD_SMALL = 1 << 14


def string_host_buffers_have_nul(bufs, n: int) -> bool:
    """True when the string host-buffer tuple built by build_host_buffers
    — (chars, validity, offsets, prefix8), see its string branch above —
    holds a NUL byte among the first ``n`` rows' chars. Lives beside the
    layout definition so the positional access cannot silently drift.
    Used to gate dictionary encoding: pandas 3.x factorize hashes object
    strings through a NUL-terminated path and MERGES 'a' with 'a\\x00',
    which would corrupt dictionary-based grouping and comparison."""
    chars, _validity, offsets = bufs[0], bufs[1], bufs[2]
    used = int(offsets[n])
    return bool(used and (chars[:used] == 0).any())


def dict_factorize_hint(values, is_string: bool):
    """Cardinality probe + full-column factorize, precomputed OFF the
    consuming task thread by the scan pipeline's decode workers
    (sql/scan_pipeline.py) and attached to decoded frames
    (``df.attrs["srt_dict_fact"]``). The per-batch dictionary encode was
    the largest single consumer-side upload cost (an element-wise
    searchsorted per low-cardinality column per batch); with the hint,
    ``host_dict_encode_stateful`` only remaps the ~cardinality uniques.

    Returns (codes (n,), uniques) or None when the column is not a
    dictionary candidate."""
    import pandas as pd
    n = len(values)
    if n == 0:
        return None
    probe = values[:_DICT_PROBE]
    try:
        nu = pd.unique(probe[~pd.isna(probe)] if is_string else probe)
    except TypeError:
        return None
    if len(nu) > DICT_MAX_CARD or len(nu) > max(64, len(probe) // 4):
        return None
    try:
        codes, uniques = pd.factorize(values, use_na_sentinel=True)
    except TypeError:
        return None
    if len(uniques) > DICT_MAX_CARD or len(uniques) == 0:
        return None
    return codes, uniques


def host_dict_encode(values: np.ndarray, validity: Optional[np.ndarray],
                     dtype: DType, capacity: int, fact=None):
    """Host-side dictionary probe+encode of a column being uploaded.

    Returns (codes int32 (capacity,), values tuple) or None. Codes are in
    [0, card] with card = NULL/padding; ``values`` is sorted so identical
    value SETS across batches produce identical (compile-key) dictionaries.
    ``fact``: precomputed (codes, uniques) from ``dict_factorize_hint``
    (skips the probe + factorize here).
    """
    n = len(values)
    if n == 0:
        return None
    if fact is None:
        fact = dict_factorize_hint(values, dtype.is_string)
        if fact is None:
            return None
    codes, uniques = fact
    card = len(uniques)
    if card > DICT_MAX_CARD or card == 0:
        return None
    if dtype.is_string:
        if any(not isinstance(u, str) for u in uniques):
            return None  # mixed/NA uniques: not a clean string dictionary
        vals = [str(u) for u in uniques]
        sort_key = np.asarray(vals, dtype=object)
    else:
        arr = np.asarray(uniques, dtype=dtype.np_dtype)
        if np.issubdtype(arr.dtype, np.floating):
            # NaN is a grouping VALUE (SQL NaN, not NULL) but factorize
            # maps it to the NA sentinel, which would collapse NaN keys
            # into the NULL group — and a NaN dictionary entry would also
            # break aux-data equality (NaN != NaN churns the jit cache).
            # Check the VALID rows, not the uniques (factorize never
            # surfaces NaN as a unique).
            vrows = np.asarray(values[:n], dtype=np.float64)
            if validity is not None:
                vrows = vrows[validity[:n]]
            if np.isnan(vrows).any():
                return None
        # python scalars: hashable, stable across numpy versions
        vals = arr.tolist()
        sort_key = arr
    # canonical order: identical value SETS across batches -> identical
    # dictionaries -> one compiled program
    order = np.argsort(sort_key, kind="stable")
    remap = np.empty(card + 1, dtype=np.int32)
    remap[order] = np.arange(card, dtype=np.int32)
    remap[card] = card  # null sentinel maps to itself
    new_codes = remap[np.where(codes < 0, card, codes)]
    if validity is not None:
        # factorize saw canonicalized fill values at null rows as real
        # values; override their codes with the null sentinel (the fill
        # value's dictionary slot simply goes unused if no valid row
        # carries it)
        new_codes = np.where(validity[:n], new_codes, card)
    out = np.full(capacity, card, dtype=np.int32)
    out[:n] = new_codes.astype(np.int32)
    return out, tuple(vals[i] for i in order)


def host_dict_encode_stateful(values: np.ndarray,
                              validity: Optional[np.ndarray], dtype: DType,
                              capacity: int, state: Optional[dict],
                              key, fact=None) -> Optional[tuple]:
    """host_dict_encode with a per-scan registry: the FIRST batch of a scan
    establishes the dictionary and every later batch encodes against it,
    so all batches of one scan share one static dictionary (one compiled
    aggregation program, no per-batch retraces). A later batch holding a
    value outside the established dictionary switches the column off for
    the remainder of the scan (bounded structure churn: at most two
    program shapes per scan). ``fact``: precomputed (codes, uniques) from
    ``dict_factorize_hint`` — later batches then pay only an
    O(cardinality) remap here instead of an element-wise searchsorted."""
    st = state.get(key) if state is not None else None
    if st is False:
        return None
    if st is None:
        enc = host_dict_encode(values, validity, dtype, capacity, fact=fact)
        if state is not None:
            state[key] = enc[1] if enc is not None else False
        return enc
    n = len(values)
    card = len(st)
    out = np.full(capacity, card, dtype=np.int32)
    if n == 0:
        return out, st
    arr = np.asarray(list(st),
                     dtype=object if dtype.is_string else dtype.np_dtype)
    need = (np.asarray(validity[:n], dtype=bool) if validity is not None
            else np.ones(n, dtype=bool))
    if fact is not None:
        codes2, uniq2 = fact
        try:
            u = np.asarray(uniq2,
                           dtype=object if dtype.is_string
                           else dtype.np_dtype)
            idx = np.searchsorted(arr, u)
        except (TypeError, ValueError):
            state[key] = False
            return None
        idx_c = np.clip(idx, 0, card - 1)
        ok_u = arr[idx_c] == u
        # remap table over the batch's OWN uniques (+1 slot for the
        # factorize NA sentinel); -1 marks a value outside the
        # established dictionary
        remap = np.empty(len(u) + 1, dtype=np.int32)
        remap[:len(u)] = np.where(ok_u, idx_c, -1)
        remap[len(u)] = -1
        codes_n = np.asarray(codes2[:n])
        c = remap[np.where(codes_n < 0, len(u), codes_n)]
        if bool(((c < 0) & need).any()):
            state[key] = False  # unseen value in a valid row
            return None
        out[:n] = np.where(need, c, card).astype(np.int32)
        return out, st
    vals_n = np.asarray(values[:n],
                        dtype=object if dtype.is_string else dtype.np_dtype)
    # null slots may hold None/NaN fills that break object comparisons;
    # park them on a real dictionary entry (their codes are overridden)
    vals_n = np.where(need, vals_n, arr[0])
    try:
        idx = np.searchsorted(arr, vals_n)
    except TypeError:
        state[key] = False
        return None
    idx_c = np.clip(idx, 0, card - 1)
    ok = arr[idx_c] == vals_n
    if not bool(np.all(ok | ~need)):
        state[key] = False  # unseen value: dictionary closed for this scan
        return None
    out[:n] = np.where(need, idx_c, card).astype(np.int32)
    return out, st


def _char_bucket(n: int, minimum: int = 16) -> int:
    """Round a char-buffer size up to a power-of-two bucket. With shape
    buckets on (spark.rapids.tpu.compile.shapeBuckets) the bucket pads
    up the coarse ladder (utils/kernelcache.bucket_dim) — char-slab
    capacities are one of the dimensions the recompile-cause analyzer
    flags as varying per value."""
    cap = minimum
    while cap < n:
        cap <<= 1
    from spark_rapids_tpu.utils.kernelcache import bucket_dim
    return bucket_dim(cap)
