from spark_rapids_tpu.columnar import dtype as dtypes  # noqa: F401
from spark_rapids_tpu.columnar.batch import (  # noqa: F401
    DeviceBatch,
    Schema,
    bucket_capacity,
)
from spark_rapids_tpu.columnar.column import DeviceColumn  # noqa: F401
