"""User-facing column functions, pyspark.sql.functions-style surface."""

from __future__ import annotations

from typing import Any, Union

from spark_rapids_tpu.columnar import dtypes
from spark_rapids_tpu.sql.exprs import aggregates as agg
from spark_rapids_tpu.sql.exprs import arithmetic as ar
from spark_rapids_tpu.sql.exprs import conditional as cond
from spark_rapids_tpu.sql.exprs import datetimeexprs as dt
from spark_rapids_tpu.sql.exprs import mathexprs as m
from spark_rapids_tpu.sql.exprs import predicates as pred
from spark_rapids_tpu.sql.exprs import stringexprs as st
from spark_rapids_tpu.sql.exprs.cast import Cast
from spark_rapids_tpu.sql.exprs.core import Alias, Col, Expression, Literal

ColumnOrName = Union["Column", str]


class Column:
    """Thin user-facing wrapper over an Expression with operator overloads."""

    def __init__(self, expr: Expression):
        self.expr = expr

    # arithmetic
    def __add__(self, other): return Column(ar.Add(self.expr, _expr(other)))
    def __radd__(self, other): return Column(ar.Add(_expr(other), self.expr))
    def __sub__(self, other): return Column(ar.Subtract(self.expr, _expr(other)))
    def __rsub__(self, other): return Column(ar.Subtract(_expr(other), self.expr))
    def __mul__(self, other): return Column(ar.Multiply(self.expr, _expr(other)))
    def __rmul__(self, other): return Column(ar.Multiply(_expr(other), self.expr))
    def __truediv__(self, other): return Column(ar.Divide(self.expr, _expr(other)))
    def __rtruediv__(self, other): return Column(ar.Divide(_expr(other), self.expr))
    def __mod__(self, other): return Column(ar.Remainder(self.expr, _expr(other)))
    def __neg__(self): return Column(ar.UnaryMinus(self.expr))

    # comparisons
    def __eq__(self, other): return Column(pred.Eq(self.expr, _expr(other)))  # type: ignore[override]
    def __ne__(self, other): return Column(pred.Neq(self.expr, _expr(other)))  # type: ignore[override]
    def __lt__(self, other): return Column(pred.Lt(self.expr, _expr(other)))
    def __le__(self, other): return Column(pred.Le(self.expr, _expr(other)))
    def __gt__(self, other): return Column(pred.Gt(self.expr, _expr(other)))
    def __ge__(self, other): return Column(pred.Ge(self.expr, _expr(other)))
    def eqNullSafe(self, other): return Column(pred.EqNullSafe(self.expr, _expr(other)))

    # boolean
    def __and__(self, other): return Column(pred.And(self.expr, _expr(other)))
    def __or__(self, other): return Column(pred.Or(self.expr, _expr(other)))
    def __invert__(self): return Column(pred.Not(self.expr))

    # misc
    def alias(self, name: str): return Column(Alias(self.expr, name))
    def cast(self, to): return Column(Cast(self.expr, _dtype(to)))
    def bitwiseAND(self, other):
        from spark_rapids_tpu.sql.exprs import bitwise as bw
        return Column(bw.BitwiseAnd(self.expr, _expr(other)))
    def bitwiseOR(self, other):
        from spark_rapids_tpu.sql.exprs import bitwise as bw
        return Column(bw.BitwiseOr(self.expr, _expr(other)))
    def bitwiseXOR(self, other):
        from spark_rapids_tpu.sql.exprs import bitwise as bw
        return Column(bw.BitwiseXor(self.expr, _expr(other)))
    def isNull(self): return Column(pred.IsNull(self.expr))
    def isNotNull(self): return Column(pred.IsNotNull(self.expr))
    def isin(self, *values):
        vals = values[0] if len(values) == 1 and isinstance(values[0], (list, tuple)) else values
        return Column(pred.In(self.expr, list(vals)))
    def startswith(self, p: str): return Column(st.StartsWith(self.expr, p))
    def endswith(self, p: str): return Column(st.EndsWith(self.expr, p))
    def contains(self, p: str): return Column(st.Contains(self.expr, p))
    def like(self, p: str): return Column(st.Like(self.expr, p))
    def substr(self, pos: int, length: int = -1):
        return Column(st.Substring(self.expr, pos, length))

    def asc(self): return SortOrder(self.expr, ascending=True)
    def desc(self): return SortOrder(self.expr, ascending=False)

    def over(self, spec) -> "Column":
        """Turn an aggregate/ranking function into a window expression
        (reference: GpuWindowExpression)."""
        from spark_rapids_tpu.sql.window import WindowExpression
        return Column(WindowExpression(self.expr, spec))

    def __hash__(self):
        return id(self.expr)

    def __repr__(self):
        return f"Column<{self.expr!r}>"


class SortOrder:
    """Sort key with direction and null ordering (Spark defaults: asc ->
    nulls first, desc -> nulls last)."""

    def __init__(self, expr: Expression, ascending: bool = True,
                 nulls_first: bool = None):
        self.expr = expr
        self.ascending = ascending
        self.nulls_first = ascending if nulls_first is None else nulls_first

    def __repr__(self):
        d = "ASC" if self.ascending else "DESC"
        n = "NULLS FIRST" if self.nulls_first else "NULLS LAST"
        return f"{self.expr!r} {d} {n}"


def _expr(x: Any) -> Expression:
    if isinstance(x, Column):
        return x.expr
    if isinstance(x, Expression):
        return x
    return Literal(x)


def _dtype(t):
    if isinstance(t, str):
        aliases = {"long": "int64", "bigint": "int64", "int": "int32",
                   "integer": "int32", "short": "int16", "byte": "int8",
                   "double": "float64", "float": "float32",
                   "boolean": "bool", "date": "date32",
                   "timestamp": "timestamp_us"}
        return dtypes.by_name(aliases.get(t, t))
    return t


# --- constructors ----------------------------------------------------------

def col(name: str) -> Column:
    return Column(Col(name))


def lit(value: Any) -> Column:
    return Column(Literal(value))


def expr_col(e: Expression) -> Column:
    return Column(e)


# --- scalar functions ------------------------------------------------------

def abs(c: ColumnOrName) -> Column: return Column(ar.Abs(_c(c)))  # noqa: A001
def sqrt(c): return Column(m.Sqrt(_c(c)))
def exp(c): return Column(m.Exp(_c(c)))
def log(c): return Column(m.Log(_c(c)))
def log2(c): return Column(m.Log2(_c(c)))
def log10(c): return Column(m.Log10(_c(c)))
def sin(c): return Column(m.Sin(_c(c)))
def cos(c): return Column(m.Cos(_c(c)))
def tan(c): return Column(m.Tan(_c(c)))
def asin(c): return Column(m.Asin(_c(c)))
def acos(c): return Column(m.Acos(_c(c)))
def atan(c): return Column(m.Atan(_c(c)))
def tanh(c): return Column(m.Tanh(_c(c)))
def floor(c): return Column(m.Floor(_c(c)))
def ceil(c): return Column(m.Ceil(_c(c)))
def signum(c): return Column(m.Signum(_c(c)))
def pow(b, e): return Column(m.Pow(_c(b), _expr(e)))  # noqa: A001
def atan2(y, x): return Column(m.Atan2(_c(y), _expr(x)))
def pmod(a, b): return Column(ar.Pmod(_c(a), _expr(b)))

def shiftleft(c, n):
    from spark_rapids_tpu.sql.exprs import bitwise as bw
    return Column(bw.ShiftLeft(_c(c), _expr(n)))
def shiftright(c, n):
    from spark_rapids_tpu.sql.exprs import bitwise as bw
    return Column(bw.ShiftRight(_c(c), _expr(n)))
def shiftrightunsigned(c, n):
    from spark_rapids_tpu.sql.exprs import bitwise as bw
    return Column(bw.ShiftRightUnsigned(_c(c), _expr(n)))
def bitwise_not(c):
    from spark_rapids_tpu.sql.exprs import bitwise as bw
    return Column(bw.BitwiseNot(_c(c)))
bitwiseNOT = bitwise_not

def isnan(c): return Column(pred.IsNan(_c(c)))
def isnull(c): return Column(pred.IsNull(_c(c)))
def coalesce(*cs): return Column(cond.Coalesce([_c(c) for c in cs]))
def nanvl(a, b): return Column(cond.NaNvl(_c(a), _c(b)))

def when(condition: Column, value) -> "WhenBuilder":
    return WhenBuilder([(condition.expr, _expr(value))])


class WhenBuilder(Column):
    def __init__(self, branches):
        self._branches = branches
        super().__init__(cond.CaseWhen(branches))

    def when(self, condition: Column, value) -> "WhenBuilder":
        return WhenBuilder(self._branches + [(condition.expr, _expr(value))])

    def otherwise(self, value) -> Column:
        return Column(cond.CaseWhen(self._branches, _expr(value)))


def length(c): return Column(st.StringLength(_c(c)))
def upper(c): return Column(st.Upper(_c(c)))
def lower(c): return Column(st.Lower(_c(c)))
def substring(c, pos: int, length_: int): return Column(st.Substring(_c(c), pos, length_))
def concat(*cs): return Column(st.ConcatStrings([_c(c) for c in cs]))

def year(c): return Column(dt.Year(_c(c)))
def month(c): return Column(dt.Month(_c(c)))
def dayofmonth(c): return Column(dt.DayOfMonth(_c(c)))
def dayofweek(c): return Column(dt.DayOfWeek(_c(c)))
def hour(c): return Column(dt.Hour(_c(c)))
def minute(c): return Column(dt.Minute(_c(c)))
def second(c): return Column(dt.Second(_c(c)))
def unix_timestamp(c, fmt: str = None):
    if fmt is None:
        return Column(dt.UnixTimestampFromTs(_c(c)))
    return Column(dt.UnixTimestampFromString(_c(c), fmt))
def date_add(c, days): return Column(dt.DateAdd(_c(c), _expr(days)))


# --- aggregate functions ---------------------------------------------------

def sum(c) -> Column: return Column(agg.Sum(_c(c)))  # noqa: A001
def count(c) -> Column:
    if isinstance(c, str) and c == "*":
        return Column(agg.Count(Literal(1)))
    return Column(agg.Count(_c(c)))
def min(c) -> Column: return Column(agg.Min(_c(c)))  # noqa: A001
def max(c) -> Column: return Column(agg.Max(_c(c)))  # noqa: A001
def avg(c) -> Column: return Column(agg.Average(_c(c)))
mean = avg
def first(c, ignorenulls: bool = False) -> Column:
    return Column(agg.First(_c(c), ignorenulls))
def last(c, ignorenulls: bool = False) -> Column:
    return Column(agg.Last(_c(c), ignorenulls))
def count_distinct(c) -> Column: return Column(agg.CountDistinct(_c(c)))
countDistinct = count_distinct
def var_samp(c) -> Column: return Column(agg.VarSamp(_c(c)))
def var_pop(c) -> Column: return Column(agg.VarPop(_c(c)))
variance = var_samp
def stddev_samp(c) -> Column: return Column(agg.StddevSamp(_c(c)))
def stddev_pop(c) -> Column: return Column(agg.StddevPop(_c(c)))
stddev = stddev_samp
def corr(a, b) -> Column: return Column(agg.Corr(_c(a), _c(b)))


def row_number() -> Column:
    from spark_rapids_tpu.sql.window import RowNumber
    return Column(RowNumber())


def rank() -> Column:
    from spark_rapids_tpu.sql.window import Rank
    return Column(Rank())


def dense_rank() -> Column:
    from spark_rapids_tpu.sql.window import DenseRank
    return Column(DenseRank())


def lead(c, offset: int = 1, default=None) -> Column:
    from spark_rapids_tpu.sql.window import LeadLag
    return Column(LeadLag(_c(c), offset, default, is_lead=True))


def lag(c, offset: int = 1, default=None) -> Column:
    from spark_rapids_tpu.sql.window import LeadLag
    return Column(LeadLag(_c(c), offset, default, is_lead=False))


def _c(x: ColumnOrName) -> Expression:
    if isinstance(x, str):
        return Col(x)
    return _expr(x)


# --- null handling / extremum ----------------------------------------------

def greatest(*cs) -> Column:
    from spark_rapids_tpu.sql.exprs import nullexprs as ne
    return Column(ne.Greatest([_c(c) for c in cs]))
def least(*cs) -> Column:
    from spark_rapids_tpu.sql.exprs import nullexprs as ne
    return Column(ne.Least([_c(c) for c in cs]))
def nvl(a, b) -> Column: return coalesce(a, b)
ifnull = nvl
def nvl2(a, b, c) -> Column:
    return when(Column(_c(a)).isNotNull(), Column(_c(b))) \
        .otherwise(Column(_c(c)))


# --- math tail --------------------------------------------------------------

def round(c, scale: int = 0) -> Column:  # noqa: A001
    return Column(m.Round(_c(c), scale))
def hypot(a, b) -> Column: return Column(m.Hypot(_c(a), _c(b)))
def cbrt(c) -> Column: return Column(m.Cbrt(_c(c)))
def expm1(c) -> Column: return Column(m.Expm1(_c(c)))
def log1p(c) -> Column: return Column(m.Log1p(_c(c)))
def rint(c) -> Column: return Column(m.Rint(_c(c)))
def sinh(c) -> Column: return Column(m.Sinh(_c(c)))
def cosh(c) -> Column: return Column(m.Cosh(_c(c)))
def degrees(c) -> Column: return Column(m.ToDegrees(_c(c)))
def radians(c) -> Column: return Column(m.ToRadians(_c(c)))


# --- string tail ------------------------------------------------------------

def trim(c) -> Column: return Column(st.Trim(_c(c)))
def ltrim(c) -> Column: return Column(st.LTrim(_c(c)))
def rtrim(c) -> Column: return Column(st.RTrim(_c(c)))
def lpad(c, n: int, pad: str = " ") -> Column:
    return Column(st.LPad(_c(c), n, pad))
def rpad(c, n: int, pad: str = " ") -> Column:
    return Column(st.RPad(_c(c), n, pad))
def locate(substr: str, c, pos: int = 1) -> Column:
    return Column(st.StringLocate(_c(c), substr, pos))
def instr(c, substr: str) -> Column:
    return Column(st.StringLocate(_c(c), substr, 1))
def regexp_replace(c, pattern: str, replacement: str) -> Column:
    return Column(st.make_regexp_replace(_c(c), pattern, replacement))
def replace(c, search: str, replacement: str) -> Column:
    return Column(st.StringReplace(_c(c), search, replacement))
def initcap(c) -> Column: return Column(st.InitCap(_c(c)))


# --- datetime tail ----------------------------------------------------------

def quarter(c) -> Column: return Column(dt.Quarter(_c(c)))
def dayofyear(c) -> Column: return Column(dt.DayOfYear(_c(c)))
def weekofyear(c) -> Column: return Column(dt.WeekOfYear(_c(c)))
def last_day(c) -> Column: return Column(dt.LastDay(_c(c)))
def date_sub(c, days) -> Column: return Column(dt.DateSub(_c(c), _expr(days)))
def datediff(end, start) -> Column:
    return Column(dt.DateDiff(_c(end), _c(start)))
def to_date(c) -> Column: return Column(dt.ToDate(_c(c)))
def from_unixtime(c) -> Column: return Column(dt.FromUnixTime(_c(c)))


# --- nondeterministic --------------------------------------------------------

def hash(*cs) -> Column:  # noqa: A001
    from spark_rapids_tpu.sql.exprs.miscexprs import Hash
    return Column(Hash([_c(c) for c in cs]))


def hex(c) -> Column:  # noqa: A001
    from spark_rapids_tpu.sql.exprs.miscexprs import Hex
    return Column(Hex(_c(c)))


def rand(seed: int = 0) -> Column:
    from spark_rapids_tpu.sql.exprs import nondet
    return Column(nondet.Rand(seed))
def spark_partition_id() -> Column:
    from spark_rapids_tpu.sql.exprs import nondet
    return Column(nondet.SparkPartitionID())
def monotonically_increasing_id() -> Column:
    from spark_rapids_tpu.sql.exprs import nondet
    return Column(nondet.MonotonicallyIncreasingID())
def input_file_name() -> Column:
    from spark_rapids_tpu.sql.exprs import nondet
    return Column(nondet.InputFileName())


# --- generators --------------------------------------------------------------

def split(c, delim: str) -> Column:
    """split(str, pattern): like Spark, metacharacter patterns are regexes
    (host-evaluated; tagged off the device); plain literals split fused on
    device via explode()."""
    from spark_rapids_tpu.sql.exprs.generators import SplitStr
    if not delim:
        raise ValueError("split() requires a non-empty delimiter")
    return Column(SplitStr(_c(c), delim))


def explode(c: Column) -> Column:
    from spark_rapids_tpu.sql.exprs.generators import ExplodeSplit
    return Column(ExplodeSplit(_expr(c), with_pos=False))


def posexplode(c: Column) -> Column:
    from spark_rapids_tpu.sql.exprs.generators import ExplodeSplit
    return Column(ExplodeSplit(_expr(c), with_pos=True))


# --- round-2 expression breadth (VERDICT r1 item 8) -------------------------

def concat_ws(sep: str, *cs) -> Column:
    return Column(st.ConcatWs(sep, [_c(c) for c in cs]))
def translate(c, matching: str, replace: str) -> Column:
    return Column(st.Translate(_c(c), matching, replace))
def reverse(c) -> Column: return Column(st.StringReverse(_c(c)))
def repeat(c, n: int) -> Column: return Column(st.StringRepeat(_c(c), n))
def ascii(c) -> Column: return Column(st.Ascii(_c(c)))  # noqa: A001
def chr_(c) -> Column: return Column(st.Chr(_c(c)))
char = chr_
def left(c, n: int) -> Column:
    return Column(st.Substring(_c(c), 1, int(n)))
def right(c, n: int) -> Column:
    return Column(st.Substring(_c(c), -int(n), int(n)))
def bround(c, scale: int = 0) -> Column:
    return Column(m.BRound(_c(c), scale))
def add_months(c, n) -> Column:
    return Column(dt.AddMonths(_c(c), _expr(n)))
def months_between(end, start) -> Column:
    return Column(dt.MonthsBetween(_c(end), _c(start)))
def trunc(c, fmt: str) -> Column:
    return Column(dt.TruncDate(_c(c), fmt))
def next_day(c, day: str) -> Column:
    return Column(dt.NextDay(_c(c), day))
