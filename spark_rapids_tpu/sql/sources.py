"""Data sources: in-memory tables and files.

File sources follow the reference's scan split: footer/metadata work and
pruning on the host, columnar decode batched (GpuParquetScan.scala pattern);
pyarrow performs the host decode, the HostToDevice transition uploads.
"""

from __future__ import annotations

import itertools
import math
from typing import List, Optional, Tuple

import numpy as np
import pandas as pd

from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.exec.base import ExecContext, Partition


_DATA_UID_COUNTER = itertools.count(1)

# uploads at or under this size get a CONTENT-derived uid: queries built
# inside a function frequently re-create small lookup frames (a name
# mapping, a 12-row month sequence) on every call, and a fresh
# counter-uid per upload changes every downstream plan fingerprint —
# capacity speculation and subtree reuse then miss on every run, each
# miss costing a full device->host sync round trip (~0.1-0.25s tunneled)
_CONTENT_UID_MAX_BYTES = 1 << 16


def _content_uid(df: pd.DataFrame, num_partitions: int):
    """Deterministic digest of a small frame's data+schema+partitioning,
    or None when the frame is too big to hash cheaply or unhashable."""
    import hashlib
    try:
        if int(df.memory_usage(deep=True).sum()) > _CONTENT_UID_MAX_BYTES:
            return None
        h = hashlib.blake2b(digest_size=8)
        h.update(("|".join(f"{c}:{t}" for c, t in
                           zip(map(str, df.columns), map(str, df.dtypes)))
                  + f"|p{num_partitions}|n{len(df)}").encode())
        h.update(pd.util.hash_pandas_object(df, index=False)
                 .to_numpy().tobytes())
        return "c" + h.hexdigest()
    except (TypeError, ValueError):
        return None


class DataSource:
    schema: Schema

    def data_uid(self) -> str:
        """Stable identity of the *data* behind this source for the
        session's adaptive caches: two scans of the same source object
        (or projection views of it, ``with_columns``) share a uid; a new
        upload gets a fresh one (a process-unique counter, never an
        ``id()`` that the allocator could reuse). Small in-memory frames
        use a content digest so re-created identical lookup tables keep
        plan fingerprints stable across executions; stale-stats risk is
        nil because every adaptive consumer verifies on device."""
        base = getattr(self, "_base", self)
        uid = getattr(base, "_data_uid", None)
        if uid is None:
            if isinstance(base, InMemorySource):
                uid = _content_uid(base.df, base.num_partitions)
            if uid is None:
                uid = next(_DATA_UID_COUNTER)
            base._data_uid = uid
        return f"{type(base).__name__}#{uid}"

    def describe(self) -> str:
        return type(self).__name__

    def cpu_partitions(self, ctx: ExecContext) -> List[Partition]:
        raise NotImplementedError

    def estimated_size_bytes(self) -> Optional[int]:
        """Size hint for broadcast-join planning (None = unknown)."""
        return None


class InMemorySource(DataSource):
    """createDataFrame equivalent: a pandas DataFrame split into partitions."""

    def __init__(self, df: pd.DataFrame, num_partitions: int = 1):
        self.df = df
        self.num_partitions = max(1, num_partitions)
        self.schema = Schema.from_pandas(df)

    def describe(self) -> str:
        return f"InMemory[{len(self.df)} rows x {len(self.df.columns)} cols]"

    def with_columns(self, columns: List[str]) -> "InMemorySource":
        """Projection-pushdown view: scan only the referenced columns.
        Cheap (pandas column view, no copy) and it keeps every later
        device kernel — filters especially — at the query's true width."""
        keep = [c for c in self.df.columns if c in columns]
        src = InMemorySource.__new__(InMemorySource)
        src.df = self.df[keep]
        src.num_partitions = self.num_partitions
        src.schema = Schema(
            keep, [self.schema.dtypes[self.schema.index_of(c)]
                   for c in keep])
        src._base = getattr(self, "_base", self)
        return src

    def estimated_size_bytes(self) -> Optional[int]:
        # deep=True so object/string columns count their payload, not just
        # the 8-byte pointers — a shallow count broadcasts huge tables
        return int(self.df.memory_usage(deep=True).sum())

    def cpu_partitions(self, ctx: ExecContext) -> List[Partition]:
        n = len(self.df)
        per = math.ceil(n / self.num_partitions) if n else 0
        if per == 0:
            def empty():
                yield self.df.iloc[0:0]

            def nothing():
                return iter(())
            return [empty] + [nothing] * (self.num_partitions - 1)

        def slice_task(i: int):
            def decode():
                return self.df.iloc[i * per:(i + 1) * per] \
                    .reset_index(drop=True)
            return decode
        from spark_rapids_tpu.sql.scan_pipeline import build_partitions
        return build_partitions(
            ctx, [(None, slice_task(i)) for i in range(self.num_partitions)])


def _expand_paths(paths: List[str], suffix: str):
    """Resolve directories to their data files, hive-style: a directory
    scan recurses and ``key=value`` path segments under the root become
    per-file partition values (the reference appends them as scalar
    columns per partition, ColumnarPartitionReaderWithPartitionValues)."""
    import os
    out = []  # (file_path, {partition_key: value})
    for p in paths:
        if not os.path.isdir(p):
            out.append((p, {}))
            continue
        for root, _dirs, files in sorted(os.walk(p)):
            rel = os.path.relpath(root, p)
            pvals = {}
            if rel != ".":
                for seg in rel.split(os.sep):
                    if "=" in seg:
                        k, v = seg.split("=", 1)
                        pvals[k] = v
            for f in sorted(files):
                if f.endswith(suffix) and not f.startswith(("_", ".")):
                    out.append((os.path.join(root, f), dict(pvals)))
    return out


_HIVE_NULL = "__HIVE_DEFAULT_PARTITION__"


def _infer_partition_value(text: str):
    if text == _HIVE_NULL:  # the writer's NULL sentinel round-trips to NULL
        return None
    try:
        return int(text)
    except ValueError:
        try:
            return float(text)
        except ValueError:
            return text


def _partition_key_dtype(values):
    """Common dtype over every directory value of one key (dtype module
    constant). Mixed or unparseable -> STRING."""
    from spark_rapids_tpu.columnar import dtype as dtmod
    kinds = {type(_infer_partition_value(v)) for v in values
             if _infer_partition_value(v) is not None}
    if kinds == {int}:
        return dtmod.INT64
    if kinds <= {int, float} and kinds:
        return dtmod.FLOAT64
    return dtmod.STRING


class ParquetSource(DataSource):
    """Parquet scan: row-group pruned, one partition per row-group chunk
    (reference: GpuParquetScan.scala:204-373 does footer parse + row-group
    clipping on the CPU before device decode). Directory inputs resolve
    hive-partitioned layouts (``key=value`` dirs)."""

    def __init__(self, paths: List[str], columns: Optional[List[str]] = None):
        import pyarrow.parquet as pq
        paths = [paths] if isinstance(paths, str) else list(paths)
        self._files = _expand_paths(paths, ".parquet")
        if not self._files:
            raise FileNotFoundError(f"no parquet files under {paths}")
        self.paths = [f for f, _ in self._files]
        self._pq = pq
        # footer parses ride the shared (path, mtime)-keyed metadata
        # cache (sql/parquet_raw.py) — split planning, _rg_stats and the
        # deviceDecode page reader all reuse ONE parse per file instead
        # of re-opening ParquetFile per consumer
        from spark_rapids_tpu.sql import parquet_raw as praw
        arrow_schema = praw.file_metadata(
            self.paths[0]).schema.to_arrow_schema()
        names, dts = [], []
        from spark_rapids_tpu.columnar import dtypes as dtmod
        for field in arrow_schema:
            if columns and field.name not in columns:
                continue
            names.append(field.name)
            dts.append(dtmod.from_arrow(field.type))
        self.columns = list(names)  # data columns only (pkeys append below)
        # partition-value columns appended after data columns, typed by
        # inference over EVERY directory value (mixed kinds -> string)
        self._pkeys = sorted({k for _, pv in self._files for k in pv})
        self._pkey_dtypes = {}
        for k in self._pkeys:
            dt = _partition_key_dtype([pv[k] for _, pv in self._files
                                       if k in pv])
            self._pkey_dtypes[k] = dt
            names.append(k)
            dts.append(dt)
        self.schema = Schema(names, dts)
        # partition plan: (path, row_group_index, partition_values)
        self.splits = []
        for p, pvals in self._files:
            for rg in range(praw.file_metadata(p).num_row_groups):
                self.splits.append((p, rg, pvals))

    def describe(self) -> str:
        return f"Parquet[{len(self.paths)} files, {len(self.splits)} row groups]"

    def estimated_size_bytes(self) -> Optional[int]:
        import os
        return sum(os.path.getsize(p) for p in self.paths)

    def with_columns(self, columns: List[str]) -> "ParquetSource":
        """Cheap projection view (no footer re-parse): read only
        ``columns`` (data columns clipped; partition keys kept if named)."""
        import copy
        src = copy.copy(self)
        src._base = getattr(self, "_base", self)
        src.columns = [c for c in self.columns if c in columns]
        src._pkeys = [k for k in self._pkeys if k in columns]
        names = list(src.columns) + list(src._pkeys)
        idx = {n: i for i, n in enumerate(self.schema.names)}
        src.schema = Schema(names,
                            [self.schema.dtypes[idx[n]] for n in names])
        return src

    # row-group-stats cache bound: footers are tiny, but a long session
    # scanning many files would otherwise grow the dict forever
    _RG_STATS_CACHE_CAP = 4096

    def _rg_stats(self, path: str, rg: int):
        """{col: (min, max, null_count, num_values)} from the footer.
        Keyed by (path, mtime, rg): a rewritten file's stale stats must
        not keep pruning row groups of its replacement. Insertion-ordered
        dict, oldest-half eviction past the cap."""
        from spark_rapids_tpu.sql import parquet_raw as praw
        base = getattr(self, "_base", self)
        cache = base.__dict__.setdefault("_stats_cache", {})
        mtime = praw.file_mtime(path)
        if (path, mtime, rg) not in cache:
            if len(cache) >= self._RG_STATS_CACHE_CAP:
                for k in list(cache)[:len(cache)
                                     - self._RG_STATS_CACHE_CAP // 2]:
                    del cache[k]
            # footer via the shared (path, mtime) metadata cache — no
            # ParquetFile re-open per split
            md = praw.file_metadata(path, mtime).row_group(rg)
            stats = {}
            for ci in range(md.num_columns):
                col = md.column(ci)
                s = col.statistics
                if s is None:
                    stats[col.path_in_schema] = (None, None, None, None)
                else:
                    stats[col.path_in_schema] = (
                        s.min if s.has_min_max else None,
                        s.max if s.has_min_max else None,
                        s.null_count, s.num_values)
            cache[(path, mtime, rg)] = stats
        return cache[(path, mtime, rg)]

    def prune_splits(self, filters) -> Tuple[list, int]:
        """(surviving splits, pruned count): row-group statistics +
        partition-value pruning for the pushed conjuncts
        (ParquetFilters, GpuParquetScan.scala:204-246)."""
        from spark_rapids_tpu.sql.pushdown import (
            maybe_matches, partition_value_matches,
        )
        keep = []
        for (p, rg, pvals) in self.splits:
            ok = True
            for name, op, value in filters:
                if name in self._pkeys:
                    pv = (_infer_partition_value(pvals[name])
                          if name in pvals else None)
                    if not partition_value_matches(pv, op, value):
                        ok = False
                        break
                    continue
                if name not in self.columns:
                    continue
                mn, mx, nulls, nvals = self._rg_stats(p, rg).get(
                    name, (None, None, None, None))
                if not maybe_matches(mn, mx, nulls, nvals, op, value):
                    ok = False
                    break
            if ok:
                keep.append((p, rg, pvals))
        return keep, len(self.splits) - len(keep)

    def cpu_partitions(self, ctx: ExecContext,
                       filters=None) -> List[Partition]:
        pq = self._pq
        splits = self.splits
        if filters:
            splits, pruned = self.prune_splits(filters)
            if ctx.metrics_enabled:
                ctx.metric_add(self.describe(), "numRowGroupsPruned",
                               pruned)

        from spark_rapids_tpu.sql.scan_pipeline import (
            build_partitions, pipeline_config,
        )
        # prefetchDepth=0 selects the LEGACY reader end to end (the
        # reference's PERFILE mode keeps its own code path the same way):
        # synchronous decode through the full arrow->pandas conversion,
        # no hints — the safe rollback path reproduces pre-pipeline
        # behavior exactly, not just its thread count
        pipelined = pipeline_config(ctx.conf)[0] > 0
        direct = pipelined and ctx.conf.get_bool(
            "spark.rapids.sql.scan.directDecode", True)

        def decode_task(path: str, rg: int, pvals):
            def decode():
                f = pq.ParquetFile(path)
                table = f.read_row_group(rg, columns=self.columns)
                df = _arrow_decode(table, direct)
                for k in self._pkeys:
                    v = (_infer_partition_value(pvals[k])
                         if k in pvals else None)
                    dt = self._pkey_dtypes[k]
                    if v is not None and not dt.is_string:
                        v = dt.np_dtype.type(v)
                    elif v is not None:
                        v = str(v)
                    df[k] = pd.Series([v] * len(df),
                                      dtype=dt.pandas_nullable
                                      if not dt.is_string else object)
                return _attach_dict_hints(df) if pipelined else df
            return decode
        if not splits:
            def empty():
                yield _empty_from_schema(self.schema)
            return [empty]
        return build_partitions(
            ctx, [(p, decode_task(p, rg, pv)) for p, rg, pv in splits])

    def raw_partitions(self, ctx: ExecContext,
                       filters=None) -> List[Partition]:
        """deviceDecode split plan (spark.rapids.sql.scan.deviceDecode):
        decode workers produce RawRowGroup decode plans (raw page bytes +
        run tables, ops/parquet_decode.py) instead of pandas frames; the
        consumer decodes them ON DEVICE. Rides the same prefetch
        machinery as cpu_partitions — bounded queue, backpressure,
        prefetchDepth=0 serial rollback. Row groups where NO column can
        ride the device path degrade to the classic pandas frame."""
        splits = self.splits
        if filters:
            splits, pruned = self.prune_splits(filters)
            if ctx.metrics_enabled:
                ctx.metric_add(self.describe(), "numRowGroupsPruned",
                               pruned)
        from spark_rapids_tpu.exec.transitions import upload_blocked_chars
        from spark_rapids_tpu.sql.scan_pipeline import (
            build_partitions, pipeline_config,
        )
        pipelined = pipeline_config(ctx.conf)[0] > 0
        direct = pipelined and ctx.conf.get_bool(
            "spark.rapids.sql.scan.directDecode", True)
        blocked = upload_blocked_chars(ctx)
        page_cache = getattr(ctx.session, "page_cache", None) \
            if ctx.session else None
        columns = list(self.columns)
        dtypes_by_name = dict(zip(self.schema.names, self.schema.dtypes))
        pkeys, pkey_dtypes = list(self._pkeys), dict(self._pkey_dtypes)

        def decode_task(path: str, rg: int, pvals):
            def decode():
                from spark_rapids_tpu.ops.parquet_decode import (
                    prepare_rowgroup,
                )
                raw = prepare_rowgroup(path, rg, pvals, columns,
                                       dtypes_by_name, blocked,
                                       page_cache=page_cache,
                                       direct=direct)
                if getattr(raw, "is_raw_rowgroup", False):
                    return raw
                # whole-split host fallback: finish exactly like the
                # classic decode_task (partition-value columns appended)
                df = raw
                if df is None:
                    f = self._pq.ParquetFile(path)
                    table = f.read_row_group(rg, columns=columns)
                    df = _arrow_decode(table, direct)
                for k in pkeys:
                    v = (_infer_partition_value(pvals[k])
                         if k in pvals else None)
                    dt = pkey_dtypes[k]
                    if v is not None and not dt.is_string:
                        v = dt.np_dtype.type(v)
                    elif v is not None:
                        v = str(v)
                    df[k] = pd.Series([v] * len(df),
                                      dtype=dt.pandas_nullable
                                      if not dt.is_string else object)
                return df
            return decode
        if not splits:
            def empty():
                yield _empty_from_schema(self.schema)
            return [empty]
        return build_partitions(
            ctx, [(p, decode_task(p, rg, pv)) for p, rg, pv in splits])


class CsvSource(DataSource):
    """CSV scan via pyarrow.csv host parse (reference: Table.readCSV from
    GpuBatchScanExec.scala:477, with host-side line splitting)."""

    def __init__(self, paths, schema: Optional[Schema] = None,
                 header: bool = True):
        import pyarrow.csv as pacsv
        paths = [paths] if isinstance(paths, str) else list(paths)
        self.paths = [f for f, _ in _expand_paths(paths, ".csv")] or paths
        self.header = header
        self._pacsv = pacsv
        if schema is not None:
            self.schema = schema
        else:
            t = pacsv.read_csv(self.paths[0])
            from spark_rapids_tpu.columnar import dtypes as dtmod
            names = [f.name for f in t.schema]
            dts = [dtmod.from_arrow(f.type) for f in t.schema]
            self.schema = Schema(names, dts)

    def describe(self) -> str:
        return f"CSV[{len(self.paths)} files]"

    def cpu_partitions(self, ctx: ExecContext) -> List[Partition]:
        pacsv = self._pacsv
        from spark_rapids_tpu.sql.scan_pipeline import (
            build_partitions, pipeline_config,
        )
        pipelined = pipeline_config(ctx.conf)[0] > 0
        direct = pipelined and ctx.conf.get_bool(
            "spark.rapids.sql.scan.directDecode", True)

        def decode_task(path: str):
            def decode():
                t = pacsv.read_csv(path)
                df = _arrow_decode(t, direct)
                df.columns = list(self.schema.names)
                return _attach_dict_hints(df) if pipelined else df
            return decode
        return build_partitions(
            ctx, [(p, decode_task(p)) for p in self.paths])


class OrcSource(DataSource):
    """ORC scan: stripe-partitioned host decode via pyarrow.orc (reference:
    GpuOrcScan.scala:711 decodes via Table.readORC after host-side stripe
    clipping; OrcFilters SARG pushdown is host-side there too)."""

    def __init__(self, paths, columns: Optional[List[str]] = None):
        import pyarrow.orc as paorc
        paths = [paths] if isinstance(paths, str) else list(paths)
        self.paths = [f for f, _ in _expand_paths(paths, ".orc")] or paths
        self._paorc = paorc
        f = paorc.ORCFile(self.paths[0])
        from spark_rapids_tpu.columnar import dtypes as dtmod
        names, dts = [], []
        for field in f.schema:
            if columns and field.name not in columns:
                continue
            names.append(field.name)
            dts.append(dtmod.from_arrow(field.type))
        self.columns = names
        self.schema = Schema(names, dts)
        # partition plan: (path, stripe index)
        self.splits = []
        for p in self.paths:
            fh = paorc.ORCFile(p)
            for s in range(fh.nstripes):
                self.splits.append((p, s))

    def describe(self) -> str:
        return f"ORC[{len(self.paths)} files, {len(self.splits)} stripes]"

    def estimated_size_bytes(self) -> Optional[int]:
        import os
        return sum(os.path.getsize(p) for p in self.paths)

    def with_columns(self, columns: List[str]) -> "OrcSource":
        import copy
        src = copy.copy(self)
        src._base = getattr(self, "_base", self)
        src.columns = [c for c in self.columns if c in columns]
        idx = {n: i for i, n in enumerate(self.schema.names)}
        src.schema = Schema(list(src.columns),
                            [self.schema.dtypes[idx[n]]
                             for n in src.columns])
        return src

    def _stripe_index(self, col: str):
        """{(path, stripe): (min, max, null_count, num_values)} for one
        column, built lazily by reading just that column per stripe once —
        pyarrow's ORC reader exposes no footer stripe statistics (the
        reference reads them natively, sql/rapids/OrcFilters.scala), so
        this one-time index plays their role across queries."""
        base = getattr(self, "_base", self)
        cache = base.__dict__.setdefault("_stripe_stats", {})
        if col not in cache:
            import pyarrow.compute as pc
            idx = {}
            for p in self.paths:
                fh = self._paorc.ORCFile(p)
                for s in range(fh.nstripes):
                    t = fh.read_stripe(s, columns=[col])
                    arr = t.column(0) if hasattr(t, "column") else t[0]
                    n = len(arr)
                    nulls = arr.null_count
                    if n - nulls > 0:
                        mn = pc.min(arr).as_py()
                        mx = pc.max(arr).as_py()
                    else:
                        mn = mx = None
                    idx[(p, s)] = (mn, mx, nulls, n - nulls)
            cache[col] = idx
        return cache[col]

    def prune_splits(self, filters) -> Tuple[list, int]:
        from spark_rapids_tpu.sql.pushdown import maybe_matches
        keep = []
        for (p, s) in self.splits:
            ok = True
            for name, op, value in filters:
                if name not in self.columns:
                    continue
                mn, mx, nulls, nvals = self._stripe_index(name).get(
                    (p, s), (None, None, None, None))
                if not maybe_matches(mn, mx, nulls, nvals, op, value):
                    ok = False
                    break
            if ok:
                keep.append((p, s))
        return keep, len(self.splits) - len(keep)

    def cpu_partitions(self, ctx: ExecContext,
                       filters=None) -> List[Partition]:
        paorc = self._paorc
        splits = self.splits
        if filters:
            splits, pruned = self.prune_splits(filters)
            if ctx.metrics_enabled:
                ctx.metric_add(self.describe(), "numStripesPruned", pruned)

        from spark_rapids_tpu.sql.scan_pipeline import (
            build_partitions, pipeline_config,
        )
        pipelined = pipeline_config(ctx.conf)[0] > 0
        direct = pipelined and ctx.conf.get_bool(
            "spark.rapids.sql.scan.directDecode", True)

        def decode_task(path: str, stripe: int):
            def decode():
                f = paorc.ORCFile(path)
                table = f.read_stripe(stripe, columns=self.columns)
                import pyarrow as pa
                if isinstance(table, pa.RecordBatch):
                    table = pa.Table.from_batches([table])
                df = _arrow_decode(table, direct)
                return _attach_dict_hints(df) if pipelined else df
            return decode
        if not splits:
            def empty():
                yield _empty_from_schema(self.schema)
            return [empty]
        return build_partitions(
            ctx, [(p, decode_task(p, s)) for p, s in splits])


def _arrow_to_pandas(table) -> pd.DataFrame:
    df = table.to_pandas(types_mapper=_types_mapper)
    return df


def _attach_dict_hints(df: pd.DataFrame) -> pd.DataFrame:
    """Precompute per-column dictionary factorizations ON THE DECODE
    WORKER (the scan pipeline runs this inside the split's decode task)
    and attach them as ``df.attrs["srt_dict_fact"]`` keyed by column
    name. The host->device upload then pays only an O(cardinality) remap
    per dictionary column (columnar/column.py dict_factorize_hint) — the
    probe + factorize were the largest consumer-thread upload cost.

    Only object/string columns are hinted: file-scan uploads skip the
    numeric dictionary probe entirely (exec/transitions.py
    scan_dict_numerics), and string ``to_numpy(object)`` is exactly the
    value space ``_pandas_to_numpy`` hands the encoder; datetime and
    nullable-extension columns convert through fills and unit casts, so
    they would need a value-space translation the hint cannot do."""
    from spark_rapids_tpu.columnar.column import dict_factorize_hint
    hints = {}
    for i in range(df.shape[1]):
        s = df.iloc[:, i]
        if (isinstance(s.dtype, np.dtype) and s.dtype.kind == "O") \
                or str(s.dtype) in ("str", "string"):
            h = dict_factorize_hint(s.to_numpy(dtype=object),
                                    is_string=True)
            if h is not None:
                hints[str(df.columns[i])] = h
    if hints:
        df.attrs["srt_dict_fact"] = hints
    return df


def _arrow_decode(table, direct: bool = True) -> pd.DataFrame:
    """arrow Table -> pandas for the scan hot path.

    ``direct``: non-nullable primitive (int/float/bool) columns convert
    arrow -> numpy -> Series directly (zero-copy where arrow allows),
    skipping the pandas nullable-extension materialization — on wide
    numeric scans that conversion is a large share of decode time.
    Columns with nulls, strings, dates/timestamps and dictionaries fall
    back to ``_arrow_to_pandas`` per column, so values (incl. null
    masks) are identical either way; only the no-null numeric dtype
    differs (plain numpy instead of the nullable extension, which every
    downstream consumer already handles — _pandas_to_numpy branches on
    exactly this)."""
    if not direct or table.num_rows == 0 or table.num_columns == 0:
        return _arrow_to_pandas(table)
    import pyarrow as pa
    series: List = []
    fallback_idx = []
    for i in range(table.num_columns):
        col = table.column(i)
        t = col.type
        if (col.null_count == 0
                and (pa.types.is_integer(t) or pa.types.is_floating(t)
                     or pa.types.is_boolean(t))):
            series.append(pd.Series(col.to_numpy(zero_copy_only=False),
                                    copy=False))
        else:
            series.append(None)
            fallback_idx.append(i)
    if fallback_idx:
        fb = _arrow_to_pandas(table.select(fallback_idx))
        for j, i in enumerate(fallback_idx):
            series[i] = fb.iloc[:, j].reset_index(drop=True)
    df = pd.concat(series, axis=1)
    df.columns = list(table.column_names)
    return df


def _types_mapper(pa_type):
    import pyarrow as pa
    # map nullable ints to pandas extension dtypes so nulls survive
    m = {pa.int8(): pd.Int8Dtype(), pa.int16(): pd.Int16Dtype(),
         pa.int32(): pd.Int32Dtype(), pa.int64(): pd.Int64Dtype(),
         pa.float32(): pd.Float32Dtype(), pa.float64(): pd.Float64Dtype(),
         pa.bool_(): pd.BooleanDtype()}
    return m.get(pa_type)


def _empty_from_schema(schema: Schema) -> pd.DataFrame:
    from spark_rapids_tpu.exec.cpu import _empty_df
    return _empty_df(schema)
