"""Raw Parquet page access for the device-resident decode path.

The device decode mode (spark.rapids.sql.scan.deviceDecode) needs what
pyarrow's table reader hides: the column-chunk BYTES and their page
structure. pyarrow's low-level metadata (FileMetaData / ColumnChunkMetaData)
exposes every offset and size we need, but NOT the per-page headers — those
are Thrift compact-protocol structs inline in the data stream, so this
module carries a minimal Thrift reader for exactly the three structs a flat
Parquet file uses (PageHeader, DataPageHeader, DictionaryPageHeader).

Everything here is host-side byte shuffling: read the chunk's byte range,
split pages, decompress payloads, and parse the *sequential* encodings'
headers (RLE/bit-packed run headers, DELTA_BINARY_PACKED block headers)
into small numpy run tables the device kernels can expand vectorized
(ops/parquet_decode.py). No value-level decode happens on the host.

Shared metadata cache: ``file_metadata`` keeps parsed footers keyed by
(path, mtime) so neither the raw-page reader nor ``ParquetSource._rg_stats``
re-opens (re-parses) a ``ParquetFile`` per split.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from spark_rapids_tpu.obs.metrics import REGISTRY

# parquet-format enums (format/PageType, format/Encoding)
PAGE_DATA = 0
PAGE_DICTIONARY = 2
PAGE_DATA_V2 = 3

ENC_PLAIN = 0
ENC_PLAIN_DICTIONARY = 2
ENC_RLE = 3
ENC_BIT_PACKED = 4
ENC_DELTA_BINARY_PACKED = 5
ENC_RLE_DICTIONARY = 8

ENCODING_NAMES = {
    0: "PLAIN", 2: "PLAIN_DICTIONARY", 3: "RLE", 4: "BIT_PACKED",
    5: "DELTA_BINARY_PACKED", 6: "DELTA_LENGTH_BYTE_ARRAY",
    7: "DELTA_BYTE_ARRAY", 8: "RLE_DICTIONARY", 9: "BYTE_STREAM_SPLIT",
}

# codecs the raw reader decompresses host-side via pyarrow.Codec. LZ4 is
# deliberately absent: parquet's legacy LZ4 framing is hadoop-specific and
# round-trips wrong through the plain codec.
_CODECS = {"UNCOMPRESSED", "SNAPPY", "GZIP", "ZSTD", "BROTLI"}

_FILE_READS = REGISTRY.counter("scan.device.fileReads")
_FILE_READ_BYTES = REGISTRY.counter("scan.device.fileReadBytes")


# ---------------------------------------------------------------------------
# Shared footer-metadata cache (satellite: _rg_stats + page index share it)
# ---------------------------------------------------------------------------

_META_CACHE: Dict[Tuple[str, Optional[float]], object] = {}
_META_LOCK = threading.Lock()
_META_CACHE_CAP = 512


def file_mtime(path: str) -> Optional[float]:
    try:
        return os.path.getmtime(path)
    except OSError:
        return None


def file_metadata(path: str, mtime: Optional[float] = None):
    """Parsed footer (pyarrow FileMetaData) for ``path``, cached by
    (path, mtime) with oldest-half eviction — one footer parse per file
    per modification, shared by row-group stats, split planning and the
    raw-page reader (which previously re-opened the file per split)."""
    import pyarrow.parquet as pq
    if mtime is None:
        mtime = file_mtime(path)
    key = (path, mtime)
    with _META_LOCK:
        md = _META_CACHE.get(key)
    if md is not None:
        return md
    md = pq.read_metadata(path)
    with _META_LOCK:
        if len(_META_CACHE) >= _META_CACHE_CAP:
            for k in list(_META_CACHE)[:_META_CACHE_CAP // 2]:
                del _META_CACHE[k]
        _META_CACHE[key] = md
    return md


# ---------------------------------------------------------------------------
# Thrift compact-protocol reader (just enough for page headers)
# ---------------------------------------------------------------------------

def _uvarint(buf: bytes, pos: int) -> Tuple[int, int]:
    out = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def _zigzag(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


_T_BOOL_TRUE, _T_BOOL_FALSE = 1, 2
_T_BYTE, _T_I16, _T_I32, _T_I64, _T_DOUBLE = 3, 4, 5, 6, 7
_T_BINARY, _T_LIST, _T_SET, _T_MAP, _T_STRUCT = 8, 9, 10, 11, 12


def _skip_value(buf: bytes, pos: int, ftype: int) -> int:
    if ftype in (_T_BOOL_TRUE, _T_BOOL_FALSE):
        return pos
    if ftype == _T_BYTE:
        return pos + 1
    if ftype in (_T_I16, _T_I32, _T_I64):
        _, pos = _uvarint(buf, pos)
        return pos
    if ftype == _T_DOUBLE:
        return pos + 8
    if ftype == _T_BINARY:
        n, pos = _uvarint(buf, pos)
        return pos + n
    if ftype in (_T_LIST, _T_SET):
        h = buf[pos]
        pos += 1
        n, etype = h >> 4, h & 0x0F
        if n == 15:
            n, pos = _uvarint(buf, pos)
        for _ in range(n):
            pos = _skip_value(buf, pos, etype)
        return pos
    if ftype == _T_MAP:
        n, pos = _uvarint(buf, pos)
        if n:
            kv = buf[pos]
            pos += 1
            for _ in range(n):
                pos = _skip_value(buf, pos, kv >> 4)
                pos = _skip_value(buf, pos, kv & 0x0F)
        return pos
    if ftype == _T_STRUCT:
        return _skip_struct(buf, pos)
    raise ValueError(f"unknown thrift compact type {ftype}")


def _skip_struct(buf: bytes, pos: int) -> int:
    fid = 0
    while True:
        b = buf[pos]
        pos += 1
        if b == 0:
            return pos
        delta, ftype = b >> 4, b & 0x0F
        if delta:
            fid += delta
        else:
            z, pos = _uvarint(buf, pos)
            fid = _zigzag(z)
        pos = _skip_value(buf, pos, ftype)


def _struct_fields(buf: bytes, pos: int):
    """Yield (field_id, type, value_pos) and finally ('end', end_pos).
    The caller consumes interesting fields; uninteresting ones must be
    skipped with _skip_value by the driver below."""
    fid = 0
    while True:
        b = buf[pos]
        pos += 1
        if b == 0:
            yield None, None, pos
            return
        delta, ftype = b >> 4, b & 0x0F
        if delta:
            fid += delta
        else:
            z, pos = _uvarint(buf, pos)
            fid = _zigzag(z)
        npos = yield fid, ftype, pos
        pos = npos if npos is not None else _skip_value(buf, pos, ftype)


@dataclass
class PageHeader:
    page_type: int = -1
    uncompressed_size: int = 0
    compressed_size: int = 0
    num_values: int = 0
    encoding: int = -1
    def_encoding: int = -1
    header_len: int = 0          # bytes consumed by the thrift struct


def _parse_inner_data_header(buf: bytes, pos: int, hdr: PageHeader) -> int:
    """DataPageHeader: 1 num_values, 2 encoding, 3 definition_level_
    encoding, 4 repetition_level_encoding, 5 statistics (skipped)."""
    fid = 0
    while True:
        b = buf[pos]
        pos += 1
        if b == 0:
            return pos
        delta, ftype = b >> 4, b & 0x0F
        if delta:
            fid += delta
        else:
            z, pos = _uvarint(buf, pos)
            fid = _zigzag(z)
        if fid in (1, 2, 3) and ftype in (_T_I16, _T_I32, _T_I64):
            z, pos = _uvarint(buf, pos)
            v = _zigzag(z)
            if fid == 1:
                hdr.num_values = v
            elif fid == 2:
                hdr.encoding = v
            else:
                hdr.def_encoding = v
        else:
            pos = _skip_value(buf, pos, ftype)


def _parse_inner_dict_header(buf: bytes, pos: int, hdr: PageHeader) -> int:
    """DictionaryPageHeader: 1 num_values, 2 encoding, 3 is_sorted."""
    fid = 0
    while True:
        b = buf[pos]
        pos += 1
        if b == 0:
            return pos
        delta, ftype = b >> 4, b & 0x0F
        if delta:
            fid += delta
        else:
            z, pos = _uvarint(buf, pos)
            fid = _zigzag(z)
        if fid in (1, 2) and ftype in (_T_I16, _T_I32, _T_I64):
            z, pos = _uvarint(buf, pos)
            v = _zigzag(z)
            if fid == 1:
                hdr.num_values = v
            else:
                hdr.encoding = v
        else:
            pos = _skip_value(buf, pos, ftype)


def parse_page_header(buf: bytes, pos: int) -> PageHeader:
    """PageHeader: 1 type, 2 uncompressed_page_size, 3 compressed_page_
    size, 4 crc, 5 data_page_header, 7 dictionary_page_header,
    8 data_page_header_v2 (left unparsed: v2 pages fall back)."""
    start = pos
    hdr = PageHeader()
    fid = 0
    while True:
        b = buf[pos]
        pos += 1
        if b == 0:
            break
        delta, ftype = b >> 4, b & 0x0F
        if delta:
            fid += delta
        else:
            z, pos = _uvarint(buf, pos)
            fid = _zigzag(z)
        if fid in (1, 2, 3) and ftype in (_T_I16, _T_I32, _T_I64):
            z, pos = _uvarint(buf, pos)
            v = _zigzag(z)
            if fid == 1:
                hdr.page_type = v
            elif fid == 2:
                hdr.uncompressed_size = v
            else:
                hdr.compressed_size = v
        elif fid == 5 and ftype == _T_STRUCT:
            pos = _parse_inner_data_header(buf, pos, hdr)
        elif fid == 7 and ftype == _T_STRUCT:
            pos = _parse_inner_dict_header(buf, pos, hdr)
        else:
            pos = _skip_value(buf, pos, ftype)
    hdr.header_len = pos - start
    return hdr


# ---------------------------------------------------------------------------
# Column-chunk page reader
# ---------------------------------------------------------------------------

@dataclass
class RawPage:
    num_values: int            # rows covered (incl. nulls)
    encoding: int
    payload: bytes             # decompressed page body


@dataclass
class RawColumnChunk:
    """One column chunk's pages, decompressed, plus the footer facts the
    decode planner needs. ``unsupported`` carries the first reason this
    chunk cannot ride the device path (None = fully parseable)."""
    name: str
    physical_type: str
    num_values: int
    max_def: int
    max_rep: int
    dict_page: Optional[RawPage] = None
    pages: List[RawPage] = field(default_factory=list)
    unsupported: Optional[str] = None
    nbytes: int = 0

    def encoded_bytes(self) -> int:
        total = sum(len(p.payload) for p in self.pages)
        if self.dict_page is not None:
            total += len(self.dict_page.payload)
        return total


def _decompress(data: bytes, codec: str, usize: int) -> bytes:
    if codec == "UNCOMPRESSED" or len(data) == usize:
        return data
    import pyarrow as pa
    return pa.Codec(codec.lower()).decompress(data, usize).to_pybytes()


def read_column_chunk(path: str, rg: int, ci: int,
                      md=None, mtime: Optional[float] = None,
                      raw: Optional[bytes] = None) -> RawColumnChunk:
    """Read + page-split one column chunk. ``raw`` lets a caller that
    already fetched the byte range (page cache) skip the file read."""
    if md is None:
        md = file_metadata(path, mtime)
    col = md.row_group(rg).column(ci)
    schema_col = md.schema.column(ci)
    chunk = RawColumnChunk(
        name=col.path_in_schema,
        physical_type=str(col.physical_type),
        num_values=int(col.num_values),
        max_def=int(schema_col.max_definition_level),
        max_rep=int(schema_col.max_repetition_level))
    codec = str(col.compression)
    if codec not in _CODECS:
        chunk.unsupported = f"codec:{codec}"
        return chunk
    if raw is None:
        start = int(col.data_page_offset)
        dict_off = col.dictionary_page_offset
        if dict_off is not None and 0 < int(dict_off) < start:
            start = int(dict_off)
        size = int(col.total_compressed_size)
        with open(path, "rb") as f:
            f.seek(start)
            raw = f.read(size)
        _FILE_READS.add(1)
        _FILE_READ_BYTES.add(len(raw))
    pos = 0
    seen = 0
    while seen < chunk.num_values and pos < len(raw):
        hdr = parse_page_header(raw, pos)
        pos += hdr.header_len
        body = raw[pos:pos + hdr.compressed_size]
        pos += hdr.compressed_size
        if hdr.page_type == PAGE_DICTIONARY:
            payload = _decompress(body, codec, hdr.uncompressed_size)
            chunk.dict_page = RawPage(hdr.num_values, hdr.encoding, payload)
            continue
        if hdr.page_type != PAGE_DATA:
            chunk.unsupported = ("pageV2" if hdr.page_type == PAGE_DATA_V2
                                 else f"pageType:{hdr.page_type}")
            return chunk
        if hdr.def_encoding not in (-1, ENC_RLE, ENC_BIT_PACKED) \
                and chunk.max_def > 0:
            chunk.unsupported = f"defEncoding:{hdr.def_encoding}"
            return chunk
        payload = _decompress(body, codec, hdr.uncompressed_size)
        chunk.pages.append(RawPage(hdr.num_values, hdr.encoding, payload))
        seen += hdr.num_values
    chunk.nbytes = chunk.encoded_bytes()
    return chunk


# ---------------------------------------------------------------------------
# Sequential-encoding header parsers -> numpy run tables
# ---------------------------------------------------------------------------

def hybrid_run_table(buf: bytes, bit_width: int, num_values: int,
                     base_bit: int = 0):
    """RLE/bit-packed hybrid stream -> run tables for vectorized device
    expansion. Host cost is O(#runs) (runs cover >= 8 values each in the
    bit-packed case and arbitrarily many in the RLE case), not O(values).

    Returns dict of numpy arrays:
      out_start (R+1,) int32 — cumulative output index of each run
      kind      (R,)  uint8  — 0 = RLE, 1 = bit-packed
      value     (R,)  int32  — the RLE run's value (0 for BP runs)
      bit_start (R,)  int64  — BP run's first bit, offset by ``base_bit``
                               (the stream's bit position in the upload
                               buffer; RLE runs carry 0)
      bw        (R,)  int32  — the run's bit width (per run, because a
                               multi-page chunk merges pages that may
                               carry different dictionary index widths)
    """
    kinds: List[int] = []
    values: List[int] = []
    bit_starts: List[int] = []
    counts: List[int] = []
    pos = 0
    out = 0
    byte_w = (bit_width + 7) // 8
    while out < num_values and pos < len(buf):
        header, pos = _uvarint(buf, pos)
        if header & 1:
            groups = header >> 1
            count = groups * 8
            kinds.append(1)
            values.append(0)
            bit_starts.append(base_bit + pos * 8)
            pos += groups * bit_width
        else:
            count = header >> 1
            v = int.from_bytes(buf[pos:pos + byte_w], "little")
            pos += byte_w
            kinds.append(0)
            values.append(v)
            bit_starts.append(0)
        if count <= 0:
            kinds.pop(); values.pop(); bit_starts.pop()
            continue
        counts.append(count)
        out += count
    out_start = np.zeros(len(counts) + 1, np.int32)
    np.cumsum(counts, out=out_start[1:])
    return {
        "out_start": out_start,
        "kind": np.asarray(kinds, np.uint8),
        "value": np.asarray(values, np.int32),
        "bit_start": np.asarray(bit_starts, np.int64),
        "bw": np.full(len(counts), bit_width, np.int32),
    }


def merge_run_tables(tables: List[dict]) -> dict:
    """Concatenate per-page hybrid run tables into one chunk-wide table
    (each page's bit_start values already carry its stream's base_bit)."""
    if len(tables) == 1:
        return tables[0]
    out_start = [np.zeros(1, np.int32)]
    base = 0
    for t in tables:
        out_start.append(t["out_start"][1:] + base)
        base += int(t["out_start"][-1])
    return {
        "out_start": np.concatenate(out_start),
        "kind": np.concatenate([t["kind"] for t in tables]),
        "value": np.concatenate([t["value"] for t in tables]),
        "bit_start": np.concatenate([t["bit_start"] for t in tables]),
        "bw": np.concatenate([t["bw"] for t in tables]),
    }


def delta_header_table(buf: bytes, base_bit: int = 0):
    """DELTA_BINARY_PACKED stream -> per-miniblock header table.

    Returns (first_value, values_per_miniblock, total_count, table) with
    table arrays (one row per miniblock that holds data):
      out_start (M+1,) int32 — cumulative DELTA index (value k's delta is
                               delta index k-1)
      bit_width (M,)  int32
      min_delta (M,)  int64  — the owning block's min delta
      bit_start (M,)  int64  — first bit of the miniblock's packed deltas
    Returns None when the stream uses a bit width > 32 (the u64 window
    extraction cannot span it — per-column fallback, reason deltaWide).
    """
    pos = 0
    block_size, pos = _uvarint(buf, pos)
    mpb, pos = _uvarint(buf, pos)
    total, pos = _uvarint(buf, pos)
    z, pos = _uvarint(buf, pos)
    first_value = _zigzag(z)
    vpm = block_size // max(mpb, 1)
    bws: List[int] = []
    mins: List[int] = []
    starts: List[int] = []
    counts: List[int] = []
    remaining = total - 1
    while remaining > 0 and pos < len(buf):
        z, pos = _uvarint(buf, pos)
        min_delta = _zigzag(z)
        widths = buf[pos:pos + mpb]
        pos += mpb
        for m in range(mpb):
            if remaining <= 0:
                break
            bw = widths[m]
            if bw > 32:
                return None
            bws.append(bw)
            mins.append(min_delta)
            starts.append(base_bit + pos * 8)
            counts.append(min(vpm, remaining))
            pos += bw * vpm // 8
            remaining -= vpm
    out_start = np.zeros(len(counts) + 1, np.int32)
    np.cumsum(counts, out=out_start[1:])
    return first_value, vpm, total, {
        "out_start": out_start,
        "bit_width": np.asarray(bws, np.int32),
        "min_delta": np.asarray(mins, np.int64),
        "bit_start": np.asarray(starts, np.int64),
    }


def plain_byte_array_starts(buf: bytes, num_values: int):
    """(starts, lens) int64/int32 arrays for a PLAIN byte-array stream
    ([u32 len][bytes]...), via vectorized numpy pointer doubling — the
    host never touches value bytes, only the length chain. O(B log n)
    vectorized passes over the page instead of an O(n) python loop."""
    b = np.frombuffer(buf, np.uint8)
    nb = len(b)
    if num_values <= 0:
        return np.zeros(0, np.int64), np.zeros(0, np.int32)
    # len32 at every byte position p (little-endian, 0 past the end)
    padded = np.zeros(nb + 4, np.uint32)
    padded[:nb] = b
    len_at = (padded[:nb] | (padded[1:nb + 1] << 8)
              | (padded[2:nb + 2] << 16) | (padded[3:nb + 3] << 24))
    nxt = np.minimum(np.arange(nb, dtype=np.int64) + 4
                     + len_at.astype(np.int64), nb)
    starts = np.empty(num_values, np.int64)
    starts[0] = 0
    filled = 1
    jump = nxt  # 2^k-step jump table, squared each round
    while filled < num_values:
        take = min(filled, num_values - filled)
        src = np.clip(starts[:take], 0, nb - 1)
        starts[filled:filled + take] = jump[src]
        filled += take
        if filled < num_values:
            jump = jump[np.clip(jump, 0, nb - 1)]
    starts = np.clip(starts, 0, max(nb - 1, 0))
    lens = len_at[starts].astype(np.int32)
    return starts + 4, lens


def parse_plain_byte_array(buf: bytes, count: int) -> List[bytes]:
    """Host parse of a (small) PLAIN byte-array stream — dictionary pages
    only; data pages ride the vectorized path above."""
    out: List[bytes] = []
    pos = 0
    for _ in range(count):
        n = int.from_bytes(buf[pos:pos + 4], "little")
        pos += 4
        out.append(buf[pos:pos + n])
        pos += n
    return out
