"""Asynchronous scan pipeline: bounded-depth split prefetch on a shared
decode thread pool.

The reference closes its scan gap with a multithreaded, coalescing Parquet
reader that overlaps host decode with device transfer (GpuParquetScan's
MULTITHREADED/COALESCING reader modes, GpuMultiFileReader.scala); the
analogue here overlaps the three serial stages of a file scan —

    host decode (pyarrow, GIL-released)  ->  host->device upload
                                         ->  device compute

— by decoding up to ``spark.rapids.sql.scan.prefetchDepth`` splits ahead of
the consuming task on a shared daemon pool, while the upload side
double-buffers (exec/transitions.py): batch i+1's ``device_put`` is
dispatched while batch i computes.

Contract (tests/test_scan_pipeline.py):

  * partition order is preserved exactly — split i's frames are yielded by
    partition i, in decode order;
  * the first decode exception propagates to the consumer of the failing
    split, and no further splits are submitted after a failure;
  * abandoning a partition generator early (CollectLimit, errors) cancels
    every not-yet-started decode and drops decoded-frame references, so the
    pipeline holds no buffers after GC;
  * ``prefetchDepth=0`` selects the LEGACY reader end to end (the
    reference keeps its PERFILE reader as a separate code path the same
    way): synchronous full arrow->pandas decode on the consuming thread
    in strict pull order, no hints, no direct decode — pre-pipeline
    behavior exactly (the safe rollback path).

Backpressure: decoded-but-unconsumed frames are host memory; submission
stalls once their estimated bytes exceed
``spark.rapids.sql.scan.prefetchMaxBytes`` (clamped to the host spill
budget) or while the device manager is over its HBM spill budget — prefetch
can never race the spill framework for memory it is trying to free. The
device side needs no extra gate: uploads happen on the consuming task
thread, which already holds a TpuSemaphore permit, and every uploaded batch
is metered against the HBM budget (memory/device.py meter_batch).
"""

from __future__ import annotations

import threading
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from typing import Callable, List, Optional, Tuple

from spark_rapids_tpu.obs.events import EVENTS
from spark_rapids_tpu.obs.metrics import REGISTRY
from spark_rapids_tpu.obs.progress import PROGRESS
from spark_rapids_tpu.obs.trace import TRACER

# one decode task per split: () -> pd.DataFrame
DecodeFn = Callable[[], "pd.DataFrame"]  # noqa: F821
# (input_file path or None for non-file sources, decode)
ScanTask = Tuple[Optional[str], DecodeFn]

_POOL: Optional[ThreadPoolExecutor] = None
_POOL_SIZE = 0
_POOL_LOCK = threading.Lock()

# observability handles, resolved once (the pipeline hot path is one
# future.result() per split; metrics must not add registry lookups)
_STALL_TIME = REGISTRY.timer("scan.prefetch.stallTime")
_DECODE_TIME = REGISTRY.timer("scan.prefetch.decodeTime")
_QUEUE_DEPTH = REGISTRY.gauge("scan.prefetch.queueDepth")
_QUEUE_PEAK = REGISTRY.gauge("scan.prefetch.queueDepthPeak")
_SPLITS = REGISTRY.counter("scan.prefetch.splits")
_CANCELLED = REGISTRY.counter("scan.prefetch.cancelled")
_BYTES = REGISTRY.counter("scan.prefetch.bytesDecoded")
_BUDGET_STALLS = REGISTRY.counter("scan.prefetch.budgetStalls")


def _nbytes(obj) -> int:
    """Host bytes a decoded split retains in the prefetch queue: pandas
    frames by column memory_usage, deviceDecode RawRowGroups (and
    anything else plan-shaped) by their ``nbytes``."""
    if obj is None:
        return 0
    mu = getattr(obj, "memory_usage", None)
    if mu is not None:
        return int(mu(deep=False).sum())
    return int(getattr(obj, "nbytes", 0) or 0)


def decode_pool(threads: int) -> ThreadPoolExecutor:
    """Shared daemon decode pool. One per process; rebuilt (old pool left
    to drain) if a session reconfigures the thread count."""
    global _POOL, _POOL_SIZE
    with _POOL_LOCK:
        if _POOL is None or _POOL_SIZE != threads:
            if _POOL is not None:
                # idle executor workers never exit on their own; release
                # the displaced pool's threads once in-flight decodes
                # drain (repeated reconfiguration must not leak threads)
                _POOL.shutdown(wait=False)
            _POOL = ThreadPoolExecutor(
                max_workers=threads, thread_name_prefix="srt-scan-decode")
            _POOL_SIZE = threads
        return _POOL


def _conf_int(conf, key: str, default: int) -> int:
    try:
        return int(conf.get(key, default))
    except (TypeError, ValueError):
        return default


def pipeline_config(conf):
    """(prefetch_depth, decode_threads, max_bytes) from a TpuConf."""
    import os
    depth = _conf_int(conf, "spark.rapids.sql.scan.prefetchDepth", 2)
    threads = _conf_int(conf, "spark.rapids.sql.scan.decodeThreads", 0)
    if threads <= 0:
        # the workers carry decode + the dictionary factorize hints, so
        # even a 2-core box wants 2 (the consuming thread's residual work
        # is upload memcpys and compute dispatch, largely GIL-released)
        threads = min(4, max(2, (os.cpu_count() or 2) - 1))
    max_bytes = _conf_int(conf, "spark.rapids.sql.scan.prefetchMaxBytes",
                          256 << 20)
    # decoded frames that overflow host memory would fight the spill
    # framework for the same RAM; clamp to the host spill budget
    spill = _conf_int(conf, "spark.rapids.memory.host.spillStorageSize",
                      1 << 30)
    return depth, threads, min(max_bytes, spill)


class ScanPrefetcher:
    """Bounded-depth, order-preserving prefetch over one scan's splits.

    ``get(i)`` submits splits ``i .. i+depth`` (so while the consumer
    drains split i, up to ``depth`` later splits decode concurrently),
    blocks on split i's future, and hands the frame over — the prefetcher
    drops its own reference so consumed frames are GC-eligible the moment
    the consumer releases them.
    """

    def __init__(self, tasks: List[ScanTask], depth: int,
                 pool: ThreadPoolExecutor, max_bytes: int):
        self._tasks = tasks
        self._depth = max(1, depth)
        self._pool = pool
        self._max_bytes = max(1, max_bytes)
        self._lock = threading.Lock()
        self._futures: dict = {}          # split index -> Future
        self._submitted: set = set()
        self._cancelled = False
        self._failed = False
        self._pending_bytes = 0           # decoded, not yet consumed
        self._inflight = 0
        self._skip: set = set()           # submitted splits never consumed
        # journal sampling state: the event log records rare facts, not
        # per-split streams — budget stalls emit on the entering
        # transition only, decode stalls emit the first _EVENT_CAP per
        # scan (exact aggregates live in the REGISTRY timers/counters)
        self._budget_stalled = False
        self._stall_events = 0

    _EVENT_CAP = 16

    # -- worker side --------------------------------------------------------
    def _decode(self, i: int):
        path, fn = self._tasks[i]
        try:
            with self._lock:
                if self._cancelled:
                    return None
            with _DECODE_TIME.time():
                with TRACER.span("scan.decode", split=i,
                                 file=path or "<memory>"):
                    df = fn()
            nbytes = _nbytes(df)
            with self._lock:
                if self._cancelled or i in self._skip:
                    # raced a cancel (or a skip of a never-consumed
                    # split) mid-decode: drop the frame so the abandoned
                    # work retains no buffers or budget
                    self._skip.discard(i)
                    return None
                self._pending_bytes += nbytes
            _BYTES.add(nbytes)
            if PROGRESS.enabled:  # live scan progress (/api/query/<id>)
                PROGRESS.scan_split(nbytes)
            return df
        finally:
            with self._lock:
                self._inflight -= 1
                _QUEUE_DEPTH.set(self._inflight)

    # -- consumer side ------------------------------------------------------
    def _over_budget_locked(self) -> bool:
        if self._pending_bytes >= self._max_bytes:
            return True
        # device spill pressure: while the HBM budget is exceeded the
        # spill handlers are freeing memory — do not pile more host
        # frames (whose uploads would immediately re-pressure it)
        from spark_rapids_tpu.memory.device import TpuDeviceManager
        dm = TpuDeviceManager.current()
        return dm is not None and dm.allocated > dm.hbm_budget

    def _submit_window_locked(self, i: int) -> None:
        if self._cancelled or self._failed:
            # the requested split itself must still decode
            hi = i
        else:
            hi = min(i + self._depth, len(self._tasks) - 1)
        for j in range(i, hi + 1):
            if j in self._submitted:
                continue
            if j > i and self._over_budget_locked():
                _BUDGET_STALLS.add(1)
                if not self._budget_stalled:
                    # backpressure fact, on the ENTERING transition only
                    # (sustained pressure re-trips per split): prefetch
                    # submission stopped here, the pipeline runs at
                    # consumer speed until the budget drains
                    self._budget_stalled = True
                    EVENTS.emit("scanBudgetStall", split=j)
                break
            self._submitted.add(j)
            self._inflight += 1
            _QUEUE_DEPTH.set(self._inflight)
            if self._inflight > int(_QUEUE_PEAK.value):
                _QUEUE_PEAK.set(self._inflight)
            self._futures[j] = self._pool.submit(self._decode, j)
        else:
            # full window submitted without hitting the budget: the next
            # budget trip is a NEW stall episode and journals again
            self._budget_stalled = False

    def get(self, i: int):
        """Decoded frame of split ``i`` (blocking). Re-raises the split's
        decode exception; marks the pipeline failed so no later splits are
        submitted after the first error."""
        with self._lock:
            # earlier splits submitted but never consumed (device-scan-
            # cache replay bypasses their partitions entirely): reclaim
            # their budget, or their frames would pin _pending_bytes for
            # the scan's lifetime and starve the window. A genuinely
            # out-of-order consumer just re-decodes inline (fut-is-None
            # path below) — correctness over overlap for that rare case.
            for j in [k for k in self._futures if k < i]:
                f = self._futures.pop(j)
                if f.cancel():
                    self._inflight -= 1
                    _QUEUE_DEPTH.set(self._inflight)
                    _CANCELLED.add(1)
                elif f.done():
                    try:
                        dfj = f.result()
                    except BaseException:
                        dfj = None
                    if dfj is not None:
                        self._pending_bytes -= _nbytes(dfj)
                else:
                    # running: drop its result on finish. The done
                    # callback reclaims the budget if the decode raced
                    # past its own skip check before the marker landed.
                    self._skip.add(j)
                    f.add_done_callback(
                        lambda fr, j=j: self._reclaim_skipped(j, fr))
            self._submit_window_locked(i)
            fut = self._futures.pop(i, None)
        _SPLITS.add(1)
        if fut is None:
            # split consumed before (a concurrently re-driven partition,
            # e.g. a racing device-scan-cache filler): decode inline —
            # correctness over overlap for the rare second consumer
            return self._tasks[i][1]()
        if not fut.done():
            import time
            t0 = time.perf_counter()
            if PROGRESS.enabled:  # live stall state, cleared below
                PROGRESS.scan_stalled(True)
            from spark_rapids_tpu.obs.syncledger import sync_scope
            with TRACER.span("scan.prefetch.stall", split=i), \
                    sync_scope("scan.stall", detail=f"split={i}"):
                wait([fut], return_when=FIRST_COMPLETED)
            if PROGRESS.enabled:
                PROGRESS.scan_stalled(False)
            stall_s = time.perf_counter() - t0
            _STALL_TIME.record(stall_s)
            with self._lock:
                self._stall_events += 1
                sample = self._stall_events <= self._EVENT_CAP
            if sample:
                # bounded sample per scan: a thousand-split scan must not
                # flood the journal/flight ring (scan.prefetch.stallTime
                # carries the exact aggregate)
                EVENTS.emit("scanStall", split=i,
                            stall_s=round(stall_s, 6))
        try:
            df = fut.result()
        except BaseException:
            with self._lock:
                self._failed = True
            raise
        if df is not None:
            with self._lock:
                self._pending_bytes -= _nbytes(df)
        return df

    def _reclaim_skipped(self, j: int, fr) -> None:
        """Done-callback for a skipped-while-running decode: if _decode
        raced past its skip check (frame returned, bytes accounted),
        reclaim the budget here — otherwise the orphaned bytes would pin
        _pending_bytes for the scan's lifetime."""
        try:
            df = fr.result()
        except BaseException:  # noqa: BLE001 — skipped split, error moot
            df = None
        with self._lock:
            if self._cancelled or j not in self._skip:
                return  # _decode saw the marker (or cancel reset budget)
            self._skip.discard(j)
            if df is not None:
                self._pending_bytes -= _nbytes(df)

    def cancel(self) -> None:
        """Early consumer exit: cancel unstarted decodes, drop every
        retained frame reference. Running decodes finish (pyarrow reads
        are not interruptible) but their results are discarded."""
        with self._lock:
            self._cancelled = True
            futures = list(self._futures.values())
            self._futures.clear()
            self._pending_bytes = 0
        n = sum(1 for f in futures if f.cancel())
        if n:
            _CANCELLED.add(n)
            with self._lock:
                # cancelled-before-start futures never run _decode's
                # accounting; settle the in-flight gauge for them here
                self._inflight -= n
                _QUEUE_DEPTH.set(self._inflight)

    def drain(self, timeout: float = 30.0) -> bool:
        """Wait for in-flight decodes to finish (tests; bounded)."""
        with self._lock:
            futures = list(self._futures.values())
        done, not_done = wait(futures, timeout=timeout)
        return not not_done


def build_partitions(ctx, tasks: List[ScanTask]) -> List["Partition"]:  # noqa: F821
    """Partition list over one scan's splits, honoring
    ``spark.rapids.sql.scan.prefetchDepth``.

    Each partition publishes its split's input file to the task context
    around the yield (try/finally — the file context must not leak across
    tasks when the consumer abandons the generator or decode raises) and,
    with a positive depth, pulls its frame from a shared ScanPrefetcher.
    """
    from spark_rapids_tpu.exec import taskctx

    depth, threads, max_bytes = pipeline_config(ctx.conf)

    if depth <= 0:
        # serial rollback path: decode on the consuming thread at pull
        # time, nothing shared, no pool — the pre-pipeline behavior
        def make_serial(path: Optional[str], fn: DecodeFn) -> "Partition":  # noqa: F821
            def run():
                if path is not None:
                    taskctx.set_input_file(path)
                try:
                    yield fn()
                finally:
                    if path is not None:
                        taskctx.clear_input_file()
            return run
        return [make_serial(p, fn) for p, fn in tasks]

    prefetcher = ScanPrefetcher(tasks, depth, decode_pool(threads),
                                max_bytes)

    def make(i: int, path: Optional[str]) -> "Partition":  # noqa: F821
        def run():
            df = prefetcher.get(i)
            if df is None:  # cancelled scan re-consumed: decode inline
                df = tasks[i][1]()
            if path is not None:
                taskctx.set_input_file(path)
            try:
                yield df
            except BaseException:
                # abandoned mid-yield (GeneratorExit) or a downstream
                # error thrown into the generator: stop feeding the pool
                prefetcher.cancel()
                raise
            finally:
                if path is not None:
                    taskctx.clear_input_file()
        return run
    return [make(i, p) for i, (p, _fn) in enumerate(tasks)]
