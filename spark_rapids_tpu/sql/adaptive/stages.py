"""Query stages and stage readers.

A **ShuffleStage** is one materialized hash exchange: the map side ran to
completion, its output sits partitioned on the host (per map task, per
reduce partition) and its ``MapOutputStatistics`` drove the re-planning
rules. The not-yet-executed remainder of the plan references the stage
through ``ShuffleStageRef`` placeholders until read planning replaces
them with ``AqeShuffleReadExec`` leaves carrying partition *specs*:

  * ``CoalescedSpec(pids)``          — reduce partitions merged into one
    task (Spark's CoalescedPartitionSpec);
  * ``PartialSpec(pid, lo, hi)``     — one reduce partition restricted to
    the map range [lo, hi) — a skew-split sub-partition (Spark's
    PartialReducerPartitionSpec).

``AqeShuffleReadExec`` is a CPU leaf (host frames); the rewrite engine
converts it to ``TpuAqeShuffleReadExec``, which re-uploads each spec's
merged frame through the shared ``upload_partition`` runner — the stage
boundary is a real host materialization point, the engine's analogue of
the reference registering map output in the shuffle catalog.
"""

from __future__ import annotations

import itertools
import threading
from typing import Iterator, List, Optional, Sequence

import pandas as pd

from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.exec.base import ExecContext, Partition, PhysicalPlan
from spark_rapids_tpu.sql.adaptive.stats import MapOutputStatistics


class CoalescedSpec:
    """Read these reduce partitions, fully, merged as one task."""

    __slots__ = ("pids",)

    def __init__(self, pids: Sequence[int]):
        self.pids = tuple(pids)

    def __repr__(self) -> str:
        return f"coalesce{list(self.pids)}"


class PartialSpec:
    """Read ONE reduce partition's output from map tasks [lo, hi) — a
    skew-split sub-partition; the join's other side replicates the full
    partition against every sub-range."""

    __slots__ = ("pid", "map_lo", "map_hi")

    def __init__(self, pid: int, map_lo: int, map_hi: int):
        self.pid = pid
        self.map_lo = map_lo
        self.map_hi = map_hi

    def __repr__(self) -> str:
        return f"skew(p{self.pid}, maps[{self.map_lo}:{self.map_hi}])"


class ShuffleStage:
    """One materialized shuffle stage's output + statistics.

    Reference-counted for cross-query exchange reuse
    (serving/caches.ExchangeReuseCache): the creating query holds the
    initial reference, the cache and every adopting query take one each
    (``retain``), and ``release`` frees the host frames only when the
    last reference drops — eviction mid-adoption can never free frames
    a running query still reads."""

    _uids = itertools.count(1)

    def __init__(self, stage_id: int, schema: Schema,
                 partitioning, map_outputs: List[List[pd.DataFrame]],
                 stats: MapOutputStatistics):
        self.id = stage_id
        self.schema = schema
        self.partitioning = partitioning
        self.map_outputs = map_outputs
        self.stats = stats
        # process-unique identity (ids recycle; uids never do) + the
        # cross-query reuse key the serving cache filed this stage under
        # (None = not offered / reuse disabled)
        self.uid = next(ShuffleStage._uids)
        self.reuse_key = None
        self._refs = 1
        self._ref_lock = threading.Lock()

    @property
    def n_partitions(self) -> int:
        return self.partitioning[-1]

    @property
    def num_maps(self) -> int:
        return len(self.map_outputs)

    @property
    def total_bytes(self) -> int:
        return self.stats.total_bytes

    def frames_for(self, spec) -> List[pd.DataFrame]:
        if self.map_outputs is None:
            raise RuntimeError(
                f"stage {self.id} already released (stage outputs free "
                "at query end)")
        out: List[pd.DataFrame] = []
        if isinstance(spec, PartialSpec):
            for m in range(spec.map_lo, spec.map_hi):
                f = self.map_outputs[m][spec.pid]
                if len(f):
                    out.append(f)
            return out
        for m in range(len(self.map_outputs)):
            for pid in spec.pids:
                f = self.map_outputs[m][pid]
                if len(f):
                    out.append(f)
        return out

    def retain(self) -> None:
        """Take one reference (cross-query reuse: the cache and every
        adopting query hold one)."""
        with self._ref_lock:
            self._refs += 1

    def release(self) -> None:
        """Drop one reference; the materialized host frames free when
        the LAST reference drops (the executed plan object outlives the
        query in session.last_plan; only the statistics are needed
        post-hoc). The pre-serving single-owner behavior is unchanged:
        one creation reference, one release, frames freed."""
        with self._ref_lock:
            self._refs -= 1
            if self._refs > 0:
                return
            self.map_outputs = None


class ShuffleStageRef(PhysicalPlan):
    """Plan placeholder for a materialized stage, replaced by an
    ``AqeShuffleReadExec`` once its consumer's partition specs are
    decided. Never executes."""

    def __init__(self, stage: ShuffleStage):
        super().__init__()
        self.stage = stage

    def output_schema(self) -> Schema:
        return self.stage.schema

    def describe(self) -> str:
        return f"ShuffleStageRef(#{self.stage.id})"

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        raise RuntimeError(
            "ShuffleStageRef executed before read planning — the adaptive "
            "executor must finalize reads first (sql/adaptive/executor.py)")


class AqeShuffleReadExec(PhysicalPlan):
    """Leaf reader over a materialized stage: one output partition per
    spec (Spark's AQEShuffleReadExec over a ShuffleQueryStage)."""

    def __init__(self, stage: ShuffleStage, specs: List):
        super().__init__()
        self.stage = stage
        self.specs = list(specs)

    def output_schema(self) -> Schema:
        return self.stage.schema

    def describe(self) -> str:
        merged = sum(1 for s in self.specs
                     if isinstance(s, CoalescedSpec) and len(s.pids) > 1)
        skews = sum(1 for s in self.specs if isinstance(s, PartialSpec))
        return (f"AqeShuffleReadExec(stage=#{self.stage.id}, "
                f"parts={len(self.specs)}, coalesced={merged}, "
                f"skewSplits={skews})")

    def fingerprint_extra(self) -> str:
        return f"stage{self.stage.id}|{self.specs!r}"

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        from spark_rapids_tpu.exec.cpu import concat_host_frames
        schema = self.stage.schema

        def make(spec) -> Partition:
            def run() -> Iterator[pd.DataFrame]:
                yield concat_host_frames(self.stage.frames_for(spec),
                                         schema)
            return run
        return [make(s) for s in self.specs]


class TpuAqeShuffleReadExec(PhysicalPlan):
    """Columnar stage reader: each spec's merged host frame re-uploads
    through the shared upload runner (exec/transitions.upload_partition,
    the path TpuScanExec and HostToDeviceExec ride)."""

    columnar_output = True

    def __init__(self, read: AqeShuffleReadExec):
        super().__init__()
        self.read = read

    def output_schema(self) -> Schema:
        return self.read.output_schema()

    def describe(self) -> str:
        return "Tpu" + self.read.describe()

    def fingerprint_extra(self) -> str:
        return self.read.fingerprint_extra()

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        from spark_rapids_tpu.columnar.batch import DeviceBatch
        from spark_rapids_tpu.exec.transitions import upload_partition
        schema = self.output_schema()
        max_rows = ctx.conf.batch_size_rows
        cpu_parts = self.read.partitions(ctx)
        # one dictionary registry per reader (the TpuScanExec pattern):
        # every spec's upload encodes against the first batch's
        # dictionaries so downstream kernels compile one program
        dict_state: dict = {}

        def make(i: int, part: Partition) -> Partition:
            def run() -> Iterator[DeviceBatch]:
                got = False
                for b in upload_partition(ctx, part, schema, max_rows,
                                          dict_state, None, i,
                                          is_scan=False):
                    got = True
                    yield b
                if not got:
                    # consumers (joins, aggregates) expect >= 1 batch per
                    # partition, like the legacy exchange's empty yield
                    yield DeviceBatch.empty(schema)
            return run
        return [make(i, p) for i, p in enumerate(cpu_parts)]


def _register_read_rule() -> None:
    from spark_rapids_tpu.sql import overrides as ov

    def _tag(meta) -> None:
        pass

    def _convert(meta, children):
        return TpuAqeShuffleReadExec(meta.plan)

    ov._register(ov.ExecRule(
        AqeShuffleReadExec,
        "adaptive shuffle read (materialized query-stage output)",
        _tag, _convert))


_register_read_rule()
