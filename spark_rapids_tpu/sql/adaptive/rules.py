"""Runtime re-optimization rules over materialized stage statistics.

Three rules, the reference's AQE triad:

  * **partition coalescing** (Spark CoalesceShufflePartitions): merge
    adjacent reduce partitions while the group's measured size stays
    under ``spark.rapids.sql.adaptive.coalesce.minPartitionSize``. Join
    inputs plan jointly over COMBINED sizes so both sides stay
    co-partitioned.
  * **dynamic broadcast conversion** (Spark DynamicJoinSelection /
    DemoteBroadcastHashJoin inverse): a shuffled join whose build side's
    *measured* total lands under the broadcast threshold becomes a
    broadcast hash join, reusing the materialized map output and eliding
    a not-yet-run stream-side shuffle.
  * **skew-join splitting** (Spark OptimizeSkewedJoin): a reduce
    partition beyond ``skewedPartitionFactor x median`` (and the absolute
    threshold) splits into map-range sub-partitions on the skewed side,
    the other side replicated per sub-range.

All pure planning — the executor applies the outputs; every function
returns decision records for the event journal.
"""

from __future__ import annotations

import statistics
from typing import Dict, List, Optional, Sequence, Set, Tuple

from spark_rapids_tpu.sql.adaptive.stages import (
    CoalescedSpec, PartialSpec, ShuffleStage,
)

# which join side may be split without changing results: splitting side S
# and replicating side O is valid iff no output row needs to see all of S
# at once — any join type where S is the probe/preserved side
SPLITTABLE_LEFT = ("inner", "left", "leftsemi", "leftanti")
SPLITTABLE_RIGHT = ("inner", "right")


def coalesce_groups(sizes: Sequence[int], min_size: int,
                    isolated: Set[int] = frozenset()) -> List[List[int]]:
    """Greedy adjacent grouping: accumulate partitions until the group's
    combined size reaches ``min_size`` (Spark's algorithm). Partitions in
    ``isolated`` (skew candidates) always stand alone."""
    groups: List[List[int]] = []
    cur: List[int] = []
    cur_sz = 0
    for p, sz in enumerate(sizes):
        if p in isolated:
            if cur:
                groups.append(cur)
                cur, cur_sz = [], 0
            groups.append([p])
            continue
        cur.append(p)
        cur_sz += sz
        if cur_sz >= min_size:
            groups.append(cur)
            cur, cur_sz = [], 0
    if cur:
        groups.append(cur)
    return groups


def split_map_ranges(map_sizes: Sequence[int],
                     target: int) -> List[Tuple[int, int]]:
    """Greedy map-range chunks of ~target bytes (Spark's
    ShufflePartitionsUtil.splitSizeListByTargetSize shape)."""
    ranges: List[Tuple[int, int]] = []
    lo, acc = 0, 0
    for m, sz in enumerate(map_sizes):
        acc += sz
        if acc >= target:
            ranges.append((lo, m + 1))
            lo, acc = m + 1, 0
    if lo < len(map_sizes):
        ranges.append((lo, len(map_sizes)))
    return ranges


def skewed_partitions(sizes: Sequence[int], factor: float,
                      threshold: int) -> Set[int]:
    """Partitions whose size exceeds factor x median AND the absolute
    threshold (both tests, like Spark's OptimizeSkewedJoin)."""
    if not sizes:
        return set()
    med = statistics.median(sizes)
    return {p for p, s in enumerate(sizes)
            if s > factor * med and s > threshold}


def solo_specs(stage: ShuffleStage, conf,
               decisions: Optional[List[dict]] = None) -> List[CoalescedSpec]:
    """Read plan for a single-stage consumer (final aggregate, sort,
    window): coalescing only — splitting an aggregation partition would
    separate rows of one key."""
    n = stage.n_partitions
    if not conf.adaptive_coalesce_enabled:
        return [CoalescedSpec((p,)) for p in range(n)]
    groups = coalesce_groups(stage.stats.bytes_by_partition,
                             conf.adaptive_coalesce_min_size)
    specs = [CoalescedSpec(tuple(g)) for g in groups]
    if decisions is not None and len(specs) < n:
        decisions.append({"rule": "coalesce", "stages": [stage.id],
                          "fromPartitions": n,
                          "toPartitions": len(specs)})
    return specs


def join_specs(left: ShuffleStage, right: ShuffleStage, join_type: str,
               conf, decisions: Optional[List[dict]] = None,
               ) -> Tuple[List, List]:
    """Joint read plan for a shuffled join's two materialized sides:
    aligned spec lists (equal length), jointly coalesced, skew-split
    where valid. Every reduce partition is covered exactly once per side
    (sub-split ranges partition the skewed side's maps)."""
    n = left.n_partitions
    assert right.n_partitions == n, (left.id, right.id)
    lsz = left.stats.bytes_by_partition
    rsz = right.stats.bytes_by_partition
    combined = [lsz[p] + rsz[p] for p in range(n)]

    # skew candidates per splittable side
    skew_side: Dict[int, str] = {}
    if conf.adaptive_skew_enabled:
        factor = conf.adaptive_skew_factor
        threshold = conf.adaptive_skew_threshold
        lskew = (skewed_partitions(lsz, factor, threshold)
                 if join_type in SPLITTABLE_LEFT and left.num_maps > 1
                 else set())
        rskew = (skewed_partitions(rsz, factor, threshold)
                 if join_type in SPLITTABLE_RIGHT and right.num_maps > 1
                 else set())
        for p in lskew | rskew:
            if p in lskew and p in rskew:
                skew_side[p] = "left" if lsz[p] >= rsz[p] else "right"
            else:
                skew_side[p] = "left" if p in lskew else "right"

    min_size = (conf.adaptive_coalesce_min_size
                if conf.adaptive_coalesce_enabled else 0)
    groups = coalesce_groups(combined, min_size,
                             isolated=set(skew_side)) \
        if min_size > 0 else \
        [[p] for p in range(n)]
    target = max(conf.adaptive_coalesce_min_size, 1)

    lspecs: List = []
    rspecs: List = []
    split_count = 0
    for g in groups:
        p = g[0]
        if len(g) == 1 and p in skew_side:
            side = skew_side[p]
            stage = left if side == "left" else right
            ranges = split_map_ranges(stage.stats.partition_map_sizes(p),
                                      target)
            if len(ranges) > 1:
                split_count += 1
                if decisions is not None:
                    decisions.append({
                        "rule": "skewSplit", "stage": stage.id,
                        "side": side, "partition": p,
                        "splits": len(ranges),
                        "bytes": int((lsz if side == "left" else rsz)[p]),
                    })
                for lo, hi in ranges:
                    if side == "left":
                        lspecs.append(PartialSpec(p, lo, hi))
                        rspecs.append(CoalescedSpec((p,)))
                    else:
                        lspecs.append(CoalescedSpec((p,)))
                        rspecs.append(PartialSpec(p, lo, hi))
                continue
        lspecs.append(CoalescedSpec(tuple(g)))
        rspecs.append(CoalescedSpec(tuple(g)))
    if decisions is not None and not split_count and len(lspecs) < n:
        decisions.append({"rule": "coalesce",
                          "stages": [left.id, right.id],
                          "fromPartitions": n,
                          "toPartitions": len(lspecs)})
    return lspecs, rspecs


def broadcast_sides(join_type: str) -> Tuple[bool, bool]:
    """(left allowed, right allowed) as the broadcast BUILD side: the
    build side must be the non-preserved side, so full outer never
    broadcasts (mirrors the static planner, sql/planner.py)."""
    if join_type == "inner":
        return True, True
    if join_type == "right":
        return True, False
    if join_type in ("left", "leftsemi", "leftanti"):
        return False, True
    return False, False
