"""Runtime shuffle statistics for adaptive execution.

``MapOutputStatistics`` is the host-side twin of what the reference's
GpuShuffleExchangeExec reports to Spark (MapStatus.partition_sizes folded
per reduce partition); ``split_frame`` is the canonical map-side
partitioner every AQE stage uses on BOTH engine paths, so the TPU-
converted and CPU-oracle executions of the same query land every row in
the same reduce partition (pandas' hash differs between plain-numpy and
masked extension dtypes — the canonicalization here removes that hazard
before hashing).
"""

from __future__ import annotations

import statistics
from typing import List, Optional, Sequence

import numpy as np
import pandas as pd


class MapOutputStatistics:
    """Observed sizes of one materialized shuffle stage: per-(map task,
    reduce partition) bytes, folded per reduce partition (the shape
    Spark's MapOutputStatistics carries; reference consumers:
    CoalesceShufflePartitions, OptimizeSkewedJoin)."""

    def __init__(self, bytes_by_map: List[List[int]],
                 rows_by_map: Optional[List[List[int]]] = None):
        self.bytes_by_map = [list(m) for m in bytes_by_map]
        self.rows_by_map = ([list(m) for m in rows_by_map]
                            if rows_by_map is not None else None)
        n = len(self.bytes_by_map[0]) if self.bytes_by_map else 0
        self.bytes_by_partition = [
            sum(m[p] for m in self.bytes_by_map) for p in range(n)]
        self.rows_by_partition = (
            [sum(m[p] for m in self.rows_by_map) for p in range(n)]
            if self.rows_by_map is not None else None)

    @property
    def num_maps(self) -> int:
        return len(self.bytes_by_map)

    @property
    def num_partitions(self) -> int:
        return len(self.bytes_by_partition)

    @property
    def total_bytes(self) -> int:
        return sum(self.bytes_by_partition)

    def max_bytes(self) -> int:
        return max(self.bytes_by_partition, default=0)

    def median_bytes(self) -> int:
        if not self.bytes_by_partition:
            return 0
        return int(statistics.median(self.bytes_by_partition))

    def partition_map_sizes(self, pid: int) -> List[int]:
        """One reduce partition's size per map task (the skew-split
        granularity: Spark splits skewed partitions by map ranges)."""
        return [m[pid] for m in self.bytes_by_map]


def estimate_frame_bytes(df: pd.DataFrame, sample: int = 1024) -> int:
    """Cheap byte estimate of a host partition frame: exact buffer sizes
    for fixed-width columns, sampled mean string length for object
    columns (deep memory_usage walks every python object — too slow on
    the exchange hot path at bench scale)."""
    n = len(df)
    if n == 0:
        return 0
    total = 0
    for i in range(df.shape[1]):
        s = df.iloc[:, i]
        arr = getattr(s, "array", None)
        if hasattr(arr, "_data"):          # masked extension: data + mask
            total += arr._data.nbytes + arr._mask.nbytes
            continue
        vals = s.to_numpy()
        if vals.dtype == object:
            take = vals if n <= sample else \
                vals[np.linspace(0, n - 1, sample).astype(np.int64)]
            lens = [len(v) if isinstance(v, str) else 8 for v in take]
            mean = (sum(lens) / len(lens)) if lens else 8.0
            total += int(n * (mean + 8))   # chars + offset word
        else:
            total += vals.nbytes
    return int(total)


def hash_partition_ids(df: pd.DataFrame, key_idx: Sequence[int],
                       n: int) -> np.ndarray:
    """Canonical reduce-partition id per row: key columns are reduced to
    (values, validity) via host_unary_values, canonicalized (-0.0 -> 0.0,
    one NaN bit pattern, NULL -> type zero) and hashed as PLAIN numpy
    columns. Nulls sharing a partition with genuine zeros is fine — the
    partitioner only owes co-location of equal keys, and SQL null keys
    never match anyway."""
    from spark_rapids_tpu.sql.exprs.hostutil import host_unary_values
    m = len(df)
    if not key_idx or m == 0:
        return np.zeros(m, dtype=np.int64)
    cols = []
    for i in key_idx:
        vals, validity, _dt = host_unary_values(df.iloc[:, i])
        if vals.dtype == object:
            canon = np.where(validity, vals, "")
        elif vals.dtype.kind == "f":
            v = vals.astype(np.float64)
            v = np.where(v == 0.0, 0.0, v)
            v = np.where(np.isnan(v), np.float64("nan"), v)
            canon = np.where(validity, v, 0.0)
        elif vals.dtype.kind == "M":
            canon = np.where(validity, vals.astype("datetime64[us]")
                             .astype(np.int64), 0)
        elif vals.dtype.kind == "b":
            canon = np.where(validity, vals.astype(np.int64), 0)
        else:
            canon = np.where(validity, vals.astype(np.int64), 0)
        cols.append(pd.Series(canon).reset_index(drop=True))
    frame = pd.concat(cols, axis=1)
    h = pd.util.hash_pandas_object(frame, index=False).to_numpy()
    return (h % np.uint64(n)).astype(np.int64)


def split_frame(df: pd.DataFrame, key_idx: Sequence[int],
                n: int) -> List[pd.DataFrame]:
    """One map task's output split into n reduce-partition frames."""
    pids = hash_partition_ids(df, key_idx, n)
    out = []
    for pid in range(n):
        sel = df[pids == pid]
        out.append(sel.reset_index(drop=True))
    return out


def stats_from_map_outputs(
        map_outputs: List[List[pd.DataFrame]]) -> MapOutputStatistics:
    """Fold per-(map, partition) frames into MapOutputStatistics."""
    bytes_by_map = [[estimate_frame_bytes(f) for f in pids]
                    for pids in map_outputs]
    rows_by_map = [[len(f) for f in pids] for pids in map_outputs]
    return MapOutputStatistics(bytes_by_map, rows_by_map)
