"""The adaptive execution driver.

Loop (Spark's AdaptiveSparkPlanExec.getFinalPhysicalPlan shape):

  1. find a *ready* stage boundary — a hash shuffle exchange whose
     subtree contains no other hash exchange — preferring build sides of
     joins so a small measured build can demote the join before the
     stream side's shuffle ever runs;
  2. finalize reads inside that subtree (earlier stages it consumes),
     convert it through the full rewrite engine (TpuOverrides +
     TransitionOverrides + fusions — the per-stage analogue of the
     reference's columnar rules applying per query stage), and call the
     converted exchange's ``materialize_stage``;
  3. replace the exchange with a ``ShuffleStageRef`` and re-optimize the
     remainder (dynamic broadcast conversion);
  4. repeat until no boundaries remain, then plan the remaining reads
     (joint coalescing + skew splits), convert the final stage and drain.

Capacity speculation (spark.rapids.sql.adaptiveCapacity.enabled) is
forced off for adaptive queries: AQE's stage materializations are
statistics barriers — the device->host syncs speculation exists to avoid
are inherent to measuring the shuffle — and a speculative re-execution
would invalidate the statistics its own re-planning consumed.
"""

from __future__ import annotations

import copy
from typing import List, Optional, Tuple

from spark_rapids_tpu.exec import cpu
from spark_rapids_tpu.exec.base import ExecContext, PhysicalPlan
from spark_rapids_tpu.sql.adaptive import rules
from spark_rapids_tpu.sql.adaptive.stages import (
    AqeShuffleReadExec, CoalescedSpec, ShuffleStage, ShuffleStageRef,
)


def _is_stage_boundary(node: PhysicalPlan) -> bool:
    return (isinstance(node, cpu.CpuShuffleExchangeExec)
            and node.partitioning[0] == "hash")


def has_adaptive_stages(plan: PhysicalPlan) -> bool:
    """Is there anything for AQE to do? (No hash exchange -> the legacy
    single-shot path is already optimal and byte-identical.)"""
    return any(_is_stage_boundary(n) for n in plan.walk())


def _replace_node(plan: PhysicalPlan, target: PhysicalPlan,
                  repl: PhysicalPlan) -> PhysicalPlan:
    if plan is target:
        return repl
    changed = False
    new_children = []
    for c in plan.children:
        nc = _replace_node(c, target, repl)
        changed = changed or nc is not c
        new_children.append(nc)
    if not changed:
        return plan
    out = copy.copy(plan)
    out.children = new_children
    return out


class AdaptiveExecutor:
    def __init__(self, session, conf, ctx: ExecContext):
        self.session = session
        self.conf = conf
        self.ctx = ctx
        self.stages: List[ShuffleStage] = []
        self.decisions: List[dict] = []
        self._stage_counter = 0
        # cross-query exchange reuse (serving/caches.py, opt-in): adopt
        # an already-materialized stage whose subtree digest matches
        # instead of recomputing it, and offer fresh stages back
        self._exchange_cache = None
        from spark_rapids_tpu.serving import caches as sc
        if conf.get_bool(sc.EXCHANGE_REUSE_ENABLED, False):
            self._exchange_cache = \
                session._serving_bundle().exchange_cache

    # -- stage discovery ----------------------------------------------------
    def _next_ready_exchange(self, plan: PhysicalPlan) -> Optional[PhysicalPlan]:
        """First ready boundary, build sides of joins first (a measured
        build side can demote the join and elide the stream shuffle)."""
        ready: List[Tuple[PhysicalPlan, Optional[PhysicalPlan]]] = []

        def rec(node: PhysicalPlan, parent: Optional[PhysicalPlan]) -> None:
            for c in node.children:
                rec(c, node)
            if _is_stage_boundary(node) and not any(
                    _is_stage_boundary(d)
                    for c in node.children for d in c.walk()):
                ready.append((node, parent))
        rec(plan, None)
        if not ready:
            return None
        for node, parent in ready:
            if (type(parent) is cpu.CpuJoinExec
                    and len(parent.children) == 2):
                build_idx = 0 if parent.join_type == "right" else 1
                if parent.children[build_idx] is node:
                    return node
        return ready[0][0]

    # -- stage materialization ----------------------------------------------
    def _materialize(self, exchange: PhysicalPlan) -> ShuffleStage:
        from spark_rapids_tpu.obs.events import EVENTS
        from spark_rapids_tpu.obs.metrics import REGISTRY
        from spark_rapids_tpu.obs.shuffleobs import record_shuffle_skew
        from spark_rapids_tpu.obs.trace import TRACER
        self._stage_counter += 1
        sid = self._stage_counter
        prog = self.ctx.progress  # live stage view (obs/progress.py)
        # cross-query exchange reuse: a cached stage whose subtree digest
        # (stage-ref substituted, source-versioned, conf-fingerprinted)
        # matches is adopted outright — map output and statistics — and
        # the whole materialization below is skipped
        reuse_key = None
        if self._exchange_cache is not None:
            from spark_rapids_tpu.serving.caches import exchange_reuse_key
            reuse_key = exchange_reuse_key(exchange, self.conf)
            adopted = self._exchange_cache.get(
                reuse_key, tenant=self.session._job_group[0])
            if adopted is not None:
                self.stages.append(adopted)  # retained by the cache.get
                decision = {"rule": "exchangeReuse", "stage": sid,
                            "reusedFrom": adopted.uid,
                            "totalBytes": int(adopted.total_bytes),
                            "partitions": adopted.stats.num_partitions}
                self._note(decision, "aqeExchangeReuse",
                           counter="aqe.exchangeReuses")
                if prog is not None:
                    prog.aqe_stage_done(
                        sid, partitions=adopted.stats.num_partitions,
                        maps=adopted.stats.num_maps,
                        totalBytes=adopted.stats.total_bytes,
                        reused=True, compiles=0, compileSeconds=0.0)
                return adopted
        if prog is not None:
            prog.aqe_stage_running(sid)
        prepared = self._finalize_reads(exchange)
        converted = self._convert(prepared)
        assert hasattr(converted, "materialize_stage"), (
            "stage root must stay the exchange after conversion, got "
            f"{converted.describe()}")
        # compile-ledger watermark: stage-split uploads and per-stage
        # kernel shapes are a known warm-up cause under AQE — attribute
        # the compiles each stage triggers to it (obs/compileledger.py)
        from spark_rapids_tpu.obs.compileledger import LEDGER
        from spark_rapids_tpu.obs.syncledger import SYNC_LEDGER
        ledger0 = LEDGER.seq
        sync0 = SYNC_LEDGER.seq
        with TRACER.span("AqeStage", stage=sid):
            map_outputs, stats = converted.materialize_stage(self.ctx)
        stage_compiles = LEDGER.entries(since_seq=ledger0)
        compile_s = round(sum(e["seconds"] for e in stage_compiles), 4)
        # sync-ledger watermark: the stage-barrier fetch is a known host
        # sync — report how many blocking points this stage's
        # materialization paid and their wall share (obs/syncledger.py)
        stage_syncs = SYNC_LEDGER.entries(since_seq=sync0)
        sync_s = round(sum(e["seconds"] for e in stage_syncs), 4)
        stage = ShuffleStage(sid, exchange.output_schema(),
                             exchange.partitioning, map_outputs, stats)
        stage.reuse_key = reuse_key
        self.stages.append(stage)
        if prog is not None:
            prog.aqe_stage_done(sid, partitions=stats.num_partitions,
                                maps=stats.num_maps,
                                totalBytes=stats.total_bytes,
                                compiles=len(stage_compiles),
                                compileSeconds=compile_s,
                                syncs=len(stage_syncs),
                                syncSeconds=sync_s)
        REGISTRY.counter("aqe.stages").add(1)
        EVENTS.emit("aqeStageStats", stage=sid,
                    partitions=stats.num_partitions, maps=stats.num_maps,
                    totalBytes=stats.total_bytes,
                    maxBytes=stats.max_bytes(),
                    medianBytes=stats.median_bytes(),
                    rows=sum(stats.rows_by_partition or []),
                    compiles=len(stage_compiles),
                    compileSeconds=compile_s,
                    syncs=len(stage_syncs), syncSeconds=sync_s)
        record_shuffle_skew(stats.bytes_by_partition,
                            source=f"aqe:stage-{sid}")
        return stage

    # -- runtime rules ------------------------------------------------------
    def _apply_broadcast_demotion(self, node: PhysicalPlan) -> PhysicalPlan:
        new = copy.copy(node)
        new.children = [self._apply_broadcast_demotion(c)
                        for c in node.children]
        threshold = self.conf.broadcast_threshold
        if (type(new) is not cpu.CpuJoinExec
                or not self.conf.adaptive_broadcast_enabled
                or threshold < 0 or new.join_type == "full"):
            return new
        left_ok, right_ok = rules.broadcast_sides(new.join_type)
        candidates = []
        for side, ok in ((0, left_ok), (1, right_ok)):
            ch = new.children[side]
            if (ok and isinstance(ch, ShuffleStageRef)
                    and ch.stage.total_bytes <= threshold):
                candidates.append((ch.stage.total_bytes, side))
        if not candidates:
            return new
        measured, side = min(candidates)
        build_ref = new.children[side]
        stage = build_ref.stage
        build = cpu.CpuBroadcastExchangeExec(AqeShuffleReadExec(
            stage, [CoalescedSpec(tuple(range(stage.n_partitions)))]))
        stream = new.children[1 - side]
        elided = False
        if _is_stage_boundary(stream):
            # the stream side's shuffle has not run: a broadcast join
            # consumes arbitrary stream partitions, so skip it entirely
            stream = stream.children[0]
            elided = True
        children = [build, stream] if side == 0 else [stream, build]
        out = cpu.CpuBroadcastHashJoinExec(
            children[0], children[1], new.join_type,
            new.left_keys, new.right_keys)
        decision = {"rule": "broadcastDemotion", "stage": stage.id,
                    "joinType": new.join_type,
                    "side": "left" if side == 0 else "right",
                    "measuredBytes": int(measured),
                    "threshold": int(threshold),
                    "elidedStreamShuffle": elided}
        self._note(decision, "aqeBroadcastDemote",
                   counter="aqe.broadcastDemotions")
        return out

    def _finalize_reads(self, node: PhysicalPlan) -> PhysicalPlan:
        """Replace every ShuffleStageRef with a spec'd reader. Shuffled
        joins plan both sides jointly (combined coalescing + skew); every
        other consumer coalesces solo."""
        if isinstance(node, ShuffleStageRef):
            pre = len(self.decisions)
            specs = rules.solo_specs(node.stage, self.conf, self.decisions)
            self._flush_decisions(pre)
            return AqeShuffleReadExec(node.stage, specs)
        if (type(node) is cpu.CpuJoinExec
                and len(node.children) == 2
                and isinstance(node.children[0], ShuffleStageRef)
                and isinstance(node.children[1], ShuffleStageRef)):
            pre = len(self.decisions)
            lspecs, rspecs = rules.join_specs(
                node.children[0].stage, node.children[1].stage,
                node.join_type, self.conf, self.decisions)
            self._flush_decisions(pre)
            out = copy.copy(node)
            out.children = [
                AqeShuffleReadExec(node.children[0].stage, lspecs),
                AqeShuffleReadExec(node.children[1].stage, rspecs)]
            return out
        out = copy.copy(node)
        out.children = [self._finalize_reads(c) for c in node.children]
        return out

    def _flush_decisions(self, start: int) -> None:
        from spark_rapids_tpu.obs.events import EVENTS
        from spark_rapids_tpu.obs.metrics import REGISTRY
        prog = self.ctx.progress
        for d in self.decisions[start:]:
            kind = {"coalesce": "aqeCoalesce",
                    "skewSplit": "aqeSkewSplit"}.get(d["rule"])
            if kind:
                EVENTS.emit(kind, **d)
                REGISTRY.counter(
                    "aqe.coalescedReads" if d["rule"] == "coalesce"
                    else "aqe.skewSplits").add(1)
                if prog is not None:
                    prog.aqe_decision(d)

    def _note(self, decision: dict, kind: str, counter: str) -> None:
        from spark_rapids_tpu.obs.events import EVENTS
        from spark_rapids_tpu.obs.metrics import REGISTRY
        self.decisions.append(decision)
        EVENTS.emit(kind, **decision)
        REGISTRY.counter(counter).add(1)
        prog = self.ctx.progress
        if prog is not None:
            prog.aqe_decision(decision)

    # -- conversion / drain -------------------------------------------------
    def _convert(self, plan: PhysicalPlan) -> PhysicalPlan:
        """The legacy per-query rewrite pipeline, applied per stage
        (session._plan_and_run's middle section)."""
        conf = self.conf
        if not conf.sql_enabled:
            return plan
        from spark_rapids_tpu.sql.overrides import (
            TpuOverrides, TransitionOverrides, assert_is_on_tpu,
        )
        overrides = TpuOverrides(conf)
        out = overrides.apply(plan)
        out = TransitionOverrides(conf).apply(out)
        if conf.get_bool("spark.rapids.sql.agg.fuseCountDistinct", True):
            from spark_rapids_tpu.exec.aggfuse import fuse_count_distinct
            out = fuse_count_distinct(out)
        if conf.get_bool("spark.rapids.sql.reuseSubtrees.enabled", True):
            from spark_rapids_tpu.exec.reuse import reuse_common_subtrees
            out = reuse_common_subtrees(out)
        if conf.test_enabled:
            assert_is_on_tpu(out, conf)
        from spark_rapids_tpu.obs.events import EVENTS
        for meta in overrides.fallback_metas():
            EVENTS.emit("cpuFallback", op=meta.plan.name,
                        describe=meta.plan.describe()[:200],
                        reasons=list(meta.reasons))
        return out

    # -- driver -------------------------------------------------------------
    def execute(self, cpu_plan: PhysicalPlan):
        """Run ``cpu_plan`` adaptively; returns (final physical plan,
        output DataFrames). The final plan is the runtime-re-planned one
        — its digest in the queryPlan event differs from the static shape
        exactly when a rule fired."""
        plan = cpu_plan
        prog = self.ctx.progress
        if prog is not None:
            prog.aqe_begin(sum(1 for n in cpu_plan.walk()
                               if _is_stage_boundary(n)))
        try:
            while True:
                exchange = self._next_ready_exchange(plan)
                if exchange is None:
                    break
                stage = self._materialize(exchange)
                plan = _replace_node(plan, exchange,
                                     ShuffleStageRef(stage))
                plan = self._apply_broadcast_demotion(plan)
            plan = self._finalize_reads(plan)
            final = self._convert(plan)
            outs = self.session._drain(final, self.ctx, self.conf)
        finally:
            # stage outputs are per-query host materializations; a failed
            # query must not pin them until the next execution. With
            # exchange reuse on, fresh keyed stages are offered to the
            # cross-query cache FIRST (it takes its own reference), then
            # this query's reference drops either way.
            if self._exchange_cache is not None:
                from spark_rapids_tpu.serving.caches import (
                    EXCHANGE_REUSE_MAX_BYTES,
                )
                max_bytes = int(self.conf.get(EXCHANGE_REUSE_MAX_BYTES,
                                              256 << 20))
                for st in self.stages:
                    if st.reuse_key is not None:
                        self._exchange_cache.put(st.reuse_key, st,
                                                 max_bytes)
            for st in self.stages:
                st.release()
        self.session.last_aqe = {
            "stages": len(self.stages),
            "decisions": list(self.decisions),
            "planChanged": bool(self.decisions),
            "plan": final.tree_string(),
        }
        return final, outs
