"""Adaptive query execution (AQE): stage-based runtime re-planning from
shuffle statistics.

The reference plugin leans on Spark's AQE — GpuShuffleExchangeExec reports
MapOutputStatistics so Spark can coalesce partitions, demote shuffled
joins to broadcast and split skewed partitions at runtime. This package
is that loop for this engine:

  * ``stats``    — map-output statistics + canonical hash splitting
  * ``stages``   — query stages, stage refs and the stage readers
  * ``rules``    — coalesce / broadcast-demotion / skew-split planning
  * ``executor`` — the stage-at-a-time driver (session._plan_and_run
                   dispatches here under spark.rapids.sql.adaptive.enabled)

Import submodules explicitly; this package init stays import-light so
exec-layer call sites (exec/cpu.py, exec/tpu.py) can reach ``stats``
without pulling the rewrite engine (sql/overrides.py) into their import
cycle.
"""
