"""Window specifications and window expressions.

reference: GpuWindowExec.scala (202) + GpuWindowExpression.scala (723) —
the reference supports Count/Sum/Min/Max/RowNumber over row frames and
time-range frames (GpuWindowExpression.scala:47-56,139,198). This build
adds rank/dense_rank/lead/lag and general cumulative range frames.
Bounded ROW frames run sum/count/avg via prefix-sum differencing and
min/max via unrolled shifts (narrow) or a sparse-table variable-window
reduction (wide). Bounded RANGE frames (the reference's time-range
frames) run on device over a single ascending nulls-first non-float
order column via per-row binary search; descending / nulls-last /
float order columns fall back to the CPU oracle with a reason.

API mirrors pyspark.sql.Window:

  w = Window.partition_by("k").order_by("ts")
  df.with_column("rn", F.row_number().over(w))
  df.with_column("cum", F.sum("v").over(w))
  w2 = w.rows_between(-3, Window.CURRENT_ROW)
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from spark_rapids_tpu.columnar import dtypes
from spark_rapids_tpu.sql.exprs.core import Expression

UNBOUNDED_PRECEDING = -(1 << 62)
UNBOUNDED_FOLLOWING = 1 << 62
CURRENT_ROW = 0


def is_bounded_range(frame_kind: str, lo: int, hi: int) -> bool:
    """True for RANGE frames with numeric offsets (vs the cumulative /
    whole-partition forms) — shared by the capability tagger and both
    executors so frame classification cannot drift."""
    return frame_kind == "range" and (
        lo > UNBOUNDED_PRECEDING
        or (hi != CURRENT_ROW and hi < UNBOUNDED_FOLLOWING))


class WindowSpec:
    def __init__(self, partition_cols: Sequence[Expression] = (),
                 orders: Sequence = (),
                 frame: Optional[Tuple[str, int, int]] = None):
        self.partition_cols = list(partition_cols)
        self.orders = list(orders)
        self.frame = frame  # (kind 'rows'|'range', lo, hi) or None

    def partition_by(self, *cols) -> "WindowSpec":
        from spark_rapids_tpu.sql.functions import _c
        return WindowSpec([_c(c) for c in cols], self.orders, self.frame)

    def order_by(self, *cols) -> "WindowSpec":
        from spark_rapids_tpu.sql.functions import SortOrder, _c
        orders = []
        for c in cols:
            if isinstance(c, SortOrder):
                orders.append(c)
            else:
                orders.append(SortOrder(_c(c)))
        return WindowSpec(self.partition_cols, orders, self.frame)

    def rows_between(self, lo: int, hi: int) -> "WindowSpec":
        return WindowSpec(self.partition_cols, self.orders, ("rows", lo, hi))

    def range_between(self, lo: int, hi: int) -> "WindowSpec":
        return WindowSpec(self.partition_cols, self.orders, ("range", lo, hi))

    def resolved_frame(self, is_ranking: bool) -> Tuple[str, int, int]:
        """Spark's frame defaulting: ranking fns use their own semantics;
        aggregates default to RANGE UNBOUNDED PRECEDING..CURRENT ROW when
        ordered, else the whole partition."""
        if self.frame is not None:
            return self.frame
        if is_ranking or self.orders:
            return ("range", UNBOUNDED_PRECEDING, CURRENT_ROW)
        return ("rows", UNBOUNDED_PRECEDING, UNBOUNDED_FOLLOWING)


class Window:
    """pyspark.sql.Window-compatible entry points."""

    unboundedPreceding = UNBOUNDED_PRECEDING
    unboundedFollowing = UNBOUNDED_FOLLOWING
    currentRow = CURRENT_ROW

    @staticmethod
    def partition_by(*cols) -> WindowSpec:
        return WindowSpec().partition_by(*cols)

    partitionBy = partition_by

    @staticmethod
    def order_by(*cols) -> WindowSpec:
        return WindowSpec().order_by(*cols)

    orderBy = order_by


class RankingFunction(Expression):
    """Base for row_number/rank/dense_rank (no value child)."""

    def __init__(self):
        super().__init__([])

    def dtype(self, schema) -> dtypes.DType:
        return dtypes.INT32

    def __repr__(self):
        return type(self).__name__


class RowNumber(RankingFunction):
    pass


class Rank(RankingFunction):
    pass


class DenseRank(RankingFunction):
    pass


class LeadLag(Expression):
    """lead/lag: value of ``child`` offset rows ahead/behind within the
    partition (Spark: offset positive = lead direction)."""

    def __init__(self, child: Expression, offset: int, default=None,
                 is_lead: bool = True):
        super().__init__([child])
        self.offset = offset
        self.default = default
        self.is_lead = is_lead

    def dtype(self, schema) -> dtypes.DType:
        return self.children[0].dtype(schema)

    def __repr__(self):
        kind = "lead" if self.is_lead else "lag"
        return f"{kind}({self.children[0]!r}, {self.offset})"


class WindowExpression(Expression):
    """One windowed computation: function + spec (reference:
    GpuWindowExpression wrapping WindowFunction + WindowSpecDefinition)."""

    def __init__(self, fn: Expression, spec: WindowSpec):
        super().__init__([fn])
        self.fn = fn
        self.spec = spec

    def dtype(self, schema) -> dtypes.DType:
        return self.fn.dtype(schema)

    def __repr__(self):
        return f"{self.fn!r} OVER ({self.spec.partition_cols}, {self.spec.orders})"
