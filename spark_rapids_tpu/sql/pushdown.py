"""Scan pushdown: predicate -> row-group/stripe pruning, projection ->
column pruning.

The reference pushes filter conjuncts into the Parquet footer reader
(ParquetFilters, GpuParquetScan.scala:204-246) and into ORC search
arguments (sql/rapids/OrcFilters.scala), and prunes read columns to the
plan's projection. Here the same decisions run host-side against pyarrow
footer statistics:

  * ``extract_pushable_filters`` splits a filter condition into conjuncts
    and keeps the shapes statistics can answer: ``col <op> literal``,
    ``IsNull/IsNotNull(col)``, ``col IN (literals)``;
  * ``maybe_matches`` is the conservative three-valued test a split's
    (min, max, null_count) statistics give — True means "may contain
    matching rows" (the filter above the scan still runs; pruning only
    removes splits that provably match nothing);
  * ``required_scan_columns`` walks a logical tree and returns every
    column name the query references, so file scans read only those
    (pyarrow column projection) — the host-decode analogue of the
    reference's readSchema clipping.

ORC note: pyarrow exposes no per-stripe statistics, so OrcSource builds a
lazy stripe min/max index by reading just the filtered column once per
file (a one-time indexing cost amortized across queries), rather than
decoding every stripe of every file on every query.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import numpy as np

from spark_rapids_tpu.sql.exprs.core import Col, Expression, Literal
from spark_rapids_tpu.sql.exprs.predicates import (
    And, Eq, Ge, Gt, In, IsNotNull, IsNull, Le, Lt, Neq,
)

# (column_name, op, value); op in < <= > >= == != isnull isnotnull in
PushedFilter = Tuple[str, str, Any]

_CMP_OPS = {Lt: "<", Le: "<=", Gt: ">", Ge: ">=", Eq: "==", Neq: "!="}
_FLIP = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "==": "==",
         "!=": "!="}


def _literal_value(e: Expression):
    if isinstance(e, Literal):
        return e.value
    return None


def extract_pushable_filters(cond: Expression) -> List[PushedFilter]:
    """Conjuncts of ``cond`` a footer-statistics test can answer. Anything
    else is ignored (the in-plan filter still applies it)."""
    out: List[PushedFilter] = []

    def visit(e: Expression) -> None:
        if isinstance(e, And):
            visit(e.children[0])
            visit(e.children[1])
            return
        if isinstance(e, IsNull) and isinstance(e.children[0], Col):
            out.append((e.children[0].name, "isnull", None))
            return
        if isinstance(e, IsNotNull) and isinstance(e.children[0], Col):
            out.append((e.children[0].name, "isnotnull", None))
            return
        if isinstance(e, In) and isinstance(e.children[0], Col):
            # a NULL in the list never equals anything, so pruning on the
            # non-null values is safe
            vals = tuple(v for v in e.values if v is not None)
            if vals:
                out.append((e.children[0].name, "in", vals))
            return
        for cls, op in _CMP_OPS.items():
            if isinstance(e, cls):
                l, r = e.children
                if isinstance(l, Col) and _literal_value(r) is not None:
                    out.append((l.name, op, _literal_value(r)))
                elif isinstance(r, Col) and _literal_value(l) is not None:
                    out.append((r.name, _FLIP[op], _literal_value(l)))
                return

    visit(cond)
    return out


def _coerce_pair(a, b):
    """Best-effort comparable pair; raises on incomparable types (caller
    treats that as 'cannot prune')."""
    import pandas as pd
    if isinstance(a, (np.datetime64, pd.Timestamp)) or isinstance(
            b, (np.datetime64, pd.Timestamp)):
        return pd.Timestamp(a), pd.Timestamp(b)
    # binary-physical parquet statistics arrive as bytes; str(b'x') would
    # yield "b'x'" and silently mis-compare (wrong pruning). Strict decode
    # only — an undecodable value raises and the caller keeps the split.
    if isinstance(a, bytes):
        a = a.decode("utf-8", "strict")
    if isinstance(b, bytes):
        b = b.decode("utf-8", "strict")
    if isinstance(a, str) or isinstance(b, str):
        return str(a), str(b)
    return a, b


def maybe_matches(mn, mx, null_count, num_values, op: str, value) -> bool:
    """Conservative test: can a split with these column statistics contain
    a row satisfying (col op value)? Unknown statistics -> True."""
    try:
        if op == "isnull":
            return null_count is None or null_count > 0
        if op == "isnotnull":
            return num_values is None or num_values > 0 or mn is not None
        if mn is None or mx is None:
            return True
        if op == "in":
            return any(maybe_matches(mn, mx, null_count, num_values,
                                     "==", v) for v in value)
        lo, v = _coerce_pair(mn, value)
        hi, _ = _coerce_pair(mx, value)
        if op == "<":
            return lo < v
        if op == "<=":
            return lo <= v
        if op == ">":
            return hi > v
        if op == ">=":
            return hi >= v
        if op == "==":
            return lo <= v <= hi
        if op == "!=":
            return not (lo == hi == v)
    except Exception:
        return True
    return True


def partition_value_matches(pval, op: str, value) -> bool:
    """Exact test for hive partition-key values (partition pruning — the
    layer Spark itself does for the reference)."""
    try:
        if op == "isnull":
            return pval is None
        if op == "isnotnull":
            return pval is not None
        if pval is None:
            return False
        if op == "in":
            return any(partition_value_matches(pval, "==", v)
                       for v in value)
        a, b = _coerce_pair(pval, value)
        return {"<": a < b, "<=": a <= b, ">": a > b, ">=": a >= b,
                "==": a == b, "!=": a != b}[op]
    except Exception:
        return True


def prune_filter_columns(root):
    """Classic top-down column pruning, rewritten where it pays most on
    this engine: a narrowing LogicalProject above every Filter (and
    semi/anti join build side) whose output carries columns no ancestor
    references. On TPU the filter's row compaction is a per-column
    gather (string columns gather their whole char slab), so dead
    columns — e.g. predicate-only strings like q19's l_shipmode — cost
    real seconds at scale. The physical layer then folds the pure
    selection INTO the filter kernel (exec/fusion.py
    fuse_selection_into_filter) so the dead columns are never gathered
    at all. Returns the (possibly rewritten) root."""
    from spark_rapids_tpu.sql import plan as lp
    from spark_rapids_tpu.sql.window import WindowExpression

    def expr_cols(e, into: set) -> None:
        if isinstance(e, Col):
            into.add(e.name)
        if isinstance(e, WindowExpression):
            for c in e.spec.partition_cols:
                expr_cols(c, into)
            for o in e.spec.orders:
                expr_cols(o.expr, into)
            expr_cols(e.fn, into)
            return
        for c in getattr(e, "children", ()):
            expr_cols(c, into)

    def cols_of(*exprs) -> set:
        out: set = set()
        for e in exprs:
            expr_cols(e, out)
        return out

    def narrow(node, required):
        """Wrap ``node`` in a name-selection project when its output has
        columns outside ``required``."""
        names = node.schema().names
        keep = [n for n in names if n in required]
        if keep and len(keep) < len(names):
            return lp.LogicalProject(node, [(n, Col(n)) for n in keep])
        return node

    # --- shared-subtree coordination ------------------------------------
    # DataFrame DAGs reference the same logical subtree from several
    # branches (q2's min-cost subquery, q11's threshold, q17's avg
    # limit). Pruning each occurrence with its own requirement set makes
    # the branches STRUCTURALLY DIFFERENT (one keeps a column the other
    # dropped), which defeats the physical common-subtree reuse pass
    # (exec/reuse.py). Shared nodes therefore prune with the UNION of
    # every requirement reaching them — requirement propagation is
    # union-distributive node-by-node, so the union is exact — and every
    # parent receives the SAME rewritten object, which the planner turns
    # into structurally identical (fingerprint-equal) physical subtrees.
    refs: dict = {}

    def count_refs(n) -> None:
        refs[id(n)] = refs.get(id(n), 0) + 1
        if refs[id(n)] == 1:
            for c in getattr(n, "children", ()):
                count_refs(c)
    count_refs(root)
    shared_ids = {i for i, c in refs.items() if c > 1}
    collecting = bool(shared_ids)
    collected: dict = {}     # id(shared node) -> [required per occurrence]
    shared_memo: dict = {}   # id(shared node) -> rewritten-once subtree

    collect_memo: set = set()

    def rewrite(node, required):
        # ``required``: names the parent needs from this node's output;
        # None = all (unknown consumer)
        if id(node) in shared_ids:
            if collecting:
                collected.setdefault(id(node), []).append(
                    None if required is None else set(required))
                # keep descending so nested shared nodes collect too
                # (the pass-A result tree is discarded) — but each
                # (node, required) pair only once: a repeat propagates
                # identical requirement sets below, and without the memo
                # nested shared nodes walk 2^depth times
                mkey = (id(node), None if required is None
                        else frozenset(required))
                if mkey in collect_memo:
                    return node
                collect_memo.add(mkey)
            else:
                got = shared_memo.get(id(node))
                if got is None:
                    reqs = collected.get(id(node), [None])
                    if any(r is None for r in reqs):
                        union = None
                    else:
                        union = set().union(*reqs)
                    sid = id(node)
                    shared_ids.discard(sid)  # rewrite the body plainly
                    got = rewrite(node, union)
                    if union is not None:
                        got = narrow(got, union)
                    shared_ids.add(sid)
                    shared_memo[sid] = got
                return got
        if isinstance(node, lp.LogicalFilter):
            out_names = set(node.schema().names)
            cond_req = cols_of(node.condition)
            child_req = (None if required is None
                         else (required | cond_req) & out_names)
            f = lp.LogicalFilter(rewrite(node.children[0], child_req),
                                 node.condition)
            return f if required is None else narrow(f, required)
        if isinstance(node, lp.LogicalProject):
            # project-output pruning: drop outputs no ancestor references
            # (with_column() re-emits EVERY input column, which would
            # otherwise stop pruning dead at each derived column — q7's
            # l_year project kept a 37-column intermediate alive through
            # a five-join chain)
            exprs = node.exprs
            if required is not None:
                kept = [(n, e) for n, e in node.exprs if n in required]
                if not kept:
                    # nothing referenced (e.g. count(*) above): keep ONE
                    # output to preserve the row count — prefer a bare
                    # column ref (zero-cost under the selection fast
                    # path) over whatever derived expr happens first
                    bare = [(n, e) for n, e in node.exprs
                            if isinstance(e, Col)]
                    kept = bare[:1] or node.exprs[:1]
                exprs = kept
            req = cols_of(*(e for _n, e in exprs))
            return lp.LogicalProject(rewrite(node.children[0], req), exprs)
        if isinstance(node, lp.LogicalAggregate):
            req = cols_of(*(e for _n, e in node.grouping),
                          *(e for _n, e in node.results))
            return lp.LogicalAggregate(rewrite(node.children[0], req),
                                       node.grouping, node.results)
        if isinstance(node, lp.LogicalJoin):
            lnames = set(node.children[0].schema().names)
            rnames = set(node.children[1].schema().names)
            keyreq_l = cols_of(*node.left_keys)
            keyreq_r = cols_of(*node.right_keys)
            cond_req = (cols_of(node.condition)
                        if node.condition is not None else set())
            if required is None:
                lreq = None
                rreq = None
            else:
                lreq = ({n for n in required if n in lnames}
                        | keyreq_l | cond_req) & lnames
                rreq = ({n for n in required if n in rnames}
                        | keyreq_r | cond_req) & rnames
            if node.join_type in ("leftsemi", "leftanti"):
                # the build side contributes no output columns: always
                # prunable down to its keys (+ condition inputs)
                rreq = (keyreq_r | cond_req) & rnames
            # narrow each side AT the join input: every dead column a
            # join carries is gathered again by every expand above it
            # (join chains ran 30+-column expands before this)
            left = rewrite(node.children[0], lreq)
            right = rewrite(node.children[1], rreq)
            if lreq is not None:
                left = narrow(left, lreq)
            if rreq is not None:
                right = narrow(right, rreq)
            return lp.LogicalJoin(
                left, right,
                node.join_type, node.left_keys, node.right_keys,
                node.condition)
        if isinstance(node, lp.LogicalSort):
            req = (None if required is None else
                   (required | cols_of(*(o.expr for o in node.orders)))
                   & set(node.schema().names))
            return lp.LogicalSort(rewrite(node.children[0], req),
                                  node.orders, node.is_global)
        import copy

        def with_children(n, kids):
            # never mutate in place: logical nodes are shared by live
            # DataFrames and may be re-planned with different consumers
            new = copy.copy(n)
            new.children = kids
            return new

        if isinstance(node, (lp.LogicalLimit, lp.LogicalRepartition,
                             lp.LogicalCoalesce)):
            return with_children(
                node, [rewrite(c, required) for c in node.children])
        if isinstance(node, lp.LogicalUnion):
            if required is None:
                return with_children(
                    node, [rewrite(c, None) for c in node.children])
            if not required:
                # count(*)-style: nothing referenced by name. Branches
                # pruned independently with an empty requirement would
                # each keep an ARBITRARY surviving column — positionally
                # misaligning the union. Coordinate on each branch's
                # position-0 column (dtypes agree positionally by union
                # precondition), keeping the row counts and alignment.
                kids = []
                for c in node.children:
                    first = {c.schema().names[0]}
                    kids.append(narrow(rewrite(c, first), first))
                return with_children(node, kids)
            # every branch must end at the SAME narrowed schema (union
            # concatenates positionally)
            return with_children(
                node, [narrow(rewrite(c, required), required)
                       for c in node.children])
        if isinstance(node, lp.LogicalWindow):
            req = (None if required is None else
                   ({n for n in required
                     if n in node.children[0].schema().names}
                    | cols_of(*(w for _n, w in node.window_exprs))))
            return with_children(node, [rewrite(node.children[0], req)])
        # unknown/opaque shapes (Expand/Generate/Write/Scan/Range/...):
        # children keep their full output
        return with_children(node,
                             [rewrite(c, None) for c in node.children])

    if collecting:
        rewrite(root, None)   # pass A: record requireds at shared nodes
        collecting = False
    return rewrite(root, None)


def annotate_scan_pruning(root) -> None:
    """Per-query scan annotation: mark each file scan with the column
    subset the query actually references (cleared when the query shape
    forbids pruning). The planner consults the mark."""
    from spark_rapids_tpu.sql import plan as lp
    cols = required_scan_columns(root)
    for node in root.walk():
        if not isinstance(node, lp.LogicalScan):
            continue
        node._pruned_columns = None
        if cols is None or not hasattr(node.source, "with_columns"):
            continue
        keep = [c for c in node.source.schema.names if c in cols]
        if keep and len(keep) < len(node.source.schema.names):
            node._pruned_columns = keep


def required_scan_columns(root) -> Optional[set]:
    """Every column name referenced by any expression in the tree, or None
    when some subtree forwards a scan's full schema to the output
    unprojected (bare collect / select *): then nothing may be pruned."""
    from spark_rapids_tpu.sql import plan as lp

    names: set = set()
    narrowing = (lp.LogicalProject, lp.LogicalAggregate)

    def exprs_of(node) -> List[Expression]:
        out = []
        for attr in ("exprs", "grouping", "results", "window_exprs"):
            for item in getattr(node, attr, ()) or ():
                out.append(item[1] if isinstance(item, tuple) else item)
        if getattr(node, "condition", None) is not None:
            out.append(node.condition)
        for key in getattr(node, "left_keys", ()) or ():
            out.append(key)
        for key in getattr(node, "right_keys", ()) or ():
            out.append(key)
        for o in getattr(node, "orders", ()) or ():
            out.append(o.expr)
        for name in getattr(node, "partition_cols", ()) or ():
            if isinstance(name, str):
                out.append(Col(name))
        for proj in getattr(node, "projections", ()) or ():
            out.extend(e for _n, e in proj)
        if getattr(node, "source", None) is not None and isinstance(
                getattr(node, "source"), Expression):
            out.append(node.source)
        return out

    def collect_cols(e) -> None:
        if isinstance(e, Col):
            names.add(e.name)
        from spark_rapids_tpu.sql.window import WindowExpression
        if isinstance(e, WindowExpression):
            for c in e.spec.partition_cols:
                collect_cols(c)
            for o in e.spec.orders:
                collect_cols(o.expr)
            collect_cols(e.fn)
            return
        for c in getattr(e, "children", ()):
            collect_cols(c)

    def narrowed(node) -> bool:
        """True if every path from a scan below ``node`` to the output
        passes a projection/aggregation that names its columns."""
        if isinstance(node, narrowing):
            return True
        if isinstance(node, lp.LogicalScan):
            return False
        kids = getattr(node, "children", ())
        if not kids:
            return True
        return all(narrowed(c) for c in kids)

    any_scan = False
    for node in root.walk():
        if isinstance(node, lp.LogicalScan):
            any_scan = True
        for e in exprs_of(node):
            collect_cols(e)
    if not any_scan:
        return None
    if not narrowed(root):
        return None
    return names
