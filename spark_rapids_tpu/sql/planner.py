"""Logical -> CPU physical planning.

Produces the plan shape Spark would hand the reference's ColumnarRule:
aggregates split into partial + exchange + final, joins into
exchange-exchange-join (shuffled hash join) with co-partitioned children,
global sorts into single-partition exchange + sort. The TPU rewrite
(sql/overrides.py) then tags and converts this CPU plan node by node —
the same two-phase flow as Plugin.scala:36-54.
"""

from __future__ import annotations

from typing import List

from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.exec.aggutil import AggPlan
from spark_rapids_tpu.exec import cpu
from spark_rapids_tpu.exec.base import PhysicalPlan
from spark_rapids_tpu.sql import plan as lp
from spark_rapids_tpu.sql.exprs.core import bind_references


class Planner:
    def __init__(self, conf):
        self.conf = conf
        # tiny-query overhead-floor fast path
        # (spark.rapids.sql.smallQuery.*): when every leaf source of the
        # logical plan reports a known row count and the total fits one
        # resident batch under the threshold, plan every exchange
        # single-partition — hash/range partitioning degenerates to the
        # exchange's LOCAL collapse (no row hashing, no pid sort, no
        # per-bucket slices) and the session skips the semaphore and the
        # collapse's shrink sync (exec/tpu.py, exec/transitions.py).
        self.small_query = False
        # row-EXPANDING plans (joins, explode, grouping-set expand) can
        # blow a tiny input far past one resident batch, so they keep the
        # HBM admission semaphore even when the fast path engages; only
        # the exchange collapse + bookkeeping elision apply to them
        self.small_query_keep_sem = False

    def _shuffle_n(self) -> int:
        return 1 if self.small_query else self.conf.shuffle_partitions

    def note_input_size(self, logical: lp.LogicalPlan) -> None:
        """Inspect the logical plan's leaf sources BEFORE planning and
        engage the small-query fast path when the measured input is a
        single resident batch under the threshold. Unknown-size sources
        (file scans without footer counts) disengage — the fast path
        never guesses."""
        if not self.conf.get_bool("spark.rapids.sql.smallQuery.enabled",
                                  True):
            return
        # TPU-path optimization only: the CPU (oracle/fallback) path keeps
        # its partitioning so fallback behavior — and CPU-side
        # observability like per-exchange skew — is unchanged
        if not self.conf.sql_enabled:
            return
        # the fast path degenerates exchanges to single-partition LOCAL
        # collapses — modes whose whole point is multi-partition exchange
        # machinery (AQE stage stats, the shuffle-manager transport wire,
        # multi-executor striping) keep the general plan
        if self.conf.get_bool("spark.rapids.sql.adaptive.enabled", False):
            return
        if self.conf.get_bool("spark.rapids.shuffle.transport.enabled",
                              False):
            return
        if self.conf.get_int("spark.rapids.shuffle.executors", 1) > 1:
            return
        if str(self.conf.get("spark.rapids.tpu.shuffle.transport.mode",
                             "legacy")) != "legacy":
            return
        # an EXPLICIT partition-count setting wins over the collapse: the
        # user asked for that fan-out (repartition tests, skew probes,
        # file-count-shaping writes)
        if "spark.rapids.sql.shuffle.partitions" in getattr(
                self.conf, "_settings", {}):
            return
        max_rows = min(
            self.conf.get_int("spark.rapids.sql.smallQuery.maxRows", 32768),
            self.conf.batch_size_rows)
        total = 0
        expanding = False
        stack = [logical]
        while stack:
            node = stack.pop()
            stack.extend(node.children)
            if isinstance(node, (lp.LogicalJoin, lp.LogicalGenerate,
                                 lp.LogicalExpand)):
                expanding = True
            if isinstance(node, lp.LogicalScan):
                df = getattr(node.source, "df", None)
                if df is None:
                    return  # unknown-size source: stay on the general path
                total += len(df)
            elif isinstance(node, lp.LogicalRange):
                if not node.step:
                    return
                total += max(
                    0, -(-(node.end - node.start) // node.step))
            if total > max_rows:
                return
        self.small_query = True
        self.small_query_keep_sem = expanding

    def plan(self, node: lp.LogicalPlan) -> PhysicalPlan:
        fn = getattr(self, f"_plan_{type(node).__name__}", None)
        if fn is None:
            raise NotImplementedError(f"no physical plan for {node.name}")
        return fn(node)

    def _plan_LogicalScan(self, node: lp.LogicalScan) -> PhysicalPlan:
        source = node.source
        pruned_cols = getattr(node, "_pruned_columns", None)
        if pruned_cols is not None and hasattr(source, "with_columns"):
            source = source.with_columns(pruned_cols)
        return cpu.CpuScanExec(source, source.schema)

    def _plan_LogicalFilter(self, node: lp.LogicalFilter) -> PhysicalPlan:
        child = self.plan(node.children[0])
        cs = child.output_schema()
        cond = bind_references(node.condition, cs)
        # predicate pushdown: statistics-answerable conjuncts reach the
        # file source for row-group/stripe/partition pruning, the in-plan
        # filter still applies them exactly (ParquetFilters,
        # GpuParquetScan.scala:204-246; sql/rapids/OrcFilters.scala)
        if isinstance(child, cpu.CpuScanExec) and hasattr(
                child.source, "prune_splits"):
            from spark_rapids_tpu.sql.pushdown import (
                extract_pushable_filters,
            )
            pushed = extract_pushable_filters(node.condition)
            if pushed:
                child.pushed_filters = pushed
        return cpu.CpuFilterExec(child, cond)

    def _plan_LogicalRange(self, node: lp.LogicalRange) -> PhysicalPlan:
        return cpu.CpuRangeExec(node.start, node.end, node.step,
                                node.num_partitions)

    def _plan_LogicalProject(self, node: lp.LogicalProject) -> PhysicalPlan:
        child = self.plan(node.children[0])
        cs = child.output_schema()
        exprs = [(n, bind_references(e, cs)) for n, e in node.exprs]
        return cpu.CpuProjectExec(child, exprs)


    def _plan_LogicalAggregate(self, node: lp.LogicalAggregate) -> PhysicalPlan:
        child = self.plan(node.children[0])
        cs = child.output_schema()
        grouping = [(n, bind_references(e, cs)) for n, e in node.grouping]
        results = [(n, _bind_non_agg(e, cs)) for n, e in node.results]
        plan = AggPlan(cs, grouping, results)
        partial = cpu.CpuHashAggregateExec(child, plan, "partial")
        if plan.num_keys == 0:
            exchange = cpu.CpuShuffleExchangeExec(partial, ("single",))
        else:
            n = self._shuffle_n()
            exchange = cpu.CpuShuffleExchangeExec(
                partial, ("hash", list(range(plan.num_keys)), n))
        return cpu.CpuHashAggregateExec(exchange, plan, "final")

    def _plan_LogicalSort(self, node: lp.LogicalSort) -> PhysicalPlan:
        child = self.plan(node.children[0])
        cs = child.output_schema()
        orders = [_bind_order(o, cs) for o in node.orders]
        if node.is_global:
            # range-partitioned parallel global sort when the keys are plain
            # columns (reference: GpuRangePartitioner.scala + Spark's
            # rangepartitioning requirement); single-partition otherwise
            from spark_rapids_tpu.sql.exprs.core import BoundRef
            n = self._shuffle_n()
            simple = all(isinstance(o.expr, BoundRef) for o in orders)
            if simple and n > 1:
                child = cpu.CpuShuffleExchangeExec(
                    child, ("range", [o.expr.index for o in orders],
                            [o.ascending for o in orders],
                            [o.nulls_first for o in orders], n))
            else:
                child = cpu.CpuShuffleExchangeExec(child, ("single",))
        return cpu.CpuSortExec(child, orders)

    def _plan_LogicalLimit(self, node: lp.LogicalLimit) -> PhysicalPlan:
        child = self.plan(node.children[0])
        local = cpu.CpuLocalLimitExec(child, node.limit)
        single = cpu.CpuShuffleExchangeExec(local, ("single",))
        return cpu.CpuGlobalLimitExec(single, node.limit)

    def plan_collect_limit(self, node: lp.LogicalLimit) -> PhysicalPlan:
        """Root-position limit: one CollectLimit operator instead of
        local-limit + exchange + global-limit (reference:
        GpuCollectLimitExec, GpuOverrides.scala:1641-1643)."""
        child = self.plan(node.children[0])
        return cpu.CpuCollectLimitExec(child, node.limit)

    def _plan_LogicalRepartition(self, node) -> PhysicalPlan:
        child = self.plan(node.children[0])
        return cpu.CpuShuffleExchangeExec(child, ("roundrobin", node.n))

    def _plan_LogicalCoalesce(self, node) -> PhysicalPlan:
        child = self.plan(node.children[0])
        return cpu.CpuCoalescePartitionsExec(child, node.n)

    def _plan_LogicalJoin(self, node: lp.LogicalJoin) -> PhysicalPlan:
        left = self.plan(node.children[0])
        right = self.plan(node.children[1])
        ls = left.output_schema()
        rs = right.output_schema()
        jt = node.join_type

        if node.condition is not None:
            # non-equi condition -> broadcast nested loop (reference:
            # GpuBroadcastNestedLoopJoinExec; inner/cross only)
            if jt not in ("inner", "cross"):
                raise NotImplementedError(
                    f"condition joins support inner/cross, not {jt!r}")
            combined = Schema(list(ls.names) + list(rs.names),
                              list(ls.dtypes) + list(rs.dtypes))
            cond = bind_references(node.condition, combined)
            right = cpu.CpuBroadcastExchangeExec(right)
            return cpu.CpuBroadcastNestedLoopJoinExec(left, right,
                                                      "inner", cond)

        if jt == "cross":
            left = cpu.CpuShuffleExchangeExec(left, ("single",))
            right = cpu.CpuShuffleExchangeExec(right, ("single",))
            return cpu.CpuCartesianProductExec(left, right)

        lkeys = [bind_references(e, ls) for e in node.left_keys]
        rkeys = [bind_references(e, rs) for e in node.right_keys]
        # keys must be plain column refs for the exec; project if needed
        lidx, left = _key_indices(left, lkeys, ls)
        ridx, right = _key_indices(right, rkeys, rs)
        # broadcast the build side when its estimate fits under the
        # threshold (reference: GpuBroadcastHashJoinExec; build side is the
        # non-preserved side, so full outer never broadcasts).
        # threshold = -1 explicitly disables broadcast; an unknown
        # estimate (None mid-tree — width-changing operators return
        # unknown — or a source whose size probe fails) falls back to the
        # shuffled join, never raises: a bad estimate must cost
        # performance, not the query. AQE (sql/adaptive/) re-makes this
        # call later from MEASURED sizes.
        threshold = self.conf.broadcast_threshold
        build_node = node.children[0] if jt == "right" else node.children[1]
        est = _estimated_size(build_node)
        can_broadcast = (jt != "full" and threshold >= 0 and est is not None
                         and est <= threshold)
        if can_broadcast:
            if jt == "right":
                left = cpu.CpuBroadcastExchangeExec(left)
            else:
                right = cpu.CpuBroadcastExchangeExec(right)
            return cpu.CpuBroadcastHashJoinExec(left, right, jt, lidx, ridx)
        n = self._shuffle_n()
        left = cpu.CpuShuffleExchangeExec(left, ("hash", lidx, n))
        right = cpu.CpuShuffleExchangeExec(right, ("hash", ridx, n))
        return cpu.CpuJoinExec(left, right, jt, lidx, ridx)

    def _plan_LogicalUnion(self, node: lp.LogicalUnion) -> PhysicalPlan:
        return cpu.CpuUnionExec([self.plan(c) for c in node.children])

    def _plan_LogicalExpand(self, node: lp.LogicalExpand) -> PhysicalPlan:
        child = self.plan(node.children[0])
        cs = child.output_schema()
        projections = [[(n, bind_references(e, cs)) for n, e in proj]
                       for proj in node.projections]
        return cpu.CpuExpandExec(child, projections)

    def _plan_LogicalGenerate(self, node: lp.LogicalGenerate) -> PhysicalPlan:
        from spark_rapids_tpu.exec.generate import CpuGenerateExec
        from spark_rapids_tpu.sql.exprs.core import BoundRef
        child = self.plan(node.children[0])
        cs = child.output_schema()
        src = bind_references(node.source, cs)
        if not isinstance(src, BoundRef):
            # computed source: pre-project it, generate, then drop the
            # helper column to keep the logical schema
            exprs = [(n, BoundRef(i, dt, n)) for i, (n, dt)
                     in enumerate(zip(cs.names, cs.dtypes))]
            exprs.append(("_gen_src", src))
            child = cpu.CpuProjectExec(child, exprs)
            gen = CpuGenerateExec(child, len(exprs) - 1, node.delim,
                                  node.out_name, node.with_pos,
                                  node.pos_name)
            gs = gen.output_schema()
            keep = [(n, BoundRef(gs.index_of(n), gs.dtype_of(n), n))
                    for n in node.schema().names]
            return cpu.CpuProjectExec(gen, keep)
        return CpuGenerateExec(child, src.index, node.delim, node.out_name,
                               node.with_pos, node.pos_name)

    def _plan_LogicalWrite(self, node: lp.LogicalWrite) -> PhysicalPlan:
        from spark_rapids_tpu.exec.write import CpuWriteExec
        child = self.plan(node.children[0])
        return CpuWriteExec(child, node.path, node.fmt, node.mode,
                            node.partition_cols)

    def _plan_LogicalWindow(self, node: lp.LogicalWindow) -> PhysicalPlan:
        from spark_rapids_tpu.exec.windowexec import CpuWindowExec
        from spark_rapids_tpu.sql.exprs.core import BoundRef
        from spark_rapids_tpu.sql.window import WindowExpression, WindowSpec

        child = self.plan(node.children[0])
        cs = child.output_schema()
        bound = []
        for name, w in node.window_exprs:
            spec = WindowSpec(
                [bind_references(e, cs) for e in w.spec.partition_cols],
                [_bind_order(o, cs) for o in w.spec.orders], w.spec.frame)
            fn = w.fn.map_children(lambda c: bind_references(c, cs))
            bound.append((name, WindowExpression(fn, spec)))
        # distribute whole partition groups to one task: hash exchange on
        # the partition keys when they are plain columns, else single
        spec0 = bound[0][1].spec
        pidx = [e.index for e in spec0.partition_cols
                if isinstance(e, BoundRef)]
        if spec0.partition_cols and len(pidx) == len(spec0.partition_cols):
            n = self._shuffle_n()
            child = cpu.CpuShuffleExchangeExec(child, ("hash", pidx, n))
        else:
            child = cpu.CpuShuffleExchangeExec(child, ("single",))
        return CpuWindowExec(child, bound)


def _estimated_size(node: lp.LogicalPlan):
    """Broadcast size hint, hardened: a raising or non-integer estimate
    reads as unknown (None) so planning falls back to the shuffled join
    instead of failing the query."""
    try:
        est = node.estimated_size_bytes()
    except Exception:  # noqa: BLE001 — estimates are advisory by contract
        return None
    if est is None:
        return None
    try:
        return int(est)
    except (TypeError, ValueError):
        return None


def _key_indices(child: PhysicalPlan, keys, schema):
    """Ensure join keys are plain column indices, projecting if necessary."""
    from spark_rapids_tpu.sql.exprs.core import BoundRef
    idx = []
    simple = True
    for k in keys:
        if isinstance(k, BoundRef):
            idx.append(k.index)
        else:
            simple = False
            break
    if simple:
        return idx, child
    # append computed key columns
    exprs = [(n, BoundRef(i, dt, n)) for i, (n, dt)
             in enumerate(zip(schema.names, schema.dtypes))]
    key_cols = []
    for j, k in enumerate(keys):
        name = f"_jk{j}"
        exprs.append((name, k))
        key_cols.append(len(exprs) - 1)
    return key_cols, cpu.CpuProjectExec(child, exprs)


def _bind_non_agg(e, schema):
    """Bind column refs inside aggregate result expressions, leaving Col
    nodes that name grouping outputs for AggPlan.finalize_exprs to handle."""
    from spark_rapids_tpu.sql.exprs.aggregates import AggregateFunction
    from spark_rapids_tpu.sql.exprs.core import Col

    def bind(x):
        if isinstance(x, AggregateFunction):
            return x.map_children(lambda c: bind_references(c, schema))
        if isinstance(x, Col):
            return x  # resolved against grouping names at finalize
        return x.map_children(bind)
    return bind(e)


def _bind_order(o, schema):
    from spark_rapids_tpu.sql.functions import SortOrder
    return SortOrder(bind_references(o.expr, schema), o.ascending,
                     o.nulls_first)
