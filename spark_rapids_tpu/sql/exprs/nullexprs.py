"""Null-handling expressions (reference: sql/rapids/nullExpressions.scala,
297 LoC — Coalesce/IsNull live in conditional.py/predicates.py) and float
normalization (reference: NormalizeFloatingNumbers.scala:38,
FloatUtils.scala:84): Greatest/Least, AtLeastNNonNulls, and
NormalizeNaNAndZero (canonical NaN, -0.0 -> +0.0) used before grouping and
joining on float keys.
"""

from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp
import numpy as np
import pandas as pd

from spark_rapids_tpu.columnar import dtypes
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.columnar.dtype import DType
from spark_rapids_tpu.sql.exprs.core import (
    DevCol, DevValue, EvalContext, Expression,
)
from spark_rapids_tpu.sql.exprs.hostutil import host_unary_values, rebuild_series


class _GreatestLeast(Expression):
    """greatest()/least(): null-skipping n-ary extremum (null only when all
    operands are null). NaN is greater than any other double, like Spark."""

    is_greatest = True

    def __init__(self, children: List[Expression]):
        assert len(children) >= 2, "greatest/least need at least 2 args"
        super().__init__(children)

    def dtype(self, schema: Schema) -> DType:
        import functools
        from spark_rapids_tpu.columnar.dtype import common_type
        dts = [c.dtype(schema) for c in self.children]
        return functools.reduce(common_type, dts)

    def sql_name(self, schema=None) -> str:
        fn = "greatest" if self.is_greatest else "least"
        args = ", ".join(c.sql_name(schema) for c in self.children)
        return f"{fn}({args})"

    def device_supported(self, schema: Schema) -> Optional[str]:
        return None

    def eval_device(self, ctx: EvalContext) -> DevValue:
        dt = self.dtype(_schema_of(ctx))
        if dt.is_string:
            from spark_rapids_tpu.ops import strings as string_ops
            out = None
            for c in self.children:
                nxt = ctx.broadcast(c.eval_device(ctx))
                if out is None:
                    out = nxt
                    continue
                cmp = string_ops.string_compare_columns(nxt, out)
                win = (cmp > 0) if self.is_greatest else (cmp < 0)
                better = jnp.where(out.validity & nxt.validity, win,
                                   nxt.validity & ~out.validity)
                out = string_ops.select_strings(
                    ctx, better, nxt, out, out.validity | nxt.validity)
            return out
        out_data = None
        out_valid = None
        for c in self.children:
            v = ctx.broadcast(c.eval_device(ctx))
            data = v.data.astype(dt.np_dtype)
            valid = v.validity
            if out_data is None:
                out_data, out_valid = data, valid
                continue
            if self.is_greatest:
                better = jnp.where(
                    out_valid & valid,
                    _nan_aware_gt(jnp, data, out_data), valid & ~out_valid)
            else:
                better = jnp.where(
                    out_valid & valid,
                    _nan_aware_lt(jnp, data, out_data), valid & ~out_valid)
            out_data = jnp.where(better, data, out_data)
            out_valid = out_valid | valid
        return DevCol(dt, out_data, out_valid)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        import functools
        from spark_rapids_tpu.columnar.dtype import common_type
        from spark_rapids_tpu.sql.exprs.hostutil import series_dtype
        parts = []
        dts = []
        for c in self.children:
            series = c.eval_host(df)
            dts.append(series_dtype(series))
            parts.append(host_unary_values(series))
        dt = functools.reduce(common_type, dts)
        if dt.is_string:
            out_data = None
            out_valid = None
            for vals, valid, _index in parts:
                # fill invalid slots before comparing (None vs str raises);
                # fills never win thanks to the validity gating
                data = np.where(valid, np.asarray(vals, dtype=object), "")
                if out_data is None:
                    out_data, out_valid = data.copy(), valid.copy()
                    continue
                both = out_valid & valid
                win = np.array(
                    [(x > y) if self.is_greatest else (x < y)
                     for x, y in zip(data, out_data)], dtype=bool)
                better = np.where(both, win, valid & ~out_valid)
                out_data = np.where(better, data, out_data)
                out_valid = out_valid | valid
            return rebuild_series(np.where(out_valid, out_data, None),
                                  out_valid, dt, parts[0][2])
        out_data = None
        out_valid = None
        for vals, valid, index in parts:
            data = vals.astype(dt.np_dtype)
            if out_data is None:
                out_data, out_valid = data.copy(), valid.copy()
                continue
            both = out_valid & valid
            if self.is_greatest:
                better = np.where(both, _nan_aware_gt(np, data, out_data),
                                  valid & ~out_valid)
            else:
                better = np.where(both, _nan_aware_lt(np, data, out_data),
                                  valid & ~out_valid)
            out_data = np.where(better, data, out_data)
            out_valid = out_valid | valid
        return rebuild_series(out_data.astype(dt.np_dtype), out_valid, dt,
                              parts[0][2])


def _nan_aware_gt(xp, a, b):
    if a.dtype.kind == "f":
        return (a > b) | (xp.isnan(a) & ~xp.isnan(b))
    return a > b


def _nan_aware_lt(xp, a, b):
    if a.dtype.kind == "f":
        return (a < b) | (xp.isnan(b) & ~xp.isnan(a))
    return a < b


class Greatest(_GreatestLeast):
    is_greatest = True


class Least(_GreatestLeast):
    is_greatest = False


class AtLeastNNonNulls(Expression):
    """Spark's internal predicate behind df.na.drop(thresh=n)."""

    def __init__(self, n: int, children: List[Expression]):
        super().__init__(children)
        self.n = int(n)

    def dtype(self, schema: Schema) -> DType:
        return dtypes.BOOL

    def sql_name(self, schema=None) -> str:
        return f"atleastnnonnulls({self.n})"

    def eval_device(self, ctx: EvalContext) -> DevValue:
        count = jnp.zeros((ctx.capacity,), jnp.int32)
        for c in self.children:
            v = ctx.broadcast(c.eval_device(ctx))
            ok = v.validity
            if v.dtype.np_dtype.kind == "f":
                ok = ok & ~jnp.isnan(v.data)
            count = count + ok.astype(jnp.int32)
        return DevCol(dtypes.BOOL, count >= self.n,
                      jnp.ones((ctx.capacity,), jnp.bool_))

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        count = np.zeros(len(df), dtype=np.int32)
        index = df.index
        for c in self.children:
            vals, valid, index = host_unary_values(c.eval_host(df))
            ok = valid.copy()
            if vals.dtype.kind == "f":
                ok &= ~np.isnan(vals)
            count += ok.astype(np.int32)
        return pd.Series(count >= self.n, index=index)


class NormalizeNaNAndZero(Expression):
    """Canonicalize floats before hashing/grouping: every NaN to the same
    bit pattern, -0.0 to +0.0 (reference: NormalizeFloatingNumbers.scala:38).
    Identity for non-float inputs."""

    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return self.children[0].dtype(schema)

    def sql_name(self, schema=None) -> str:
        return self.children[0].sql_name(schema)

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = ctx.broadcast(self.children[0].eval_device(ctx))
        if v.dtype.np_dtype.kind != "f":
            return v
        data = v.data + jnp.zeros_like(v.data)   # -0.0 + 0.0 == +0.0
        data = jnp.where(jnp.isnan(data), jnp.asarray(
            jnp.nan, dtype=data.dtype), data)
        return v.with_(data=data)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        s = self.children[0].eval_host(df)
        vals, valid, index = host_unary_values(s)
        if vals.dtype.kind != "f":
            return s
        data = vals + np.zeros_like(vals)
        data = np.where(np.isnan(data), np.nan, data)
        from spark_rapids_tpu.sql.exprs.hostutil import series_dtype
        return rebuild_series(data, valid, series_dtype(s), index)


def _schema_of(ctx: EvalContext) -> Schema:
    """Pseudo-schema from context columns (dtype resolution inside eval)."""
    return Schema([f"c{i}" for i in range(len(ctx.cols))],
                  [c.dtype for c in ctx.cols])
