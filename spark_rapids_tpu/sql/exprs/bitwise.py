"""Bitwise expressions (reference: sql/rapids/bitwise.scala, 145 LoC):
and/or/xor/not and the three shifts. Integral operands only; shifts follow
Java semantics (the shift amount is masked to the operand width, result
keeps the left operand's type)."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np
import pandas as pd

from spark_rapids_tpu.columnar import dtypes
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.columnar.dtype import DType
from spark_rapids_tpu.sql.exprs.arithmetic import BinaryArithmetic
from spark_rapids_tpu.sql.exprs.core import (
    DevCol, DevScalar, DevValue, EvalContext, Expression,
)
from spark_rapids_tpu.sql.exprs.hostutil import host_unary_values, rebuild_series


class BinaryBitwise(BinaryArithmetic):
    def device_supported(self, schema: Schema) -> Optional[str]:
        for c in self.children:
            if not c.dtype(schema).is_integral:
                return (f"bitwise {self.symbol} requires integral operands, "
                        f"got {c.dtype(schema)}")
        return None


class BitwiseAnd(BinaryBitwise):
    symbol = "&"

    def compute(self, xp, a, b, out_dt):
        return a & b, None


class BitwiseOr(BinaryBitwise):
    symbol = "|"

    def compute(self, xp, a, b, out_dt):
        return a | b, None


class BitwiseXor(BinaryBitwise):
    symbol = "^"

    def compute(self, xp, a, b, out_dt):
        return a ^ b, None


class _Shift(BinaryBitwise):
    """Result type = left operand type; amount masked to the operand width
    (Java << / >> / >>> semantics, which Spark inherits)."""

    def dtype_from_children(self, lt: DType, rt: DType) -> DType:
        return lt

    def dtype(self, schema: Schema) -> DType:
        return self.children[0].dtype(schema)

    def _mask(self, out_dt: DType) -> int:
        return 63 if out_dt == dtypes.INT64 else 31


class ShiftLeft(_Shift):
    symbol = "<<"

    def compute(self, xp, a, b, out_dt):
        return a << (b.astype(a.dtype) & self._mask(out_dt)), None


class ShiftRight(_Shift):
    symbol = ">>"

    def compute(self, xp, a, b, out_dt):
        return a >> (b.astype(a.dtype) & self._mask(out_dt)), None


class ShiftRightUnsigned(_Shift):
    symbol = ">>>"

    def compute(self, xp, a, b, out_dt):
        width = self._mask(out_dt) + 1
        unsigned = a.view(getattr(xp, f"uint{width}"))
        out = unsigned >> (b.astype(unsigned.dtype) & self._mask(out_dt))
        return out.view(a.dtype), None


class BitwiseNot(Expression):
    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return self.children[0].dtype(schema)

    def sql_name(self, schema=None) -> str:
        return f"~{self.children[0].sql_name(schema)}"

    def device_supported(self, schema: Schema) -> Optional[str]:
        if not self.children[0].dtype(schema).is_integral:
            return "bitwise ~ requires an integral operand"
        return None

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = self.children[0].eval_device(ctx)
        if isinstance(v, DevScalar):
            return DevScalar(v.dtype, ~v.value, v.valid)
        return v.with_(data=~v.data)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        values, validity, index = host_unary_values(
            self.children[0].eval_host(df))
        return rebuild_series(~values, validity,
                              dtypes.from_numpy(values.dtype), index)
