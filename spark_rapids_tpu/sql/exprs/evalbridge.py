"""Bridge between DeviceBatch and expression evaluation contexts."""

from __future__ import annotations

from typing import List

import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import DeviceBatch, Schema
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.sql.exprs.core import (
    DevCol, DevScalar, DevValue, EvalContext, Expression,
)


def make_context(batch: DeviceBatch) -> EvalContext:
    cols = [DevCol(c.dtype, c.data, c.validity, c.offsets,
                   dict_codes=c.dict_codes, dict_values=c.dict_values,
                   prefix8=c.prefix8)
            for c in batch.columns]
    mask = jnp.arange(batch.capacity, dtype=jnp.int32) < batch.num_rows
    return EvalContext(cols, mask, batch.num_rows, batch.capacity)


def to_device_column(ctx: EvalContext, v: DevValue) -> DeviceColumn:
    c = ctx.broadcast(v)
    # mask out padding rows so stale values never leak past num_rows
    validity = c.validity & ctx.row_mask
    return DeviceColumn(c.dtype, c.data, validity, c.offsets)


def eval_projection(batch: DeviceBatch, exprs: List[Expression],
                    names: List[str]) -> DeviceBatch:
    """Evaluate bound expressions into a new DeviceBatch (traceable)."""
    ctx = make_context(batch)
    out_cols = []
    out_dtypes = []
    for e in exprs:
        v = e.eval_device(ctx)
        col = to_device_column(ctx, v)
        out_cols.append(col)
        out_dtypes.append(col.dtype)
    schema = Schema(names, out_dtypes)
    return DeviceBatch(schema, out_cols, batch.num_rows)
