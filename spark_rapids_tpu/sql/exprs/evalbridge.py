"""Bridge between DeviceBatch and expression evaluation contexts."""

from __future__ import annotations

from typing import List

import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import DeviceBatch, Schema
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.sql.exprs.core import (
    DevCol, DevScalar, DevValue, EvalContext, Expression,
)


def make_context(batch: DeviceBatch) -> EvalContext:
    # lazy (codes-only) string columns stay lazy: chars materialize only
    # if an expression reads .data/.offsets (DevCol._src) — an eager read
    # here would rebuild the char slab inside every projection kernel
    cols = []
    for c in batch.columns:
        lazy = c.dtype.is_string and c.is_lazy
        cols.append(DevCol(c.dtype,
                           None if lazy else c.data, c.validity,
                           None if lazy else c.offsets,
                           dict_codes=c.dict_codes,
                           dict_values=c.dict_values,
                           prefix8=c.prefix8, src=c))
    mask = jnp.arange(batch.capacity, dtype=jnp.int32) < batch.num_rows
    return EvalContext(cols, mask, batch.num_rows, batch.capacity)


def to_device_column(ctx: EvalContext, v: DevValue) -> DeviceColumn:
    c = ctx.broadcast(v)
    # mask out padding rows so stale values never leak past num_rows
    validity = c.validity & ctx.row_mask
    if (c.dtype.is_string and getattr(c, "dict_values", None) is not None
            and c.dict_codes is not None):
        # dictionary metadata survives the projection: codes re-normalized
        # so masked rows carry the NULL sentinel (= card), matching the
        # scan contract consumers rely on for slot addressing
        card = len(c.dict_values)
        codes = jnp.where(validity, c.dict_codes, jnp.int32(card))
        pre = (jnp.where(validity, c.prefix8, jnp.uint64(0))
               if c.prefix8 is not None else None)
        lazy = isinstance(c, DevCol) and c.is_lazy
        return DeviceColumn(c.dtype,
                            None if lazy else c.data, validity,
                            None if lazy else c.offsets,
                            prefix8=pre, dict_codes=codes,
                            dict_values=c.dict_values)
    return DeviceColumn(c.dtype, c.data, validity, c.offsets)


def eval_projection(batch: DeviceBatch, exprs: List[Expression],
                    names: List[str]) -> DeviceBatch:
    """Evaluate bound expressions into a new DeviceBatch (traceable)."""
    ctx = make_context(batch)
    out_cols = []
    out_dtypes = []
    for e in exprs:
        v = e.eval_device(ctx)
        col = to_device_column(ctx, v)
        out_cols.append(col)
        out_dtypes.append(col.dtype)
    schema = Schema(names, out_dtypes)
    return DeviceBatch(schema, out_cols, batch.num_rows)
