"""Math expressions (reference: sql/rapids/mathExpressions.scala, 378 LoC).

Unary math follows Spark: inputs coerce to double, domain errors yield NaN
(not NULL) matching java.lang.Math. One formula for host (numpy) and device
(jax.numpy).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np
import pandas as pd

from spark_rapids_tpu.columnar import dtypes
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.columnar.dtype import DType
from spark_rapids_tpu.sql.exprs.core import (
    DevCol, DevValue, EvalContext, Expression,
)
from spark_rapids_tpu.sql.exprs.hostutil import host_unary_values, rebuild_series


class UnaryMath(Expression):
    fname = "?"

    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.FLOAT64

    def sql_name(self, schema=None) -> str:
        return f"{self.fname}({self.children[0].sql_name(schema)})"

    def device_supported(self, schema: Schema) -> Optional[str]:
        if self.children[0].dtype(schema).is_string:
            return "string input"
        return None

    def compute(self, xp, x):
        raise NotImplementedError

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = ctx.broadcast(self.children[0].eval_device(ctx))
        x = v.data.astype(jnp.float64)
        return DevCol(dtypes.FLOAT64, self.compute(jnp, x), v.validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        values, validity, index = host_unary_values(self.children[0].eval_host(df))
        with np.errstate(all="ignore"):
            data = self.compute(np, values.astype(np.float64))
        return rebuild_series(data, validity, dtypes.FLOAT64, index)


def _make_unary(name: str, fn: Callable) -> type:
    cls = type(name.capitalize(), (UnaryMath,), {
        "fname": name,
        "compute": staticmethod(lambda xp, x, _fn=fn: _fn(xp, x)),
    })
    # staticmethod on compute loses self; wrap properly:
    def compute(self, xp, x, _fn=fn):
        return _fn(xp, x)
    cls.compute = compute
    return cls


Sqrt = _make_unary("sqrt", lambda xp, x: xp.sqrt(x))
Exp = _make_unary("exp", lambda xp, x: xp.exp(x))
Expm1 = _make_unary("expm1", lambda xp, x: xp.expm1(x))
Log = _make_unary("ln", lambda xp, x: xp.log(x))
Log2 = _make_unary("log2", lambda xp, x: xp.log2(x))
Log10 = _make_unary("log10", lambda xp, x: xp.log10(x))
Log1p = _make_unary("log1p", lambda xp, x: xp.log1p(x))
Sin = _make_unary("sin", lambda xp, x: xp.sin(x))
Cos = _make_unary("cos", lambda xp, x: xp.cos(x))
Tan = _make_unary("tan", lambda xp, x: xp.tan(x))
Asin = _make_unary("asin", lambda xp, x: xp.arcsin(x))
Acos = _make_unary("acos", lambda xp, x: xp.arccos(x))
Atan = _make_unary("atan", lambda xp, x: xp.arctan(x))
Sinh = _make_unary("sinh", lambda xp, x: xp.sinh(x))
Cosh = _make_unary("cosh", lambda xp, x: xp.cosh(x))
Tanh = _make_unary("tanh", lambda xp, x: xp.tanh(x))
Cbrt = _make_unary("cbrt", lambda xp, x: xp.cbrt(x))
Rint = _make_unary("rint", lambda xp, x: xp.rint(x))
Signum = _make_unary("signum", lambda xp, x: xp.sign(x))
ToDegrees = _make_unary("degrees", lambda xp, x: xp.degrees(x))
ToRadians = _make_unary("radians", lambda xp, x: xp.radians(x))


class Floor(Expression):
    """floor/ceil return LongType in Spark."""
    fname = "floor"
    _fn = staticmethod(np.floor)

    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.INT64

    def sql_name(self, schema=None) -> str:
        return f"{self.fname}({self.children[0].sql_name(schema)})"

    def _int_div_expr(self, schema=None):
        """floor(a / b) / ceil(a / b) with INTEGER a, b: computed as an
        exact int64 floor-division instead of float64 divide+floor —
        float64 is software-emulated on TPU and the divide dominated
        profiles (mortgage ETL's josh_mody projections, 1.5s of a 2.9M-row
        batch). Exact for all int64 (f64 rounds above 2^53); both paths
        use it so CPU/TPU agree bit-for-bit. Returns the Divide node
        when the rewrite statically applies (integer operand dtypes),
        else None — decided WITHOUT evaluating the operands, so the
        generic path never pays a double evaluation."""
        from spark_rapids_tpu.sql.exprs.arithmetic import Divide
        ch = self.children[0]
        if not isinstance(ch, Divide):
            return None
        try:
            ldt = ch.children[0].dtype(schema)
            rdt = ch.children[1].dtype(schema)
        except Exception:  # noqa: BLE001 — unresolvable statically
            return None
        if not (np.issubdtype(np.dtype(ldt.np_dtype), np.integer)
                and np.issubdtype(np.dtype(rdt.np_dtype), np.integer)):
            return None
        return ch

    def eval_device(self, ctx: EvalContext) -> DevValue:
        intdiv = self._int_div_expr()
        if intdiv is not None:
            lv = ctx.broadcast(intdiv.children[0].eval_device(ctx))
            rv = ctx.broadcast(intdiv.children[1].eval_device(ctx))
            a = lv.data.astype(jnp.int64)
            b = rv.data.astype(jnp.int64)
            zero = b == 0
            safe = jnp.where(zero, jnp.int64(1), b)
            q = (jnp.floor_divide(a, safe) if self.fname == "floor"
                 else -jnp.floor_divide(-a, safe))
            return DevCol(dtypes.INT64, q,
                          lv.validity & rv.validity & ~zero)
        v = ctx.broadcast(self.children[0].eval_device(ctx))
        x = v.data.astype(jnp.float64)
        fn = jnp.floor if self.fname == "floor" else jnp.ceil
        return DevCol(dtypes.INT64, fn(x).astype(jnp.int64), v.validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        from spark_rapids_tpu.columnar.batch import Schema
        intdiv = self._int_div_expr(Schema.from_pandas(df))
        if intdiv is not None:
            (av, avalid, aidx) = host_unary_values(
                intdiv.children[0].eval_host(df))
            (bv, bvalid, _bidx) = host_unary_values(
                intdiv.children[1].eval_host(df))
            a = av.astype(np.int64)
            b = bv.astype(np.int64)
            zero = b == 0
            safe = np.where(zero, 1, b)
            q = (np.floor_divide(a, safe) if self.fname == "floor"
                 else -np.floor_divide(-a, safe))
            return rebuild_series(q, avalid & bvalid & ~zero,
                                  dtypes.INT64, aidx)
        values, validity, index = host_unary_values(self.children[0].eval_host(df))
        fn = np.floor if self.fname == "floor" else np.ceil
        with np.errstate(all="ignore"):
            data = fn(values.astype(np.float64)).astype(np.int64)
        return rebuild_series(data, validity, dtypes.INT64, index)


class Ceil(Floor):
    fname = "ceil"


class Pow(Expression):
    def __init__(self, left: Expression, right: Expression):
        super().__init__([left, right])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.FLOAT64

    def sql_name(self, schema=None) -> str:
        return (f"pow({self.children[0].sql_name(schema)}, "
                f"{self.children[1].sql_name(schema)})")

    def eval_device(self, ctx: EvalContext) -> DevValue:
        lv = ctx.broadcast(self.children[0].eval_device(ctx))
        rv = ctx.broadcast(self.children[1].eval_device(ctx))
        data = jnp.power(lv.data.astype(jnp.float64),
                         rv.data.astype(jnp.float64))
        return DevCol(dtypes.FLOAT64, data, lv.validity & rv.validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        a, av, index = host_unary_values(self.children[0].eval_host(df))
        b, bv, _ = host_unary_values(self.children[1].eval_host(df))
        with np.errstate(all="ignore"):
            data = np.power(a.astype(np.float64), b.astype(np.float64))
        return rebuild_series(data, av & bv, dtypes.FLOAT64, index)


class Atan2(Expression):
    def __init__(self, left: Expression, right: Expression):
        super().__init__([left, right])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.FLOAT64

    def sql_name(self, schema=None) -> str:
        return (f"atan2({self.children[0].sql_name(schema)}, "
                f"{self.children[1].sql_name(schema)})")

    def eval_device(self, ctx: EvalContext) -> DevValue:
        lv = ctx.broadcast(self.children[0].eval_device(ctx))
        rv = ctx.broadcast(self.children[1].eval_device(ctx))
        data = jnp.arctan2(lv.data.astype(jnp.float64),
                           rv.data.astype(jnp.float64))
        return DevCol(dtypes.FLOAT64, data, lv.validity & rv.validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        a, av, index = host_unary_values(self.children[0].eval_host(df))
        b, bv, _ = host_unary_values(self.children[1].eval_host(df))
        with np.errstate(all="ignore"):
            data = np.arctan2(a.astype(np.float64), b.astype(np.float64))
        return rebuild_series(data, av & bv, dtypes.FLOAT64, index)


class Hypot(Expression):
    def __init__(self, left: Expression, right: Expression):
        super().__init__([left, right])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.FLOAT64

    def sql_name(self, schema=None) -> str:
        return (f"hypot({self.children[0].sql_name(schema)}, "
                f"{self.children[1].sql_name(schema)})")

    def eval_device(self, ctx: EvalContext) -> DevValue:
        lv = ctx.broadcast(self.children[0].eval_device(ctx))
        rv = ctx.broadcast(self.children[1].eval_device(ctx))
        data = jnp.hypot(lv.data.astype(jnp.float64),
                         rv.data.astype(jnp.float64))
        return DevCol(dtypes.FLOAT64, data, lv.validity & rv.validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        a, av, index = host_unary_values(self.children[0].eval_host(df))
        b, bv, _ = host_unary_values(self.children[1].eval_host(df))
        with np.errstate(all="ignore"):
            data = np.hypot(a.astype(np.float64), b.astype(np.float64))
        return rebuild_series(data, av & bv, dtypes.FLOAT64, index)


class Round(Expression):
    """round(x, scale) with Spark/Java HALF_UP semantics (numpy/XLA rint is
    HALF_EVEN, so the kernel is sign(x) * floor(|x| * 10^s + 0.5) / 10^s)."""

    def __init__(self, child: Expression, scale: int = 0):
        super().__init__([child])
        self.scale = int(scale)

    def dtype(self, schema: Schema) -> DType:
        t = self.children[0].dtype(schema)
        if t.is_integral and self.scale >= 0:
            return t
        return dtypes.FLOAT64

    def sql_name(self, schema=None) -> str:
        return f"round({self.children[0].sql_name(schema)}, {self.scale})"

    def device_supported(self, schema: Schema) -> Optional[str]:
        if self.children[0].dtype(schema).is_string:
            return "string input"
        return None

    def _compute(self, xp, x, integral: bool):
        if integral and self.scale >= 0:
            return x
        p = float(10.0 ** self.scale)
        y = xp.floor(xp.abs(x.astype(np.float64)) * p + 0.5) / p
        out = xp.where(x < 0, -y, y)
        return out

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = ctx.broadcast(self.children[0].eval_device(ctx))
        integral = v.dtype.is_integral
        out = self._compute(jnp, v.data, integral)
        dt = v.dtype if (integral and self.scale >= 0) else dtypes.FLOAT64
        return DevCol(dt, out.astype(dt.np_dtype), v.validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        s = self.children[0].eval_host(df)
        values, validity, index = host_unary_values(s)
        from spark_rapids_tpu.sql.exprs.hostutil import series_dtype
        integral = series_dtype(s).is_integral
        with np.errstate(all="ignore"):
            out = self._compute(np, values, integral)
        dt = series_dtype(s) if (integral and self.scale >= 0) \
            else dtypes.FLOAT64
        return rebuild_series(np.asarray(out).astype(dt.np_dtype), validity,
                              dt, index)


class BRound(Round):
    """bround(x, scale): HALF_EVEN (banker's) rounding — numpy/XLA rint IS
    half-even, so the kernel is rint(x * 10^s) / 10^s (reference:
    GpuBRound in GpuOverrides round rules)."""

    def sql_name(self, schema=None) -> str:
        return f"bround({self.children[0].sql_name(schema)}, {self.scale})"

    def _compute(self, xp, x, integral: bool):
        if integral and self.scale >= 0:
            return x
        p = float(10.0 ** self.scale)
        return xp.rint(x.astype(np.float64) * p) / p
