"""Predicates and boolean logic (reference: sql/rapids/predicates.scala,
621 LoC): comparisons, Kleene AND/OR, NOT, IsNull/IsNotNull/IsNan, In/InSet.

SQL three-valued logic is computed explicitly on (data, validity) pairs with
one shared formula for both the host and device paths.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax.numpy as jnp
import numpy as np
import pandas as pd

from spark_rapids_tpu.columnar import dtypes
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.columnar.dtype import DType, common_type
from spark_rapids_tpu.sql.exprs.core import (
    DevCol, DevScalar, DevValue, EvalContext, Expression, data_of, valid_and,
)
from spark_rapids_tpu.sql.exprs.hostutil import (
    host_binary_values, host_unary_values, rebuild_series,
)


class BinaryComparison(Expression):
    symbol = "?"

    def __init__(self, left: Expression, right: Expression):
        super().__init__([left, right])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.BOOL

    def sql_name(self, schema=None) -> str:
        return (f"({self.children[0].sql_name(schema)} {self.symbol} "
                f"{self.children[1].sql_name(schema)})")

    def compute(self, xp, a, b):
        raise NotImplementedError

    def eval_device(self, ctx: EvalContext) -> DevValue:
        lv = self.children[0].eval_device(ctx)
        rv = self.children[1].eval_device(ctx)
        if lv.dtype.is_string or rv.dtype.is_string:
            return self._eval_device_string(ctx, lv, rv)
        ct = common_type(lv.dtype, rv.dtype) if lv.dtype != rv.dtype else lv.dtype
        a = _promote(ctx, lv, ct)
        b = _promote(ctx, rv, ct)
        data = self.compute(jnp, a, b)
        return DevCol(dtypes.BOOL, data, valid_and(ctx, lv, rv))

    def _eval_device_string(self, ctx: EvalContext, lv, rv) -> DevValue:
        from spark_rapids_tpu.ops import strings as string_ops
        if isinstance(self, (Eq, Neq)):
            eq, validity = string_ops.string_equal(ctx, lv, rv)
            data = eq if isinstance(self, Eq) else ~eq
            return DevCol(dtypes.BOOL, data, validity)
        cmp, validity = string_ops.string_compare(ctx, lv, rv)
        zero = jnp.int8(0)
        data = self.compute(jnp, cmp, zero)
        return DevCol(dtypes.BOOL, data, validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        ls = self.children[0].eval_host(df)
        rs = self.children[1].eval_host(df)
        (a, b), validity, index = host_binary_values(ls, rs)
        if a.dtype == object or b.dtype == object:  # strings
            a = np.asarray(a, dtype=object)
            b = np.asarray(b, dtype=object)
            if isinstance(self, Eq):
                data = np.array([x == y for x, y in zip(a, b)], dtype=np.bool_)
            elif isinstance(self, Neq):
                data = np.array([x != y for x, y in zip(a, b)], dtype=np.bool_)
            else:
                fill = ""
                a2 = np.where(validity, a, fill)
                b2 = np.where(validity, b, fill)
                data = np.array(
                    [self.compute(np, x, y) for x, y in zip(a2, b2)],
                    dtype=np.bool_)
        else:
            ct = common_type(dtypes.from_numpy(a.dtype), dtypes.from_numpy(b.dtype))
            data = self.compute(np, a.astype(ct.np_dtype), b.astype(ct.np_dtype))
        return rebuild_series(data, validity, dtypes.BOOL, index)


class Eq(BinaryComparison):
    symbol = "="
    def compute(self, xp, a, b): return a == b


class Neq(BinaryComparison):
    symbol = "!="
    def compute(self, xp, a, b): return a != b


class Lt(BinaryComparison):
    symbol = "<"
    def compute(self, xp, a, b): return a < b


class Le(BinaryComparison):
    symbol = "<="
    def compute(self, xp, a, b): return a <= b


class Gt(BinaryComparison):
    symbol = ">"
    def compute(self, xp, a, b): return a > b


class Ge(BinaryComparison):
    symbol = ">="
    def compute(self, xp, a, b): return a >= b


class EqNullSafe(BinaryComparison):
    """<=> : never NULL; NULL <=> NULL is TRUE."""
    symbol = "<=>"

    def compute(self, xp, a, b): return a == b

    def eval_device(self, ctx: EvalContext) -> DevValue:
        lv = self.children[0].eval_device(ctx)
        rv = self.children[1].eval_device(ctx)
        if lv.dtype.is_string or rv.dtype.is_string:
            from spark_rapids_tpu.ops import strings as string_ops
            eq, validity = string_ops.string_equal(ctx, lv, rv)
            lval = _validity_vec(ctx, lv)
            rval = _validity_vec(ctx, rv)
            data = (lval & rval & eq) | (~lval & ~rval)
            return DevCol(dtypes.BOOL, data,
                          jnp.ones((ctx.capacity,), dtype=jnp.bool_))
        ct = common_type(lv.dtype, rv.dtype) if lv.dtype != rv.dtype else lv.dtype
        a = data_of(ctx, lv).astype(ct.np_dtype)
        b = data_of(ctx, rv).astype(ct.np_dtype)
        lval = _validity_vec(ctx, lv)
        rval = _validity_vec(ctx, rv)
        data = (lval & rval & (a == b)) | (~lval & ~rval)
        return DevCol(dtypes.BOOL, data,
                      jnp.ones((ctx.capacity,), dtype=jnp.bool_))

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        ls = self.children[0].eval_host(df)
        rs = self.children[1].eval_host(df)
        av, amask, index = host_unary_values(ls)
        bv, bmask, _ = host_unary_values(rs)
        if av.dtype == object or bv.dtype == object:
            eq = np.array([x == y for x, y in zip(av, bv)], dtype=np.bool_)
        else:
            ct = common_type(dtypes.from_numpy(av.dtype),
                             dtypes.from_numpy(bv.dtype))
            eq = av.astype(ct.np_dtype) == bv.astype(ct.np_dtype)
        data = (amask & bmask & eq) | (~amask & ~bmask)
        return rebuild_series(data, np.ones(len(data), np.bool_), dtypes.BOOL,
                              index)


def _promote(ctx: EvalContext, v: DevValue, ct):
    """Raw data promoted to the common type, scaling date->timestamp
    properly via the cast matrix."""
    from spark_rapids_tpu.sql.exprs.cast import cast_data
    data = data_of(ctx, v)
    if v.dtype == ct:
        return data
    out, _ = cast_data(jnp, data, v.dtype, ct)
    return out


def _validity_vec(ctx: EvalContext, v: DevValue):
    if isinstance(v, DevScalar):
        return jnp.full((ctx.capacity,), v.valid, dtype=jnp.bool_)
    return v.validity


class And(Expression):
    """Kleene AND: FALSE AND NULL = FALSE."""

    def __init__(self, left: Expression, right: Expression):
        super().__init__([left, right])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.BOOL

    def sql_name(self, schema=None) -> str:
        return (f"({self.children[0].sql_name(schema)} AND "
                f"{self.children[1].sql_name(schema)})")

    def eval_device(self, ctx: EvalContext) -> DevValue:
        lv = ctx.broadcast(self.children[0].eval_device(ctx))
        rv = ctx.broadcast(self.children[1].eval_device(ctx))
        a, av = lv.data, lv.validity
        b, bv = rv.data, rv.validity
        # invalid slots hold False so a&b is correct whenever result is valid
        data = a & b
        validity = (av & bv) | (av & ~a) | (bv & ~b)
        return DevCol(dtypes.BOOL, data, validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        a, av, index = host_unary_values(self.children[0].eval_host(df))
        b, bv, _ = host_unary_values(self.children[1].eval_host(df))
        a = a.astype(np.bool_) & av  # canonicalize null slots to False
        b = b.astype(np.bool_) & bv
        data = a & b
        validity = (av & bv) | (av & ~a) | (bv & ~b)
        return rebuild_series(data, validity, dtypes.BOOL, index)


class Or(Expression):
    """Kleene OR: TRUE OR NULL = TRUE."""

    def __init__(self, left: Expression, right: Expression):
        super().__init__([left, right])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.BOOL

    def sql_name(self, schema=None) -> str:
        return (f"({self.children[0].sql_name(schema)} OR "
                f"{self.children[1].sql_name(schema)})")

    def eval_device(self, ctx: EvalContext) -> DevValue:
        lv = ctx.broadcast(self.children[0].eval_device(ctx))
        rv = ctx.broadcast(self.children[1].eval_device(ctx))
        a, av = lv.data, lv.validity
        b, bv = rv.data, rv.validity
        data = a | b
        validity = (av & bv) | (av & a) | (bv & b)
        return DevCol(dtypes.BOOL, data, validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        a, av, index = host_unary_values(self.children[0].eval_host(df))
        b, bv, _ = host_unary_values(self.children[1].eval_host(df))
        a = a.astype(np.bool_) & av
        b = b.astype(np.bool_) & bv
        data = a | b
        validity = (av & bv) | (av & a) | (bv & b)
        return rebuild_series(data, validity, dtypes.BOOL, index)


class Not(Expression):
    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.BOOL

    def sql_name(self, schema=None) -> str:
        return f"(NOT {self.children[0].sql_name(schema)})"

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = ctx.broadcast(self.children[0].eval_device(ctx))
        return DevCol(dtypes.BOOL, ~v.data & v.validity, v.validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        a, av, index = host_unary_values(self.children[0].eval_host(df))
        data = ~a.astype(np.bool_) & av
        return rebuild_series(data, av, dtypes.BOOL, index)


class IsNull(Expression):
    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.BOOL

    def sql_name(self, schema=None) -> str:
        return f"({self.children[0].sql_name(schema)} IS NULL)"

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = self.children[0].eval_device(ctx)
        validity = _validity_vec(ctx, v)
        return DevCol(dtypes.BOOL, ~validity,
                      jnp.ones((ctx.capacity,), dtype=jnp.bool_))

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        _, validity, index = host_unary_values(self.children[0].eval_host(df))
        return pd.Series(~validity, index=index)


class IsNotNull(Expression):
    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.BOOL

    def sql_name(self, schema=None) -> str:
        return f"({self.children[0].sql_name(schema)} IS NOT NULL)"

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = self.children[0].eval_device(ctx)
        validity = _validity_vec(ctx, v)
        return DevCol(dtypes.BOOL, validity,
                      jnp.ones((ctx.capacity,), dtype=jnp.bool_))

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        _, validity, index = host_unary_values(self.children[0].eval_host(df))
        return pd.Series(validity.copy(), index=index)


class IsNan(Expression):
    """Spark IsNaN is never NULL: isnan(NULL) = false."""

    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.BOOL

    def sql_name(self, schema=None) -> str:
        return f"isnan({self.children[0].sql_name(schema)})"

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = ctx.broadcast(self.children[0].eval_device(ctx))
        data = jnp.isnan(v.data) & v.validity
        return DevCol(dtypes.BOOL, data,
                      jnp.ones((ctx.capacity,), dtype=jnp.bool_))

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        a, av, index = host_unary_values(self.children[0].eval_host(df))
        data = np.isnan(a.astype(np.float64)) & av
        return pd.Series(data, index=index)


class In(Expression):
    """value IN (<literals>). NULL value -> NULL; a NULL in the list turns
    non-matches into NULL (SQL semantics)."""

    def __init__(self, child: Expression, values: Sequence):
        super().__init__([child])
        self.values: List = list(values)

    def dtype(self, schema: Schema) -> DType:
        return dtypes.BOOL

    def sql_name(self, schema=None) -> str:
        return f"({self.children[0].sql_name(schema)} IN {tuple(self.values)})"

    def device_supported(self, schema: Schema) -> Optional[str]:
        return None

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = self.children[0].eval_device(ctx)
        has_null_lit = any(x is None for x in self.values)
        vals = [x for x in self.values if x is not None]
        if isinstance(v, DevScalar):
            v = ctx.broadcast(v)
        if v.dtype.is_string:
            from spark_rapids_tpu.ops import strings as string_ops
            match = jnp.zeros((ctx.capacity,), dtype=jnp.bool_)
            for x in vals:
                eq, _ = string_ops.string_equal_literal(ctx, v, str(x))
                match = match | eq
        else:
            match = jnp.zeros((ctx.capacity,), dtype=jnp.bool_)
            for x in vals:
                match = match | (v.data == jnp.asarray(x, dtype=v.dtype.np_dtype))
        validity = v.validity
        if has_null_lit:
            validity = validity & match  # non-match becomes NULL
        return DevCol(dtypes.BOOL, match & v.validity, validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        a, av, index = host_unary_values(self.children[0].eval_host(df))
        has_null_lit = any(x is None for x in self.values)
        vals = [x for x in self.values if x is not None]
        if a.dtype == object:
            match = np.array([x in vals for x in a], dtype=np.bool_)
        else:
            match = np.isin(a, np.asarray(vals, dtype=a.dtype))
        validity = av & match if has_null_lit else av
        return rebuild_series(match & av, validity, dtypes.BOOL, index)
