"""String expressions (reference: sql/rapids/stringFunctions.scala, 698 LoC).

Device kernels live in ops/strings.py. Like the reference, complex regex is
restricted: LIKE patterns that reduce to prefix/suffix/contains run on
device, anything else tags the plan off (GpuOverrides.scala:334-379 applies
the same restriction)."""

from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp
import numpy as np
import pandas as pd

from spark_rapids_tpu.columnar import dtypes
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.columnar.dtype import DType
from spark_rapids_tpu.ops import strings as string_ops
from spark_rapids_tpu.sql.exprs.core import (
    DevCol, DevScalar, DevValue, EvalContext, Expression, Literal,
)
from spark_rapids_tpu.sql.exprs.hostutil import host_unary_values, rebuild_series


class StringLength(Expression):
    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.INT32

    def sql_name(self, schema=None) -> str:
        return f"length({self.children[0].sql_name(schema)})"

    def device_supported(self, schema: Schema) -> Optional[str]:
        # byte-length == char-length only for ASCII; see ops/strings.py note
        return None

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = self.children[0].eval_device(ctx)
        assert isinstance(v, DevCol)
        return DevCol(dtypes.INT32, string_ops.lengths_of(v), v.validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        values, validity, index = host_unary_values(self.children[0].eval_host(df))
        data = np.array([len(x.encode("utf-8")) if x is not None else 0
                         for x in values], dtype=np.int32)
        return rebuild_series(data, validity, dtypes.INT32, index)


class _CaseMap(Expression):
    upper = True

    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.STRING

    def sql_name(self, schema=None) -> str:
        fn = "upper" if self.upper else "lower"
        return f"{fn}({self.children[0].sql_name(schema)})"

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = self.children[0].eval_device(ctx)
        assert isinstance(v, DevCol)
        return (string_ops.upper_ascii(v) if self.upper
                else string_ops.lower_ascii(v))

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        values, validity, index = host_unary_values(self.children[0].eval_host(df))
        # ASCII-only to match the device kernel
        fn = str.upper if self.upper else str.lower
        data = np.array([_ascii_case(x, self.upper) if x is not None else None
                         for x in values], dtype=object)
        return rebuild_series(data, validity, dtypes.STRING, index)


def _ascii_case(s: str, upper: bool) -> str:
    out = []
    for ch in s:
        o = ord(ch)
        if upper and 97 <= o <= 122:
            out.append(chr(o - 32))
        elif not upper and 65 <= o <= 90:
            out.append(chr(o + 32))
        else:
            out.append(ch)
    return "".join(out)


class Upper(_CaseMap):
    upper = True


class Lower(_CaseMap):
    upper = False


class Substring(Expression):
    def __init__(self, child: Expression, pos: int, length: int = -1):
        super().__init__([child])
        self.pos = pos
        self.length = length

    def dtype(self, schema: Schema) -> DType:
        return dtypes.STRING

    def sql_name(self, schema=None) -> str:
        return (f"substring({self.children[0].sql_name(schema)}, {self.pos}, "
                f"{self.length})")

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = self.children[0].eval_device(ctx)
        assert isinstance(v, DevCol)
        return string_ops.substring(ctx, v, self.pos, self.length)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        values, validity, index = host_unary_values(self.children[0].eval_host(df))
        out = np.empty(len(values), dtype=object)
        for i, x in enumerate(values):
            if x is None:
                out[i] = None
                continue
            b = x.encode("utf-8")
            if self.pos > 0:
                start = self.pos - 1
            elif self.pos == 0:
                start = 0
            else:
                start = max(len(b) + self.pos, 0)
            end = len(b) if self.length < 0 else min(start + self.length, len(b))
            out[i] = b[start:end].decode("utf-8", errors="replace")
        return rebuild_series(out, validity, dtypes.STRING, index)


class _LiteralPatternPredicate(Expression):
    """Base for startswith/endswith/contains with a literal pattern."""
    fn_name = "?"

    def __init__(self, child: Expression, pattern: str):
        super().__init__([child])
        self.pattern = pattern

    def dtype(self, schema: Schema) -> DType:
        return dtypes.BOOL

    def sql_name(self, schema=None) -> str:
        return f"{self.fn_name}({self.children[0].sql_name(schema)}, {self.pattern!r})"

    def device_kernel(self, ctx, col):
        raise NotImplementedError

    def host_match(self, s: str) -> bool:
        raise NotImplementedError

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = self.children[0].eval_device(ctx)
        assert isinstance(v, DevCol)
        data, validity = self.device_kernel(ctx, v)
        return DevCol(dtypes.BOOL, data, validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        values, validity, index = host_unary_values(self.children[0].eval_host(df))
        data = np.array([self.host_match(x) if x is not None else False
                         for x in values], dtype=np.bool_)
        return rebuild_series(data, validity, dtypes.BOOL, index)


class StartsWith(_LiteralPatternPredicate):
    fn_name = "startswith"
    def device_kernel(self, ctx, col):
        return string_ops.starts_with(ctx, col, self.pattern)
    def host_match(self, s: str) -> bool:
        return s.startswith(self.pattern)


class EndsWith(_LiteralPatternPredicate):
    fn_name = "endswith"
    def device_kernel(self, ctx, col):
        return string_ops.ends_with(ctx, col, self.pattern)
    def host_match(self, s: str) -> bool:
        return s.endswith(self.pattern)


class Contains(_LiteralPatternPredicate):
    fn_name = "contains"
    def device_kernel(self, ctx, col):
        return string_ops.contains(ctx, col, self.pattern)
    def host_match(self, s: str) -> bool:
        return self.pattern in s


class Like(Expression):
    """SQL LIKE with literal pattern. Patterns reducible to
    prefix/suffix/contains/exact run on device; others tag off (the
    reference restricts regex the same way, GpuOverrides.scala:334-379)."""

    def __init__(self, child: Expression, pattern: str):
        super().__init__([child])
        self.pattern = pattern
        self._kind, self._needle = _classify_like(pattern)

    def dtype(self, schema: Schema) -> DType:
        return dtypes.BOOL

    def sql_name(self, schema=None) -> str:
        return f"({self.children[0].sql_name(schema)} LIKE {self.pattern!r})"

    def device_supported(self, schema: Schema) -> Optional[str]:
        if self._kind is None:
            return (f"LIKE pattern {self.pattern!r} needs general regex, "
                    "which is not supported on TPU")
        return None

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = self.children[0].eval_device(ctx)
        assert isinstance(v, DevCol)
        if self._kind == "exact":
            data, validity = string_ops.string_equal_literal(ctx, v, self._needle)
        elif self._kind == "prefix":
            data, validity = string_ops.starts_with(ctx, v, self._needle)
        elif self._kind == "suffix":
            data, validity = string_ops.ends_with(ctx, v, self._needle)
        elif self._kind == "contains":
            data, validity = string_ops.contains(ctx, v, self._needle)
        else:
            raise RuntimeError(self._kind)
        return DevCol(dtypes.BOOL, data, validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        import re
        regex = re.compile(_like_to_regex(self.pattern), re.DOTALL)
        values, validity, index = host_unary_values(self.children[0].eval_host(df))
        data = np.array([bool(regex.fullmatch(x)) if x is not None else False
                         for x in values], dtype=np.bool_)
        return rebuild_series(data, validity, dtypes.BOOL, index)


def _classify_like(p: str):
    """Map a LIKE pattern to (kind, needle) if it avoids general regex."""
    if "_" in p:
        return None, None
    body = p.strip("%")
    if "%" in body:
        return None, None  # interior wildcard
    starts = p.startswith("%")
    ends = p.endswith("%")
    if starts and ends:
        return "contains", body
    if ends:
        return "prefix", body
    if starts:
        return "suffix", body
    return "exact", body


def _like_to_regex(p: str) -> str:
    import re
    out = []
    for ch in p:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return "".join(out)


class ConcatStrings(Expression):
    def __init__(self, children: List[Expression]):
        super().__init__(children)

    def dtype(self, schema: Schema) -> DType:
        return dtypes.STRING

    def sql_name(self, schema=None) -> str:
        return f"concat({', '.join(c.sql_name(schema) for c in self.children)})"

    def eval_device(self, ctx: EvalContext) -> DevValue:
        cols = [ctx.broadcast(c.eval_device(ctx)) for c in self.children]
        return string_ops.concat_columns(ctx, cols)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        parts = [host_unary_values(c.eval_host(df)) for c in self.children]
        n = len(df)
        validity = parts[0][1].copy()
        for _, v, _ in parts[1:]:
            validity &= v
        out = np.empty(n, dtype=object)
        for i in range(n):
            if validity[i]:
                out[i] = "".join(p[0][i] for p in parts)
            else:
                out[i] = None
        return rebuild_series(out, validity, dtypes.STRING, parts[0][2])


class _TrimBase(Expression):
    """trim/ltrim/rtrim with an optional literal trim-char set."""
    fn_name = "trim"
    left = True
    right = True

    def __init__(self, child: Expression, chars: Optional[str] = None):
        super().__init__([child])
        # Spark's trim/ltrim/rtrim strip only the space character
        self.chars = chars if chars is not None else " "

    def dtype(self, schema: Schema) -> DType:
        return dtypes.STRING

    def sql_name(self, schema=None) -> str:
        return f"{self.fn_name}({self.children[0].sql_name(schema)})"

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = self.children[0].eval_device(ctx)
        assert isinstance(v, DevCol)
        return string_ops.trim(ctx, v, self.chars, self.left, self.right)

    def _host_one(self, s: str) -> str:
        if self.left and self.right:
            return s.strip(self.chars)
        if self.left:
            return s.lstrip(self.chars)
        return s.rstrip(self.chars)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        values, validity, index = host_unary_values(self.children[0].eval_host(df))
        out = np.array([self._host_one(x) if x is not None else None
                        for x in values], dtype=object)
        return rebuild_series(out, validity, dtypes.STRING, index)


class Trim(_TrimBase):
    fn_name, left, right = "trim", True, True


class LTrim(_TrimBase):
    fn_name, left, right = "ltrim", True, False


class RTrim(_TrimBase):
    fn_name, left, right = "rtrim", False, True


class _PadBase(Expression):
    fn_name = "lpad"
    left = True

    def __init__(self, child: Expression, n: int, pad: str = " "):
        super().__init__([child])
        self.n = int(n)
        self.pad = pad or " "

    def dtype(self, schema: Schema) -> DType:
        return dtypes.STRING

    def sql_name(self, schema=None) -> str:
        return f"{self.fn_name}({self.children[0].sql_name(schema)}, {self.n})"

    def device_supported(self, schema: Schema) -> Optional[str]:
        if len(self.pad.encode("utf-8")) != 1:
            return "only single-byte pad characters run on TPU"
        return None

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = self.children[0].eval_device(ctx)
        assert isinstance(v, DevCol)
        return string_ops.pad(ctx, v, self.n, self.pad, self.left)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        values, validity, index = host_unary_values(self.children[0].eval_host(df))
        out = np.empty(len(values), dtype=object)
        for i, x in enumerate(values):
            if x is None:
                out[i] = None
            elif len(x) >= self.n:
                out[i] = x[:self.n]
            elif self.left:
                out[i] = self.pad * (self.n - len(x)) + x
            else:
                out[i] = x + self.pad * (self.n - len(x))
        return rebuild_series(out, validity, dtypes.STRING, index)


class LPad(_PadBase):
    fn_name, left = "lpad", True


class RPad(_PadBase):
    fn_name, left = "rpad", False


class StringLocate(Expression):
    """locate(substr, str, pos) / instr(str, substr): 1-based, 0 = absent."""

    def __init__(self, child: Expression, substr: str, start_pos: int = 1):
        super().__init__([child])
        self.substr = substr
        self.start_pos = int(start_pos)

    def dtype(self, schema: Schema) -> DType:
        return dtypes.INT32

    def sql_name(self, schema=None) -> str:
        return (f"locate({self.substr!r}, "
                f"{self.children[0].sql_name(schema)}, {self.start_pos})")

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = self.children[0].eval_device(ctx)
        assert isinstance(v, DevCol)
        return DevCol(dtypes.INT32,
                      string_ops.locate(ctx, v, self.substr, self.start_pos),
                      v.validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        values, validity, index = host_unary_values(self.children[0].eval_host(df))
        out = np.zeros(len(values), dtype=np.int32)
        for i, x in enumerate(values):
            if x is None:
                continue
            out[i] = x.find(self.substr, self.start_pos - 1) + 1
        return rebuild_series(out, validity, dtypes.INT32, index)


class StringReplace(Expression):
    """replace(str, search, replacement) with literal arguments."""

    def __init__(self, child: Expression, search: str, replacement: str):
        super().__init__([child])
        self.search = search
        self.replacement = replacement

    def dtype(self, schema: Schema) -> DType:
        return dtypes.STRING

    def sql_name(self, schema=None) -> str:
        return (f"replace({self.children[0].sql_name(schema)}, "
                f"{self.search!r}, {self.replacement!r})")

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = self.children[0].eval_device(ctx)
        assert isinstance(v, DevCol)
        return string_ops.replace_literal(ctx, v, self.search,
                                          self.replacement)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        values, validity, index = host_unary_values(self.children[0].eval_host(df))
        out = np.array([x.replace(self.search, self.replacement)
                        if x is not None else None
                        for x in values], dtype=object)
        return rebuild_series(out, validity, dtypes.STRING, index)


class InitCap(Expression):
    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.STRING

    def sql_name(self, schema=None) -> str:
        return f"initcap({self.children[0].sql_name(schema)})"

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = self.children[0].eval_device(ctx)
        assert isinstance(v, DevCol)
        return string_ops.initcap_ascii(v)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        values, validity, index = host_unary_values(self.children[0].eval_host(df))

        def one(s):
            out = []
            prev_space = True
            for ch in s:
                o = ord(ch)
                if prev_space and 97 <= o <= 122:
                    out.append(chr(o - 32))
                elif not prev_space and 65 <= o <= 90:
                    out.append(chr(o + 32))
                else:
                    out.append(ch)
                prev_space = ch == " "
            return "".join(out)
        out = np.array([one(x) if x is not None else None for x in values],
                       dtype=object)
        return rebuild_series(out, validity, dtypes.STRING, index)


class RegexpReplace(Expression):
    """regexp_replace: general regex stays on the CPU (the reference also
    restricts the regex dialect, GpuOverrides.scala:334-379); literal
    patterns collapse to StringReplace during planning via
    maybe_literal_regex()."""

    def __init__(self, child: Expression, pattern: str, replacement: str):
        super().__init__([child])
        self.pattern = pattern
        self.replacement = replacement

    def dtype(self, schema: Schema) -> DType:
        return dtypes.STRING

    def sql_name(self, schema=None) -> str:
        return (f"regexp_replace({self.children[0].sql_name(schema)}, "
                f"{self.pattern!r})")

    def device_supported(self, schema: Schema) -> Optional[str]:
        return (f"regular expression {self.pattern!r} is not supported on "
                "TPU (only literal patterns run on device)")

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        import re
        rx = re.compile(self.pattern)
        values, validity, index = host_unary_values(self.children[0].eval_host(df))
        out = np.array([rx.sub(self.replacement, x) if x is not None else None
                        for x in values], dtype=object)
        return rebuild_series(out, validity, dtypes.STRING, index)


_REGEX_META = set("\\^$.|?*+()[]{}")


def maybe_literal_regex(pattern: str) -> Optional[str]:
    """If a regex pattern contains no metacharacters it is a plain literal."""
    if any(ch in _REGEX_META for ch in pattern):
        return None
    return pattern


def make_regexp_replace(child: Expression, pattern: str,
                        replacement: str) -> Expression:
    lit = maybe_literal_regex(pattern)
    if lit is not None and "$" not in replacement:
        return StringReplace(child, lit, replacement)
    return RegexpReplace(child, pattern, replacement)


class ConcatWs(Expression):
    """concat_ws(sep, s1, s2, ...): joins NON-NULL parts; never NULL
    (reference: GpuConcatWs, GpuOverrides string rules)."""

    def __init__(self, sep: str, children: List[Expression]):
        super().__init__(children)
        self.sep = str(sep)

    def dtype(self, schema: Schema) -> DType:
        return dtypes.STRING

    def sql_name(self, schema=None) -> str:
        parts = ", ".join(c.sql_name(schema) for c in self.children)
        return f"concat_ws({self.sep!r}, {parts})"

    def device_supported(self, schema: Schema) -> Optional[str]:
        if not self.children:
            return "concat_ws with no arguments"
        for c in self.children:
            if not c.dtype(schema).is_string:
                return "concat_ws over non-string inputs"
        return None

    def eval_device(self, ctx: EvalContext) -> DevValue:
        cols = [ctx.broadcast(c.eval_device(ctx)) for c in self.children]
        return string_ops.concat_ws_columns(ctx, self.sep, cols)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        n = len(df)
        if not self.children:
            return rebuild_series(np.full(n, "", dtype=object),
                                  np.ones(n, np.bool_), dtypes.STRING,
                                  df.index)
        parts = [host_unary_values(c.eval_host(df)) for c in self.children]
        out = np.empty(n, dtype=object)
        for i in range(n):
            vals = [str(p[0][i]) for p in parts if p[1][i]]
            out[i] = self.sep.join(vals)
        return rebuild_series(out, np.ones(n, np.bool_), dtypes.STRING,
                              parts[0][2])


class Translate(Expression):
    """translate(str, matching, replace) with literal maps."""

    def __init__(self, child: Expression, matching: str, replace: str):
        super().__init__([child])
        self.matching = str(matching)
        self.replace = str(replace)

    def dtype(self, schema: Schema) -> DType:
        return dtypes.STRING

    def sql_name(self, schema=None) -> str:
        return (f"translate({self.children[0].sql_name(schema)}, "
                f"{self.matching!r}, {self.replace!r})")

    def device_supported(self, schema: Schema) -> Optional[str]:
        if any(ord(c) > 127 for c in self.matching + self.replace):
            return "translate with non-ASCII map is not supported on TPU"
        return None

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = self.children[0].eval_device(ctx)
        col = ctx.broadcast(v)
        return string_ops.translate_string(ctx, col, self.matching,
                                           self.replace)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        values, validity, index = host_unary_values(
            self.children[0].eval_host(df))
        table = {ord(m): (self.replace[i] if i < len(self.replace) else None)
                 for i, m in enumerate(self.matching)}
        out = np.empty(len(values), dtype=object)
        for i, s in enumerate(values):
            out[i] = s.translate(table) if validity[i] else None
        return rebuild_series(out, validity, dtypes.STRING, index)


class StringReverse(Expression):
    """BYTE-oriented reverse, exact for ASCII (the framework's string
    kernels are byte-indexed, see ops/strings.py); multi-byte UTF-8 input
    reverses bytes, not codepoints — a documented divergence from Spark.
    The host twin mirrors the byte semantics so the differential oracle
    agrees with the device."""

    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.STRING

    def sql_name(self, schema=None) -> str:
        return f"reverse({self.children[0].sql_name(schema)})"

    def eval_device(self, ctx: EvalContext) -> DevValue:
        col = ctx.broadcast(self.children[0].eval_device(ctx))
        return string_ops.reverse_string(ctx, col)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        values, validity, index = host_unary_values(
            self.children[0].eval_host(df))
        out = np.array(
            [s.encode("utf-8")[::-1].decode("utf-8", errors="replace")
             if v else None for s, v in zip(values, validity)],
            dtype=object)
        return rebuild_series(out, validity, dtypes.STRING, index)


class StringRepeat(Expression):
    def __init__(self, child: Expression, n: int):
        super().__init__([child])
        self.n = int(n)

    def dtype(self, schema: Schema) -> DType:
        return dtypes.STRING

    def sql_name(self, schema=None) -> str:
        return f"repeat({self.children[0].sql_name(schema)}, {self.n})"

    def eval_device(self, ctx: EvalContext) -> DevValue:
        col = ctx.broadcast(self.children[0].eval_device(ctx))
        return string_ops.repeat_string(ctx, col, self.n)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        values, validity, index = host_unary_values(
            self.children[0].eval_host(df))
        out = np.array([s * max(self.n, 0) if v else None
                        for s, v in zip(values, validity)], dtype=object)
        return rebuild_series(out, validity, dtypes.STRING, index)


class Ascii(Expression):
    """First BYTE of the UTF-8 encoding, exact for ASCII (byte-indexed
    kernels, see ops/strings.py); for multi-byte leading characters Spark
    returns the codepoint while this returns the lead byte — a documented
    divergence. The host twin mirrors the byte semantics so the
    differential oracle agrees with the device."""

    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.INT32

    def sql_name(self, schema=None) -> str:
        return f"ascii({self.children[0].sql_name(schema)})"

    def eval_device(self, ctx: EvalContext) -> DevValue:
        col = ctx.broadcast(self.children[0].eval_device(ctx))
        return string_ops.ascii_first(ctx, col)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        values, validity, index = host_unary_values(
            self.children[0].eval_host(df))
        out = np.array([(s.encode("utf-8")[0] if s else 0) if v else 0
                        for s, v in zip(values, validity)], dtype=np.int32)
        return rebuild_series(out, validity, dtypes.INT32, index)


class Chr(Expression):
    """chr(n) over the ASCII/byte range (n % 256; negative -> '')."""

    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.STRING

    def sql_name(self, schema=None) -> str:
        return f"char({self.children[0].sql_name(schema)})"

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = self.children[0].eval_device(ctx)
        col = ctx.broadcast(v)
        return string_ops.chr_from_int(ctx, col.data, col.validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        values, validity, index = host_unary_values(
            self.children[0].eval_host(df))
        out = np.empty(len(values), dtype=object)
        for i, (x, v) in enumerate(zip(values, validity)):
            if not v:
                out[i] = None
            elif int(x) < 0:
                out[i] = ""
            else:
                out[i] = chr(int(x) % 256)
        return rebuild_series(out, validity, dtypes.STRING, index)
