"""String expressions (reference: sql/rapids/stringFunctions.scala, 698 LoC).

Device kernels live in ops/strings.py. Like the reference, complex regex is
restricted: LIKE patterns that reduce to prefix/suffix/contains run on
device, anything else tags the plan off (GpuOverrides.scala:334-379 applies
the same restriction)."""

from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp
import numpy as np
import pandas as pd

from spark_rapids_tpu.columnar import dtypes
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.columnar.dtype import DType
from spark_rapids_tpu.ops import strings as string_ops
from spark_rapids_tpu.sql.exprs.core import (
    DevCol, DevScalar, DevValue, EvalContext, Expression, Literal,
)
from spark_rapids_tpu.sql.exprs.hostutil import host_unary_values, rebuild_series


class StringLength(Expression):
    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.INT32

    def sql_name(self, schema=None) -> str:
        return f"length({self.children[0].sql_name(schema)})"

    def device_supported(self, schema: Schema) -> Optional[str]:
        # byte-length == char-length only for ASCII; see ops/strings.py note
        return None

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = self.children[0].eval_device(ctx)
        assert isinstance(v, DevCol)
        return DevCol(dtypes.INT32, string_ops.lengths_of(v), v.validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        values, validity, index = host_unary_values(self.children[0].eval_host(df))
        data = np.array([len(x.encode("utf-8")) if x is not None else 0
                         for x in values], dtype=np.int32)
        return rebuild_series(data, validity, dtypes.INT32, index)


class _CaseMap(Expression):
    upper = True

    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.STRING

    def sql_name(self, schema=None) -> str:
        fn = "upper" if self.upper else "lower"
        return f"{fn}({self.children[0].sql_name(schema)})"

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = self.children[0].eval_device(ctx)
        assert isinstance(v, DevCol)
        return (string_ops.upper_ascii(v) if self.upper
                else string_ops.lower_ascii(v))

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        values, validity, index = host_unary_values(self.children[0].eval_host(df))
        # ASCII-only to match the device kernel
        fn = str.upper if self.upper else str.lower
        data = np.array([_ascii_case(x, self.upper) if x is not None else None
                         for x in values], dtype=object)
        return rebuild_series(data, validity, dtypes.STRING, index)


def _ascii_case(s: str, upper: bool) -> str:
    out = []
    for ch in s:
        o = ord(ch)
        if upper and 97 <= o <= 122:
            out.append(chr(o - 32))
        elif not upper and 65 <= o <= 90:
            out.append(chr(o + 32))
        else:
            out.append(ch)
    return "".join(out)


class Upper(_CaseMap):
    upper = True


class Lower(_CaseMap):
    upper = False


class Substring(Expression):
    def __init__(self, child: Expression, pos: int, length: int = -1):
        super().__init__([child])
        self.pos = pos
        self.length = length

    def dtype(self, schema: Schema) -> DType:
        return dtypes.STRING

    def sql_name(self, schema=None) -> str:
        return (f"substring({self.children[0].sql_name(schema)}, {self.pos}, "
                f"{self.length})")

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = self.children[0].eval_device(ctx)
        assert isinstance(v, DevCol)
        return string_ops.substring(ctx, v, self.pos, self.length)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        values, validity, index = host_unary_values(self.children[0].eval_host(df))
        out = np.empty(len(values), dtype=object)
        for i, x in enumerate(values):
            if x is None:
                out[i] = None
                continue
            b = x.encode("utf-8")
            if self.pos > 0:
                start = self.pos - 1
            elif self.pos == 0:
                start = 0
            else:
                start = max(len(b) + self.pos, 0)
            end = len(b) if self.length < 0 else min(start + self.length, len(b))
            out[i] = b[start:end].decode("utf-8", errors="replace")
        return rebuild_series(out, validity, dtypes.STRING, index)


class _LiteralPatternPredicate(Expression):
    """Base for startswith/endswith/contains with a literal pattern."""
    fn_name = "?"

    def __init__(self, child: Expression, pattern: str):
        super().__init__([child])
        self.pattern = pattern

    def dtype(self, schema: Schema) -> DType:
        return dtypes.BOOL

    def sql_name(self, schema=None) -> str:
        return f"{self.fn_name}({self.children[0].sql_name(schema)}, {self.pattern!r})"

    def device_kernel(self, ctx, col):
        raise NotImplementedError

    def host_match(self, s: str) -> bool:
        raise NotImplementedError

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = self.children[0].eval_device(ctx)
        assert isinstance(v, DevCol)
        data, validity = self.device_kernel(ctx, v)
        return DevCol(dtypes.BOOL, data, validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        values, validity, index = host_unary_values(self.children[0].eval_host(df))
        data = np.array([self.host_match(x) if x is not None else False
                         for x in values], dtype=np.bool_)
        return rebuild_series(data, validity, dtypes.BOOL, index)


class StartsWith(_LiteralPatternPredicate):
    fn_name = "startswith"
    def device_kernel(self, ctx, col):
        return string_ops.starts_with(ctx, col, self.pattern)
    def host_match(self, s: str) -> bool:
        return s.startswith(self.pattern)


class EndsWith(_LiteralPatternPredicate):
    fn_name = "endswith"
    def device_kernel(self, ctx, col):
        return string_ops.ends_with(ctx, col, self.pattern)
    def host_match(self, s: str) -> bool:
        return s.endswith(self.pattern)


class Contains(_LiteralPatternPredicate):
    fn_name = "contains"
    def device_kernel(self, ctx, col):
        return string_ops.contains(ctx, col, self.pattern)
    def host_match(self, s: str) -> bool:
        return self.pattern in s


class Like(Expression):
    """SQL LIKE with literal pattern. Patterns reducible to
    prefix/suffix/contains/exact run on device; others tag off (the
    reference restricts regex the same way, GpuOverrides.scala:334-379)."""

    def __init__(self, child: Expression, pattern: str):
        super().__init__([child])
        self.pattern = pattern
        self._kind, self._needle = _classify_like(pattern)

    def dtype(self, schema: Schema) -> DType:
        return dtypes.BOOL

    def sql_name(self, schema=None) -> str:
        return f"({self.children[0].sql_name(schema)} LIKE {self.pattern!r})"

    def device_supported(self, schema: Schema) -> Optional[str]:
        if self._kind is None:
            return (f"LIKE pattern {self.pattern!r} needs general regex, "
                    "which is not supported on TPU")
        return None

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = self.children[0].eval_device(ctx)
        assert isinstance(v, DevCol)
        if self._kind == "exact":
            data, validity = string_ops.string_equal_literal(ctx, v, self._needle)
        elif self._kind == "prefix":
            data, validity = string_ops.starts_with(ctx, v, self._needle)
        elif self._kind == "suffix":
            data, validity = string_ops.ends_with(ctx, v, self._needle)
        elif self._kind == "contains":
            data, validity = string_ops.contains(ctx, v, self._needle)
        else:
            raise RuntimeError(self._kind)
        return DevCol(dtypes.BOOL, data, validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        import re
        regex = re.compile(_like_to_regex(self.pattern), re.DOTALL)
        values, validity, index = host_unary_values(self.children[0].eval_host(df))
        data = np.array([bool(regex.fullmatch(x)) if x is not None else False
                         for x in values], dtype=np.bool_)
        return rebuild_series(data, validity, dtypes.BOOL, index)


def _classify_like(p: str):
    """Map a LIKE pattern to (kind, needle) if it avoids general regex."""
    if "_" in p:
        return None, None
    body = p.strip("%")
    if "%" in body:
        return None, None  # interior wildcard
    starts = p.startswith("%")
    ends = p.endswith("%")
    if starts and ends:
        return "contains", body
    if ends:
        return "prefix", body
    if starts:
        return "suffix", body
    return "exact", body


def _like_to_regex(p: str) -> str:
    import re
    out = []
    for ch in p:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return "".join(out)


class ConcatStrings(Expression):
    def __init__(self, children: List[Expression]):
        super().__init__(children)

    def dtype(self, schema: Schema) -> DType:
        return dtypes.STRING

    def sql_name(self, schema=None) -> str:
        return f"concat({', '.join(c.sql_name(schema) for c in self.children)})"

    def eval_device(self, ctx: EvalContext) -> DevValue:
        cols = []
        for c in self.children:
            v = c.eval_device(ctx)
            if isinstance(v, DevScalar):
                raise NotImplementedError("concat with scalar operand")
            cols.append(v)
        return string_ops.concat_columns(ctx, cols)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        parts = [host_unary_values(c.eval_host(df)) for c in self.children]
        n = len(df)
        validity = parts[0][1].copy()
        for _, v, _ in parts[1:]:
            validity &= v
        out = np.empty(n, dtype=object)
        for i in range(n):
            if validity[i]:
                out[i] = "".join(p[0][i] for p in parts)
            else:
                out[i] = None
        return rebuild_series(out, validity, dtypes.STRING, parts[0][2])
