"""Nondeterministic expressions (reference:
catalyst/expressions/GpuRandomExpressions.scala:75,
GpuMonotonicallyIncreasingID.scala:75, GpuSparkPartitionID.scala:58,
GpuInputFileBlock.scala:114).

These read task-scoped state (partition index, rows emitted so far, current
input file) from ``exec.taskctx``, so projections containing them are
evaluated *eagerly* per batch rather than through the cached-jit path — the
operator checks ``is_nondeterministic`` and opts out of kernel caching (the
reference similarly forces coalesce-disable around input-file expressions,
GpuTransitionOverrides.scala:110-123).

``Rand`` uses a stateless splitmix64-style counter hash of
(seed, partition, row index) — the identical integer formula on host (numpy)
and device (jax.numpy), so CPU and TPU paths produce bit-equal streams
(unlike the reference, whose GPU rand is documented incompatible with
Spark's XORShift sequence).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np
import pandas as pd

from spark_rapids_tpu.columnar import dtypes
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.columnar.dtype import DType
from spark_rapids_tpu.exec import taskctx
from spark_rapids_tpu.sql.exprs.core import (
    DevCol, DevValue, EvalContext, Expression,
)


def _splitmix64(xp, x):
    """Finalizer of the splitmix64 generator; uint64 in, uint64 out."""
    x = (x + xp.uint64(0x9E3779B97F4A7C15)) & xp.uint64(0xFFFFFFFFFFFFFFFF)
    x = ((x ^ (x >> xp.uint64(30))) * xp.uint64(0xBF58476D1CE4E5B9)) \
        & xp.uint64(0xFFFFFFFFFFFFFFFF)
    x = ((x ^ (x >> xp.uint64(27))) * xp.uint64(0x94D049BB133111EB)) \
        & xp.uint64(0xFFFFFFFFFFFFFFFF)
    return x ^ (x >> xp.uint64(31))


class Rand(Expression):
    """rand(seed): uniform [0, 1) double; stream determined by
    (seed, partition index, row position)."""

    is_nondeterministic = True

    def __init__(self, seed: int = 0):
        super().__init__()
        self.seed = int(seed)

    def dtype(self, schema: Schema) -> DType:
        return dtypes.FLOAT64

    def sql_name(self, schema=None) -> str:
        return f"rand({self.seed})"

    def _uniform(self, xp, idx):
        mixed = _splitmix64(
            xp, idx.astype(xp.uint64)
            ^ (xp.uint64(self.seed & 0xFFFFFFFFFFFFFFFF))
            ^ (xp.uint64(taskctx.partition_id()) << xp.uint64(32)))
        # take the top 53 bits for a double in [0, 1)
        return (mixed >> xp.uint64(11)).astype(xp.float64) / float(1 << 53)

    def eval_device(self, ctx: EvalContext) -> DevValue:
        idx = jnp.arange(ctx.capacity, dtype=jnp.uint64) \
            + jnp.uint64(taskctx.row_base())
        return DevCol(dtypes.FLOAT64, self._uniform(jnp, idx),
                      jnp.ones((ctx.capacity,), jnp.bool_))

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        idx = np.arange(len(df), dtype=np.uint64) \
            + np.uint64(taskctx.row_base())
        return pd.Series(self._uniform(np, idx), index=df.index)


class SparkPartitionID(Expression):
    """spark_partition_id() (reference: GpuSparkPartitionID.scala:58)."""

    is_nondeterministic = True

    def dtype(self, schema: Schema) -> DType:
        return dtypes.INT32

    def sql_name(self, schema=None) -> str:
        return "SPARK_PARTITION_ID()"

    def eval_device(self, ctx: EvalContext) -> DevValue:
        pid = jnp.full((ctx.capacity,), taskctx.partition_id(), jnp.int32)
        return DevCol(dtypes.INT32, pid, jnp.ones((ctx.capacity,), jnp.bool_))

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        return pd.Series(np.full(len(df), taskctx.partition_id(),
                                 dtype=np.int32), index=df.index)


class MonotonicallyIncreasingID(Expression):
    """(partition id << 33) + row position within the partition — Spark's
    exact layout (reference: GpuMonotonicallyIncreasingID.scala:75)."""

    is_nondeterministic = True

    def dtype(self, schema: Schema) -> DType:
        return dtypes.INT64

    def sql_name(self, schema=None) -> str:
        return "monotonically_increasing_id()"

    def eval_device(self, ctx: EvalContext) -> DevValue:
        base = (np.int64(taskctx.partition_id()) << np.int64(33)) \
            + np.int64(taskctx.row_base())
        data = jnp.arange(ctx.capacity, dtype=jnp.int64) + jnp.int64(base)
        return DevCol(dtypes.INT64, data,
                      jnp.ones((ctx.capacity,), jnp.bool_))

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        base = (np.int64(taskctx.partition_id()) << np.int64(33)) \
            + np.int64(taskctx.row_base())
        return pd.Series(np.arange(len(df), dtype=np.int64) + base,
                         index=df.index)


class InputFileName(Expression):
    """input_file_name(): path of the file being scanned, '' otherwise
    (reference: GpuInputFileBlock.scala:114)."""

    is_nondeterministic = True

    def dtype(self, schema: Schema) -> DType:
        return dtypes.STRING

    def sql_name(self, schema=None) -> str:
        return "input_file_name()"

    def eval_device(self, ctx: EvalContext) -> DevValue:
        from spark_rapids_tpu.sql.exprs.core import DevScalar
        return ctx.broadcast(
            DevScalar(dtypes.STRING, taskctx.input_file()))

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        return pd.Series([taskctx.input_file()] * len(df), dtype="str",
                         index=df.index)


def has_nondeterministic(expr: Expression) -> bool:
    from spark_rapids_tpu.sql.exprs.core import walk
    return any(getattr(n, "is_nondeterministic", False) for n in walk(expr))
