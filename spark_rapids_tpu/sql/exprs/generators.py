"""Generator expressions: split + explode/posexplode markers (reference:
GpuGenerateExec.scala; Spark's Generate node). ``SplitStr`` produces an
array value that only ``ExplodeSplit`` can consume — the framework has no
first-class array columns (the reference's type gate also excludes arrays,
GpuOverrides.scala:383-395), so the planner fuses split+explode into one
Generate operator.
"""

from __future__ import annotations

from typing import Optional

from spark_rapids_tpu.columnar import dtypes
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.columnar.dtype import DType
from spark_rapids_tpu.sql.exprs.core import Expression


class SplitStr(Expression):
    """split(str, delim) with a literal delimiter."""

    def __init__(self, child: Expression, delim: str):
        super().__init__([child])
        self.delim = delim

    def dtype(self, schema: Schema) -> DType:
        raise TypeError("split() produces an array; it can only be consumed "
                        "by explode()/posexplode()")

    def sql_name(self, schema=None) -> str:
        return f"split({self.children[0].sql_name(schema)}, {self.delim!r})"


class ExplodeSplit(Expression):
    """explode(split(...)) / posexplode(split(...)) marker, lowered to a
    Generate plan node by DataFrame.with_column."""

    def __init__(self, split: SplitStr, with_pos: bool):
        assert isinstance(split, SplitStr), \
            "explode() supports split(column, delimiter) input"
        super().__init__([split])
        self.with_pos = with_pos

    @property
    def split(self) -> SplitStr:
        return self.children[0]

    def dtype(self, schema: Schema) -> DType:
        return dtypes.STRING

    def sql_name(self, schema=None) -> str:
        fn = "posexplode" if self.with_pos else "explode"
        return f"{fn}({self.children[0].sql_name(schema)})"
