"""Date/time expressions (reference: sql/rapids/datetimeExpressions.scala,
533 LoC): year/month/day/hour/minute/second extraction, dayofweek, date
arithmetic, unix timestamps. UTC only, like the reference
(GpuOverrides.scala:389-393).

Calendar math uses Howard Hinnant's civil-from-days algorithm in pure integer
arithmetic — identical formula on host (numpy) and device (jax.numpy), and
verified against pandas' calendar in tests.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np
import pandas as pd

from spark_rapids_tpu.columnar import dtypes
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.columnar.dtype import DType
from spark_rapids_tpu.sql.exprs.core import (
    DevCol, DevValue, EvalContext, Expression,
)
from spark_rapids_tpu.sql.exprs.hostutil import host_unary_values, rebuild_series

MICROS_PER_SEC = 1_000_000
MICROS_PER_DAY = 86_400 * MICROS_PER_SEC


def civil_from_days(xp, z):
    """days-since-epoch -> (year, month [1-12], day [1-31]).

    Hinnant's algorithm (http://howardhinnant.github.io/date_algorithms.html),
    valid over the entire int32 day range; all ops integer."""
    z = z.astype(np.int64) + 719468
    era = xp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097                                   # [0, 146096]
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)          # [0, 365]
    mp = (5 * doy + 2) // 153                                # [0, 11]
    d = doy - (153 * mp + 2) // 5 + 1                        # [1, 31]
    m = xp.where(mp < 10, mp + 3, mp - 9)                    # [1, 12]
    y = y + (m <= 2)
    return y, m, d


def days_from_micros(xp, micros):
    return xp.floor_divide(micros.astype(np.int64), MICROS_PER_DAY)


def time_of_day_micros(xp, micros):
    m = micros.astype(np.int64)
    return m - xp.floor_divide(m, MICROS_PER_DAY) * MICROS_PER_DAY


class ExtractDatePart(Expression):
    """Base for year/month/dayofmonth/hour/minute/second/dayofweek."""
    fname = "?"

    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.INT32

    def sql_name(self, schema=None) -> str:
        return f"{self.fname}({self.children[0].sql_name(schema)})"

    def device_supported(self, schema: Schema) -> Optional[str]:
        t = self.children[0].dtype(schema)
        if not t.is_datetime:
            return f"{self.fname} requires a date or timestamp input, got {t}"
        return None

    def compute_from_parts(self, xp, days, tod_micros):
        raise NotImplementedError

    def _split(self, xp, data, src: DType):
        if src == dtypes.DATE32:
            return data.astype(np.int64), None
        days = days_from_micros(xp, data)
        tod = time_of_day_micros(xp, data)
        return days, tod

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = ctx.broadcast(self.children[0].eval_device(ctx))
        days, tod = self._split(jnp, v.data, v.dtype)
        data = self.compute_from_parts(jnp, days, tod).astype(jnp.int32)
        return DevCol(dtypes.INT32, data, v.validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        values, validity, index = host_unary_values(self.children[0].eval_host(df))
        # host twin carries datetime64 -> int64 micros via host_unary_values
        days, tod = self._split(np, values, dtypes.TIMESTAMP_US)
        data = self.compute_from_parts(np, days, tod).astype(np.int32)
        return rebuild_series(data, validity, dtypes.INT32, index)


class Year(ExtractDatePart):
    fname = "year"
    def compute_from_parts(self, xp, days, tod):
        y, m, d = civil_from_days(xp, days)
        return y


class Month(ExtractDatePart):
    fname = "month"
    def compute_from_parts(self, xp, days, tod):
        y, m, d = civil_from_days(xp, days)
        return m


class DayOfMonth(ExtractDatePart):
    fname = "dayofmonth"
    def compute_from_parts(self, xp, days, tod):
        y, m, d = civil_from_days(xp, days)
        return d


class DayOfWeek(ExtractDatePart):
    """Spark: 1 = Sunday ... 7 = Saturday. Epoch day 0 was a Thursday."""
    fname = "dayofweek"
    def compute_from_parts(self, xp, days, tod):
        return (days + 4) % 7 + 1


class Hour(ExtractDatePart):
    fname = "hour"
    def compute_from_parts(self, xp, days, tod):
        return tod // (3600 * MICROS_PER_SEC)


class Minute(ExtractDatePart):
    fname = "minute"
    def compute_from_parts(self, xp, days, tod):
        return (tod // (60 * MICROS_PER_SEC)) % 60


class Second(ExtractDatePart):
    fname = "second"
    def compute_from_parts(self, xp, days, tod):
        return (tod // MICROS_PER_SEC) % 60


class UnixTimestampFromTs(Expression):
    """to_unix_timestamp on a timestamp column -> long seconds."""

    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.INT64

    def sql_name(self, schema=None) -> str:
        return f"unix_timestamp({self.children[0].sql_name(schema)})"

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = ctx.broadcast(self.children[0].eval_device(ctx))
        data = jnp.floor_divide(v.data.astype(jnp.int64), MICROS_PER_SEC)
        return DevCol(dtypes.INT64, data, v.validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        values, validity, index = host_unary_values(self.children[0].eval_host(df))
        data = np.floor_divide(values.astype(np.int64), MICROS_PER_SEC)
        return rebuild_series(data, validity, dtypes.INT64, index)


class DateAdd(Expression):
    """date_add(date, n days)."""

    def __init__(self, left: Expression, right: Expression):
        super().__init__([left, right])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.DATE32

    def sql_name(self, schema=None) -> str:
        return (f"date_add({self.children[0].sql_name(schema)}, "
                f"{self.children[1].sql_name(schema)})")

    def device_supported(self, schema: Schema) -> Optional[str]:
        if self.children[0].dtype(schema) != dtypes.DATE32:
            return "date_add requires a date input"
        return None

    def eval_device(self, ctx: EvalContext) -> DevValue:
        lv = ctx.broadcast(self.children[0].eval_device(ctx))
        rv = ctx.broadcast(self.children[1].eval_device(ctx))
        data = (lv.data.astype(jnp.int32) + rv.data.astype(jnp.int32))
        return DevCol(dtypes.DATE32, data, lv.validity & rv.validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        a, av, index = host_unary_values(self.children[0].eval_host(df))
        b, bv, _ = host_unary_values(self.children[1].eval_host(df))
        # host dates ride as datetime64->micros; truncate to the day first
        # (Spark casts timestamp inputs to date) then add days in micro space
        days = days_from_micros(np, a) + b.astype(np.int64)
        out = rebuild_series(days * MICROS_PER_DAY, av & bv,
                             dtypes.TIMESTAMP_US, index)
        out.attrs["srt_logical_dtype"] = "date32"
        return out


class Quarter(ExtractDatePart):
    fname = "quarter"
    def compute_from_parts(self, xp, days, tod):
        y, m, d = civil_from_days(xp, days)
        return (m - 1) // 3 + 1


class DayOfYear(ExtractDatePart):
    fname = "dayofyear"
    def compute_from_parts(self, xp, days, tod):
        y, m, d = civil_from_days(xp, days)
        # days since Jan 1 of the same year
        jan1 = days_from_civil(xp, y, xp.ones_like(m), xp.ones_like(d))
        return (days - jan1 + 1)


class WeekOfYear(ExtractDatePart):
    """ISO-8601 week number (Spark's weekofyear)."""
    fname = "weekofyear"
    def compute_from_parts(self, xp, days, tod):
        # ISO week: Thursday of the current week determines the year;
        # week number = (doy_of_thursday - 1) // 7 + 1
        dow = (days + 3) % 7            # 0 = Monday ... 6 = Sunday
        thursday = days + (3 - dow)
        y, m, d = civil_from_days(xp, thursday)
        jan1 = days_from_civil(xp, y, xp.ones_like(m), xp.ones_like(d))
        return (thursday - jan1) // 7 + 1


def days_from_civil(xp, y, m, d):
    """Inverse of civil_from_days (Hinnant's days_from_civil)."""
    y = y.astype(np.int64) - (m <= 2)
    era = xp.where(y >= 0, y, y - 399) // 400
    yoe = y - era * 400
    mp = xp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


class LastDay(Expression):
    """last_day(date): last day of the month."""

    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.DATE32

    def sql_name(self, schema=None) -> str:
        return f"last_day({self.children[0].sql_name(schema)})"

    def device_supported(self, schema: Schema) -> Optional[str]:
        if not self.children[0].dtype(schema).is_datetime:
            return "last_day requires a date input"
        return None

    def _compute(self, xp, days):
        y, m, d = civil_from_days(xp, days)
        ny = xp.where(m == 12, y + 1, y)
        nm = xp.where(m == 12, xp.ones_like(m), m + 1)
        first_next = days_from_civil(xp, ny, nm, xp.ones_like(d))
        return (first_next - 1).astype(np.int32)

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = ctx.broadcast(self.children[0].eval_device(ctx))
        days = (v.data.astype(jnp.int64) if v.dtype == dtypes.DATE32
                else days_from_micros(jnp, v.data))
        return DevCol(dtypes.DATE32, self._compute(jnp, days), v.validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        values, validity, index = host_unary_values(self.children[0].eval_host(df))
        days = days_from_micros(np, values)   # host datetimes ride as micros
        out_days = self._compute(np, days).astype(np.int64)
        out = rebuild_series(out_days * MICROS_PER_DAY, validity,
                             dtypes.TIMESTAMP_US, index)
        out.attrs["srt_logical_dtype"] = "date32"
        return out


class DateSub(DateAdd):
    """date_sub(date, n days)."""

    def sql_name(self, schema=None) -> str:
        return (f"date_sub({self.children[0].sql_name(schema)}, "
                f"{self.children[1].sql_name(schema)})")

    def eval_device(self, ctx: EvalContext) -> DevValue:
        lv = ctx.broadcast(self.children[0].eval_device(ctx))
        rv = ctx.broadcast(self.children[1].eval_device(ctx))
        data = (lv.data.astype(jnp.int32) - rv.data.astype(jnp.int32))
        return DevCol(dtypes.DATE32, data, lv.validity & rv.validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        a, av, index = host_unary_values(self.children[0].eval_host(df))
        b, bv, _ = host_unary_values(self.children[1].eval_host(df))
        days = days_from_micros(np, a) - b.astype(np.int64)
        out = rebuild_series(days * MICROS_PER_DAY, av & bv,
                             dtypes.TIMESTAMP_US, index)
        out.attrs["srt_logical_dtype"] = "date32"
        return out


class DateDiff(Expression):
    """datediff(end, start) in whole days."""

    def __init__(self, end: Expression, start: Expression):
        super().__init__([end, start])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.INT32

    def sql_name(self, schema=None) -> str:
        return (f"datediff({self.children[0].sql_name(schema)}, "
                f"{self.children[1].sql_name(schema)})")

    def device_supported(self, schema: Schema) -> Optional[str]:
        for c in self.children:
            if not c.dtype(schema).is_datetime:
                return "datediff requires date/timestamp inputs"
        return None

    def _days(self, xp, data, dt: DType):
        if dt == dtypes.DATE32:
            return data.astype(np.int64)
        return days_from_micros(xp, data)

    def eval_device(self, ctx: EvalContext) -> DevValue:
        lv = ctx.broadcast(self.children[0].eval_device(ctx))
        rv = ctx.broadcast(self.children[1].eval_device(ctx))
        out = (self._days(jnp, lv.data, lv.dtype)
               - self._days(jnp, rv.data, rv.dtype)).astype(jnp.int32)
        return DevCol(dtypes.INT32, out, lv.validity & rv.validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        a, av, index = host_unary_values(self.children[0].eval_host(df))
        b, bv, _ = host_unary_values(self.children[1].eval_host(df))
        out = (days_from_micros(np, a) - days_from_micros(np, b)).astype(np.int32)
        return rebuild_series(out, av & bv, dtypes.INT32, index)


class ToDate(Expression):
    """to_date(timestamp) — truncate to the day."""

    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.DATE32

    def sql_name(self, schema=None) -> str:
        return f"to_date({self.children[0].sql_name(schema)})"

    def device_supported(self, schema: Schema) -> Optional[str]:
        t = self.children[0].dtype(schema)
        if t.is_string:
            # to_date(string) == cast(string as date) in Spark: same
            # device gate as the cast
            from spark_rapids_tpu.sql.exprs.cast import Cast
            if Cast._conf_enabled(
                    "spark.rapids.sql.castStringToDate.enabled"):
                return None
            return ("to_date over strings parses dates and is gated off "
                    "by default (spark.rapids.sql.castStringToDate.enabled)")
        if not t.is_datetime:
            return f"to_date requires a date/timestamp/string input, got {t}"
        return None

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = ctx.broadcast(self.children[0].eval_device(ctx))
        if v.dtype == dtypes.DATE32:
            return v
        if v.dtype.is_string:
            from spark_rapids_tpu.ops import strings as string_ops
            days, ok = string_ops.string_to_date(ctx, v)
            return DevCol(dtypes.DATE32, days, v.validity & ok)
        days = days_from_micros(jnp, v.data).astype(jnp.int32)
        return DevCol(dtypes.DATE32, days, v.validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        values, validity, index = host_unary_values(self.children[0].eval_host(df))
        if values.dtype == object:  # string input: cast-to-date semantics
            from spark_rapids_tpu.sql.exprs.cast import _cast_strings_host
            days, validity = _cast_strings_host(values, validity,
                                                dtypes.STRING, dtypes.DATE32)
            days = days.astype(np.int64)
        else:
            days = days_from_micros(np, values)
        out = rebuild_series(days * MICROS_PER_DAY, validity,
                             dtypes.TIMESTAMP_US, index)
        # host dates ride as midnight micros; mark the logical type for
        # date-aware consumers (Cast renders 'yyyy-MM-dd', not a timestamp)
        out.attrs["srt_logical_dtype"] = "date32"
        return out


class FromUnixTime(Expression):
    """from_unixtime(seconds) -> timestamp (no format string: the reference
    also restricts strftime conversions, UnixTimeExprMeta)."""

    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.TIMESTAMP_US

    def sql_name(self, schema=None) -> str:
        return f"from_unixtime({self.children[0].sql_name(schema)})"

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = ctx.broadcast(self.children[0].eval_device(ctx))
        data = v.data.astype(jnp.int64) * MICROS_PER_SEC
        return DevCol(dtypes.TIMESTAMP_US, data, v.validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        values, validity, index = host_unary_values(self.children[0].eval_host(df))
        data = values.astype(np.int64) * MICROS_PER_SEC
        return rebuild_series(data, validity, dtypes.TIMESTAMP_US, index)


class UnixTimestampFromString(Expression):
    """unix_timestamp(string, fmt) -> long epoch seconds (UTC), NULL on
    parse failure (reference: UnixTimeExprMeta's strf-pattern subset —
    the device supports the two fixed-width forms; other formats fall
    back to the host's strptime)."""

    _DEVICE_FMTS = ("yyyy-MM-dd", "yyyy-MM-dd HH:mm:ss")
    _JAVA_TO_PY = (("yyyy", "%Y"), ("MM", "%m"), ("dd", "%d"),
                   ("HH", "%H"), ("mm", "%M"), ("ss", "%S"))

    def __init__(self, child: Expression, fmt: str):
        super().__init__([child])
        self.fmt = fmt
        # reject format tokens neither side implements at construction —
        # an unmapped token would silently parse nothing (all NULLs)
        import re
        residual = fmt
        for j, _ in self._JAVA_TO_PY:
            residual = residual.replace(j, "")
        if re.search(r"[A-Za-z]", residual):
            raise ValueError(
                f"unsupported unix_timestamp format token in {fmt!r} "
                f"(supported tokens: yyyy MM dd HH mm ss)")

    def dtype(self, schema: Schema) -> DType:
        return dtypes.INT64

    def sql_name(self, schema=None) -> str:
        return (f"unix_timestamp({self.children[0].sql_name(schema)}, "
                f"{self.fmt!r})")

    def device_supported(self, schema: Schema) -> Optional[str]:
        t = self.children[0].dtype(schema)
        if t.is_datetime:
            return None  # format is ignored for date/timestamp inputs
        if not t.is_string:
            return (f"unix_timestamp requires a string or date/timestamp "
                    f"input, got {t}")
        if self.fmt not in self._DEVICE_FMTS:
            return (f"unix_timestamp format {self.fmt!r} is not supported "
                    f"on TPU (supported: {', '.join(self._DEVICE_FMTS)})")
        return None

    def eval_device(self, ctx: EvalContext) -> DevValue:
        from spark_rapids_tpu.ops import strings as string_ops
        v = ctx.broadcast(self.children[0].eval_device(ctx))
        if v.dtype.is_datetime:  # Spark ignores fmt for these inputs
            if v.dtype == dtypes.DATE32:
                secs = v.data.astype(jnp.int64) * 86400
            else:
                secs = jnp.floor_divide(v.data.astype(jnp.int64),
                                        MICROS_PER_SEC)
            return DevCol(dtypes.INT64, secs, v.validity)
        secs, ok = string_ops.string_to_unix_ts(
            ctx, v, with_time=" " in self.fmt)
        return DevCol(dtypes.INT64, secs, v.validity & ok)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        import calendar
        import datetime as _dt
        import re
        values, validity, index = host_unary_values(
            self.children[0].eval_host(df))
        pyfmt = self.fmt
        for j, p in self._JAVA_TO_PY:
            pyfmt = pyfmt.replace(j, p)
        # fixed-width pre-check: strptime leniently accepts '1:02:03' for
        # %H:%M:%S, the device kernels require the pattern's digit widths
        strict = re.escape(pyfmt)
        strict = strict.replace(re.escape("%Y"), r"\d{4}")
        for tok in ("%m", "%d", "%H", "%M", "%S"):
            strict = strict.replace(re.escape(tok), r"\d{2}")
        strict_re = re.compile("^" + strict + "$", re.ASCII)
        if values.dtype != object:  # date/timestamp input: fmt ignored
            secs = np.floor_divide(values.astype(np.int64), MICROS_PER_SEC)
            return rebuild_series(secs, validity, dtypes.INT64, index)
        out = np.zeros(len(values), np.int64)
        ok = validity.copy()
        for i, v in enumerate(values):
            if not validity[i]:
                continue
            try:
                t = str(v).strip(" \t\n\r\v\f")
                if not strict_re.match(t):
                    raise ValueError(t)
                tm = _dt.datetime.strptime(t, pyfmt)
                out[i] = calendar.timegm(tm.timetuple())
            except ValueError:
                ok[i] = False
        return rebuild_series(out, ok, dtypes.INT64, index)


def _civil_add_months(xp, days, months):
    """day-count -> day-count, adding calendar months with end-of-month
    clamping (Spark's add_months)."""
    y, m, d = civil_from_days(xp, days.astype(np.int64))
    total = (y * 12 + (m - 1)) + months.astype(np.int64)
    ny = total // 12
    nm = total % 12 + 1
    # clamp day to the target month's length
    is_leap = ((ny % 4 == 0) & (ny % 100 != 0)) | (ny % 400 == 0)
    mdays_tbl = np.array([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31],
                         dtype=np.int64)
    mdays = xp.asarray(mdays_tbl)[nm - 1]
    mdays = xp.where((nm == 2) & is_leap, 29, mdays)
    nd = xp.minimum(d, mdays)
    return days_from_civil(xp, ny, nm, nd)


class AddMonths(Expression):
    """add_months(date, n) (reference: GpuOverrides datetime rules)."""

    def __init__(self, left: Expression, right: Expression):
        super().__init__([left, right])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.DATE32

    def sql_name(self, schema=None) -> str:
        return (f"add_months({self.children[0].sql_name(schema)}, "
                f"{self.children[1].sql_name(schema)})")

    def device_supported(self, schema: Schema) -> Optional[str]:
        if not self.children[0].dtype(schema).is_datetime:
            return "add_months requires a date input"
        return None

    def eval_device(self, ctx: EvalContext) -> DevValue:
        lv = ctx.broadcast(self.children[0].eval_device(ctx))
        rv = ctx.broadcast(self.children[1].eval_device(ctx))
        days = (lv.data.astype(jnp.int64) if lv.dtype == dtypes.DATE32
                else days_from_micros(jnp, lv.data))
        out = _civil_add_months(jnp, days, rv.data)
        return DevCol(dtypes.DATE32, out.astype(jnp.int32),
                      lv.validity & rv.validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        a, av, index = host_unary_values(self.children[0].eval_host(df))
        b, bv, _ = host_unary_values(self.children[1].eval_host(df))
        days = days_from_micros(np, a)
        out = _civil_add_months(np, days, b.astype(np.int64))
        s = rebuild_series(out * MICROS_PER_DAY, av & bv,
                           dtypes.TIMESTAMP_US, index)
        s.attrs["srt_logical_dtype"] = "date32"
        return s


class MonthsBetween(Expression):
    """months_between(end, start): whole-month difference + fractional
    31-day remainder; both-last-day pairs count as whole months."""

    def __init__(self, left: Expression, right: Expression):
        super().__init__([left, right])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.FLOAT64

    def sql_name(self, schema=None) -> str:
        return (f"months_between({self.children[0].sql_name(schema)}, "
                f"{self.children[1].sql_name(schema)})")

    def device_supported(self, schema: Schema) -> Optional[str]:
        for c in self.children:
            if not c.dtype(schema).is_datetime:
                return "months_between requires date inputs"
        return None

    @staticmethod
    def _compute(xp, d_end, d_start):
        y1, m1, day1 = civil_from_days(xp, d_end.astype(np.int64))
        y2, m2, day2 = civil_from_days(xp, d_start.astype(np.int64))
        # last-day-of-month flags
        next1 = civil_from_days(xp, d_end.astype(np.int64) + 1)[2]
        next2 = civil_from_days(xp, d_start.astype(np.int64) + 1)[2]
        last1 = next1 == 1
        last2 = next2 == 1
        months = (y1 - y2) * 12 + (m1 - m2)
        frac = (day1 - day2) / 31.0
        whole = (day1 == day2) | (last1 & last2)
        return xp.where(whole, months.astype(np.float64),
                        months.astype(np.float64) + frac)

    def eval_device(self, ctx: EvalContext) -> DevValue:
        lv = ctx.broadcast(self.children[0].eval_device(ctx))
        rv = ctx.broadcast(self.children[1].eval_device(ctx))
        d1 = (lv.data.astype(jnp.int64) if lv.dtype == dtypes.DATE32
              else days_from_micros(jnp, lv.data))
        d2 = (rv.data.astype(jnp.int64) if rv.dtype == dtypes.DATE32
              else days_from_micros(jnp, rv.data))
        out = self._compute(jnp, d1, d2)
        return DevCol(dtypes.FLOAT64, out, lv.validity & rv.validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        a, av, index = host_unary_values(self.children[0].eval_host(df))
        b, bv, _ = host_unary_values(self.children[1].eval_host(df))
        out = self._compute(np, days_from_micros(np, a),
                            days_from_micros(np, b))
        return rebuild_series(out, av & bv, dtypes.FLOAT64, index)


class TruncDate(Expression):
    """trunc(date, 'year'|'month'|'week') -> first day of the period."""

    SUPPORTED = {"year": "year", "yyyy": "year", "yy": "year",
                 "month": "month", "mon": "month", "mm": "month",
                 "week": "week", "quarter": "quarter"}

    def __init__(self, child: Expression, fmt: str):
        super().__init__([child])
        self.fmt = str(fmt).lower()

    def dtype(self, schema: Schema) -> DType:
        return dtypes.DATE32

    def sql_name(self, schema=None) -> str:
        return f"trunc({self.children[0].sql_name(schema)}, {self.fmt!r})"

    def device_supported(self, schema: Schema) -> Optional[str]:
        if self.fmt not in self.SUPPORTED:
            return f"trunc format {self.fmt!r} is not supported"
        if not self.children[0].dtype(schema).is_datetime:
            return "trunc requires a date input"
        return None

    def _compute(self, xp, days):
        kind = self.SUPPORTED[self.fmt]
        y, m, d = civil_from_days(xp, days.astype(np.int64))
        if kind == "year":
            return days_from_civil(xp, y, xp.ones_like(m), xp.ones_like(m))
        if kind == "month":
            return days_from_civil(xp, y, m, xp.ones_like(m))
        if kind == "quarter":
            qm = ((m - 1) // 3) * 3 + 1
            return days_from_civil(xp, y, qm, xp.ones_like(m))
        # week: previous (or same) Monday; 1970-01-01 was a Thursday
        dow = (days.astype(np.int64) + 3) % 7  # 0 = Monday
        return days.astype(np.int64) - dow

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = ctx.broadcast(self.children[0].eval_device(ctx))
        days = (v.data.astype(jnp.int64) if v.dtype == dtypes.DATE32
                else days_from_micros(jnp, v.data))
        out = self._compute(jnp, days)
        return DevCol(dtypes.DATE32, out.astype(jnp.int32), v.validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        a, av, index = host_unary_values(self.children[0].eval_host(df))
        if self.fmt not in self.SUPPORTED:
            # Spark: invalid trunc format -> NULL
            av = np.zeros_like(av)
            out = np.zeros(len(a), dtype=np.int64)
        else:
            out = self._compute(np, days_from_micros(np, a))
        s = rebuild_series(out * MICROS_PER_DAY, av,
                           dtypes.TIMESTAMP_US, index)
        s.attrs["srt_logical_dtype"] = "date32"
        return s


class NextDay(Expression):
    """next_day(date, 'mon'..'sun'): the next date after ``date`` that is
    the given day of week."""

    DOW = {"mon": 0, "monday": 0, "tue": 1, "tuesday": 1, "wed": 2,
           "wednesday": 2, "thu": 3, "thursday": 3, "fri": 4, "friday": 4,
           "sat": 5, "saturday": 5, "sun": 6, "sunday": 6}

    def __init__(self, child: Expression, day: str):
        super().__init__([child])
        self.day = str(day).lower()

    def dtype(self, schema: Schema) -> DType:
        return dtypes.DATE32

    def sql_name(self, schema=None) -> str:
        return f"next_day({self.children[0].sql_name(schema)}, {self.day!r})"

    def device_supported(self, schema: Schema) -> Optional[str]:
        if self.day not in self.DOW:
            return f"next_day day {self.day!r} is not supported"
        if not self.children[0].dtype(schema).is_datetime:
            return "next_day requires a date input"
        return None

    def _compute(self, xp, days):
        target = self.DOW[self.day]
        dow = (days.astype(np.int64) + 3) % 7  # 0 = Monday
        ahead = (target - dow) % 7
        ahead = xp.where(ahead == 0, 7, ahead)
        return days.astype(np.int64) + ahead

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = ctx.broadcast(self.children[0].eval_device(ctx))
        days = (v.data.astype(jnp.int64) if v.dtype == dtypes.DATE32
                else days_from_micros(jnp, v.data))
        out = self._compute(jnp, days)
        return DevCol(dtypes.DATE32, out.astype(jnp.int32), v.validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        a, av, index = host_unary_values(self.children[0].eval_host(df))
        if self.day not in self.DOW:
            # Spark: invalid day-of-week name -> NULL
            av = np.zeros_like(av)
            out = np.zeros(len(a), dtype=np.int64)
        else:
            out = self._compute(np, days_from_micros(np, a))
        s = rebuild_series(out * MICROS_PER_DAY, av,
                           dtypes.TIMESTAMP_US, index)
        s.attrs["srt_logical_dtype"] = "date32"
        return s
