from spark_rapids_tpu.sql.exprs.core import (  # noqa: F401
    Alias,
    BoundRef,
    Col,
    DevCol,
    DevScalar,
    EvalContext,
    Expression,
    Literal,
    bind_references,
    first_unsupported,
    walk,
)
