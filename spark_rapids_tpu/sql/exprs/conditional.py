"""Conditional and null-handling expressions (reference:
sql/rapids/conditionalExpressions.scala, 251 LoC and nullExpressions.scala,
297 LoC): if/case-when, coalesce, nanvl."""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax.numpy as jnp
import numpy as np
import pandas as pd

from spark_rapids_tpu.columnar import dtypes
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.columnar.dtype import DType, common_type
from spark_rapids_tpu.sql.exprs.core import (
    DevCol, DevScalar, DevValue, EvalContext, Expression,
)
from spark_rapids_tpu.sql.exprs.hostutil import host_unary_values, rebuild_series


def _result_type(schema: Schema, exprs: List[Expression]) -> DType:
    out = exprs[0].dtype(schema)
    for e in exprs[1:]:
        t = e.dtype(schema)
        if t != out:
            out = common_type(out, t)
    return out


def _as_pair(ctx: EvalContext, v: DevValue, dt: DType):
    """(data, validity) at batch capacity, cast to dt."""
    c = ctx.broadcast(v)
    data = c.data if dt.is_string else c.data.astype(dt.np_dtype)
    return data, c.validity, c.offsets


class If(Expression):
    def __init__(self, pred: Expression, then: Expression, other: Expression):
        super().__init__([pred, then, other])

    def dtype(self, schema: Schema) -> DType:
        return _result_type(schema, self.children[1:])

    def sql_name(self, schema=None) -> str:
        p, t, f = (c.sql_name(schema) for c in self.children)
        return f"if({p}, {t}, {f})"

    def device_supported(self, schema: Schema) -> Optional[str]:
        return None

    def eval_device(self, ctx: EvalContext) -> DevValue:
        dt = None
        pv = ctx.broadcast(self.children[0].eval_device(ctx))
        tv = self.children[1].eval_device(ctx)
        fv = self.children[2].eval_device(ctx)
        if tv.dtype.is_string or fv.dtype.is_string:
            from spark_rapids_tpu.ops import strings as string_ops
            tc, fc = ctx.broadcast(tv), ctx.broadcast(fv)
            cond = pv.data & pv.validity  # NULL predicate -> else branch
            validity = jnp.where(cond, tc.validity, fc.validity)
            return string_ops.select_strings(ctx, cond, tc, fc, validity)
        dt = tv.dtype if tv.dtype == fv.dtype else common_type(tv.dtype, fv.dtype)
        tdata, tval, _ = _as_pair(ctx, tv, dt)
        fdata, fval, _ = _as_pair(ctx, fv, dt)
        # NULL predicate chooses the else branch (Spark semantics)
        cond = pv.data & pv.validity
        data = jnp.where(cond, tdata, fdata)
        validity = jnp.where(cond, tval, fval)
        return DevCol(dt, data, validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        p, pval, index = host_unary_values(self.children[0].eval_host(df))
        t, tval, _ = host_unary_values(self.children[1].eval_host(df))
        f, fval, _ = host_unary_values(self.children[2].eval_host(df))
        cond = p.astype(np.bool_) & pval
        if t.dtype == object or f.dtype == object:
            data = np.where(cond, t, f)
            dt = dtypes.STRING
        else:
            dt = common_type(dtypes.from_numpy(t.dtype), dtypes.from_numpy(f.dtype))
            data = np.where(cond, t.astype(dt.np_dtype), f.astype(dt.np_dtype))
        validity = np.where(cond, tval, fval)
        return rebuild_series(data, validity, dt, index)


class CaseWhen(Expression):
    """CASE WHEN p1 THEN v1 ... [ELSE ve] END."""

    def __init__(self, branches: List[Tuple[Expression, Expression]],
                 else_value: Optional[Expression] = None):
        flat: List[Expression] = []
        for p, v in branches:
            flat += [p, v]
        if else_value is not None:
            flat.append(else_value)
        super().__init__(flat)
        self.n_branches = len(branches)
        self.has_else = else_value is not None

    def _branches(self):
        return [(self.children[2 * i], self.children[2 * i + 1])
                for i in range(self.n_branches)]

    def _else(self) -> Optional[Expression]:
        return self.children[-1] if self.has_else else None

    def dtype(self, schema: Schema) -> DType:
        values = [v for _, v in self._branches()]
        if self.has_else:
            values.append(self._else())
        return _result_type(schema, values)

    def sql_name(self, schema=None) -> str:
        parts = ["CASE"]
        for p, v in self._branches():
            parts.append(f"WHEN {p.sql_name(schema)} THEN {v.sql_name(schema)}")
        if self.has_else:
            parts.append(f"ELSE {self._else().sql_name(schema)}")
        parts.append("END")
        return " ".join(parts)

    def device_supported(self, schema: Schema) -> Optional[str]:
        return None

    def eval_device(self, ctx: EvalContext) -> DevValue:
        evaluated = [(ctx.broadcast(p.eval_device(ctx)), v.eval_device(ctx))
                     for p, v in self._branches()]
        if any(v.dtype.is_string for _, v in evaluated):
            # fold branches back-to-front through the string row-select
            # kernel: else-value (or all-null) is the running accumulator
            from spark_rapids_tpu.ops import strings as string_ops
            from spark_rapids_tpu.sql.exprs.core import DevScalar
            if self.has_else:
                acc = ctx.broadcast(self._else().eval_device(ctx))
            else:
                acc = ctx.broadcast(DevScalar(dtypes.STRING, None,
                                              valid=False))
            for p, v in reversed(evaluated):
                cond = p.data & p.validity
                vc = ctx.broadcast(v)
                validity = jnp.where(cond, vc.validity, acc.validity)
                acc = string_ops.select_strings(ctx, cond, vc, acc, validity)
            return acc
        dts = [v.dtype for _, v in evaluated]
        ev = self._else().eval_device(ctx) if self.has_else else None
        if ev is not None:
            dts.append(ev.dtype)
        dt = dts[0]
        for t in dts[1:]:
            if t != dt:
                dt = common_type(dt, t)
        if ev is not None:
            data, validity, _ = _as_pair(ctx, ev, dt)
        else:
            data = jnp.full((ctx.capacity,), dtypes.null_fill_value(dt),
                            dtype=dt.np_dtype)
            validity = jnp.zeros((ctx.capacity,), dtype=jnp.bool_)
        taken = jnp.zeros((ctx.capacity,), dtype=jnp.bool_)
        for p, v in evaluated:
            cond = p.data & p.validity & ~taken
            vdata, vval, _ = _as_pair(ctx, v, dt)
            data = jnp.where(cond, vdata, data)
            validity = jnp.where(cond, vval, validity)
            taken = taken | (p.data & p.validity)
        return DevCol(dt, data, validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        evaluated = []
        for p, v in self._branches():
            pv, pval, index = host_unary_values(p.eval_host(df))
            vv, vval, _ = host_unary_values(v.eval_host(df))
            evaluated.append((pv.astype(np.bool_) & pval, vv, vval))
        dts = [dtypes.from_numpy(vv.dtype) if vv.dtype != object else dtypes.STRING
               for _, vv, _ in evaluated]
        if self.has_else:
            ev, eval_, index = host_unary_values(self._else().eval_host(df))
            dts.append(dtypes.from_numpy(ev.dtype) if ev.dtype != object
                       else dtypes.STRING)
        dt = dts[0]
        for t in dts[1:]:
            if t != dt:
                dt = common_type(dt, t)
        n = len(df)
        if self.has_else:
            data = ev if dt.is_string else ev.astype(dt.np_dtype)
            validity = eval_
        else:
            data = np.full(n, dtypes.null_fill_value(dt) if not dt.is_string
                           else None, dtype=object if dt.is_string else dt.np_dtype)
            validity = np.zeros(n, dtype=np.bool_)
        taken = np.zeros(n, dtype=np.bool_)
        for cond, vv, vval in evaluated:
            use = cond & ~taken
            vv2 = vv if dt.is_string else vv.astype(dt.np_dtype)
            data = np.where(use, vv2, data)
            validity = np.where(use, vval, validity)
            taken = taken | cond
        index = df.index
        return rebuild_series(data, validity, dt, index)


class Coalesce(Expression):
    def __init__(self, children: List[Expression]):
        super().__init__(children)

    def dtype(self, schema: Schema) -> DType:
        return _result_type(schema, self.children)

    def sql_name(self, schema=None) -> str:
        return f"coalesce({', '.join(c.sql_name(schema) for c in self.children)})"

    def device_supported(self, schema: Schema) -> Optional[str]:
        return None

    def eval_device(self, ctx: EvalContext) -> DevValue:
        evaluated = [c.eval_device(ctx) for c in self.children]
        if any(v.dtype.is_string for v in evaluated):
            from spark_rapids_tpu.ops import strings as string_ops
            cols = [ctx.broadcast(v) for v in evaluated]
            out = cols[0]
            for nxt in cols[1:]:
                # rows already valid keep their bytes; others take nxt's
                out = string_ops.select_strings(
                    ctx, out.validity, out, nxt, out.validity | nxt.validity)
            return out
        dt = evaluated[0].dtype
        for v in evaluated[1:]:
            if v.dtype != dt:
                dt = common_type(dt, v.dtype)
        data = jnp.full((ctx.capacity,), dtypes.null_fill_value(dt),
                        dtype=dt.np_dtype)
        validity = jnp.zeros((ctx.capacity,), dtype=jnp.bool_)
        for v in evaluated:
            vdata, vval, _ = _as_pair(ctx, v, dt)
            take = ~validity & vval
            data = jnp.where(take, vdata, data)
            validity = validity | vval
        return DevCol(dt, data, validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        pairs = [host_unary_values(c.eval_host(df)) for c in self.children]
        dts = [dtypes.from_numpy(v.dtype) if v.dtype != object else dtypes.STRING
               for v, _, _ in pairs]
        dt = dts[0]
        for t in dts[1:]:
            if t != dt:
                dt = common_type(dt, t)
        n = len(df)
        data = np.full(n, None if dt.is_string else dtypes.null_fill_value(dt),
                       dtype=object if dt.is_string else dt.np_dtype)
        validity = np.zeros(n, dtype=np.bool_)
        for v, vval, _ in pairs:
            take = ~validity & vval
            v2 = v if dt.is_string else v.astype(dt.np_dtype)
            data = np.where(take, v2, data)
            validity = validity | vval
        return rebuild_series(data, validity, dt, df.index)


class NaNvl(Expression):
    """nanvl(a, b): b where a is NaN, else a."""

    def __init__(self, left: Expression, right: Expression):
        super().__init__([left, right])

    def dtype(self, schema: Schema) -> DType:
        return common_type(self.children[0].dtype(schema),
                           self.children[1].dtype(schema))

    def sql_name(self, schema=None) -> str:
        return (f"nanvl({self.children[0].sql_name(schema)}, "
                f"{self.children[1].sql_name(schema)})")

    def eval_device(self, ctx: EvalContext) -> DevValue:
        lv = ctx.broadcast(self.children[0].eval_device(ctx))
        rv = ctx.broadcast(self.children[1].eval_device(ctx))
        dt = common_type(lv.dtype, rv.dtype)
        a = lv.data.astype(dt.np_dtype)
        b = rv.data.astype(dt.np_dtype)
        use_b = jnp.isnan(a) & lv.validity
        data = jnp.where(use_b, b, a)
        validity = jnp.where(use_b, rv.validity, lv.validity)
        return DevCol(dt, data, validity)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        a, av, index = host_unary_values(self.children[0].eval_host(df))
        b, bv, _ = host_unary_values(self.children[1].eval_host(df))
        dt = common_type(dtypes.from_numpy(a.dtype), dtypes.from_numpy(b.dtype))
        a = a.astype(dt.np_dtype)
        b = b.astype(dt.np_dtype)
        use_b = np.isnan(a) & av
        data = np.where(use_b, b, a)
        validity = np.where(use_b, bv, av)
        return rebuild_series(data, validity, dt, index)
