"""Misc expressions: hash() and hex() (reference: Spark's Murmur3Hash /
Hex used by the Mortgage workload's loan anonymization,
integration_tests/.../mortgage/MortgageSpark.scala:370,394).

hash() here is the framework's own 64->32-bit mixer (splitmix64 over
fixed-width bits, dual polynomial hashes for strings — ops/hashing.py),
NOT Spark's murmur3_32: the contract the workloads need is "deterministic,
well-mixed, identical on the CPU and TPU paths", which the shared-constant
numpy/jax twin kernels guarantee."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np
import pandas as pd

from spark_rapids_tpu.columnar import dtypes
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.columnar.dtype import DType
from spark_rapids_tpu.ops import hashing
from spark_rapids_tpu.sql.exprs.core import (
    DevCol, DevValue, EvalContext, Expression,
)
from spark_rapids_tpu.sql.exprs.hostutil import host_unary_values, rebuild_series


class Hash(Expression):
    """hash(c1, c2, ...) -> int32; never NULL (NULL inputs feed a fixed
    null sentinel into the mix, like Spark's seed-based null handling)."""

    def __init__(self, children):
        super().__init__(list(children))

    def dtype(self, schema: Schema) -> DType:
        return dtypes.INT32

    def sql_name(self, schema=None) -> str:
        args = ", ".join(c.sql_name(schema) for c in self.children)
        return f"hash({args})"

    def device_supported(self, schema: Schema) -> Optional[str]:
        return None

    def eval_device(self, ctx: EvalContext) -> DevValue:
        hs = []
        for c in self.children:
            v = ctx.broadcast(c.eval_device(ctx))
            if v.dtype.is_string:
                hs.append(hashing.hash_string_col(v.offsets, v.data,
                                                  v.validity))
            else:
                hs.append(hashing.hash_fixed_width(v.data, v.validity))
        combined = hashing.combine_hashes(hs)
        data = combined.astype(jnp.uint32).view(jnp.int32).astype(jnp.int32)
        return DevCol(dtypes.INT32, data,
                      jnp.ones(data.shape, jnp.bool_))

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        hs = []
        index = df.index
        for c in self.children:
            values, validity, index = host_unary_values(c.eval_host(df))
            if values.dtype == object or str(values.dtype) in ("str",
                                                               "string"):
                hs.append(hashing.np_string_hashes(list(values), validity))
            else:
                hs.append(hashing.np_hash_fixed_width(values, validity))
        combined = hashing.np_combine_hashes(hs)
        data = combined.astype(np.uint32).view(np.int32)
        return rebuild_series(data, np.ones(len(data), np.bool_),
                              dtypes.INT32, index)


class Hex(Expression):
    """hex(n) -> uppercase hex string (negatives as 16-digit two's
    complement, Spark semantics). String-producing, so it runs on the CPU
    path and the plan rewriter tags the reason."""

    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.STRING

    def sql_name(self, schema=None) -> str:
        return f"hex({self.children[0].sql_name(schema)})"

    def device_supported(self, schema: Schema) -> Optional[str]:
        return "hex produces variable-length strings; runs on CPU"

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        values, validity, index = host_unary_values(
            self.children[0].eval_host(df))
        out = np.empty(len(values), dtype=object)
        for i, v in enumerate(values):
            if not validity[i]:
                out[i] = None
            else:
                out[i] = format(int(v) & 0xFFFFFFFFFFFFFFFF, "X")
        return rebuild_series(out, validity, dtypes.STRING, index)
