"""Expression framework core.

The TPU analogue of the reference's ``GpuExpression`` hierarchy
(sql-plugin/.../GpuExpressions.scala:74-372): expressions evaluate columnar,
on whole batches. Two evaluation paths per node:

  * ``eval_device(ctx)`` — pure-jax, traceable; consumed inside a single
    ``jax.jit``-compiled operator stage (so XLA fuses expression trees into
    the surrounding operator — the TPU-first improvement over cuDF's
    one-kernel-per-op dispatch).
  * ``eval_host(df)``   — pandas, the CPU fallback path and the differential
    test oracle (the reference tests GPU vs CPU Spark the same way,
    SparkQueryCompareTestSuite.scala:66-205).

Values flowing through device evaluation are ``DevCol`` (data + validity
[+ offsets for strings]) or ``DevScalar`` — the analogue of cuDF
``ColumnVector``/``Scalar`` results from ``columnarEval``
(GpuExpressions.scala:98-149).

Null discipline on device: ``validity`` is a bool vector, True = valid;
invalid slots hold a canonical fill value so arithmetic never traps. All
kernels compute data and validity separately.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np
import pandas as pd

from spark_rapids_tpu.columnar import dtypes
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.columnar.dtype import DType


class DevCol:
    """Device column value during expression evaluation (traced).

    ``dict_codes``/``dict_values``/``prefix8``: upload-computed metadata
    carried through from scanned DeviceColumns (columnar/column.py) —
    string predicates compile to dense code/image compares instead of
    per-row char gathers when present. Derived values carry None.

    Lazy (codes-only) source columns keep their laziness here: ``data``/
    ``offsets`` materialize chars from the static dictionary only when an
    expression actually reads them (``_src`` holds the backing
    DeviceColumn). An eager read in the eval-context constructor would
    rebuild the full char slab inside EVERY projection kernel touching a
    dict-encoded string the projection never inspects."""

    __slots__ = ("dtype", "_data", "validity", "_offsets", "dict_codes",
                 "dict_values", "prefix8", "_src")

    def __init__(self, dtype: DType, data, validity, offsets=None,
                 dict_codes=None, dict_values=None, prefix8=None,
                 src=None):
        self.dtype = dtype
        self._data = data         # (capacity,) or chars for strings
        self.validity = validity  # (capacity,) bool
        self._offsets = offsets   # strings: (capacity+1,) int32
        self.dict_codes = dict_codes
        self.dict_values = dict_values
        self.prefix8 = prefix8
        self._src = src           # lazy backing DeviceColumn (or None)

    @property
    def data(self):
        if self._data is None and self._src is not None:
            self._data = self._src.data  # materializes lazy chars
            self._offsets = self._src.offsets
        return self._data

    @data.setter
    def data(self, v) -> None:
        self._data = v

    @property
    def offsets(self):
        if (self._offsets is None and self._src is not None
                and self.dtype.is_string):
            self._data = self._src.data
            self._offsets = self._src.offsets
        return self._offsets

    @offsets.setter
    def offsets(self, v) -> None:
        self._offsets = v

    @property
    def is_lazy(self) -> bool:
        return self._data is None and self._src is not None

    def with_(self, data=None, validity=None, dtype=None) -> "DevCol":
        return DevCol(dtype or self.dtype,
                      self.data if data is None else data,
                      self.validity if validity is None else validity,
                      self.offsets)


class DevScalar:
    """Device scalar value (literal or reduced value), possibly null."""

    __slots__ = ("dtype", "value", "valid")

    def __init__(self, dtype: DType, value, valid=True):
        self.dtype = dtype
        self.value = value
        self.valid = valid


DevValue = Union[DevCol, DevScalar]


class EvalContext:
    """Binds a traced batch to expression evaluation.

    ``cols`` are the input DevCols (one per input schema field), ``row_mask``
    marks live rows (leading num_rows of the capacity).
    """

    def __init__(self, cols: List[DevCol], row_mask, num_rows, capacity: int):
        self.cols = cols
        self.row_mask = row_mask
        self.num_rows = num_rows
        self.capacity = capacity

    def broadcast(self, v: DevValue) -> DevCol:
        """Materialize a scalar into a column of this batch's capacity."""
        if isinstance(v, DevCol):
            return v
        if v.dtype.is_string:
            cap = self.capacity
            if not v.valid or v.value is None:
                return DevCol(v.dtype, jnp.zeros((16,), jnp.uint8),
                              jnp.zeros((cap,), jnp.bool_),
                              jnp.zeros((cap + 1,), jnp.int32))
            raw = np.frombuffer(str(v.value).encode("utf-8"), dtype=np.uint8)
            chars = jnp.asarray(np.tile(raw, cap)) if len(raw) else \
                jnp.zeros((16,), jnp.uint8)
            offsets = (jnp.arange(cap + 1, dtype=jnp.int32)
                       * jnp.int32(len(raw)))
            return DevCol(v.dtype, chars,
                          jnp.ones((cap,), jnp.bool_), offsets)
        data = jnp.full((self.capacity,), v.value,
                        dtype=v.dtype.np_dtype)
        validity = jnp.full((self.capacity,), v.valid, dtype=jnp.bool_)
        return DevCol(v.dtype, data, validity)


class Expression:
    """Base class. Subclasses define children, typing and the two evals."""

    def __init__(self, children: Sequence["Expression"] = ()):  # noqa: D401
        self.children: List[Expression] = list(children)

    # -- metadata -----------------------------------------------------------
    def dtype(self, schema: Schema) -> DType:
        raise NotImplementedError

    @property
    def pretty_name(self) -> str:
        return type(self).__name__

    def sql_name(self, schema: Optional[Schema] = None) -> str:
        """Column name this expression would produce (Spark-style)."""
        return self.pretty_name.lower()

    def __repr__(self) -> str:
        if self.children:
            return f"{self.pretty_name}({', '.join(map(repr, self.children))})"
        return self.pretty_name

    # -- evaluation ---------------------------------------------------------
    def eval_device(self, ctx: EvalContext) -> DevValue:
        raise NotImplementedError(f"{self.pretty_name} has no device kernel")

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        raise NotImplementedError(f"{self.pretty_name} has no host eval")

    # -- rewriting ----------------------------------------------------------
    def map_children(self, fn) -> "Expression":
        import copy
        new = copy.copy(self)
        new.children = [fn(c) for c in self.children]
        return new

    # -- support gate (used by the plan-rewrite tagging pass) ---------------
    def device_supported(self, schema: Schema) -> Optional[str]:
        """Return None if this node can run on the TPU, else a human-readable
        reason (the reference's willNotWorkOnGpu message,
        RapidsMeta.scala:123-124)."""
        return None


# ---------------------------------------------------------------------------
# Leaves
# ---------------------------------------------------------------------------

class Literal(Expression):
    def __init__(self, value: Any, dtype_: Optional[DType] = None):
        super().__init__()
        if dtype_ is None:
            dtype_ = _infer_literal_dtype(value)
        self.value = _canonicalize_literal(value, dtype_)
        self._dtype = dtype_

    def dtype(self, schema: Schema) -> DType:
        return self._dtype

    def sql_name(self, schema=None) -> str:
        return str(self.value)

    def __repr__(self) -> str:
        return f"lit({self.value!r})"

    def eval_device(self, ctx: EvalContext) -> DevValue:
        if self.value is None:
            fill = (0 if not self._dtype.is_string
                    else None)
            return DevScalar(self._dtype, fill, valid=False)
        if self._dtype.is_string:
            return DevScalar(self._dtype, self.value)
        return DevScalar(self._dtype,
                         jnp.asarray(self.value, dtype=self._dtype.np_dtype))

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        n = len(df)
        if self.value is None:
            return pd.Series([pd.NA] * n, dtype=self._dtype.pandas_nullable,
                             index=df.index)
        if self._dtype.is_string:
            return pd.Series([self.value] * n, dtype="str", index=df.index)
        if self._dtype == dtypes.TIMESTAMP_US:
            return pd.Series(np.full(n, self.value, dtype="datetime64[us]"),
                             index=df.index)
        if self._dtype == dtypes.DATE32:
            return pd.Series(
                np.full(n, self.value, dtype="datetime64[D]").astype(
                    "datetime64[s]"), index=df.index)
        return pd.Series(np.full(n, self.value, dtype=self._dtype.np_dtype),
                         index=df.index)


def _infer_literal_dtype(value: Any) -> DType:
    import datetime
    if isinstance(value, bool):
        return dtypes.BOOL
    if isinstance(value, (int, np.integer)):
        return dtypes.INT64 if not isinstance(value, np.int32) else dtypes.INT32
    if isinstance(value, (float, np.floating)):
        return dtypes.FLOAT64
    if isinstance(value, str):
        return dtypes.STRING
    if isinstance(value, (datetime.datetime, pd.Timestamp, np.datetime64)):
        return dtypes.TIMESTAMP_US
    if isinstance(value, datetime.date):
        return dtypes.DATE32
    if value is None:
        raise TypeError("null literal needs an explicit dtype")
    raise TypeError(f"cannot infer literal type for {value!r}")


def _canonicalize_literal(value: Any, dt: DType) -> Any:
    """Store date/timestamp literals in their physical representation
    (days / microseconds since epoch)."""
    import datetime
    if value is None:
        return None
    if dt == dtypes.DATE32 and isinstance(value, datetime.date) \
            and not isinstance(value, datetime.datetime):
        return (np.datetime64(value, "D") - np.datetime64(0, "D")).astype(int)
    if dt == dtypes.TIMESTAMP_US and isinstance(
            value, (datetime.datetime, pd.Timestamp, np.datetime64)):
        return int(np.datetime64(value, "us").astype(np.int64))
    return value


class Col(Expression):
    """Unresolved column reference by name."""

    def __init__(self, name: str):
        super().__init__()
        self.name = name

    def dtype(self, schema: Schema) -> DType:
        return schema.dtype_of(self.name)

    def sql_name(self, schema=None) -> str:
        return self.name

    def __repr__(self) -> str:
        return f"col({self.name!r})"

    def eval_device(self, ctx: EvalContext) -> DevValue:
        raise RuntimeError(f"unbound column reference {self.name!r}; "
                           "bind_references must run before execution")

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        return df[self.name]


class BoundRef(Expression):
    """Column reference bound to an input ordinal (the reference's
    GpuBoundReference, GpuBoundAttribute.scala:89)."""

    def __init__(self, index: int, dtype_: DType, name: str = ""):
        super().__init__()
        self.index = index
        self._dtype = dtype_
        self.name = name

    def dtype(self, schema: Schema) -> DType:
        return self._dtype

    def sql_name(self, schema=None) -> str:
        return self.name or f"c{self.index}"

    def __repr__(self) -> str:
        return f"input[{self.index}]"

    def eval_device(self, ctx: EvalContext) -> DevValue:
        return ctx.cols[self.index]

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        s = df.iloc[:, self.index]
        if self._dtype == dtypes.DATE32:
            # host dates ride as datetime64 micros; mark the logical type
            # for date-aware consumers (shallow copy: attrs are per-object)
            s = s.copy(deep=False)
            s.attrs["srt_logical_dtype"] = "date32"
        return s


class Alias(Expression):
    def __init__(self, child: Expression, name: str):
        super().__init__([child])
        self.name = name

    def dtype(self, schema: Schema) -> DType:
        return self.children[0].dtype(schema)

    def sql_name(self, schema=None) -> str:
        return self.name

    def __repr__(self) -> str:
        return f"{self.children[0]!r} AS {self.name}"

    def eval_device(self, ctx: EvalContext) -> DevValue:
        return self.children[0].eval_device(ctx)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        return self.children[0].eval_host(df)

    def device_supported(self, schema: Schema) -> Optional[str]:
        return None


# ---------------------------------------------------------------------------
# Binding / traversal helpers
# ---------------------------------------------------------------------------

def bind_references(expr: Expression, schema: Schema) -> Expression:
    """Replace Col(name) with BoundRef(ordinal) against ``schema``."""
    if isinstance(expr, Col):
        idx = schema.index_of(expr.name)
        return BoundRef(idx, schema.dtypes[idx], expr.name)
    return expr.map_children(lambda c: bind_references(c, schema))


def walk(expr: Expression):
    yield expr
    for c in expr.children:
        yield from walk(c)


def first_unsupported(expr: Expression, schema: Schema) -> Optional[str]:
    """Depth-first search for the first device-unsupported node; returns the
    reason string or None. Used by the tagging pass."""
    for node in walk(expr):
        reason = node.device_supported(schema)
        if reason:
            return f"{node.pretty_name}: {reason}"
        # a node with no device kernel at all
        if type(node).eval_device is Expression.eval_device:
            return f"{node.pretty_name} has no TPU implementation"
    return None


# ---------------------------------------------------------------------------
# Shared device helpers
# ---------------------------------------------------------------------------

def valid_and(ctx: EvalContext, *vals: DevValue):
    """Conjunction of validity across operands (standard SQL null
    propagation for non-Kleene ops)."""
    out = None
    for v in vals:
        if isinstance(v, DevScalar):
            cur = jnp.full((ctx.capacity,), bool(v.valid) if isinstance(v.valid, bool) else v.valid,
                           dtype=jnp.bool_)
        else:
            cur = v.validity
        out = cur if out is None else (out & cur)
    return out


def data_of(ctx: EvalContext, v: DevValue):
    """Raw data array (broadcasting scalars)."""
    if isinstance(v, DevScalar):
        return jnp.asarray(v.value, dtype=v.dtype.np_dtype)
    return v.data


def is_nullable_series(s: pd.Series) -> bool:
    return s.isna().any()
