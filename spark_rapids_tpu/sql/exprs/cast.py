"""Cast (reference: GpuCast.scala:240-877 — the dtype x dtype matrix).

Non-ANSI Spark semantics, Java-style conversions:
  * int -> narrower int truncates (wraps) like Java;
  * float -> int: NaN -> 0, +/-inf and out-of-range clamp to min/max, else
    truncate toward zero ((int) in Java);
  * bool <-> numeric; timestamp <-> long is *seconds*; date <-> timestamp;
  * string casts are gated behind conf flags like the reference
    (RapidsConf.scala:393-423) and tag the plan off-device when disabled.

One generic formula evaluated under numpy (host) or jax.numpy (device).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np
import pandas as pd

from spark_rapids_tpu.columnar import dtypes
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.columnar.dtype import DType
from spark_rapids_tpu.sql.exprs.core import (
    DevCol, DevScalar, DevValue, EvalContext, Expression,
)
from spark_rapids_tpu.sql.exprs.hostutil import host_unary_values, rebuild_series

_INT_RANGE = {
    "int8": (-128, 127),
    "int16": (-(1 << 15), (1 << 15) - 1),
    "int32": (-(1 << 31), (1 << 31) - 1),
    "int64": (-(1 << 63), (1 << 63) - 1),
}

MICROS_PER_SEC = 1_000_000
MICROS_PER_DAY = 86_400 * MICROS_PER_SEC


def cast_data(xp, data, src: DType, dst: DType):
    """Cast raw (already null-canonicalized) data. Returns (data, extra_null)
    where extra_null marks rows that become NULL."""
    if src == dst:
        return data, None
    if src == dtypes.BOOL:
        return data.astype(dst.np_dtype), None
    if dst == dtypes.BOOL:
        return (data != 0), None
    if src.is_integral and dst.is_integral:
        return data.astype(dst.np_dtype), None  # wraps like Java
    if src.is_integral and dst.is_floating:
        return data.astype(dst.np_dtype), None
    if src.is_floating and dst.is_integral:
        lo, hi = _INT_RANGE[dst.name]
        d64 = data.astype(np.float64)
        out = xp.where(xp.isnan(d64), 0.0, d64)
        out = xp.clip(xp.trunc(out), float(lo), float(hi))
        return out.astype(dst.np_dtype), None
    if src.is_floating and dst.is_floating:
        return data.astype(dst.np_dtype), None
    if src == dtypes.TIMESTAMP_US and dst.is_integral:
        # cast timestamp -> long yields seconds (floor)
        secs = xp.floor_divide(data, MICROS_PER_SEC)
        return secs.astype(dst.np_dtype), None
    if src.is_integral and dst == dtypes.TIMESTAMP_US:
        return (data.astype(np.int64) * MICROS_PER_SEC), None
    if src == dtypes.TIMESTAMP_US and dst == dtypes.DATE32:
        days = xp.floor_divide(data, MICROS_PER_DAY)
        return days.astype(np.int32), None
    if src == dtypes.DATE32 and dst == dtypes.TIMESTAMP_US:
        return data.astype(np.int64) * MICROS_PER_DAY, None
    if src == dtypes.TIMESTAMP_US and dst.is_floating:
        return (data.astype(np.float64) / MICROS_PER_SEC).astype(dst.np_dtype), None
    raise NotImplementedError(f"cast {src} -> {dst}")


def _cast_strings_host(values, validity, src: DType, dst: DType):
    """String-involved casts on the host path (non-ANSI Spark semantics:
    unparseable strings become NULL; reference GpuCast.scala:240-877
    string<->numeric/timestamp arms, gated off-device by the same confs).
    """
    n = len(values)
    if dst.is_string:
        out = np.empty(n, dtype=object)
        for i in range(n):
            if not validity[i]:
                out[i] = None
                continue
            v = values[i]
            if src == dtypes.BOOL:
                out[i] = "true" if v else "false"
            elif src == dtypes.DATE32:
                out[i] = str(np.datetime64(int(v), "D"))
            elif src == dtypes.TIMESTAMP_US:
                out[i] = str(np.datetime64(int(v), "us")).replace("T", " ")
            elif src.is_floating:
                fv = float(v)
                if np.isnan(fv):
                    out[i] = "NaN"
                elif np.isinf(fv):
                    out[i] = "Infinity" if fv > 0 else "-Infinity"
                else:
                    out[i] = repr(fv)
            elif src.is_string:
                out[i] = v
            else:
                out[i] = str(int(v))
        return out, validity.copy()

    # string -> typed
    out_validity = validity.copy()
    if dst.is_string:
        raise AssertionError  # handled above
    fill = dtypes.null_fill_value(dst)
    out = np.full(n, fill, dtype=dst.np_dtype)
    for i in range(n):
        if not validity[i]:
            continue
        # the explicit ASCII whitespace set shared with the device
        # parsers (ops/strings.py _nonws_span) — python's default strip()
        # also removes exotic unicode spaces the device does not
        text = str(values[i]).strip(" \t\n\r\v\f")
        try:
            if dst == dtypes.BOOL:
                low = text.lower()
                if low in ("true", "t", "yes", "y", "1"):
                    out[i] = True
                elif low in ("false", "f", "no", "n", "0"):
                    out[i] = False
                else:
                    raise ValueError(text)
            elif dst.is_integral:
                # accepted form shared with the device parser
                # (ops/strings.py string_to_integral): optional sign,
                # >=1 integer digits, optional truncated '.digits*' tail;
                # exponent forms are NULL
                import re
                if not re.match(r"^[+-]?\d+(\.\d*)?$", text, re.ASCII):
                    raise ValueError(text)
                v = int(text.split(".")[0])
                lo, hi = _INT_RANGE[dst.name]
                if not (lo <= v <= hi):
                    raise ValueError(text)
                out[i] = v
            elif dst.is_floating:
                out[i] = float(text)
            elif dst == dtypes.DATE32:
                import re
                if not re.match(r"^\d{4}-\d{2}-\d{2}", text, re.ASCII):
                    raise ValueError(text)  # Spark needs yyyy-MM-dd...
                out[i] = (np.datetime64(text[:10], "D")
                          - np.datetime64(0, "D")).astype(np.int32)
            elif dst == dtypes.TIMESTAMP_US:
                import re
                if not re.match(r"^\d{4}-\d{2}-\d{2}", text, re.ASCII):
                    raise ValueError(text)
                out[i] = np.datetime64(
                    text.replace(" ", "T"), "us").astype(np.int64)
            else:
                raise ValueError(f"cast string -> {dst}")
        except (ValueError, OverflowError):
            out_validity[i] = False  # unparseable -> NULL (non-ANSI)
    return out, out_validity


def _castable(src: DType, dst: DType) -> bool:
    try:
        probe = np.zeros(1, dtype=src.np_dtype) if not src.is_string else None
        if src.is_string or dst.is_string:
            return False
        cast_data(np, probe, src, dst)
        return True
    except NotImplementedError:
        return False


class Cast(Expression):
    def __init__(self, child: Expression, to: DType):
        super().__init__([child])
        self.to = to

    def dtype(self, schema: Schema) -> DType:
        return self.to

    def sql_name(self, schema=None) -> str:
        return f"CAST({self.children[0].sql_name(schema)} AS {self.to.name})"

    @staticmethod
    def _conf_enabled(key: str) -> bool:
        from spark_rapids_tpu.session import TpuSparkSession
        s = TpuSparkSession._active
        return bool(s and s.conf.get(key))

    def device_supported(self, schema: Schema) -> Optional[str]:
        src = self.children[0].dtype(schema)
        if src == self.to:
            return None
        if self.to.is_string:
            # to-string renders on device like cuDF's castTo
            # (GpuCast.scala:240-877) for integral/bool/date sources;
            # float/timestamp formatting stays host-side
            if src.is_integral or src == dtypes.BOOL or src == dtypes.DATE32:
                return None
            return (f"cast {src} -> string formatting is not supported "
                    "on TPU")
        if src.is_string:
            if self.to.is_integral and self._conf_enabled(
                    "spark.rapids.sql.castStringToInteger.enabled"):
                return None
            if self.to == dtypes.DATE32 and self._conf_enabled(
                    "spark.rapids.sql.castStringToDate.enabled"):
                return None
            return (f"cast {src} -> {self.to} involves string parsing and "
                    "is gated off by default "
                    "(see spark.rapids.sql.castStringTo*)")
        if not _castable(src, self.to):
            return f"cast {src} -> {self.to} is not supported"
        return None

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = self.children[0].eval_device(ctx)
        if v.dtype == self.to:
            return v
        if self.to.is_string or v.dtype.is_string:
            return self._eval_device_string(ctx, v)
        if isinstance(v, DevScalar):
            data, extra = cast_data(jnp, jnp.asarray(v.value), v.dtype, self.to)
            return DevScalar(self.to, data, v.valid)
        data, extra = cast_data(jnp, v.data, v.dtype, self.to)
        validity = v.validity if extra is None else v.validity & ~extra
        return DevCol(self.to, data, validity)

    def _eval_device_string(self, ctx: EvalContext, v) -> DevValue:
        from spark_rapids_tpu.ops import strings as string_ops
        if isinstance(v, DevScalar) and v.dtype.is_string:
            # string literals carry concrete python str values: parse at
            # trace time, emit a typed scalar
            if not v.valid:
                return DevScalar(self.to,
                                 None if self.to.is_string else jnp.asarray(
                                     0, dtype=self.to.np_dtype), False)
            host, hv = _cast_strings_host(
                np.array([v.value], dtype=object),
                np.array([True]), v.dtype, self.to)
            if self.to.is_string:
                return DevScalar(self.to, host[0], bool(hv[0]))
            return DevScalar(
                self.to, jnp.asarray(host[0], dtype=self.to.np_dtype),
                bool(hv[0]))
        if isinstance(v, DevScalar):
            # numeric/bool/date scalar -> string: the value may be a
            # tracer, so render through the column kernels on a broadcast
            v = ctx.broadcast(v)
        if self.to.is_string:
            if v.dtype == dtypes.BOOL:
                return string_ops.strings_from_choices(
                    ctx, v.data.astype(jnp.int32), ["false", "true"],
                    v.validity)
            if v.dtype == dtypes.DATE32:
                return string_ops.date_to_string(ctx, v.data, v.validity)
            assert v.dtype.is_integral, v.dtype
            return string_ops.integral_to_string(ctx, v.data, v.validity)
        if self.to == dtypes.DATE32:
            days, ok = string_ops.string_to_date(ctx, v)
            return DevCol(self.to, days, v.validity & ok)
        assert v.dtype.is_string and self.to.is_integral, (v.dtype, self.to)
        data, ok = string_ops.string_to_integral(ctx, v, self.to)
        return DevCol(self.to, data.astype(self.to.np_dtype),
                      v.validity & ok)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        s = self.children[0].eval_host(df)
        values, validity, index = host_unary_values(s)
        from spark_rapids_tpu.sql.exprs.hostutil import series_dtype
        # the logical dtype, not the unpacked numpy dtype: timestamps/dates
        # unpack to int64 micros / int32 days and would mis-dispatch
        src = series_dtype(s)
        if (self.to.is_string and src == dtypes.TIMESTAMP_US
                and s.attrs.get("srt_logical_dtype") == "date32"):
            # logically a date riding as midnight micros (host convention):
            # unpack to days so string rendering says 'yyyy-MM-dd'
            src = dtypes.DATE32
            values = values.astype(np.int64) // 86_400_000_000
        if src.is_string or self.to.is_string:
            data, validity = _cast_strings_host(values, validity, src,
                                                self.to)
            return rebuild_series(data, validity, self.to, index)
        # the host twin stores timestamps as datetime64 -> int64 micros already
        with np.errstate(all="ignore"):
            data, extra = cast_data(np, values, src, self.to)
        if extra is not None:
            validity = validity & ~extra
        return rebuild_series(data, validity, self.to, index)
