"""Host (pandas) evaluation helpers.

The CPU path unpacks pandas Series (numpy-backed or nullable-extension) into
plain (values, validity) numpy pairs, applies the same formula the device
kernel uses, and rebuilds a Series — keeping null semantics identical to the
device path's (data, validity) discipline.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np
import pandas as pd

from spark_rapids_tpu.columnar import dtypes
from spark_rapids_tpu.columnar.batch import _numpy_to_pandas, _pandas_col_dtype, _pandas_to_numpy
from spark_rapids_tpu.columnar.dtype import DType


def host_unary_values(s: pd.Series) -> Tuple[np.ndarray, np.ndarray, pd.Index]:
    dt = _pandas_col_dtype(s)
    values, validity = _pandas_to_numpy(s, dt)
    return values, validity, s.index


def host_binary_values(a: pd.Series, b: pd.Series):
    av, amask, index = host_unary_values(a)
    bv, bmask, _ = host_unary_values(b)
    return (av, bv), amask & bmask, index


def rebuild_series(data: np.ndarray, validity: np.ndarray, dt: DType,
                   index: pd.Index) -> pd.Series:
    data = np.asarray(data)
    if not dt.is_string and data.dtype != dt.np_dtype:
        data = data.astype(dt.np_dtype)
    # canonicalize nulls so padding never leaks values
    if not validity.all():
        if dt.is_string:
            data = data.copy()
            data[~validity] = None
        else:
            data = np.where(validity, data,
                            np.asarray(dtypes.null_fill_value(dt),
                                       dtype=data.dtype))
    s = _numpy_to_pandas(data, validity, dt)
    s.index = index
    return s


def series_dtype(s: pd.Series) -> DType:
    return _pandas_col_dtype(s)
