"""Aggregate functions (reference: sql/rapids/AggregateFunctions.scala:69-502).

Each aggregate declares itself as *update* reductions over input expressions,
*merge* reductions over intermediate columns, and a *finalize* expression —
exactly the reference's ``CudfAggregate`` update/merge pair design, which is
what makes partial/final (two-phase, shuffle-separated) aggregation work.

Reduction kinds understood by the device groupby kernel (ops/groupby.py) and
the host path: 'sum', 'min', 'max', 'count_valid', 'first', 'last', 'any'.

SQL null semantics: aggregates skip NULLs; sum/min/max/avg of an all-NULL (or
empty) group is NULL; count is 0.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import pandas as pd

from spark_rapids_tpu.columnar import dtypes
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.columnar.dtype import DType
from spark_rapids_tpu.sql.exprs.core import Expression


class AggregateFunction(Expression):
    """Children are the input value expressions."""

    is_aggregate = True

    def dtype(self, schema: Schema) -> DType:
        raise NotImplementedError

    # --- the CudfAggregate-style decomposition -----------------------------
    def update_ops(self) -> List[Tuple[str, int]]:
        """[(reduction_kind, child_index)] producing intermediate columns."""
        raise NotImplementedError

    def merge_ops(self) -> List[str]:
        """reduction kinds merging intermediates across batches/partitions."""
        raise NotImplementedError

    def intermediate_dtypes(self, schema: Schema) -> List[DType]:
        raise NotImplementedError

    def finalize(self, refs: List[Expression], schema: Schema) -> Expression:
        """Expression over intermediate refs computing the final value."""
        raise NotImplementedError

    def device_supported(self, schema: Schema) -> Optional[str]:
        for c in self.children:
            if c.dtype(schema).is_string:
                return f"{self.pretty_name} over strings is not supported on TPU"
        return None


def _sum_result_dtype(t: DType) -> DType:
    if t.is_integral or t == dtypes.BOOL:
        return dtypes.INT64
    return dtypes.FLOAT64


class Sum(AggregateFunction):
    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return _sum_result_dtype(self.children[0].dtype(schema))

    def sql_name(self, schema=None) -> str:
        return f"sum({self.children[0].sql_name(schema)})"

    def update_ops(self): return [("sum", 0)]
    def merge_ops(self): return ["sum"]

    def intermediate_dtypes(self, schema):
        return [self.dtype(schema)]

    def finalize(self, refs, schema):
        return refs[0]


class Count(AggregateFunction):
    """count(expr): counts non-NULL rows. count(lit(1)) == count(*)."""

    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.INT64

    def sql_name(self, schema=None) -> str:
        return f"count({self.children[0].sql_name(schema)})"

    def update_ops(self): return [("count_valid", 0)]
    def merge_ops(self): return ["sum"]

    def intermediate_dtypes(self, schema):
        return [dtypes.INT64]

    def finalize(self, refs, schema):
        return refs[0]

    def device_supported(self, schema: Schema) -> Optional[str]:
        return None  # count works for any input type incl. strings


class Min(AggregateFunction):
    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return self.children[0].dtype(schema)

    def sql_name(self, schema=None) -> str:
        return f"min({self.children[0].sql_name(schema)})"

    def update_ops(self): return [("min", 0)]
    def merge_ops(self): return ["min"]

    def intermediate_dtypes(self, schema):
        return [self.dtype(schema)]

    def finalize(self, refs, schema):
        return refs[0]


class Max(AggregateFunction):
    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return self.children[0].dtype(schema)

    def sql_name(self, schema=None) -> str:
        return f"max({self.children[0].sql_name(schema)})"

    def update_ops(self): return [("max", 0)]
    def merge_ops(self): return ["max"]

    def intermediate_dtypes(self, schema):
        return [self.dtype(schema)]

    def finalize(self, refs, schema):
        return refs[0]


class Average(AggregateFunction):
    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.FLOAT64

    def sql_name(self, schema=None) -> str:
        return f"avg({self.children[0].sql_name(schema)})"

    def update_ops(self): return [("sum", 0), ("count_valid", 0)]
    def merge_ops(self): return ["sum", "sum"]

    def intermediate_dtypes(self, schema):
        return [dtypes.FLOAT64, dtypes.INT64]

    def finalize(self, refs, schema):
        from spark_rapids_tpu.sql.exprs.arithmetic import Divide
        # Divide yields NULL on zero count — matching avg(empty) = NULL
        return Divide(refs[0], refs[1])


class First(AggregateFunction):
    def __init__(self, child: Expression, ignore_nulls: bool = False):
        super().__init__([child])
        self.ignore_nulls = ignore_nulls

    def dtype(self, schema: Schema) -> DType:
        return self.children[0].dtype(schema)

    def sql_name(self, schema=None) -> str:
        return f"first({self.children[0].sql_name(schema)})"

    def update_ops(self):
        return [("first_valid" if self.ignore_nulls else "first", 0)]

    def merge_ops(self):
        return ["first_valid" if self.ignore_nulls else "first"]

    def intermediate_dtypes(self, schema):
        return [self.dtype(schema)]

    def finalize(self, refs, schema):
        return refs[0]


class Last(AggregateFunction):
    def __init__(self, child: Expression, ignore_nulls: bool = False):
        super().__init__([child])
        self.ignore_nulls = ignore_nulls

    def dtype(self, schema: Schema) -> DType:
        return self.children[0].dtype(schema)

    def sql_name(self, schema=None) -> str:
        return f"last({self.children[0].sql_name(schema)})"

    def update_ops(self):
        return [("last_valid" if self.ignore_nulls else "last", 0)]

    def merge_ops(self):
        return ["last_valid" if self.ignore_nulls else "last"]

    def intermediate_dtypes(self, schema):
        return [self.dtype(schema)]

    def finalize(self, refs, schema):
        return refs[0]


def find_aggregates(expr: Expression) -> List[AggregateFunction]:
    out = []
    if isinstance(expr, AggregateFunction):
        out.append(expr)
        return out
    for c in expr.children:
        out.extend(find_aggregates(c))
    return out


def has_aggregate(expr: Expression) -> bool:
    return len(find_aggregates(expr)) > 0
