"""Aggregate functions (reference: sql/rapids/AggregateFunctions.scala:69-502).

Each aggregate declares itself as *update* reductions over input expressions,
*merge* reductions over intermediate columns, and a *finalize* expression —
exactly the reference's ``CudfAggregate`` update/merge pair design, which is
what makes partial/final (two-phase, shuffle-separated) aggregation work.

Reduction kinds understood by the device groupby kernel (ops/groupby.py) and
the host path: 'sum', 'min', 'max', 'count_valid', 'first', 'last', 'any'.

SQL null semantics: aggregates skip NULLs; sum/min/max/avg of an all-NULL (or
empty) group is NULL; count is 0.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import pandas as pd

from spark_rapids_tpu.columnar import dtypes
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.columnar.dtype import DType
from spark_rapids_tpu.sql.exprs.core import Expression


class AggregateFunction(Expression):
    """Children are the input value expressions."""

    is_aggregate = True

    def dtype(self, schema: Schema) -> DType:
        raise NotImplementedError

    # --- the CudfAggregate-style decomposition -----------------------------
    def update_ops(self) -> List[Tuple[str, int]]:
        """[(reduction_kind, child_index)] producing intermediate columns."""
        raise NotImplementedError

    def merge_ops(self) -> List[str]:
        """reduction kinds merging intermediates across batches/partitions."""
        raise NotImplementedError

    def intermediate_dtypes(self, schema: Schema) -> List[DType]:
        raise NotImplementedError

    def finalize(self, refs: List[Expression], schema: Schema) -> Expression:
        """Expression over intermediate refs computing the final value."""
        raise NotImplementedError

    def device_supported(self, schema: Schema) -> Optional[str]:
        for c in self.children:
            if c.dtype(schema).is_string:
                return f"{self.pretty_name} over strings is not supported on TPU"
        return None


def _sum_result_dtype(t: DType) -> DType:
    if t.is_integral or t == dtypes.BOOL:
        return dtypes.INT64
    return dtypes.FLOAT64


class Sum(AggregateFunction):
    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return _sum_result_dtype(self.children[0].dtype(schema))

    def sql_name(self, schema=None) -> str:
        return f"sum({self.children[0].sql_name(schema)})"

    def update_ops(self): return [("sum", 0)]
    def merge_ops(self): return ["sum"]

    def intermediate_dtypes(self, schema):
        return [self.dtype(schema)]

    def finalize(self, refs, schema):
        return refs[0]


class Count(AggregateFunction):
    """count(expr): counts non-NULL rows. count(lit(1)) == count(*)."""

    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.INT64

    def sql_name(self, schema=None) -> str:
        return f"count({self.children[0].sql_name(schema)})"

    def update_ops(self): return [("count_valid", 0)]
    def merge_ops(self): return ["sum"]

    def intermediate_dtypes(self, schema):
        return [dtypes.INT64]

    def finalize(self, refs, schema):
        return refs[0]

    def device_supported(self, schema: Schema) -> Optional[str]:
        return None  # count works for any input type incl. strings


class Min(AggregateFunction):
    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return self.children[0].dtype(schema)

    def sql_name(self, schema=None) -> str:
        return f"min({self.children[0].sql_name(schema)})"

    def update_ops(self): return [("min", 0)]
    def merge_ops(self): return ["min"]

    def intermediate_dtypes(self, schema):
        return [self.dtype(schema)]

    def finalize(self, refs, schema):
        return refs[0]

    def device_supported(self, schema: Schema) -> Optional[str]:
        return None  # selection-based reductions support strings on device


class Max(AggregateFunction):
    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return self.children[0].dtype(schema)

    def sql_name(self, schema=None) -> str:
        return f"max({self.children[0].sql_name(schema)})"

    def update_ops(self): return [("max", 0)]
    def merge_ops(self): return ["max"]

    def intermediate_dtypes(self, schema):
        return [self.dtype(schema)]

    def finalize(self, refs, schema):
        return refs[0]

    def device_supported(self, schema: Schema) -> Optional[str]:
        return None  # selection-based reductions support strings on device


class Average(AggregateFunction):
    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.FLOAT64

    def sql_name(self, schema=None) -> str:
        return f"avg({self.children[0].sql_name(schema)})"

    def update_ops(self): return [("sum", 0), ("count_valid", 0)]
    def merge_ops(self): return ["sum", "sum"]

    def intermediate_dtypes(self, schema):
        return [dtypes.FLOAT64, dtypes.INT64]

    def finalize(self, refs, schema):
        from spark_rapids_tpu.sql.exprs.arithmetic import Divide
        # Divide yields NULL on zero count — matching avg(empty) = NULL
        return Divide(refs[0], refs[1])


class First(AggregateFunction):
    def __init__(self, child: Expression, ignore_nulls: bool = False):
        super().__init__([child])
        self.ignore_nulls = ignore_nulls

    def dtype(self, schema: Schema) -> DType:
        return self.children[0].dtype(schema)

    def sql_name(self, schema=None) -> str:
        return f"first({self.children[0].sql_name(schema)})"

    def update_ops(self):
        return [("first_valid" if self.ignore_nulls else "first", 0)]

    def merge_ops(self):
        return ["first_valid" if self.ignore_nulls else "first"]

    def intermediate_dtypes(self, schema):
        return [self.dtype(schema)]

    def finalize(self, refs, schema):
        return refs[0]

    def device_supported(self, schema: Schema) -> Optional[str]:
        return None  # selection-based reductions support strings on device


class Last(AggregateFunction):
    def __init__(self, child: Expression, ignore_nulls: bool = False):
        super().__init__([child])
        self.ignore_nulls = ignore_nulls

    def dtype(self, schema: Schema) -> DType:
        return self.children[0].dtype(schema)

    def sql_name(self, schema=None) -> str:
        return f"last({self.children[0].sql_name(schema)})"

    def update_ops(self):
        return [("last_valid" if self.ignore_nulls else "last", 0)]

    def merge_ops(self):
        return ["last_valid" if self.ignore_nulls else "last"]

    def intermediate_dtypes(self, schema):
        return [self.dtype(schema)]

    def finalize(self, refs, schema):
        return refs[0]

    def device_supported(self, schema: Schema) -> Optional[str]:
        return None  # selection-based reductions support strings on device


def _float(e: Expression) -> Expression:
    from spark_rapids_tpu.sql.exprs.cast import Cast
    return Cast(e, dtypes.FLOAT64)


def _null_if_other_null(value: Expression, other: Expression) -> Expression:
    """``value`` where ``other`` is non-NULL, else NULL — pairwise-deletion
    masking for the bivariate moments (SQL corr skips a row if either
    input is NULL)."""
    from spark_rapids_tpu.sql.exprs.conditional import If
    from spark_rapids_tpu.sql.exprs.core import Literal
    from spark_rapids_tpu.sql.exprs.predicates import IsNotNull
    return If(IsNotNull(other), value, Literal(None, dtypes.FLOAT64))


class _CentralMoment(AggregateFunction):
    """var/stddev via the (n, Σx, Σx²) sufficient statistics — three plain
    sums that re-aggregate across batches and shuffle partitions, the shape
    the two-phase update/merge pipeline wants (no Welford state needed: the
    merge operator is just +)."""

    sample = True  # n-1 denominator

    def __init__(self, child: Expression):
        x = _float(child)
        from spark_rapids_tpu.sql.exprs.arithmetic import Multiply
        super().__init__([x, Multiply(x, x)])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.FLOAT64

    def update_ops(self):
        return [("count_valid", 0), ("sum", 0), ("sum", 1)]

    def merge_ops(self): return ["sum", "sum", "sum"]

    def intermediate_dtypes(self, schema):
        return [dtypes.INT64, dtypes.FLOAT64, dtypes.FLOAT64]

    def _variance(self, refs, schema):
        from spark_rapids_tpu.sql.exprs.arithmetic import (
            Divide, Multiply, Subtract,
        )
        from spark_rapids_tpu.sql.exprs.cast import Cast
        from spark_rapids_tpu.sql.exprs.core import Literal
        from spark_rapids_tpu.sql.exprs.nullexprs import Greatest
        n, sx, sxx = refs
        nf = Cast(n, dtypes.FLOAT64)
        # Σ(x-μ)² = Σx² - (Σx)²/n; clamp the tiny negative residue floating
        # point can leave so sqrt never sees it
        ss = Greatest([Subtract(sxx, Divide(Multiply(sx, sx), nf)),
                       Literal(0.0)])
        denom = (Subtract(nf, Literal(1.0)) if self.sample else nf)
        # Divide-by-zero yields NULL: var_samp of 1 row / var_pop of 0 rows
        return Divide(ss, denom)

    def finalize(self, refs, schema):
        return self._variance(refs, schema)


class VarSamp(_CentralMoment):
    sample = True

    def sql_name(self, schema=None) -> str:
        return f"var_samp({self.children[0].sql_name(schema)})"


class VarPop(_CentralMoment):
    sample = False

    def sql_name(self, schema=None) -> str:
        return f"var_pop({self.children[0].sql_name(schema)})"


class StddevSamp(_CentralMoment):
    sample = True

    def sql_name(self, schema=None) -> str:
        return f"stddev_samp({self.children[0].sql_name(schema)})"

    def finalize(self, refs, schema):
        from spark_rapids_tpu.sql.exprs.mathexprs import Sqrt
        return Sqrt(self._variance(refs, schema))


class StddevPop(_CentralMoment):
    sample = False

    def sql_name(self, schema=None) -> str:
        return f"stddev_pop({self.children[0].sql_name(schema)})"

    def finalize(self, refs, schema):
        from spark_rapids_tpu.sql.exprs.mathexprs import Sqrt
        return Sqrt(self._variance(refs, schema))


class Corr(AggregateFunction):
    """Pearson correlation from the five pairwise-masked sums + the pair
    count — again all-+ merges, so partial/final and the mesh shuffle
    need nothing new."""

    def __init__(self, left: Expression, right: Expression):
        from spark_rapids_tpu.sql.exprs.arithmetic import Multiply
        x, y = _float(left), _float(right)
        xm = _null_if_other_null(x, y)
        ym = _null_if_other_null(y, x)
        super().__init__([xm, ym, Multiply(x, y),
                          Multiply(xm, xm), Multiply(ym, ym)])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.FLOAT64

    def sql_name(self, schema=None) -> str:
        return "corr(...)"

    def update_ops(self):
        return [("count_valid", 2), ("sum", 0), ("sum", 1),
                ("sum", 2), ("sum", 3), ("sum", 4)]

    def merge_ops(self): return ["sum"] * 6

    def intermediate_dtypes(self, schema):
        return [dtypes.INT64] + [dtypes.FLOAT64] * 5

    def finalize(self, refs, schema):
        from spark_rapids_tpu.sql.exprs.arithmetic import (
            Divide, Multiply, Subtract,
        )
        from spark_rapids_tpu.sql.exprs.cast import Cast
        from spark_rapids_tpu.sql.exprs.core import Literal
        from spark_rapids_tpu.sql.exprs.mathexprs import Sqrt
        from spark_rapids_tpu.sql.exprs.nullexprs import Greatest
        n, sx, sy, sxy, sxx, syy = refs
        nf = Cast(n, dtypes.FLOAT64)
        cov = Subtract(sxy, Divide(Multiply(sx, sy), nf))
        vx = Greatest([Subtract(sxx, Divide(Multiply(sx, sx), nf)),
                       Literal(0.0)])
        vy = Greatest([Subtract(syy, Divide(Multiply(sy, sy), nf)),
                       Literal(0.0)])
        # zero variance -> sqrt gives 0 -> Divide yields NULL
        return Divide(cov, Sqrt(Multiply(vx, vy)))


class CountDistinct(AggregateFunction):
    """count(DISTINCT expr). Never executed directly: the DataFrame layer
    rewrites an aggregation containing it into a two-level aggregation
    (group by keys+expr with partial non-distinct aggs, then group by keys
    re-aggregating + counting the now-unique expr values) — the same
    distinct-expansion Spark plans and the reference falls back on when it
    can't (aggregate.scala:40-225 tags distinct+multiple-agg cases)."""

    is_distinct = True

    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return dtypes.INT64

    def sql_name(self, schema=None) -> str:
        return f"count(DISTINCT {self.children[0].sql_name(schema)})"

    def _not_executable(self):
        raise RuntimeError(
            "CountDistinct must be rewritten by the grouped-aggregation "
            "planner before execution")

    def update_ops(self): self._not_executable()
    def merge_ops(self): self._not_executable()
    def intermediate_dtypes(self, schema): self._not_executable()

    def device_supported(self, schema: Schema) -> Optional[str]:
        return None


def find_aggregates(expr: Expression) -> List[AggregateFunction]:
    out = []
    if isinstance(expr, AggregateFunction):
        out.append(expr)
        return out
    for c in expr.children:
        out.extend(find_aggregates(c))
    return out


def has_aggregate(expr: Expression) -> bool:
    return len(find_aggregates(expr)) > 0
