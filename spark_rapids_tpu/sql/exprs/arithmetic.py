"""Arithmetic expressions (reference: sql/rapids/arithmetic.scala, 227 LoC).

Semantics follow Spark SQL non-ANSI mode:
  * integer overflow wraps (Java semantics — numpy/jax match);
  * ``/`` (Divide) always produces double; divide-by-zero yields NULL;
  * ``%`` (Remainder) takes the sign of the dividend (Java), NULL on zero
    divisor; ``pmod`` is always non-negative.

Each op's formula is written once against an array namespace (numpy on the
host path, jax.numpy on the device path) so CPU and TPU results are computed
by the same code — differential parity by construction.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np
import pandas as pd

from spark_rapids_tpu.columnar import dtypes
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.columnar.dtype import DType, common_type
from spark_rapids_tpu.sql.exprs.core import (
    DevCol, DevScalar, DevValue, EvalContext, Expression, data_of, valid_and,
)
from spark_rapids_tpu.sql.exprs.hostutil import (
    host_binary_values, host_unary_values, rebuild_series,
)


class BinaryArithmetic(Expression):
    symbol = "?"

    def __init__(self, left: Expression, right: Expression):
        super().__init__([left, right])

    def dtype(self, schema: Schema) -> DType:
        return common_type(self.children[0].dtype(schema),
                           self.children[1].dtype(schema))

    def sql_name(self, schema=None) -> str:
        return (f"({self.children[0].sql_name(schema)} {self.symbol} "
                f"{self.children[1].sql_name(schema)})")

    def device_supported(self, schema: Schema) -> Optional[str]:
        for c in self.children:
            t = c.dtype(schema)
            if t.is_string:
                return "string operands are not supported for arithmetic"
            if t.is_datetime:
                # plain +,-,*,/ on dates/timestamps would reinterpret
                # day-counts as microseconds; use date_add & friends
                return (f"{self.symbol} on {t} is not supported; use the "
                        "date/time functions")
        return None

    # formula over the array namespace; result (data, extra_null_mask|None)
    def compute(self, xp, a, b, out_dt: DType):
        raise NotImplementedError

    def eval_device(self, ctx: EvalContext) -> DevValue:
        lv = self.children[0].eval_device(ctx)
        rv = self.children[1].eval_device(ctx)
        out_dt = self.dtype_from_children(lv.dtype, rv.dtype)
        a = data_of(ctx, lv).astype(out_dt.np_dtype)
        b = data_of(ctx, rv).astype(out_dt.np_dtype)
        data, extra_null = self.compute(jnp, a, b, out_dt)
        validity = valid_and(ctx, lv, rv)
        if extra_null is not None:
            validity = validity & ~extra_null
            data = jnp.where(extra_null, dtypes.null_fill_value(out_dt), data)
        return DevCol(out_dt, data, validity)

    def dtype_from_children(self, lt: DType, rt: DType) -> DType:
        return common_type(lt, rt)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        ls = self.children[0].eval_host(df)
        rs = self.children[1].eval_host(df)
        (a, b), validity, index = host_binary_values(ls, rs)
        out_dt = self.dtype_from_children(dtypes.from_numpy(a.dtype),
                                          dtypes.from_numpy(b.dtype))
        a = a.astype(out_dt.np_dtype)
        b = b.astype(out_dt.np_dtype)
        with np.errstate(all="ignore"):
            data, extra_null = self.compute(np, a, b, out_dt)
        if extra_null is not None:
            validity = validity & ~extra_null
        return rebuild_series(data, validity, out_dt, index)


class Add(BinaryArithmetic):
    symbol = "+"
    def compute(self, xp, a, b, out_dt):
        return a + b, None


class Subtract(BinaryArithmetic):
    symbol = "-"
    def compute(self, xp, a, b, out_dt):
        return a - b, None


class Multiply(BinaryArithmetic):
    symbol = "*"
    def compute(self, xp, a, b, out_dt):
        return a * b, None


class Divide(BinaryArithmetic):
    """Spark Divide: inputs coerced to double; x/0 -> NULL."""
    symbol = "/"

    def dtype(self, schema: Schema) -> DType:
        return dtypes.FLOAT64

    def dtype_from_children(self, lt: DType, rt: DType) -> DType:
        return dtypes.FLOAT64

    def compute(self, xp, a, b, out_dt):
        zero = b == 0.0
        safe = xp.where(zero, 1.0, b)
        return a / safe, zero


class IntegralDivide(BinaryArithmetic):
    """Spark ``div``: long division truncating toward zero; x div 0 -> NULL."""
    symbol = "div"

    def dtype(self, schema: Schema) -> DType:
        return dtypes.INT64

    def dtype_from_children(self, lt: DType, rt: DType) -> DType:
        return dtypes.INT64

    def compute(self, xp, a, b, out_dt):
        zero = b == 0
        safe = xp.where(zero, 1, b)
        # trunc toward zero, unlike // which floors
        q = xp.sign(a) * xp.sign(safe) * (abs(a) // abs(safe))
        return q.astype(out_dt.np_dtype), zero


class Remainder(BinaryArithmetic):
    """Java-style %: sign of the dividend; x % 0 -> NULL."""
    symbol = "%"

    def compute(self, xp, a, b, out_dt):
        zero = b == 0
        safe = xp.where(zero, 1, b)
        return xp.fmod(a, safe), zero


class Pmod(BinaryArithmetic):
    symbol = "pmod"

    def sql_name(self, schema=None) -> str:
        return (f"pmod({self.children[0].sql_name(schema)}, "
                f"{self.children[1].sql_name(schema)})")

    def compute(self, xp, a, b, out_dt):
        zero = b == 0
        safe = xp.where(zero, 1, b)
        # ((a % b) + b) % b — result takes the sign of the divisor
        return xp.fmod(xp.fmod(a, safe) + safe, safe), zero


class UnaryMinus(Expression):
    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return self.children[0].dtype(schema)

    def sql_name(self, schema=None) -> str:
        return f"(- {self.children[0].sql_name(schema)})"

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = self.children[0].eval_device(ctx)
        if isinstance(v, DevScalar):
            return DevScalar(v.dtype, -v.value, v.valid)
        return v.with_(data=-v.data)

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        s = self.children[0].eval_host(df)
        values, validity, index = host_unary_values(s)
        return rebuild_series(-values, validity,
                              dtypes.from_numpy(values.dtype), index)


class Abs(Expression):
    def __init__(self, child: Expression):
        super().__init__([child])

    def dtype(self, schema: Schema) -> DType:
        return self.children[0].dtype(schema)

    def sql_name(self, schema=None) -> str:
        return f"abs({self.children[0].sql_name(schema)})"

    def eval_device(self, ctx: EvalContext) -> DevValue:
        v = self.children[0].eval_device(ctx)
        if isinstance(v, DevScalar):
            return DevScalar(v.dtype, jnp.abs(v.value), v.valid)
        return v.with_(data=jnp.abs(v.data))

    def eval_host(self, df: pd.DataFrame) -> pd.Series:
        s = self.children[0].eval_host(df)
        values, validity, index = host_unary_values(s)
        return rebuild_series(np.abs(values), validity,
                              dtypes.from_numpy(values.dtype), index)
