"""Logical plan nodes.

The reference plugs into Spark Catalyst and never owns a logical plan; this
framework is standalone, so it carries a minimal Catalyst-equivalent. The
interesting machinery — the tag/convert rewrite — operates on the *physical*
plan exactly like the reference (GpuOverrides works on SparkPlan).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from spark_rapids_tpu.columnar import dtypes
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.sql.exprs.core import Expression
from spark_rapids_tpu.sql.functions import SortOrder


class LogicalPlan:
    def __init__(self, children: Sequence["LogicalPlan"] = ()):  # noqa: D401
        self.children: List[LogicalPlan] = list(children)

    def schema(self) -> Schema:
        raise NotImplementedError

    @property
    def name(self) -> str:
        return type(self).__name__

    def estimated_size_bytes(self):
        """Broadcast-join size hint. Narrow operators pass their child's
        estimate through; anything width-changing returns unknown."""
        if len(self.children) == 1:
            return self.children[0].estimated_size_bytes()
        return None

    def walk(self):
        yield self
        for c in self.children:
            yield from c.walk()


class LogicalScan(LogicalPlan):
    def __init__(self, source):
        super().__init__()
        self.source = source

    def schema(self) -> Schema:
        return self.source.schema

    def estimated_size_bytes(self):
        return self.source.estimated_size_bytes()


class LogicalRange(LogicalPlan):
    def __init__(self, start: int, end: int, step: int, num_partitions: int):
        super().__init__()
        self.start, self.end, self.step = start, end, step
        self.num_partitions = num_partitions

    def schema(self) -> Schema:
        return Schema(["id"], [dtypes.INT64])


class LogicalProject(LogicalPlan):
    def __init__(self, child: LogicalPlan,
                 exprs: Sequence[Tuple[str, Expression]]):
        super().__init__([child])
        self.exprs = list(exprs)

    def schema(self) -> Schema:
        cs = self.children[0].schema()
        return Schema([n for n, _ in self.exprs],
                      [e.dtype(cs) for _, e in self.exprs])


class LogicalFilter(LogicalPlan):
    def __init__(self, child: LogicalPlan, condition: Expression):
        super().__init__([child])
        self.condition = condition

    def schema(self) -> Schema:
        return self.children[0].schema()


class LogicalAggregate(LogicalPlan):
    def __init__(self, child: LogicalPlan,
                 grouping: Sequence[Tuple[str, Expression]],
                 results: Sequence[Tuple[str, Expression]]):
        super().__init__([child])
        self.grouping = list(grouping)
        self.results = list(results)

    def schema(self) -> Schema:
        cs = self.children[0].schema()
        # key results are Col(output_name) references resolved at
        # finalize; their dtype must come from the GROUPING expr, not
        # from evaluating the name against the child schema — a computed
        # key aliased to an existing column name would otherwise report
        # the shadowing raw column's dtype
        gdt = {n: e.dtype(cs) for n, e in self.grouping}
        dts = []
        for n, e in self.results:
            base = e
            from spark_rapids_tpu.sql.exprs.core import Col
            if isinstance(base, Col) and base.name in gdt:
                dts.append(gdt[base.name])
            else:
                dts.append(e.dtype(cs))
        return Schema([n for n, _ in self.results], dts)


class LogicalSort(LogicalPlan):
    def __init__(self, child: LogicalPlan, orders: Sequence[SortOrder],
                 is_global: bool = True):
        super().__init__([child])
        self.orders = list(orders)
        self.is_global = is_global

    def schema(self) -> Schema:
        return self.children[0].schema()


class LogicalLimit(LogicalPlan):
    def __init__(self, child: LogicalPlan, limit: int):
        super().__init__([child])
        self.limit = limit

    def schema(self) -> Schema:
        return self.children[0].schema()


class LogicalRepartition(LogicalPlan):
    """repartition(n): full round-robin row redistribution (Spark's
    RepartitionByExpression-less form)."""

    def __init__(self, child: LogicalPlan, n: int):
        super().__init__([child])
        self.n = max(1, int(n))

    def schema(self) -> Schema:
        return self.children[0].schema()


class LogicalCoalesce(LogicalPlan):
    """coalesce(n): merge adjacent partitions, no shuffle (Spark's
    CoalesceExec; reference rule GpuOverrides.scala:1611-1615)."""

    def __init__(self, child: LogicalPlan, n: int):
        super().__init__([child])
        self.n = max(1, int(n))

    def schema(self) -> Schema:
        return self.children[0].schema()


class LogicalJoin(LogicalPlan):
    def __init__(self, left: LogicalPlan, right: LogicalPlan, join_type: str,
                 left_keys: Sequence[Expression],
                 right_keys: Sequence[Expression],
                 condition: Optional[Expression] = None):
        super().__init__([left, right])
        self.join_type = join_type
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        # non-equi condition, bound against the combined left+right schema
        # (reference: GpuBroadcastNestedLoopJoinExec)
        self.condition = condition

    def schema(self) -> Schema:
        ls = self.children[0].schema()
        rs = self.children[1].schema()
        if self.join_type in ("leftsemi", "leftanti"):
            return ls
        return Schema(list(ls.names) + list(rs.names),
                      list(ls.dtypes) + list(rs.dtypes))


class LogicalWindow(LogicalPlan):
    """Appends window-computed columns to the child's output (Spark's
    WindowExec shape; reference: GpuWindowExec)."""

    def __init__(self, child: LogicalPlan, window_exprs):
        super().__init__([child])
        self.window_exprs = list(window_exprs)  # [(name, WindowExpression)]

    def schema(self) -> Schema:
        cs = self.children[0].schema()
        return Schema(
            list(cs.names) + [n for n, _ in self.window_exprs],
            list(cs.dtypes) + [w.dtype(cs) for _, w in self.window_exprs])


class LogicalExpand(LogicalPlan):
    """Each input row emits one output row per projection set (Spark's
    ExpandExec, the engine under rollup/cube/grouping-sets; reference:
    GpuExpandExec.scala:202)."""

    def __init__(self, child: LogicalPlan, projections):
        super().__init__([child])
        self.projections = [list(p) for p in projections]

    def schema(self) -> Schema:
        cs = self.children[0].schema()
        first = self.projections[0]
        return Schema([n for n, _ in first],
                      [e.dtype(cs) for _, e in first])


class LogicalWrite(LogicalPlan):
    """Terminal write command (reference: GpuDataWritingCommandExec wrapping
    InsertIntoHadoopFsRelationCommand)."""

    def __init__(self, child: LogicalPlan, path: str, fmt: str, mode: str,
                 partition_cols: Sequence[str] = ()):
        super().__init__([child])
        self.path = path
        self.fmt = fmt
        self.mode = mode
        self.partition_cols = list(partition_cols)

    def schema(self) -> Schema:
        return Schema([], [])


class LogicalUnion(LogicalPlan):
    def __init__(self, children: Sequence[LogicalPlan]):
        super().__init__(children)

    def schema(self) -> Schema:
        return self.children[0].schema()


class LogicalGenerate(LogicalPlan):
    """Explode-style generator appended to the child's output (Spark's
    Generate; reference: GpuGenerateExec.scala). Carries the fused
    split+explode: source string column expr, literal delimiter."""

    def __init__(self, child: LogicalPlan, source, delim: str,
                 out_name: str, with_pos: bool, pos_name: str = "pos"):
        super().__init__([child])
        self.source = source
        self.delim = delim
        self.out_name = out_name
        self.with_pos = with_pos
        self.pos_name = pos_name

    def schema(self) -> Schema:
        from spark_rapids_tpu.exec.generate import generate_output_schema
        return generate_output_schema(self.children[0].schema(),
                                      self.with_pos, self.pos_name,
                                      self.out_name)
