"""The plan-rewrite engine: tag, convert, insert transitions.

This is the heart of the framework, the re-design of the reference's
GpuOverrides + RapidsMeta + GpuTransitionOverrides
(GpuOverrides.scala:1704-1761, RapidsMeta.scala:64-284,
GpuTransitionOverrides.scala:34-289):

  1. every CPU physical operator is wrapped in an ``ExecMeta``;
  2. ``tag()`` walks children-first, accumulating human-readable
     ``will_not_work`` reasons (per-op conf keys, expression support,
     dtype gates — the same checks RapidsMeta.tagForGpu performs);
  3. ``convert()`` replaces cleanly-tagged nodes with Tpu*Exec equivalents,
     leaving tagged-off subtrees on the CPU;
  4. ``TransitionOverrides`` inserts HostToDevice / DeviceToHost at every
     boundary;
  5. ``explain_text()`` renders the tag tree — the reference's hallmark
     "explain why not" feature (spark.rapids.sql.explain).

Per-operator enable keys are auto-generated ``spark.rapids.sql.exec.<Name>``
exactly like GpuOverrides.scala:122-130.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Type

from spark_rapids_tpu.config.conf import TpuConf
from spark_rapids_tpu.exec import cpu, tpu
from spark_rapids_tpu.exec.base import PhysicalPlan
from spark_rapids_tpu.exec.transitions import DeviceToHostExec, HostToDeviceExec
from spark_rapids_tpu.sql.exprs.core import Expression, first_unsupported
from spark_rapids_tpu.sql.sources import (
    CsvSource, InMemorySource, OrcSource, ParquetSource,
)


class ExecRule:
    """(CPU exec class) -> conversion recipe + doc + conf key
    (reference: ReplacementRule/ExecRule, GpuOverrides.scala:62-266)."""

    def __init__(self, cpu_class: Type[PhysicalPlan], desc: str,
                 tag_fn: Callable[["ExecMeta"], None],
                 convert_fn: Callable[["ExecMeta", List[PhysicalPlan]],
                                      PhysicalPlan],
                 incompat: Optional[str] = None,
                 disabled_by_default: bool = False):
        self.cpu_class = cpu_class
        self.desc = desc
        self.tag_fn = tag_fn
        self.convert_fn = convert_fn
        self.incompat = incompat
        self.disabled_by_default = disabled_by_default

    @property
    def conf_key(self) -> str:
        name = self.cpu_class.__name__.removeprefix("Cpu")
        return f"spark.rapids.sql.exec.{name}"


class ExprMeta:
    """Per-expression meta tree built during tagging — the explain output
    names the exact offending expression NODE, not just the operator
    (reference: BaseExprMeta and the expression meta tree,
    RapidsMeta.scala:566-726)."""

    def __init__(self, expr: Expression, schema):
        from spark_rapids_tpu.sql.exprs.core import (
            Expression as ExprBase,
        )
        self.expr = expr
        reason = expr.device_supported(schema)
        if reason is None and type(expr).eval_device is ExprBase.eval_device:
            reason = "has no TPU implementation"
        self.reason = reason
        self.children = [ExprMeta(c, schema) for c in expr.children]

    @property
    def subtree_ok(self) -> bool:
        return self.reason is None and all(c.subtree_ok
                                           for c in self.children)

    def first_reason(self):
        """Pre-order first failing node's message, formatted exactly like
        first_unsupported (the single support traversal serves both the
        operator reason and the explain tree)."""
        if self.reason is not None:
            if self.reason == "has no TPU implementation":
                return f"{self.expr.pretty_name} has no TPU implementation"
            return f"{self.expr.pretty_name}: {self.reason}"
        for c in self.children:
            r = c.first_reason()
            if r:
                return r
        return None

    def explain_lines(self, depth: int = 0) -> List[str]:
        marker = "*" if self.reason is None else "!"
        line = "  " * depth + f"{marker} <{self.expr.pretty_name}> " \
            f"{self.expr!r}"
        if self.reason:
            line += f"  <-- {self.reason}"
        out = [line]
        for c in self.children:
            out.extend(c.explain_lines(depth + 1))
        return out


class ExecMeta:
    """Wraps one CPU physical operator during tagging
    (reference: SparkPlanMeta, RapidsMeta.scala:402-545)."""

    def __init__(self, plan: PhysicalPlan, rule: Optional[ExecRule],
                 conf: TpuConf, parent: Optional["ExecMeta"]):
        self.plan = plan
        self.rule = rule
        self.conf = conf
        self.parent = parent
        self.children: List[ExecMeta] = []
        self.reasons: List[str] = []
        # (label, ExprMeta) per checked expression (RapidsMeta.scala:566+)
        self.expr_metas: List[tuple] = []

    def will_not_work(self, reason: str) -> None:
        if reason not in self.reasons:
            self.reasons.append(reason)

    @property
    def can_run_on_tpu(self) -> bool:
        return not self.reasons

    def tag(self) -> None:
        for c in self.children:
            c.tag()
        if self.rule is None:
            self.will_not_work(
                f"no TPU replacement rule for {self.plan.name}")
            return
        if not self.conf.is_operator_enabled(
                self.rule.conf_key,
                incompat=self.rule.incompat is not None,
                disabled_by_default=self.rule.disabled_by_default):
            extra = ""
            if self.rule.incompat and not self.conf.incompatible_ops_enabled:
                extra = (f" (incompatible: {self.rule.incompat}; enable with "
                         f"{self.rule.conf_key}=true or "
                         "spark.rapids.sql.incompatibleOps.enabled=true)")
            self.will_not_work(f"{self.plan.name} is disabled by conf "
                               f"{self.rule.conf_key}{extra}")
            return
        self.rule.tag_fn(self)

    def check_exprs(self, exprs: List[Expression], what: str = "") -> None:
        schema = (self.plan.children[0].output_schema()
                  if self.plan.children else self.plan.output_schema())
        for e in exprs:
            em = ExprMeta(e, schema)
            reason = em.first_reason()
            if reason:
                self.expr_metas.append((what or "expr", em))
                prefix = f"{what}: " if what else ""
                self.will_not_work(prefix + reason)

    def convert(self) -> PhysicalPlan:
        """convertIfNeeded (RapidsMeta.scala:529-544)."""
        new_children = [c.convert() for c in self.children]
        if self.can_run_on_tpu and self.rule is not None:
            return self.rule.convert_fn(self, new_children)
        return self._keep_on_cpu(new_children)

    def _keep_on_cpu(self, new_children: List[PhysicalPlan]) -> PhysicalPlan:
        import copy
        new = copy.copy(self.plan)
        new.children = new_children
        return new

    def explain_lines(self, depth: int = 0) -> List[str]:
        """RapidsMeta.explain tree printer (RapidsMeta.scala:245-283);
        expression meta subtrees print under their operator so the
        offending expression NODE is named (RapidsMeta.scala:566-726)."""
        marker = "*" if self.can_run_on_tpu else "!"
        line = "  " * depth + f"{marker} {self.plan.describe()}"
        if self.reasons:
            line += "  <-- " + "; ".join(self.reasons)
        out = [line]
        for what, em in self.expr_metas:
            out.append("  " * (depth + 1) + f"@{what}:")
            out.extend(em.explain_lines(depth + 2))
        for c in self.children:
            out.extend(c.explain_lines(depth + 1))
        return out


# ---------------------------------------------------------------------------
# Rule table (reference: GpuOverrides.scala:1582-1699)
# ---------------------------------------------------------------------------

def _tag_project(meta: ExecMeta) -> None:
    meta.check_exprs([e for _, e in meta.plan.exprs], "projection")


def _convert_project(meta: ExecMeta, children) -> PhysicalPlan:
    return tpu.TpuProjectExec(children[0], meta.plan.exprs)


def _tag_filter(meta: ExecMeta) -> None:
    meta.check_exprs([meta.plan.condition], "filter condition")


def _convert_filter(meta: ExecMeta, children) -> PhysicalPlan:
    return tpu.TpuFilterExec(children[0], meta.plan.condition)


def _tag_agg(meta: ExecMeta) -> None:
    plan = meta.plan.plan  # AggPlan
    mode = meta.plan.mode
    replace = meta.conf.hash_agg_replace_mode
    if replace != "all" and replace != mode:
        meta.will_not_work(
            f"hashAgg replace mode {replace!r} excludes {mode} aggregation")
    schema = plan.child_schema
    for name, e in plan.grouping:
        reason = first_unsupported(e, schema)
        if reason:
            meta.will_not_work(f"group key {name}: {reason}")
            meta.expr_metas.append((f"group key {name}",
                                    ExprMeta(e, schema)))
    for fn in plan.agg_fns:
        reason = fn.device_supported(schema)
        if reason:
            meta.will_not_work(reason)
        for c in fn.children:
            r = first_unsupported(c, schema)
            if r:
                meta.will_not_work(f"aggregate input: {r}")
                meta.expr_metas.append(
                    (f"aggregate input of {fn.pretty_name}",
                     ExprMeta(c, schema)))
    if mode == "final":
        for name, e in plan.finalize_exprs():
            r = first_unsupported(e, plan.partial_schema)
            if r:
                meta.will_not_work(f"result {name}: {r}")
    _STRING_RED_KINDS = ("count_valid", "min", "max", "first", "last",
                         "first_valid", "last_valid")
    for fn, ops in zip(plan.agg_fns, plan.update_plan):
        for kind, input_idx, idt in ops:
            if idt.is_string and kind not in _STRING_RED_KINDS:
                meta.will_not_work(
                    f"{kind} over string values is not supported on TPU")


def _convert_agg(meta: ExecMeta, children) -> PhysicalPlan:
    return tpu.TpuHashAggregateExec(children[0], meta.plan.plan,
                                    meta.plan.mode)


def _tag_sort(meta: ExecMeta) -> None:
    schema = meta.plan.children[0].output_schema()
    for o in meta.plan.orders:
        reason = first_unsupported(o.expr, schema)
        if reason:
            meta.will_not_work(f"sort key: {reason}")


def _convert_sort(meta: ExecMeta, children) -> PhysicalPlan:
    return tpu.TpuSortExec(children[0], meta.plan.orders)


def _tag_exchange(meta: ExecMeta) -> None:
    kind = meta.plan.partitioning[0]
    if kind not in ("hash", "single", "roundrobin", "range"):
        meta.will_not_work(f"partitioning {kind!r} not supported on TPU")


def _convert_exchange(meta: ExecMeta, children) -> PhysicalPlan:
    return tpu.TpuShuffleExchangeExec(children[0], meta.plan.partitioning)


def _tag_scan(meta: ExecMeta) -> None:
    src = meta.plan.source
    c = meta.conf
    if isinstance(src, ParquetSource):
        if not (c.get("spark.rapids.sql.format.parquet.enabled")
                and c.get("spark.rapids.sql.format.parquet.read.enabled")):
            meta.will_not_work("Parquet scan disabled by conf")
    elif isinstance(src, CsvSource):
        if not (c.get("spark.rapids.sql.format.csv.enabled")
                and c.get("spark.rapids.sql.format.csv.read.enabled")):
            meta.will_not_work("CSV scan disabled by conf")
    elif isinstance(src, OrcSource):
        if not (c.get("spark.rapids.sql.format.orc.enabled")
                and c.get("spark.rapids.sql.format.orc.read.enabled")):
            meta.will_not_work("ORC scan disabled by conf")
    elif isinstance(src, InMemorySource):
        pass
    else:
        meta.will_not_work(f"source {src.describe()} has no TPU scan")


def _convert_scan(meta: ExecMeta, children) -> PhysicalPlan:
    return tpu.TpuScanExec(meta.plan.source, meta.plan.output_schema(),
                           getattr(meta.plan, "pushed_filters", None))


def _tag_join(meta: ExecMeta) -> None:
    from spark_rapids_tpu.exec.tpujoin import SUPPORTED_JOIN_TYPES
    if meta.plan.join_type not in SUPPORTED_JOIN_TYPES:
        meta.will_not_work(
            f"join type {meta.plan.join_type!r} not supported on TPU")


def _convert_join(meta: ExecMeta, children) -> PhysicalPlan:
    from spark_rapids_tpu.exec.tpujoin import TpuShuffledHashJoinExec
    return TpuShuffledHashJoinExec(
        children[0], children[1], meta.plan.join_type, meta.plan.left_keys,
        meta.plan.right_keys,
        exact_long_strings=meta.conf.get_bool(
            "spark.rapids.sql.join.exactLongStrings", True))


def _tag_nothing(meta: ExecMeta) -> None:
    pass


_RULES: Dict[Type[PhysicalPlan], ExecRule] = {}


def _register(rule: ExecRule) -> None:
    _RULES[rule.cpu_class] = rule


_register(ExecRule(cpu.CpuProjectExec, "columnar projection",
                   _tag_project, _convert_project))
_register(ExecRule(cpu.CpuFilterExec, "columnar filter",
                   _tag_filter, _convert_filter))
_register(ExecRule(cpu.CpuHashAggregateExec, "hash aggregate",
                   _tag_agg, _convert_agg))
_register(ExecRule(cpu.CpuSortExec, "device sort",
                   _tag_sort, _convert_sort))
_register(ExecRule(cpu.CpuShuffleExchangeExec, "columnar shuffle exchange",
                   _tag_exchange, _convert_exchange))
_register(ExecRule(cpu.CpuScanExec, "columnar scan",
                   _tag_scan, _convert_scan))
def _tag_expand(meta: ExecMeta) -> None:
    for proj in meta.plan.projections:
        meta.check_exprs([e for _, e in proj], "expand projection")


_register(ExecRule(cpu.CpuExpandExec, "expand (rollup/cube engine)",
                   _tag_expand,
                   lambda m, ch: tpu.TpuExpandExec(ch[0],
                                                   m.plan.projections)))
_register(ExecRule(cpu.CpuJoinExec, "shuffled hash join",
                   _tag_join, _convert_join))


def _convert_broadcast_join(meta: ExecMeta, children) -> PhysicalPlan:
    from spark_rapids_tpu.exec.tpujoin import TpuBroadcastHashJoinExec
    return TpuBroadcastHashJoinExec(children[0], children[1],
                                    meta.plan.join_type, meta.plan.left_keys,
                                    meta.plan.right_keys)


_register(ExecRule(cpu.CpuBroadcastHashJoinExec, "broadcast hash join",
                   _tag_join, _convert_broadcast_join))
def _convert_cartesian(meta: ExecMeta, children) -> PhysicalPlan:
    from spark_rapids_tpu.exec.tpujoin import TpuCartesianProductExec
    return TpuCartesianProductExec(children[0], children[1])


# Deviation from the reference's default (GpuOverrides gates
# CartesianProduct off): on this backend a device-resident cartesian is
# strictly better than the fallback, which pays TWO device->host result
# fetches (~0.1s each over the tunnel) plus a re-upload — scalar-
# subquery cross joins (tpch q11's threshold) hit it on every query.
# The conf remains available to disable.
_register(ExecRule(cpu.CpuCartesianProductExec, "cartesian product",
                   _tag_nothing, _convert_cartesian))


def _tag_bnlj(meta: ExecMeta) -> None:
    cond = meta.plan.condition
    if cond is not None:
        reason = first_unsupported(cond, meta.plan.output_schema())
        if reason:
            meta.will_not_work(f"join condition: {reason}")


def _convert_bnlj(meta: ExecMeta, children) -> PhysicalPlan:
    from spark_rapids_tpu.exec.tpujoin import TpuBroadcastNestedLoopJoinExec
    return TpuBroadcastNestedLoopJoinExec(children[0], children[1],
                                          meta.plan.join_type,
                                          meta.plan.condition)


_register(ExecRule(cpu.CpuBroadcastNestedLoopJoinExec,
                   "broadcast nested loop join",
                   _tag_bnlj, _convert_bnlj, disabled_by_default=True))
def _convert_broadcast(meta: ExecMeta, children) -> PhysicalPlan:
    from spark_rapids_tpu.exec.tpujoin import TpuBroadcastExchangeExec
    return TpuBroadcastExchangeExec(children[0])


def _tag_window(meta: ExecMeta) -> None:
    from spark_rapids_tpu.exec.windowexec import resolve_descriptor
    cs = meta.plan.children[0].output_schema()
    for name, w in meta.plan.window_exprs:
        _, vexpr, err = resolve_descriptor(w, cs)
        if err:
            meta.will_not_work(f"window column {name}: {err}")
            continue
        for e in (w.spec.partition_cols
                  + [o.expr for o in w.spec.orders]
                  + ([vexpr] if vexpr is not None else [])):
            reason = first_unsupported(e, cs)
            if reason:
                meta.will_not_work(f"window column {name}: {reason}")


def _convert_window(meta: ExecMeta, children) -> PhysicalPlan:
    from spark_rapids_tpu.exec.windowexec import TpuWindowExec
    return TpuWindowExec(children[0], meta.plan.window_exprs)


_register(ExecRule(cpu.CpuBroadcastExchangeExec, "broadcast exchange",
                   _tag_nothing, _convert_broadcast))


def _register_window_rule() -> None:
    from spark_rapids_tpu.exec.windowexec import CpuWindowExec
    _register(ExecRule(CpuWindowExec, "windowed computation",
                       _tag_window, _convert_window))


_register_window_rule()


def _tag_write(meta: ExecMeta) -> None:
    c = meta.conf
    fmt = meta.plan.fmt
    if fmt == "parquet":
        if not (c.get("spark.rapids.sql.format.parquet.enabled")
                and c.get("spark.rapids.sql.format.parquet.write.enabled")):
            meta.will_not_work("Parquet write disabled by conf")
    elif fmt == "orc":
        if not (c.get("spark.rapids.sql.format.orc.enabled")
                and c.get("spark.rapids.sql.format.orc.write.enabled")):
            meta.will_not_work("ORC write disabled by conf")
    elif fmt == "csv":
        # the reference does not accelerate CSV writes either; ours rides
        # the same columnar D2H path so it is enabled by default
        if not c.get("spark.rapids.sql.format.csv.enabled"):
            meta.will_not_work("CSV write disabled by conf")
    else:
        meta.will_not_work(f"write format {fmt!r} has no TPU path")


def _convert_write(meta: ExecMeta, children) -> PhysicalPlan:
    from spark_rapids_tpu.exec.write import TpuWriteExec
    return TpuWriteExec(children[0], meta.plan.path, meta.plan.fmt,
                        meta.plan.mode, meta.plan.partition_cols)


def _register_write_rule() -> None:
    from spark_rapids_tpu.exec.write import CpuWriteExec
    _register(ExecRule(CpuWriteExec, "data writing command",
                       _tag_write, _convert_write))


_register_write_rule()
def _tag_generate(meta: ExecMeta) -> None:
    plan = meta.plan
    cs = plan.children[0].output_schema()
    if not cs.dtypes[plan.col_idx].is_string:
        meta.will_not_work("explode source must be a string column")
    if len(plan.delim.encode("utf-8")) != 1:
        meta.will_not_work(
            f"delimiter {plan.delim!r}: only single-byte delimiters run on "
            "TPU (multi-byte/regex split stays on CPU)")
    elif plan.delim in "\\^$.|?*+()[]{}":
        meta.will_not_work(
            f"delimiter {plan.delim!r} is a regex metacharacter (Spark "
            "split() patterns are regexes); runs on CPU")


def _convert_generate(meta: ExecMeta, children) -> PhysicalPlan:
    from spark_rapids_tpu.exec.generate import TpuGenerateExec
    p = meta.plan
    return TpuGenerateExec(children[0], p.col_idx, p.delim, p.out_name,
                           p.with_pos, p.pos_name)


def _register_generate_rule() -> None:
    from spark_rapids_tpu.exec.generate import CpuGenerateExec
    _register(ExecRule(CpuGenerateExec, "explode-style generator",
                       _tag_generate, _convert_generate))


_register_generate_rule()
_register(ExecRule(cpu.CpuLocalLimitExec, "local limit", _tag_nothing,
                   lambda m, ch: tpu.TpuLocalLimitExec(ch[0], m.plan.limit)))
_register(ExecRule(cpu.CpuGlobalLimitExec, "global limit", _tag_nothing,
                   lambda m, ch: tpu.TpuGlobalLimitExec(ch[0], m.plan.limit)))
_register(ExecRule(cpu.CpuCollectLimitExec,
                   "collect limit (reference GpuOverrides.scala:1641-1643)",
                   _tag_nothing,
                   lambda m, ch: tpu.TpuCollectLimitExec(ch[0],
                                                         m.plan.limit)))
_register(ExecRule(cpu.CpuCoalescePartitionsExec,
                   "partition coalesce (reference GpuOverrides.scala:1611)",
                   _tag_nothing,
                   lambda m, ch: tpu.TpuCoalescePartitionsExec(ch[0],
                                                               m.plan.n)))
_register(ExecRule(cpu.CpuUnionExec, "columnar union", _tag_nothing,
                   lambda m, ch: tpu.TpuUnionExec(ch)))
_register(ExecRule(cpu.CpuRangeExec, "device range source", _tag_nothing,
                   lambda m, ch: tpu.TpuRangeExec(
                       m.plan.start, m.plan.end, m.plan.step,
                       m.plan.num_partitions, m.plan.col_name)))


def _run_after_tag_rules(root: ExecMeta) -> None:
    """Cross-tree tag fixups after per-node tagging (the reference's
    runAfterTagRules, RapidsMeta.scala:430-485): decisions that depend on
    NEIGHBORING nodes' tags, not just the node itself."""
    _fixup_join_hash_consistency(root)
    _fixup_exchange_overhead(root)


def _fixup_join_hash_consistency(meta: ExecMeta) -> None:
    """A shuffled hash join and the exchanges feeding it must agree on the
    partitioning hash function. If the join stays on CPU, its child TPU
    exchanges fall back too (CPU join would read TPU-hash-partitioned
    rows); if a feeding exchange stays on CPU, the join falls back
    (reference makeShuffleConsistent, RapidsMeta.scala:430-445)."""
    from spark_rapids_tpu.exec.cpu import (
        CpuBroadcastHashJoinExec, CpuCartesianProductExec, CpuJoinExec,
        CpuShuffleExchangeExec,
    )
    for c in meta.children:
        _fixup_join_hash_consistency(c)
    # only SHUFFLED equi-joins depend on partitioning-hash agreement;
    # broadcast/cartesian joins consume stream partitions independently
    if (not isinstance(meta.plan, CpuJoinExec)
            or isinstance(meta.plan, (CpuBroadcastHashJoinExec,
                                      CpuCartesianProductExec))):
        return
    exch_children = [c for c in meta.children
                     if isinstance(c.plan, CpuShuffleExchangeExec)]
    if not exch_children:
        return
    if not meta.can_run_on_tpu:
        for c in exch_children:
            if c.can_run_on_tpu:
                c.will_not_work(
                    "the shuffled join it feeds stays on CPU, so the "
                    "partitioning hash must stay on CPU for consistency")
    elif any(not c.can_run_on_tpu for c in exch_children):
        meta.will_not_work(
            "an input exchange stays on CPU, so the join must use the "
            "CPU partitioning hash for consistency")
        for c in exch_children:
            if c.can_run_on_tpu:
                c.will_not_work(
                    "the shuffled join it feeds stays on CPU, so the "
                    "partitioning hash must stay on CPU for consistency")


def _fixup_exchange_overhead(meta: ExecMeta) -> None:
    """An exchange with no columnar neighbors only adds two transitions
    around a shuffle — keep it on CPU (reference's exchange-overhead
    fixup, RapidsMeta.scala:447-454)."""
    from spark_rapids_tpu.exec.cpu import CpuShuffleExchangeExec
    for c in meta.children:
        _fixup_exchange_overhead(c)
    if not isinstance(meta.plan, CpuShuffleExchangeExec):
        return
    if not meta.can_run_on_tpu:
        return
    parent_columnar = meta.parent is not None and meta.parent.can_run_on_tpu
    child_columnar = any(c.can_run_on_tpu for c in meta.children)
    if not parent_columnar and not child_columnar:
        meta.will_not_work(
            "columnar exchange between CPU operators only adds "
            "host<->device transition overhead")


class TpuOverrides:
    """The preColumnarTransitions rule (GpuOverrides.apply,
    GpuOverrides.scala:1704-1761)."""

    def __init__(self, conf: TpuConf):
        self.conf = conf
        self.root_meta: Optional[ExecMeta] = None

    def wrap(self, plan: PhysicalPlan,
             parent: Optional[ExecMeta] = None) -> ExecMeta:
        rule = _RULES.get(type(plan))
        meta = ExecMeta(plan, rule, self.conf, parent)
        meta.children = [self.wrap(c, meta) for c in plan.children]
        return meta

    def apply(self, plan: PhysicalPlan) -> PhysicalPlan:
        self.root_meta = self.wrap(plan)
        self.root_meta.tag()
        _run_after_tag_rules(self.root_meta)
        explain = self.conf.explain
        if explain in ("ALL", "NOT_ON_TPU"):
            print(self.explain_text(explain))
        return self.root_meta.convert()

    def explain_text(self, mode: str = "ALL") -> str:
        assert self.root_meta is not None
        lines = self.root_meta.explain_lines()
        if mode == "NOT_ON_TPU":
            lines = [ln for ln in lines if ln.lstrip().startswith("!")]
        return "\n".join(lines)

    def fallback_metas(self) -> List[ExecMeta]:
        """Every tagged-off operator meta after apply(), pre-order — the
        machine-readable twin of the "!" explain lines. The session turns
        each into one ``cpuFallback`` journal event (obs/events.py) so
        the explain-why-not record survives the query."""
        assert self.root_meta is not None
        out: List[ExecMeta] = []
        stack = [self.root_meta]
        while stack:
            meta = stack.pop()
            if meta.reasons:
                out.append(meta)
            stack.extend(reversed(meta.children))
        return out


class TransitionOverrides:
    """postColumnarTransitions: insert transitions at CPU/TPU boundaries
    (GpuTransitionOverrides.scala:152-169) and coalesce batches above
    fragmenting producers (insertCoalesce, :64-147)."""

    def __init__(self, conf: TpuConf):
        self.conf = conf

    def apply(self, plan: PhysicalPlan) -> PhysicalPlan:
        from spark_rapids_tpu.exec.coalesce import insert_coalesce
        from spark_rapids_tpu.exec.fusion import (
            fuse_filter_into_aggregate, fuse_selection_into_filter,
        )
        from spark_rapids_tpu.exec.stagecompiler import compile_stages
        # fuse BEFORE coalesce insertion: a fused-away Filter is no longer
        # a fragmenting producer, so no coalesce node appears above it.
        # Whole-stage fusion runs LAST, over the final operator layout
        # (coalesce nodes included — the stage absorbs them), so the
        # legacy, AQE per-stage and plan-cache paths all cut identically.
        return compile_stages(
            insert_coalesce(
                fuse_filter_into_aggregate(
                    fuse_selection_into_filter(self._apply(plan),
                                               self.conf),
                    self.conf),
                self.conf),
            self.conf)

    def _apply(self, plan: PhysicalPlan) -> PhysicalPlan:
        # a TPU operator consumes device batches; a CPU operator consumes
        # host DataFrames — insert the matching transition under each child.
        # columnar_input (terminal commands like TpuWriteExec) overrides
        # the output-kind default.
        wants_columnar = getattr(plan, "columnar_input",
                                 plan.columnar_output)
        new_children = []
        for c in plan.children:
            c2 = self._apply(c)
            if wants_columnar and not c2.columnar_output:
                c2 = HostToDeviceExec(c2)
            elif not wants_columnar and c2.columnar_output:
                c2 = DeviceToHostExec(c2)
            new_children.append(c2)
        out = plan.map_children(lambda c: c)
        out.children = new_children
        return out


def assert_is_on_tpu(plan: PhysicalPlan, conf: TpuConf) -> None:
    """Test-mode enforcement (GpuTransitionOverrides.assertIsOnTheGpu,
    GpuTransitionOverrides.scala:225-263): fail the query if a
    non-allow-listed operator stayed on the CPU."""
    # only the transitions themselves are implicitly allowed; a scan that
    # stayed on the CPU must be named via spark.rapids.sql.test.allowedNonTpu
    # exactly like any other fallback (the reference asserts scans too,
    # GpuTransitionOverrides.scala:225-263)
    allowed = set(conf.test_allowed_nontpu) | {
        "HostToDeviceExec", "DeviceToHostExec",
    }
    offenders = []
    for node in plan.walk():
        on_tpu = (node.columnar_output
                  or getattr(node, "columnar_input", False))
        if not on_tpu and node.name not in allowed:
            offenders.append(node.name)
    if offenders:
        raise AssertionError(
            f"operators did not run on the TPU: {sorted(set(offenders))} "
            "(spark.rapids.sql.test.enabled=true)")
