"""TPU join operators (reference: GpuShuffledHashJoinExec /
GpuBroadcastHashJoinExec / GpuCartesianProductExec,
shims/spark300/.../GpuHashJoin.scala:113-244).

Execution shape matches the reference's hash join: the build side is
concatenated into one device batch and held; stream batches probe it one at
a time. Probe and expand are separately jitted (ops/joins.py) because the
expand specializes on the bucketed output capacity — the single
device->host sync per stream batch that dynamic join cardinality costs.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import DeviceBatch, Schema, bucket_capacity
from spark_rapids_tpu.columnar.column import _char_bucket
from spark_rapids_tpu.exec.base import ExecContext, Partition, PhysicalPlan
from spark_rapids_tpu.ops import joins as join_ops
from spark_rapids_tpu.utils.kernelcache import bucket_dim, cached_jit

SUPPORTED_JOIN_TYPES = ("inner", "left", "right", "full", "leftsemi",
                        "leftanti", "cross")


def _start_host_copies(arrays) -> None:
    """Begin async device->host transfers so the deferred speculation-
    verification fetch (session._verify_speculation) overlaps the rest of
    the query instead of paying its own round trip at the end.
    Delegates to the shared tree-walking prefetch (columnar/batch.py)."""
    from spark_rapids_tpu.columnar.batch import _start_host_copies_tree
    _start_host_copies_tree(list(arrays))


class TpuBroadcastExchangeExec(PhysicalPlan):
    """Materializes the child once as a single device batch shared by every
    consumer partition (reference: GpuBroadcastExchangeExec.scala:230-436
    re-materializes the broadcast on device per task; here the batch is
    already device-resident so it is simply cached)."""

    columnar_output = True

    def __init__(self, child: PhysicalPlan):
        super().__init__([child])
        self._cache = {}

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        child = self.children[0]
        growth = ctx.conf.capacity_growth

        def materialize():
            from spark_rapids_tpu.exec.tpu import (
                _concat_device, _fused_filter_source, _select_view,
            )
            src_node, mask_kernel, out_sel = _fused_filter_source(child, ctx)
            parts = src_node.executed_partitions(ctx)
            batches = [b for p in parts for b in p()]
            if not batches:
                return _concat_device(batches, child.output_schema(),
                                      growth, coarse=True)
            masks = None
            if mask_kernel is not None:
                masks = [mask_kernel(b) for b in batches]
                if out_sel is not None:
                    batches = [_select_view(b, out_sel) for b in batches]
            out = _concat_device(batches, child.output_schema(), growth,
                                 masks, coarse=True)
            if ctx.metrics_enabled:
                # build-table size on record: the broadcast twin of the
                # exchanges' MapStatus sizes, so a (static or AQE-demoted)
                # broadcast's actual footprint is visible next to the
                # threshold that chose it (obs/events.py taxonomy)
                from spark_rapids_tpu.obs.events import EVENTS
                from spark_rapids_tpu.obs.metrics import REGISTRY
                nbytes = out.device_memory_size()
                REGISTRY.gauge("shuffle.broadcast.bytes").set(nbytes)
                REGISTRY.counter("shuffle.broadcast.builds").add(1)
                EVENTS.emit("broadcastMaterialized", bytes=int(nbytes),
                            batches=len(batches))
            return out

        if ctx.session is None:
            def run():
                if "batch" not in self._cache:
                    self._cache["batch"] = materialize()
                yield self._cache["batch"]
            return [run]

        # the broadcast table lives in the spillable BufferCatalog (the
        # reference materializes broadcasts as spillable device buffers,
        # GpuBroadcastExchangeExec.scala:230-436): consumers acquire per
        # use, faulting a spilled table back; OUTPUT_FOR_WRITE band so
        # shuffle output (OUTPUT_FOR_READ) evicts first
        def run_catalog():
            from spark_rapids_tpu.memory.spill import SpillPriorities
            bid = self._cache.get("bid")
            if bid is None or not ctx.session.buffer_catalog.contains(bid):
                # first use, or the entry was swept (query-end transient
                # release / speculation re-execution): re-materialize
                bid = self._cache["bid"] = ctx.session.add_transient_batch(
                    materialize(), SpillPriorities.OUTPUT_FOR_WRITE)
            yield ctx.session.buffer_catalog.acquire_batch(bid)
        return [run_catalog]


class TpuShuffledHashJoinExec(PhysicalPlan):
    columnar_output = True

    def __init__(self, left: PhysicalPlan, right: PhysicalPlan,
                 join_type: str, left_keys: List[int], right_keys: List[int],
                 exact_long_strings: bool = True):
        super().__init__([left, right])
        assert join_type in SUPPORTED_JOIN_TYPES, join_type
        self.join_type = join_type
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        # >64-byte string key equality: exact full-length verification
        # (default) vs dual-hash tiebreak (incompat,
        # spark.rapids.sql.join.exactLongStrings=false)
        self.exact_long_strings = exact_long_strings

        # right outer streams the right side against a left-side build so
        # every preserved row is a stream row (the reference flips build
        # side the same way, GpuHashJoin.scala:60-76)
        self._stream_is_left = join_type != "right"
        jt = join_type
        cross = jt == "cross"
        skey = tuple(self.left_keys if self._stream_is_left
                     else self.right_keys)
        bkey = tuple(self.right_keys if self._stream_is_left
                     else self.left_keys)
        sig = f"join|{jt}|{skey}|{bkey}|x{int(exact_long_strings)}"
        self._sig = sig
        self._skey, self._bkey = skey, bkey
        self._probe = cached_jit(sig + "|probe", lambda: jax.jit(
            lambda b, s: join_ops.join_probe(
                b, s, bkey, skey, cross=cross,
                exact_long_strings=exact_long_strings)))
        outer = jt in ("left", "right", "full")
        swap = not self._stream_is_left

        def expand(build, stream, counts, bstart, bperm, out_cap, s_caps,
                   b_caps):
            adj = (join_ops.outer_adjusted_counts(stream, counts)
                   if outer else counts)
            return join_ops.join_expand(build, stream, counts, adj, bstart,
                                        bperm, out_cap, swap, s_caps, b_caps)
        self._expand = cached_jit(sig + "|expand", lambda: jax.jit(
            expand, static_argnums=(5, 6, 7)))

        def totals(build, stream, counts, bstart, bperm):
            adj = (join_ops.outer_adjusted_counts(stream, counts)
                   if outer else counts)
            return join_ops.expand_totals(build, stream, counts, adj, bperm,
                                          bstart)
        self._totals = cached_jit(sig + "|totals", lambda: jax.jit(totals))
        if jt == "full":
            self._match_flags = cached_jit(sig + "|mf", lambda: jax.jit(
                join_ops.build_match_flags))
            self._unmatched = cached_jit(sig + "|unm", lambda: jax.jit(
                lambda b, m, ss: join_ops.unmatched_build_batch(
                    b, m, ss, swap_sides=False),
                static_argnums=(2,)))
        if jt in ("leftsemi", "leftanti"):
            self._semi = cached_jit(sig + "|semi", lambda: jax.jit(
                lambda s, c: join_ops.semi_anti_filter(
                    s, c, anti=jt == "leftanti")))

    def output_schema(self) -> Schema:
        ls = self.children[0].output_schema()
        rs = self.children[1].output_schema()
        if self.join_type in ("leftsemi", "leftanti"):
            return ls
        return Schema(list(ls.names) + list(rs.names),
                      list(ls.dtypes) + list(rs.dtypes))

    def describe(self) -> str:
        return (f"TpuShuffledHashJoinExec({self.join_type}, "
                f"l={self.left_keys}, r={self.right_keys})")

    def _sides(self):
        """(stream_child_idx, build_child_idx)."""
        return (0, 1) if self._stream_is_left else (1, 0)

    # dense-key fast path: direct-index probe over a bounded key range
    # (ops/joins.join_probe_dense). Applicable to single-int-key equi
    # joins whose build key has scan-derived advisory bounds small enough
    # to table. The reference's equivalent is cuDF's hash build+probe;
    # here the "hash table" is the identity map over the key range.
    _DENSE_MAX_RANGE = 1 << 24

    def _dense_plan(self, ctx, build_schema):
        """(lo, table_size) when the dense path applies, else None."""
        if self.join_type == "cross" or len(self._bkey) != 1:
            return None
        if ctx.session is None:
            return None
        bk = self._bkey[0]
        dt = build_schema.dtypes[bk]
        if dt.is_string or not jnp.issubdtype(
                jnp.dtype(dt.np_dtype), jnp.integer):
            return None
        # resolve the build key's name through the rename-alias map to
        # scan stats; union bounds over every candidate source (multiple
        # sources only loosen — the device verification catches any
        # residual mismatch)
        reg = ctx.session.column_stats
        amap = ctx.session.column_aliases
        names = {build_schema.names[bk]}
        frontier = set(names)
        for _ in range(8):  # alias chains are shallow; bound the walk
            nxt = set()
            for n in frontier:
                nxt |= amap.get(n, set()) - names
            if not nxt:
                break
            names |= nxt
            frontier = nxt
        bounds = [reg[n] for n in names if n in reg]
        if not bounds:
            return None
        lo = min(b[0] for b in bounds)
        hi = max(b[1] for b in bounds)
        rng = hi - lo + 1
        if rng <= 0 or rng > self._DENSE_MAX_RANGE:
            return None
        table_size = 1024
        while table_size < rng:
            table_size <<= 1
        return lo, bucket_dim(table_size)

    def _dense_kernel(self, table_size: int):
        bk, sk = self._bkey[0], self._skey[0]
        return cached_jit(
            f"{self._sig}|dense{table_size}",
            lambda: jax.jit(
                lambda b, s, lo: join_ops.join_probe_dense(
                    b, s, bk, sk, lo, table_size)))

    def _hash_probe_kernel(self, ctx, build_schema, stream_schema):
        """Pallas hash-table probe (ops/pallas_kernels.hash_join_probe)
        replacing the union-lexsort probe when it applies: every key
        fixed-width (the u64 image IS the exact value — strings fall
        back to the sort probe), SPARK_RAPIDS_TPU_PALLAS selects the
        pallas/interpret path, and spark.rapids.sql.fusion.hashKernels
        is on. Same (counts, bstart, bperm) contract, so expand/totals/
        match-flags/semi downstream run unchanged. Returns None when
        inapplicable — the sort probe is always the correct fallback."""
        if self.join_type == "cross" or not self._bkey:
            return None
        from spark_rapids_tpu.ops import pallas_kernels as pk
        mode = pk.hash_kernels_mode()
        if mode == "off":
            return None
        if not ctx.conf.get_bool("spark.rapids.sql.fusion.hashKernels",
                                 True):
            return None
        for schema, keys in ((build_schema, self._bkey),
                             (stream_schema, self._skey)):
            for ki in keys:
                if schema.dtypes[ki].is_string:
                    return None
        bkey, skey = self._bkey, self._skey

        def build():
            def probe(b, s):
                from spark_rapids_tpu.ops.sortops import u64_key_image
                bimgs, simgs = [], []
                for bk, sk in zip(bkey, skey):
                    bimgs.extend(u64_key_image(b.columns[bk]))
                    simgs.extend(u64_key_image(s.columns[sk]))
                bkv = join_ops._key_valid(b, bkey)
                skv = join_ops._key_valid(s, skey)
                return pk.hash_join_probe(
                    bimgs, bkv, simgs, skv,
                    pk.hash_table_size(b.capacity), mode=mode)
            return jax.jit(probe)
        return cached_jit(f"{self._sig}|hashprobe|{mode}", build)

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        si, bi = self._sides()
        stream_parts = self.children[si].executed_partitions(ctx)
        build_parts = self.children[bi].executed_partitions(ctx)
        growth = ctx.conf.capacity_growth
        build_schema = self.children[bi].output_schema()
        # pallas hash-table probe (opt-in via SPARK_RAPIDS_TPU_PALLAS):
        # replaces the union-lexsort probe; the dense direct-index path
        # still wins when scan stats bound the key range
        hash_probe = self._hash_probe_kernel(
            ctx, build_schema, self.children[si].output_schema())
        probe_fn = hash_probe if hash_probe is not None else self._probe
        if len(stream_parts) != len(build_parts):
            # broadcast build side: one build partition shared by every
            # stream partition (full outer never broadcasts — the unmatched-
            # build scan must see all stream rows, planner guarantees this)
            assert len(build_parts) == 1 and self.join_type != "full", \
                "join children must be co-partitioned or build broadcast"
            mesh = getattr(ctx.session, "mesh", None) if ctx.session else None
            if mesh is not None:
                # replicate the build table over the mesh with ONE
                # collective device_put (parallel/distributed.mesh_broadcast
                # — GpuBroadcastExchangeExec.scala:230-436's executor-side
                # rebuild); stream partition i probes the copy resident on
                # ITS device, so the probe kernel never crosses devices
                orig_bp = build_parts[0]
                n_dev = mesh.devices.size
                bstate: dict = {}

                def views():
                    if "v" not in bstate:
                        from spark_rapids_tpu.exec.tpu import _concat_device
                        from spark_rapids_tpu.parallel.distributed import (
                            mesh_broadcast,
                        )
                        build0 = _concat_device(list(orig_bp()),
                                                build_schema, growth,
                                                coarse=True)
                        bstate["v"] = mesh_broadcast(mesh, build0)
                    return bstate["v"]

                def mk_view(i: int) -> Partition:
                    return lambda: iter([views()[i % n_dev]])
                build_parts = [mk_view(i) for i in range(len(stream_parts))]
            else:
                build_parts = build_parts * len(stream_parts)
        jt = self.join_type

        dense = None

        # adaptive capacity speculation (spark.rapids.sql.adaptiveCapacity.
        # enabled): the expansion-size fetch below is the ONE unavoidable
        # device->host sync dynamic join cardinality costs (module
        # docstring) — ~150-250ms per round trip on a tunneled attachment,
        # so a 6-join plan pays ~1-1.5s of pure latency in steady state.
        # The session remembers each (join, partition)'s sizes keyed by
        # the structural plan fingerprint (data-uid-stamped, base.py) and
        # later executions expand straight into the remembered buckets;
        # the exact device-side sizes are still computed and verified in
        # ONE deferred fetch at query end (session._verify_speculation),
        # which transparently re-executes the query without speculation on
        # any miss. Capacities only pad — a covered speculation is EXACT.
        spec_fp = None

        def spec_key(idx: int) -> Optional[str]:
            nonlocal spec_fp
            if not getattr(ctx, "speculate", False):
                return None
            if spec_fp is None:
                from spark_rapids_tpu.exec.base import plan_fingerprint
                from spark_rapids_tpu.exec.reuse import subtree_deterministic
                # a nondeterministic input (rand() filter) changes sizes
                # every run: speculation would alternate learn/miss and
                # re-execute every other query
                spec_fp = (plan_fingerprint(self)
                           if subtree_deterministic(self) else False)
            if spec_fp is False:
                return None
            return f"{spec_fp}|g{growth}|part{idx}"

        def make(sp: Partition, bp: Partition, pidx: int) -> Partition:
            def run() -> Iterator[DeviceBatch]:
                from spark_rapids_tpu.exec.tpu import _concat_device
                # out-of-core: when the measured working set (build +
                # stream batches) exceeds the budget, grace-hash-
                # partition both sides onto the spill store and join
                # bucket by bucket (exec/outofcore.py) instead of
                # holding one giant build table
                from spark_rapids_tpu.exec import outofcore as ooc
                sp_local, bp_local = sp, bp
                if ooc.join_applicable(ctx, self):
                    # streaming probe on BOTH sides (never materializes
                    # past the budget): the build side is consumed up to
                    # the budget; if it fits, the stream side gets the
                    # remainder; on engagement the unconsumed tails flow
                    # straight into the grace driver's staging pass
                    import itertools
                    budget = ooc.working_set_budget(ctx)
                    bpre, brest, bover = ooc.split_stream_on_budget(
                        ctx, iter(bp()), budget)
                    if bover:
                        yield from ooc.grace_join(
                            ctx, self, itertools.chain(bpre, brest),
                            sp(), growth)
                        return
                    bbytes = ooc.total_batch_bytes(bpre)
                    spre, srest, sover = ooc.split_stream_on_budget(
                        ctx, iter(sp()), max(budget - bbytes, 1))
                    if sover:
                        yield from ooc.grace_join(
                            ctx, self, bpre,
                            itertools.chain(spre, srest), growth)
                        return
                    bp_local = lambda bl=bpre: iter(bl)  # noqa: E731
                    sp_local = lambda sl=spre: iter(sl)  # noqa: E731
                build = _concat_device(list(bp_local()), build_schema,
                                       growth, coarse=True)
                matched_acc = None
                emitted = False
                nonlocal dense
                if dense is None:
                    dense = self._dense_plan(ctx, build_schema) or False
                if dense:
                    lo_arr = jnp.asarray(dense[0], jnp.int64)
                    dkern = self._dense_kernel(dense[1])
                key = spec_key(pidx)
                cache = (ctx.session.capacity_cache
                         if key is not None else None)
                if jt in ("leftsemi", "leftanti"):
                    if dense:
                        # probe every batch first, ONE ok-flag fetch for
                        # all of them (a per-batch device_get would pay a
                        # full RTT each on the tunneled attachment)
                        streams = list(sp_local())
                        raw = [dkern(build, s, lo_arr) for s in streams]
                        oks_d = [r[3] for r in raw]
                        entry = cache.get(key) if cache is not None else None
                        if (entry is not None and entry.get("dense_ok")
                                and entry.get("n") == len(streams)):
                            # speculate: last run's advisory bounds held;
                            # defer the ok-flag check to query end
                            _start_host_copies(oks_d)
                            ctx.session.capacity_spec_hits += 1
                            ctx.spec_pending.append((key, [], [], oks_d, None))
                            for stream, r in zip(streams, raw):
                                emitted = True
                                yield self._semi(stream, r[0])
                        else:
                            oks = jax.device_get(oks_d)
                            if cache is not None:
                                cache[key] = {"dense_ok": all(map(bool, oks)),
                                              "n": len(streams)}
                            for stream, r, ok in zip(streams, raw, oks):
                                emitted = True
                                counts = (r[0] if bool(ok)
                                          else self._probe(build, stream)[0])
                                yield self._semi(stream, counts)
                    else:
                        for stream in sp_local():
                            emitted = True
                            yield self._semi(stream,
                                             probe_fn(build, stream)[0])
                else:
                    # probe EVERY stream batch first (dispatch is async and
                    # nearly free), then fetch all expansion totals in ONE
                    # device->host round trip — a per-batch fetch would pay
                    # ~150-250ms each on a tunneled attachment.
                    # NB: exec/outofcore.py _join_bucket is this loop's
                    # simplified per-bucket twin — semantic changes to the
                    # probe/totals/expand contract must be mirrored there
                    streams = list(sp_local())
                    oks_d = []
                    if dense:
                        raw = [dkern(build, s, lo_arr) for s in streams]
                        probes = [r[:3] for r in raw]
                        oks_d = [r[3] for r in raw]
                        del raw  # or probes[i]=None below frees nothing
                    else:
                        probes = [probe_fn(build, s) for s in streams]
                    totals_d = [self._totals(build, s, *pr)
                                for s, pr in zip(streams, probes)]
                    entry = cache.get(key) if cache is not None else None
                    spec_hit = (
                        entry is not None and entry.get("n") == len(streams)
                        and entry.get("dense_ok", True)
                        and entry.get("sizes") is not None)
                    if spec_hit:
                        # speculate: expand into last run's buckets; the
                        # async host copies overlap the expand dispatches
                        # so the deferred verification fetch is ~free
                        sizes_all = entry["sizes"]
                        _start_host_copies(totals_d + oks_d)
                        ctx.session.capacity_spec_hits += 1
                        caps_used: list = []
                        ctx.spec_pending.append(
                            (key, totals_d, caps_used, oks_d, None))
                    elif dense:
                        fetch = jax.device_get(
                            list(zip(totals_d, oks_d)))
                        sizes_all = []
                        all_ok = True
                        for bi_, (sizes_d, ok) in enumerate(fetch):
                            if bool(ok):
                                sizes_all.append(sizes_d)
                                continue
                            all_ok = False
                            # advisory bounds were wrong for this build:
                            # exact sort probe, one extra fetch (rare)
                            pr = self._probe(build, streams[bi_])
                            probes[bi_] = pr
                            sizes_all.append(jax.device_get(
                                self._totals(build, streams[bi_], *pr)))
                        if cache is not None:
                            cache[key] = {
                                "dense_ok": all_ok, "n": len(streams),
                                "sizes": [[int(x) for x in s]
                                          for s in sizes_all]}
                    else:
                        sizes_all = jax.device_get(totals_d)
                        if cache is not None:
                            cache[key] = {
                                "n": len(streams),
                                "sizes": [[int(x) for x in s]
                                          for s in sizes_all]}
                    for bi_, (stream, (counts, bstart, bperm),
                              sizes_d) in enumerate(
                            zip(streams, probes, sizes_all)):
                        # free consumed inputs as the loop advances: with
                        # many large stream batches, holding every batch +
                        # probe triple for the whole emission loop would
                        # grow peak HBM from O(batch) to O(partition)
                        streams[bi_] = probes[bi_] = None
                        sizes = [int(x) for x in sizes_d]
                        total = sizes[0]
                        if jt == "full":
                            flags = self._match_flags(build, counts, bstart,
                                                      bperm)
                            matched_acc = (flags if matched_acc is None
                                           else matched_acc | flags)
                        if total == 0:
                            if spec_hit:
                                # asserted-empty: verification requires
                                # the actual total to be 0 as well
                                caps_used.append(None)
                            continue
                        n_s = sum(1 for d in stream.schema.dtypes
                                  if d.is_string)
                        s_caps = tuple(_char_bucket(c)
                                       for c in sizes[1:1 + n_s])
                        b_caps = tuple(_char_bucket(c)
                                       for c in sizes[1 + n_s:])
                        out_cap = bucket_dim(
                            bucket_capacity(total, growth))
                        if spec_hit:
                            caps_used.append((out_cap, s_caps, b_caps))
                        emitted = True
                        expanded = self._expand(build, stream, counts,
                                                bstart, bperm, out_cap,
                                                s_caps, b_caps)
                        from spark_rapids_tpu.memory.device import (
                            TpuDeviceManager,
                        )
                        dm = TpuDeviceManager.current()
                        if dm is not None:
                            dm.meter_batch(expanded)
                        yield expanded
                if jt == "full":
                    if matched_acc is None:
                        matched_acc = jnp.zeros((build.capacity,), jnp.bool_)
                    stream_schema = self.children[si].output_schema()
                    tail = self._unmatched(build, matched_acc, stream_schema)
                    if tail.num_rows_host() > 0 or not emitted:
                        emitted = True
                        yield tail
                if not emitted:
                    yield DeviceBatch.empty(self.output_schema())
            return run
        return [make(sp, bp, i)
                for i, (sp, bp) in enumerate(zip(stream_parts, build_parts))]


class TpuBroadcastHashJoinExec(TpuShuffledHashJoinExec):
    """Equi-join streaming against a broadcast build batch (reference:
    GpuBroadcastHashJoinExec, shims/spark300). The probe/expand machinery is
    TpuShuffledHashJoinExec's; the distinct class carries its own rule/conf
    key, like the reference's separate exec."""


class TpuCartesianProductExec(TpuShuffledHashJoinExec):
    """Unconditioned cross product (reference: GpuCartesianProductExec.scala,
    disabled by default there as well)."""

    def __init__(self, left: PhysicalPlan, right: PhysicalPlan):
        super().__init__(left, right, "cross", [], [])

    def describe(self) -> str:
        return "TpuCartesianProductExec"


class TpuBroadcastNestedLoopJoinExec(PhysicalPlan):
    """Condition (non-equi) join: device cross product of each stream batch
    with the broadcast build batch, then one fused condition-filter kernel
    over the combined row (reference:
    execution/GpuBroadcastNestedLoopJoinExec.scala:258, inner/cross,
    disabled by default)."""

    columnar_output = True

    def __init__(self, left: PhysicalPlan, right: PhysicalPlan,
                 join_type: str, condition):
        super().__init__([left, right])
        assert join_type in ("inner", "cross"), join_type
        self.join_type = join_type
        self.condition = condition
        self._cross = TpuShuffledHashJoinExec(left, right, "cross", [], [])
        if condition is not None:
            from spark_rapids_tpu.ops import rowops
            from spark_rapids_tpu.sql.exprs.evalbridge import (
                make_context, to_device_column,
            )

            def fkernel(batch):
                ctx = make_context(batch)
                pred = to_device_column(ctx, condition.eval_device(ctx))
                keep = pred.data & pred.validity
                return rowops.filter_batch(batch, keep)
            from spark_rapids_tpu.utils.kernelcache import (
                cached_jit, expr_signature,
            )
            self._filter = cached_jit(
                "bnlj|" + expr_signature(condition),
                lambda: jax.jit(fkernel))
        else:
            self._filter = None

    def output_schema(self) -> Schema:
        return self._cross.output_schema()

    def describe(self) -> str:
        return f"TpuBroadcastNestedLoopJoinExec({self.join_type})"

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        # keep the cross exec's children in sync with post-transition
        # children (TransitionOverrides rewrites self.children)
        self._cross.children = list(self.children)
        cross_parts = self._cross.partitions(ctx)

        def make(part: Partition) -> Partition:
            def run() -> Iterator[DeviceBatch]:
                for batch in part():
                    yield (self._filter(batch) if self._filter is not None
                           else batch)
            return run
        return [make(p) for p in cross_parts]
