"""Pipeline cutting for whole-stage fusion.

Walks the CONVERTED physical plan (post TpuOverrides + transitions +
coalesce insertion) and replaces each maximal chain of fusible unary
operators with one ``TpuFusedStageExec``. Everything that is not a
deterministic Project/Filter/Coalesce is a stage boundary — exchanges
(AQE cuts its query stages at the same edges, sql/adaptive/executor
``_is_stage_boundary``; this is the non-AQE twin over the converted
tree), scans, joins, aggregates, host<->device transitions and CPU
fallback operators all end a pipeline.

Two deliberate exclusions keep fusion-ON from regressing existing
fusions:

  * a (Coalesce +) Filter directly below a shuffle/broadcast exchange is
    left out of the chain whenever ``spark.rapids.sql.exchange
    .fuseFilter`` is on — the exchange's collapse concat claims exactly
    that filter as a single-gather mask (exec/tpu._fused_filter_source),
    which beats running the compaction inside a fused program;
  * chains with fewer than ``spark.rapids.sql.fusion.minOperators``
    compute members do not fuse (fusing one operator only renames its
    dispatch).

Input donation (``fusion.donateInputs``) engages only when the stage
input comes from a known single-consumer producer: exchange reads, join
and aggregate outputs, and coalesce concats mint fresh buffers per
consumer, while scan-cache batches, broadcast tables and reused
subtrees are shared across consumers/queries and must never be donated.
"""

from __future__ import annotations

from typing import List, Optional

from spark_rapids_tpu.exec.base import PhysicalPlan

FUSION_ENABLED_KEY = "spark.rapids.sql.fusion.stageEnabled"
FUSION_MIN_OPS_KEY = "spark.rapids.sql.fusion.minOperators"
FUSION_DONATE_KEY = "spark.rapids.sql.fusion.donateInputs"


def _is_fusible(node: PhysicalPlan) -> bool:
    from spark_rapids_tpu.exec import tpu as tpuexec
    from spark_rapids_tpu.exec.coalesce import TpuCoalesceBatchesExec
    if isinstance(node, TpuCoalesceBatchesExec):
        return True
    if isinstance(node, (tpuexec.TpuProjectExec, tpuexec.TpuFilterExec)):
        return not node._impure
    return False


def _is_compute(node: PhysicalPlan) -> bool:
    """Does this member do real device work? Coalesces are re-batching
    and pure-selection projects are ZERO-COPY column views unfused
    (exec/tpu.TpuProjectExec: 'a jitted identity kernel would copy every
    buffer') — neither counts toward minOperators, so a chain of views
    alone never fuses into a program that would copy what the views
    merely re-arranged. They still ride along inside a chain with real
    compute, where they are free."""
    from spark_rapids_tpu.exec import tpu as tpuexec
    from spark_rapids_tpu.exec.coalesce import TpuCoalesceBatchesExec
    if isinstance(node, TpuCoalesceBatchesExec):
        return False
    if isinstance(node, tpuexec.TpuProjectExec) and node._pure_selection:
        return False
    return True


def _parent_claims_filter(parent: Optional[PhysicalPlan],
                          top: PhysicalPlan, conf) -> bool:
    """Does the consumer fold a directly-below Filter into its own concat
    (exec/tpu._fused_filter_source)? Broadcast materializations always
    do; shuffle exchanges only on the single/collapse path — hash/range
    kinds with local collapse on, no accelerated shuffle manager, and no
    padded (aggregate) producer below. A mesh also disables the collapse
    but is session state the cutter cannot see, so mesh sessions keep
    the conservative skip (the filter stays a standalone dispatch there,
    exactly as before fusion)."""
    from spark_rapids_tpu.exec.tpu import TpuShuffleExchangeExec
    from spark_rapids_tpu.exec.tpujoin import TpuBroadcastExchangeExec
    if not conf.get_bool("spark.rapids.sql.exchange.fuseFilter", True):
        return False
    if isinstance(parent, TpuBroadcastExchangeExec):
        return True
    if not isinstance(parent, TpuShuffleExchangeExec):
        return False
    # an aggregate/limit producer keeps the shrinking exchange path,
    # which never claims the filter — for the single kind too
    # (exec/tpu.py checks _padded_producer before _fused_filter_source
    # on both)
    if TpuShuffleExchangeExec._padded_producer(top):
        return False
    kind = parent.partitioning[0]
    if kind == "single":
        return True
    if kind not in ("hash", "range"):
        return False  # roundrobin never collapses
    if conf.get_bool("spark.rapids.shuffle.transport.enabled", False):
        return False  # manager path partitions for real
    return conf.get_bool("spark.rapids.sql.shuffle.localCollapse", True)


def _fresh_producer(node: PhysicalPlan) -> bool:
    """Does this producer mint fresh device buffers per consumer pull —
    safe to donate into the fused program? Conservative allow-list;
    scans (device scan cache) and broadcasts (shared table) are exactly
    what it excludes. A coalesce can never be the stage input (it is
    fusible, so the chain walk absorbs it)."""
    from spark_rapids_tpu.exec import tpu as tpuexec
    from spark_rapids_tpu.exec.tpujoin import TpuShuffledHashJoinExec
    return isinstance(node, (tpuexec.TpuShuffleExchangeExec,
                             tpuexec.TpuHashAggregateExec,
                             TpuShuffledHashJoinExec))


def _try_fuse(top: PhysicalPlan, parent: Optional[PhysicalPlan],
              conf, min_ops: int, donate_conf: bool) -> PhysicalPlan:
    """Fuse the maximal fusible chain starting at ``top`` (downward),
    returning the rewritten node (or ``top`` untouched)."""
    from spark_rapids_tpu.exec.coalesce import TpuCoalesceBatchesExec
    from spark_rapids_tpu.exec.stagecompiler.fusedexec import (
        TpuFusedStageExec,
    )
    from spark_rapids_tpu.exec.tpu import TpuFilterExec
    chain: List[PhysicalPlan] = []
    cur = top
    while _is_fusible(cur) and len(cur.children) == 1:
        chain.append(cur)
        cur = cur.children[0]
    if not chain:
        return top
    # leading coalesces stay OUTSIDE the stage: a coalesce at the chain
    # top re-batches what the CONSUMER sees (insert_coalesce put it
    # there for the consumer's dispatch count), and absorbing it as
    # identity would hand the consumer one low-occupancy fragment per
    # input batch — the interior/bottom absorption rules don't apply
    skip = 0
    while skip < len(chain) and isinstance(chain[skip],
                                           TpuCoalesceBatchesExec):
        skip += 1
    # ...and the exchange-claimed filter below them stays out too.
    # _fused_filter_source looks through exactly ONE coalesce
    # (exec/tpu.py), so a filter under two stacked coalesces is NOT
    # claimed and stays eligible for fusion
    if (_parent_claims_filter(parent, top, conf) and skip <= 1
            and skip < len(chain)
            and isinstance(chain[skip], TpuFilterExec)):
        skip += 1
    fused_nodes = chain[skip:]
    if sum(1 for m in fused_nodes if _is_compute(m)) < min_ops:
        return top
    child = fused_nodes[-1].children[0]
    donate = donate_conf and _fresh_producer(child)
    fused = TpuFusedStageExec(child, list(reversed(fused_nodes)),
                              donate=donate)
    # rebuild the unfused prefix (shallow copies) above the fused stage
    out: PhysicalPlan = fused
    for node in reversed(chain[:skip]):
        node = node.map_children(lambda c: c)
        node.children = [out]
        out = node
    return out


def compile_stages(plan: PhysicalPlan, conf) -> PhysicalPlan:
    """Entry point (sql/overrides.TransitionOverrides wires it in, so
    the legacy, AQE per-stage and plan-cache paths all fuse). Returns
    the plan UNTOUCHED (same object) when the conf is off — the
    byte-identical rollback contract."""
    if not conf.get_bool(FUSION_ENABLED_KEY, False):
        return plan
    min_ops = max(1, conf.get_int(FUSION_MIN_OPS_KEY, 2))
    # donation is decided BEFORE reuse dedup runs (reuse_common_subtrees
    # rewrites the tree after this pass and would replay the SAME batch
    # objects to every consumer of a shared subtree — donating those
    # would hand later consumers deleted buffers), so it only engages
    # when subtree reuse is off; _fresh_producer cannot see a rewrite
    # that has not happened yet
    donate_conf = (conf.get_bool(FUSION_DONATE_KEY, False)
                   and not conf.get_bool(
                       "spark.rapids.sql.reuseSubtrees.enabled", True))

    def rec(node: PhysicalPlan) -> PhysicalPlan:
        new_children = []
        for c in node.children:
            c2 = rec(c)
            if not _is_fusible(node):
                # chains cut only at their maximal top: a fusible parent
                # extends the chain upward and cuts at ITS consumer
                c2 = _try_fuse(c2, node, conf, min_ops, donate_conf)
            new_children.append(c2)
        out = node.map_children(lambda c: c)
        out.children = new_children
        return out

    root = rec(plan)
    return _try_fuse(root, None, conf, min_ops, donate_conf)
