"""TpuFusedStageExec: one jit'd program per fused operator pipeline.

The fused stage holds its member operators (bottom-up execution order)
and builds a single ``cached_jit`` kernel applying each member's device
function in sequence — the whole-stage-codegen move (HyPer / Spark WSCG)
in the XLA world: what used to be N python dispatches and N kernel
launches per batch is one dispatch of one executable, and XLA reuses
(donates) the buffers between member ops inside the program instead of
materializing each operator's output to HBM.

Members are restricted to deterministic operators whose per-batch work
is a pure batch -> batch device function: TpuProjectExec, TpuFilterExec
and TpuCoalesceBatchesExec (absorbed — the bottom-most coalesce's goal
becomes the stage's INPUT re-batching so capacity buckets stay as
stable as the unfused pipeline's; interior ones are identity inside one
program). Anything else — exchanges, joins, aggregates, scans,
transitions, CPU fallbacks, nondeterministic expressions — is a stage
boundary (cutter.py).

Observability: the fused node is first-class everywhere. ``describe()``
names the member pipeline (profile tree, progress records, plan
digests); ``member_ops`` rides the exec op-scope so a compile fired
inside the stage lands in the ledger with the member list
(obs/compileledger.py); a kernel failure emits a ``fusedStageFailure``
event naming the member pipeline — captured by the always-on flight
recorder, so a queryFailed dump says WHICH fused pipeline died, not
just that a fused node did — and re-raises with the pipeline in the
message.
"""

from __future__ import annotations

from typing import Iterator, List

import jax

from spark_rapids_tpu.columnar.batch import DeviceBatch, Schema
from spark_rapids_tpu.exec.base import ExecContext, Partition, PhysicalPlan
from spark_rapids_tpu.utils.kernelcache import cached_jit, expr_signature


def member_fn(node: PhysicalPlan):
    """(batch -> batch device function, kernel signature) of one fusible
    member, or (None, sig) for an absorbed coalesce. Raises TypeError on
    a non-fusible node — the cutter must never hand one over."""
    from spark_rapids_tpu.exec import tpu as tpuexec
    from spark_rapids_tpu.exec.coalesce import TpuCoalesceBatchesExec
    from spark_rapids_tpu.sql.exprs.evalbridge import eval_projection
    if isinstance(node, TpuCoalesceBatchesExec):
        return None, f"coalesce|{node.goal!r}"
    if isinstance(node, tpuexec.TpuProjectExec) and not node._impure:
        if node._pure_selection:
            # pure column selection/rename: pytree restructuring only —
            # inside the fused program this is free (no buffer copies;
            # the jit boundary is the stage's, not this member's).
            # The SOURCE INDICES are part of the cache key: the closure
            # bakes them, and two selections outputting the same names
            # from different ordinals must not share a compiled program
            # (the TpuFilterExec out_sel sig guards the same hazard)
            kern = node._kernel
            from spark_rapids_tpu.sql.exprs.core import Alias, BoundRef

            def as_ref(e):
                while isinstance(e, Alias):
                    e = e.children[0]
                return e if isinstance(e, BoundRef) else None
            names = [n for n, _ in node.exprs]
            idx = [as_ref(e).index for _, e in node.exprs]
            sig = f"sel|{tuple(idx)}:{','.join(names)}"
            return (lambda b: kern(b)), sig
        # computed/mixed projections deliberately use the PLAIN
        # eval_projection spelling rather than the node's mixed kernel:
        # that kernel splits computed vs passthrough outputs to avoid
        # jit-BOUNDARY buffer copies (exec/tpu.py), a concern that does
        # not exist inside one fused program
        bound = [e for _, e in node.exprs]
        names = [n for n, _ in node.exprs]
        sig = "project|" + "|".join(
            f"{n}={expr_signature(e)}" for n, e in node.exprs)
        return (lambda b: eval_projection(b, bound, names)), sig
    if isinstance(node, tpuexec.TpuFilterExec) and not node._impure:
        out_sel = node.out_sel
        sel_sig = ("" if out_sel is None
                   else f"|sel={tuple(out_sel[1])}"
                        f":{','.join(out_sel[0])}")
        # the node's own un-jitted closure — one filter spelling for the
        # standalone and fused paths
        return node._raw_kernel, \
            "filter|" + expr_signature(node.condition) + sel_sig
    raise TypeError(f"not fusible: {node.describe()}")


class TpuFusedStageExec(PhysicalPlan):
    """One fused pipeline of member operators as a single plan node.

    ``members`` is bottom-up (execution order): members[0] consumes the
    stage input, members[-1] produces the stage output. ``donate`` adds
    jax buffer donation of the stage INPUT (cutter decides it only for
    known single-consumer producers)."""

    columnar_output = True

    def __init__(self, child: PhysicalPlan,
                 members: List[PhysicalPlan], donate: bool = False):
        super().__init__([child])
        from spark_rapids_tpu.exec.coalesce import TpuCoalesceBatchesExec
        self.members = list(members)
        self.member_ops = [m.describe() for m in self.members]
        self.donate = bool(donate)
        self.input_goal = None
        # an absorbed INTERIOR coalesce must not silently fragment the
        # consumer: inside one program its re-batching is free to drop,
        # but the consumer then sees one output per input batch instead
        # of the coalesced stream — so the TOPMOST interior coalesce's
        # goal re-batches the stage OUTPUT (filters/projects preserve
        # capacity, so the grouping matches what the interior coalesce
        # would have produced)
        self.output_goal = None
        fns, sigs = [], []
        for i, m in enumerate(self.members):
            fn, sig = member_fn(m)
            if fn is None:
                if isinstance(m, TpuCoalesceBatchesExec):
                    if i == 0:
                        # the bottom coalesce keeps its re-batching role
                        # at the stage input (capacity-bucket stability)
                        self.input_goal = m.goal
                    else:
                        self.output_goal = m.goal
                continue
            fns.append(fn)
            sigs.append(sig)
        self._fns = fns
        sig = "fusedstage|" + "|".join(sigs) \
            + (f"|donate" if self.donate else "")
        self._sig = sig

        def fused(batch: DeviceBatch) -> DeviceBatch:
            for fn in fns:
                batch = fn(batch)
            return batch
        if self.donate:
            self._kernel = cached_jit(
                sig, lambda: jax.jit(fused, donate_argnums=(0,)))
        else:
            self._kernel = cached_jit(sig, lambda: jax.jit(fused))

    # -- plan-node surface ---------------------------------------------------
    def output_schema(self) -> Schema:
        return self.members[-1].output_schema()

    def describe(self) -> str:
        shorts = [m.describe().split("(", 1)[0] for m in self.members]
        return f"TpuFusedStageExec([{' -> '.join(shorts)}])"

    def fingerprint_extra(self) -> str:
        # full member identity: the fused node must be as precise as its
        # members were (reuse dedup, capacity speculation, plan caches
        # all key on describe()+fingerprint_extra)
        parts = [f"{m.describe()}#{m.fingerprint_extra()}"
                 for m in self.members]
        return (f"goal={self.input_goal!r}|out={self.output_goal!r}|"
                + ";".join(parts))

    # -- execution -----------------------------------------------------------
    def _pipeline_label(self) -> str:
        return " -> ".join(
            m.describe().split("(", 1)[0] for m in self.members)

    def _note_failure(self, e: BaseException) -> None:
        """A failure inside the fused program must name the member
        pipeline, not just this node: the event lands in the always-on
        flight recorder, so the queryFailed dump carries it."""
        from spark_rapids_tpu.obs.events import EVENTS
        EVENTS.emit("fusedStageFailure", op=self.describe()[:200],
                    members=[m[:200] for m in self.member_ops],
                    error=f"{type(e).__name__}: {e}"[:300])

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        child_parts = self.children[0].executed_partitions(ctx)
        growth = ctx.conf.capacity_growth
        in_schema = self.children[0].output_schema()
        goal = self.input_goal

        def input_batches(part: Partition) -> Iterator[DeviceBatch]:
            if goal is None:
                yield from part()
                return
            from spark_rapids_tpu.exec.coalesce import coalesce_iter
            # coarse re-batching: the fused program's compile rides the
            # input capacity, so tail fragments pad onto the shape-
            # bucket ladder (compile.shapeBuckets; identity when off)
            yield from coalesce_iter(part(), goal, in_schema, growth,
                                     coarse=True)

        out_goal = self.output_goal
        out_schema = self.output_schema()

        def make(part: Partition) -> Partition:
            def fused_outputs() -> Iterator[DeviceBatch]:
                for batch in input_batches(part):
                    try:
                        out = self._kernel(batch)
                    except Exception as e:  # noqa: BLE001
                        self._note_failure(e)
                        raise RuntimeError(
                            f"fused stage [{self._pipeline_label()}] "
                            f"failed: {e}") from e
                    yield out

            def run() -> Iterator[DeviceBatch]:
                if out_goal is None:
                    yield from fused_outputs()
                    return
                from spark_rapids_tpu.exec.coalesce import coalesce_iter
                yield from coalesce_iter(fused_outputs(), out_goal,
                                         out_schema, growth)
            return run
        return [make(p) for p in child_parts]
