"""Whole-stage fusion compiler (ROADMAP item 2).

The converted physical plan dispatches one jitted kernel per operator per
batch; on a high-latency attachment the python dispatch gap between tiny
kernels — not device time — is what keeps 12 of 44 bench queries below
1x (PR 6's device/transfer/dispatch breakdown names it per operator).
This subsystem collapses each fusible pipeline into ONE compiled
program:

  * ``cutter``    — walks the converted plan and cuts maximal chains of
    fusible operators at exchange/scan/fallback boundaries (the same
    boundaries AQE's stage cutting keys on — a hash exchange is a stage
    edge in both worlds; see sql/adaptive/executor._is_stage_boundary
    for the CPU-plan twin this reuses the shape of);
  * ``fusedexec`` — ``TpuFusedStageExec``, the first-class plan node
    that runs the whole member pipeline as one ``cached_jit`` program
    and reports member-operator identity to the compile ledger, the
    profile tree, progress records and the flight recorder.

Gate: ``spark.rapids.sql.fusion.stageEnabled`` (default false — today's
per-operator plans stay byte-identical; bench turns it on).
"""

from spark_rapids_tpu.exec.stagecompiler.cutter import compile_stages
from spark_rapids_tpu.exec.stagecompiler.fusedexec import TpuFusedStageExec

__all__ = ["compile_stages", "TpuFusedStageExec"]
