"""Per-task execution context (thread-local).

The analogue of Spark's ``TaskContext`` + ``InputFileBlockHolder``: the
reference's nondeterministic expressions (GpuSparkPartitionID.scala:58,
GpuMonotonicallyIncreasingID.scala:75, GpuInputFileBlock.scala:114) read the
partition index and the current input file from task-scoped state that the
scan/exec machinery maintains. Here every operator's partition runner sets
the partition index before iterating, and file sources publish the file they
are currently decoding.
"""

from __future__ import annotations

import threading

_state = threading.local()


def set_partition(index: int) -> None:
    _state.part_id = index
    _state.row_base = 0


def partition_id() -> int:
    return getattr(_state, "part_id", 0)


def row_base() -> int:
    """Rows already emitted by earlier batches of this partition — the
    monotonically_increasing_id intra-partition offset. Each operator that
    evaluates nondeterministic expressions tracks its own count locally and
    publishes it with ``set_row_base`` right before evaluating, so stacked
    operators in one generator pipeline cannot corrupt each other."""
    return getattr(_state, "row_base", 0)


def set_row_base(n: int) -> None:
    _state.row_base = n


def set_input_file(path: str) -> None:
    _state.input_file = path


def input_file() -> str:
    """Empty string outside a file scan, like Spark's input_file_name()."""
    return getattr(_state, "input_file", "")


def clear_input_file() -> None:
    _state.input_file = ""
