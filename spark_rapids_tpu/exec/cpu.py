"""CPU physical operators (the fallback path and differential-test oracle).

These play the role Spark's own row-based operators play for the reference:
anything the TPU cannot run falls back here, and the test harness compares
TPU results against them (SparkQueryCompareTestSuite pattern). Payload:
pandas DataFrames per partition.
"""

from __future__ import annotations

from typing import Callable, Iterator, List, Optional, Sequence, Tuple

import numpy as np
import pandas as pd

from spark_rapids_tpu.columnar import dtypes
from spark_rapids_tpu.columnar.batch import Schema, _numpy_to_pandas
from spark_rapids_tpu.exec.aggutil import AggPlan
from spark_rapids_tpu.exec.base import ExecContext, Partition, PhysicalPlan
from spark_rapids_tpu.exec.hostagg import grouped_aggregate
from spark_rapids_tpu.sql.exprs.core import Expression
from spark_rapids_tpu.sql.exprs.hostutil import host_unary_values
from spark_rapids_tpu.sql.functions import SortOrder


def _is_masked(s: pd.Series) -> bool:
    """Is this series backed by a masked (nullable-extension) array —
    Int64/Float64/boolean — i.e. does it carry an explicit null mask?"""
    arr = getattr(s, "array", None)
    return hasattr(arr, "_mask") and hasattr(arr, "_data")


def _lift_masked(s: pd.Series) -> pd.Series:
    """Plain-numpy series -> the matching masked extension dtype with an
    all-False mask. Constructed from the raw buffer (NOT pd.array/astype,
    which coerce float NaN to NA) so a genuine NaN VALUE survives as a
    value — NaN and NULL are distinct in this engine's null discipline
    (columnar/batch.py)."""
    if _is_masked(s):
        return s
    vals = s.to_numpy()
    mask = np.zeros(len(vals), dtype=bool)
    try:
        if vals.dtype.kind == "f":
            arr = pd.arrays.FloatingArray(vals, mask)
        elif vals.dtype.kind in "iu":
            arr = pd.arrays.IntegerArray(vals, mask)
        elif vals.dtype.kind == "b":
            arr = pd.arrays.BooleanArray(vals, mask)
        else:
            return s
    except (TypeError, ValueError):
        return s
    return pd.Series(arr, name=s.name)


def concat_host_frames(dfs: List[pd.DataFrame],
                       schema: Schema) -> pd.DataFrame:
    """Null-mask-preserving concat of partition frames.

    pd.concat decides the result dtype from the pieces: a masked
    (nullable-extension) column next to plain-numpy siblings downcasts to
    plain float and its NA values become NaN — but NaN is a VALUE here,
    so the null mask is silently destroyed (tpcxbb q17: a partial
    aggregate's NULL sum from an empty partition merged as NaN and
    poisoned the final sum). When pieces disagree, plain pieces are
    lifted to the masked dtype first (all-False mask — genuine NaN values
    keep being values)."""
    dfs = [df for df in dfs]
    if not dfs:
        return _empty_df(schema)
    if len(dfs) == 1:
        return dfs[0]
    ncols = dfs[0].shape[1]
    mixed = []
    for i in range(ncols):
        kinds = [_is_masked(df.iloc[:, i]) for df in dfs]
        mixed.append(any(kinds) and not all(kinds))
    if any(mixed):
        lifted = []
        for df in dfs:
            series = [(_lift_masked(df.iloc[:, i]) if mixed[i]
                       else df.iloc[:, i]).reset_index(drop=True)
                      for i in range(ncols)]
            # positional assembly: join outputs may carry duplicate names
            nd = (pd.concat(series, axis=1) if series
                  else pd.DataFrame(index=range(len(df))))
            nd.columns = list(df.columns)
            lifted.append(nd)
        dfs = lifted
    return pd.concat(dfs, ignore_index=True)


def _concat_parts(it: Iterator[pd.DataFrame], schema: Schema) -> pd.DataFrame:
    return concat_host_frames(list(it), schema)


def _empty_df(schema: Schema) -> pd.DataFrame:
    cols = {}
    for name, dt in zip(schema.names, schema.dtypes):
        if dt.is_string:
            cols[name] = pd.Series(np.empty(0, dtype=object), dtype="str")
        elif dt.is_datetime:
            cols[name] = pd.Series(np.empty(0, dtype="datetime64[us]"))
        else:
            cols[name] = pd.Series(np.empty(0, dtype=dt.np_dtype))
    return pd.DataFrame(cols)


class CpuScanExec(PhysicalPlan):
    """Scan over an in-memory or file source (source yields partitions of
    pandas DataFrames)."""

    def __init__(self, source, schema: Schema):
        super().__init__()
        self.source = source
        self._schema = schema
        # statistics-answerable filter conjuncts the planner pushed down
        # (sql/pushdown.py); file sources use them to prune splits
        self.pushed_filters = None

    def output_schema(self) -> Schema:
        return self._schema

    def describe(self) -> str:
        return f"CpuScanExec({self.source.describe()})"

    def fingerprint_extra(self) -> str:
        return self.source.data_uid()

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        if self.pushed_filters and hasattr(self.source, "prune_splits"):
            return self.source.cpu_partitions(ctx, self.pushed_filters)
        return self.source.cpu_partitions(ctx)


class CpuProjectExec(PhysicalPlan):
    def __init__(self, child: PhysicalPlan,
                 exprs: Sequence[Tuple[str, Expression]]):
        super().__init__([child])
        self.exprs = list(exprs)

    def output_schema(self) -> Schema:
        cs = self.children[0].output_schema()
        return Schema([n for n, _ in self.exprs],
                      [e.dtype(cs) for _, e in self.exprs])

    def describe(self) -> str:
        return f"CpuProjectExec([{', '.join(n for n, _ in self.exprs)}])"

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        from spark_rapids_tpu.exec import taskctx
        from spark_rapids_tpu.sql.exprs.nondet import has_nondeterministic
        child_parts = self.children[0].executed_partitions(ctx)
        impure = any(has_nondeterministic(e) for _, e in self.exprs)

        def make(index: int, part: Partition) -> Partition:
            def run():
                seen = 0
                for df in part():
                    if impure:
                        taskctx.set_partition(index)
                        taskctx.set_row_base(seen)
                        seen += len(df)
                    out = {}
                    for name, e in self.exprs:
                        out[name] = e.eval_host(df).reset_index(drop=True)
                    yield pd.DataFrame(out, columns=[n for n, _ in self.exprs])
            return run
        return [make(i, p) for i, p in enumerate(child_parts)]


class CpuFilterExec(PhysicalPlan):
    def __init__(self, child: PhysicalPlan, condition: Expression):
        super().__init__([child])
        self.condition = condition

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def describe(self) -> str:
        return f"CpuFilterExec({self.condition!r})"

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        from spark_rapids_tpu.exec import taskctx
        from spark_rapids_tpu.sql.exprs.nondet import has_nondeterministic
        child_parts = self.children[0].executed_partitions(ctx)
        impure = has_nondeterministic(self.condition)

        def make(index: int, part: Partition) -> Partition:
            def run():
                seen = 0
                for df in part():
                    if impure:
                        taskctx.set_partition(index)
                        taskctx.set_row_base(seen)
                        seen += len(df)
                    pred = self.condition.eval_host(df)
                    vals, validity, _ = host_unary_values(pred)
                    keep = vals.astype(np.bool_) & validity
                    yield df[keep].reset_index(drop=True)
            return run
        return [make(i, p) for i, p in enumerate(child_parts)]


class CpuHashAggregateExec(PhysicalPlan):
    """mode 'partial': group by key exprs, emit keys + update intermediates.
    mode 'final': group by leading key columns, merge intermediates, emit
    finalize projection."""

    def __init__(self, child: PhysicalPlan, plan: AggPlan, mode: str):
        super().__init__([child])
        self.plan = plan
        self.mode = mode

    def output_schema(self) -> Schema:
        return (self.plan.partial_schema if self.mode == "partial"
                else self.plan.output_schema)

    def describe(self) -> str:
        keys = ", ".join(n for n, _ in self.plan.grouping)
        return f"CpuHashAggregateExec(mode={self.mode}, keys=[{keys}])"

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        child_parts = self.children[0].executed_partitions(ctx)

        def make(part: Partition) -> Partition:
            def run():
                df = _concat_parts(part(), self.children[0].output_schema())
                yield self._aggregate(df)
            return run
        return [make(p) for p in child_parts]

    def _aggregate(self, df: pd.DataFrame) -> pd.DataFrame:
        plan = self.plan
        if self.mode == "partial":
            keys = [host_unary_values(e.eval_host(df))[:2]
                    for _, e in plan.grouping]
            reductions = []
            inputs = [host_unary_values(e.eval_host(df))[:2]
                      for e in plan.update_inputs]
            for ops in plan.update_plan:
                for kind, input_idx, idt in ops:
                    v, m = inputs[input_idx]
                    reductions.append((kind, v, m, idt))
            key_out, red_out = grouped_aggregate(keys, reductions)
            out = {}
            schema = plan.partial_schema
            for i, (name, dt) in enumerate(zip(schema.names, schema.dtypes)):
                if i < plan.num_keys:
                    v, m = key_out[i]
                else:
                    v, m = red_out[i - plan.num_keys]
                out[name] = _numpy_to_pandas(np.asarray(v), np.asarray(m), dt)
            return pd.DataFrame(out, columns=list(schema.names))
        # final: group by leading key cols of the partial schema
        schema = plan.partial_schema
        keys = [host_unary_values(df.iloc[:, i])[:2]
                for i in range(plan.num_keys)]
        reductions = []
        for merged in plan.merge_plan:
            for kind, col, idt in merged:
                v, m = host_unary_values(df.iloc[:, col])[:2]
                reductions.append((kind, v, m, idt))
        key_out, red_out = grouped_aggregate(keys, reductions)
        # rebuild merged partial frame, then run finalize projection
        merged_cols = {}
        ri = 0
        for i, (name, dt) in enumerate(zip(schema.names, schema.dtypes)):
            if i < plan.num_keys:
                if key_out:
                    v, m = key_out[i]
                else:
                    v, m = np.zeros(0), np.zeros(0, np.bool_)
                merged_cols[name] = _numpy_to_pandas(np.asarray(v),
                                                     np.asarray(m), dt)
            else:
                v, m = red_out[ri]
                ri += 1
                merged_cols[name] = _numpy_to_pandas(np.asarray(v),
                                                     np.asarray(m), dt)
        mdf = pd.DataFrame(merged_cols, columns=list(schema.names))
        out = {}
        for name, e in plan.finalize_exprs():
            out[name] = e.eval_host(mdf).reset_index(drop=True)
        return pd.DataFrame(out, columns=[n for n, _ in plan.results])


class CpuShuffleExchangeExec(PhysicalPlan):
    """Materialization barrier repartitioning child output.

    partitioning: ('hash', [col indices], n) | ('single',) |
    ('roundrobin', n) |
    ('range', [key indices], [ascending], [nulls_first], n)."""

    def __init__(self, child: PhysicalPlan, partitioning):
        super().__init__([child])
        self.partitioning = partitioning

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def describe(self) -> str:
        return f"CpuShuffleExchangeExec({self.partitioning[0]})"

    def materialize_stage(self, ctx: ExecContext):
        """AQE query-stage materialization (sql/adaptive/): run the map
        side (this exchange's child), split every map partition by the
        CANONICAL hash of the key columns, and report per-(map, reduce
        partition) byte sizes — the host-side role of
        MapStatus.partition_sizes on the manager path. Returns
        (map_outputs[map][pid] -> DataFrame, MapOutputStatistics-shaped
        stats from sql/adaptive/stats.py)."""
        from spark_rapids_tpu.sql.adaptive import stats as aqestats
        assert self.partitioning[0] == "hash", self.partitioning
        key_idx = list(self.partitioning[1])
        n = self.partitioning[2]
        schema = self.children[0].output_schema()
        from spark_rapids_tpu.obs.progress import PROGRESS
        map_outputs = []
        for part in self.children[0].executed_partitions(ctx):
            df = concat_host_frames(list(part()), schema)
            map_outputs.append(aqestats.split_frame(df, key_idx, n))
            if PROGRESS.enabled:  # live per-map-partition stage progress
                PROGRESS.shuffle_map_partition()
        return map_outputs, aqestats.stats_from_map_outputs(map_outputs)

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        child_parts = self.children[0].executed_partitions(ctx)
        schema = self.children[0].output_schema()
        kind = self.partitioning[0]
        if kind == "single":
            def single():
                dfs = [df for p in child_parts for df in p()]
                yield concat_host_frames(dfs, schema)
            return [single]
        if kind in ("hash", "roundrobin"):
            n = self.partitioning[-1]
            buckets: List[List[pd.DataFrame]] = [[] for _ in range(n)]
            for p in child_parts:
                for df in p():
                    if kind == "hash":
                        idx = self.partitioning[1]
                        if idx:
                            h = pd.util.hash_pandas_object(
                                df.iloc[:, list(idx)], index=False).to_numpy()
                        else:
                            h = np.zeros(len(df), dtype=np.uint64)
                        pids = (h % n).astype(np.int64)
                    else:
                        pids = np.arange(len(df), dtype=np.int64) % n
                    for pid in range(n):
                        sel = df[pids == pid]
                        if len(sel):
                            buckets[pid].append(sel.reset_index(drop=True))
            if kind == "hash" and n > 1 and ctx.metrics_enabled:
                # shuffle-skew observability, independent of AQE: per-
                # shuffle max/median partition-size ratio (obs/shuffleobs)
                from spark_rapids_tpu.obs.shuffleobs import (
                    record_shuffle_skew,
                )
                from spark_rapids_tpu.sql.adaptive.stats import (
                    estimate_frame_bytes,
                )
                record_shuffle_skew(
                    [sum(estimate_frame_bytes(f) for f in b)
                     for b in buckets], source="cpu:hash")

            def make(pid: int) -> Partition:
                def run():
                    yield concat_host_frames(buckets[pid], schema)
                return run
            return [make(i) for i in range(n)]
        if kind == "range":
            # ('range', [key indices], [ascending], [nulls_first], n):
            # the host oracle sorts everything once with the same comparator
            # CpuSortExec uses and hands out contiguous chunks — a valid
            # range partitioning by construction (the device path samples
            # bounds instead, GpuRangePartitioner.scala:42-120)
            from spark_rapids_tpu.sql.exprs.core import BoundRef
            key_idx, asc, nf, n = self.partitioning[1:]
            orders = [SortOrder(BoundRef(i, schema.dtypes[i],
                                         schema.names[i]), a, f)
                      for i, a, f in zip(key_idx, asc, nf)]

            state: dict = {}

            def chunks():
                if "parts" in state:
                    return state["parts"]
                dfs = [df for p in child_parts for df in p()]
                df = concat_host_frames(dfs, schema)
                idx = host_sort_indices(df, orders)
                df = df.iloc[idx].reset_index(drop=True)
                per = -(-len(df) // n) if len(df) else 0
                state["parts"] = [
                    df.iloc[i * per:(i + 1) * per].reset_index(drop=True)
                    if per else _empty_df(schema) for i in range(n)]
                return state["parts"]

            def make(pid: int) -> Partition:
                def run():
                    yield chunks()[pid]
                return run
            return [make(i) for i in range(n)]
        raise ValueError(f"unknown partitioning {kind}")


def _assemble_join(ldf: pd.DataFrame, rdf: pd.DataFrame, ls: Schema,
                   rs: Schema, lrow: np.ndarray,
                   rrow: np.ndarray) -> pd.DataFrame:
    """Build join output columns by gathering original-side values at the
    pair indices; -1 marks a missing side (outer join null)."""
    series = []
    for df, schema, rows in ((ldf, ls, lrow), (rdf, rs, rrow)):
        present = rows >= 0
        safe = np.clip(rows, 0, max(len(df) - 1, 0))
        for i, dt in enumerate(schema.dtypes):
            vals, validity, _ = host_unary_values(df.iloc[:, i])
            if len(df):
                out_v = vals[safe]
                out_m = validity[safe] & present
            else:
                out_v = np.empty(len(rows),
                                 dtype=object if dt.is_string else dt.np_dtype)
                out_m = np.zeros(len(rows), np.bool_)
            if dt.is_string and (~out_m).any():
                out_v = out_v.copy()
                out_v[~out_m] = None
            series.append(_numpy_to_pandas(out_v, out_m, dt)
                          .reset_index(drop=True))
    out = pd.concat(series, axis=1) if series else pd.DataFrame(
        index=range(len(lrow)))
    out.columns = list(ls.names) + list(rs.names)
    return out


class CpuBroadcastExchangeExec(PhysicalPlan):
    """Collects the child once and shares it with every consumer partition
    (reference: GpuBroadcastExchangeExec.scala:47-178 collects child batches
    and Spark-broadcasts them)."""

    def __init__(self, child: PhysicalPlan):
        super().__init__([child])
        self._cache = {}

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        child = self.children[0]

        def run():
            if "df" not in self._cache:
                parts = child.executed_partitions(ctx)
                self._cache["df"] = _concat_parts(
                    (df for p in parts for df in p()), child.output_schema())
            yield self._cache["df"]
        return [run]


def sort_key_arrays(df: pd.DataFrame, orders: Sequence[SortOrder]):
    """Numpy lexsort keys implementing Spark ordering: per-key null
    flag + order-preserving image (floats: NaN largest, -0.0 == 0.0;
    strings: exact lexicographic via factorize-of-sorted-uniques)."""
    keys = []  # most significant first
    for so in orders:
        vals, validity, _ = host_unary_values(so.expr.eval_host(df))
        if vals.dtype == object:
            # NUL-exact: numpy '<U' comparison pads with NULs and merges
            # 'a' with 'a\x00'; dictionary-encode via arrow, rank the
            # (small) dictionary with python compares
            import pyarrow as pa
            filled = np.where(validity, vals, "")
            d = (pa.array(filled, type=pa.string(), from_pandas=True)
                 .dictionary_encode())
            codes = d.indices.to_numpy(zero_copy_only=False).astype(np.int64)
            uniq = np.asarray(d.dictionary.to_pylist(), dtype=object)
            order = np.argsort(uniq)
            rank = np.empty(len(uniq), dtype=np.int64)
            rank[order] = np.arange(len(uniq), dtype=np.int64)
            img = rank[codes]
        elif vals.dtype.kind == "f":
            # exact host image (the CPU oracle models Spark, which orders
            # denormals properly; only the DEVICE image flushes them, an
            # unavoidable TPU FTZ property — ops/floatbits.py)
            f = vals.astype(np.float64)
            f = np.where(f == 0.0, 0.0, f)
            f = np.where(np.isnan(f), np.nan, f)
            bits = f.view(np.uint64)
            sign = bits >> np.uint64(63)
            img = np.where(sign == 1, ~bits,
                           bits | (np.uint64(1) << np.uint64(63))).astype(np.uint64)
        elif vals.dtype == np.bool_:
            img = vals.astype(np.int64)
        else:
            img = vals.astype(np.int64)
        if not so.ascending:
            img = img.max(initial=0) - img if img.dtype != np.uint64 else ~img
            if img.dtype == np.int64:
                pass
        null_flag = np.where(validity, 1, 0) if so.nulls_first else \
            np.where(validity, 0, 1)
        keys.append((null_flag, img))
    return keys


def host_sort_indices(df: pd.DataFrame, orders: Sequence[SortOrder]) -> np.ndarray:
    keys = sort_key_arrays(df, orders)
    # np.lexsort: last key is primary -> reverse
    lex = []
    for null_flag, img in reversed(keys):
        lex.append(img)
        lex.append(null_flag)
    if not lex:
        return np.arange(len(df))
    return np.lexsort(lex)


class CpuSortExec(PhysicalPlan):
    def __init__(self, child: PhysicalPlan, orders: Sequence[SortOrder]):
        super().__init__([child])
        self.orders = list(orders)

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def describe(self) -> str:
        return f"CpuSortExec({self.orders})"

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        child_parts = self.children[0].executed_partitions(ctx)

        def make(part: Partition) -> Partition:
            def run():
                df = _concat_parts(part(), self.children[0].output_schema())
                idx = host_sort_indices(df, self.orders)
                yield df.iloc[idx].reset_index(drop=True)
            return run
        return [make(p) for p in child_parts]


class CpuLocalLimitExec(PhysicalPlan):
    def __init__(self, child: PhysicalPlan, limit: int):
        super().__init__([child])
        self.limit = limit

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        child_parts = self.children[0].executed_partitions(ctx)

        def make(part: Partition) -> Partition:
            def run():
                remaining = self.limit
                for df in part():
                    if remaining <= 0:
                        break
                    take = df.head(remaining)
                    remaining -= len(take)
                    yield take
            return run
        return [make(p) for p in child_parts]


class CpuGlobalLimitExec(CpuLocalLimitExec):
    pass


class CpuUnionExec(PhysicalPlan):
    def __init__(self, children: Sequence[PhysicalPlan]):
        super().__init__(children)

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        out: List[Partition] = []
        for c in self.children:
            out.extend(c.executed_partitions(ctx))
        return out


class CpuRangeExec(PhysicalPlan):
    """Spark's Range source (reference analogue: GpuRangeExec,
    basicPhysicalOperators.scala:181)."""

    def __init__(self, start: int, end: int, step: int, num_partitions: int,
                 name: str = "id"):
        super().__init__()
        self.start, self.end, self.step = start, end, step
        self.num_partitions = num_partitions
        self.col_name = name

    def output_schema(self) -> Schema:
        return Schema([self.col_name], [dtypes.INT64])

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        total = max(0, -(-(self.end - self.start) // self.step))
        per = -(-total // self.num_partitions) if total else 0

        def make(i: int) -> Partition:
            def run():
                lo = i * per
                hi = min(total, (i + 1) * per)
                vals = self.start + np.arange(lo, hi, dtype=np.int64) * self.step
                yield pd.DataFrame({self.col_name: vals})
            return run
        return [make(i) for i in range(self.num_partitions)]


class CpuExpandExec(PhysicalPlan):
    """One output row per (input row x projection set)."""

    def __init__(self, child: PhysicalPlan, projections):
        super().__init__([child])
        self.projections = [list(p) for p in projections]

    def output_schema(self) -> Schema:
        cs = self.children[0].output_schema()
        first = self.projections[0]
        return Schema([n for n, _ in first],
                      [e.dtype(cs) for _, e in first])

    def describe(self) -> str:
        return f"CpuExpandExec({len(self.projections)} sets)"

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        child_parts = self.children[0].executed_partitions(ctx)
        names = [n for n, _ in self.projections[0]]

        def make(part: Partition) -> Partition:
            def run():
                for df in part():
                    for proj in self.projections:
                        out = {}
                        for j, (name, e) in enumerate(proj):
                            out[j] = e.eval_host(df).reset_index(drop=True)
                        frame = pd.concat(out.values(), axis=1) if out else \
                            pd.DataFrame(index=range(len(df)))
                        frame.columns = names
                        yield frame
            return run
        return [make(p) for p in child_parts]


class CpuJoinExec(PhysicalPlan):
    """Equi-join via pandas merge with SQL null-key semantics (null keys
    never match). join_type: inner, left, right, full, leftsemi, leftanti,
    cross."""

    def __init__(self, left: PhysicalPlan, right: PhysicalPlan,
                 join_type: str, left_keys: List[int], right_keys: List[int]):
        super().__init__([left, right])
        self.join_type = join_type
        self.left_keys = left_keys
        self.right_keys = right_keys

    def output_schema(self) -> Schema:
        ls = self.children[0].output_schema()
        rs = self.children[1].output_schema()
        if self.join_type in ("leftsemi", "leftanti"):
            return ls
        return Schema(list(ls.names) + list(rs.names),
                      list(ls.dtypes) + list(rs.dtypes))

    def describe(self) -> str:
        return f"CpuJoinExec({self.join_type})"

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        left_parts = self.children[0].executed_partitions(ctx)
        right_parts = self.children[1].executed_partitions(ctx)
        # broadcast pairing: a single-partition broadcast side joins against
        # every partition of the other side
        if len(left_parts) != len(right_parts):
            if len(right_parts) == 1:
                right_parts = right_parts * len(left_parts)
            elif len(left_parts) == 1:
                left_parts = left_parts * len(right_parts)
            else:
                raise AssertionError("join children must be co-partitioned "
                                     "or one side broadcast")

        def make(lp: Partition, rp: Partition) -> Partition:
            def run():
                ldf = _concat_parts(lp(), self.children[0].output_schema())
                rdf = _concat_parts(rp(), self.children[1].output_schema())
                yield self._join(ldf, rdf)
            return run
        return [make(lp, rp) for lp, rp in zip(left_parts, right_parts)]

    def _join(self, ldf: pd.DataFrame, rdf: pd.DataFrame) -> pd.DataFrame:
        """Gather-based assembly: pandas merge only produces the
        (left_row, right_row) pair list; output columns are rebuilt from
        the ORIGINAL frames so missing-side values are true NULLs, never
        the NaN a pandas-merge upcast would fabricate (NaN is a SQL value
        in this engine's null discipline, batch.py)."""
        ls = self.children[0].output_schema()
        rs = self.children[1].output_schema()
        nl, nr = len(ldf), len(rdf)
        lkey_frame = pd.DataFrame(
            {f"k{j}": ldf.iloc[:, i].reset_index(drop=True)
             for j, i in enumerate(self.left_keys)})
        rkey_frame = pd.DataFrame(
            {f"k{j}": rdf.iloc[:, i].reset_index(drop=True)
             for j, i in enumerate(self.right_keys)})
        lvalid = np.ones(nl, np.bool_)
        for c in range(lkey_frame.shape[1]):
            lvalid &= host_unary_values(lkey_frame.iloc[:, c])[1]
        rvalid = np.ones(nr, np.bool_)
        for c in range(rkey_frame.shape[1]):
            rvalid &= host_unary_values(rkey_frame.iloc[:, c])[1]
        lkey_frame["_lrow"] = np.arange(nl, dtype=np.int64)
        rkey_frame["_rrow"] = np.arange(nr, dtype=np.int64)
        keys = [f"k{j}" for j in range(len(self.left_keys))]

        jt = self.join_type
        if jt == "cross":
            lrow = np.repeat(np.arange(nl, dtype=np.int64), nr)
            rrow = np.tile(np.arange(nr, dtype=np.int64), nl)
            return _assemble_join(ldf, rdf, ls, rs, lrow, rrow)

        lm = lkey_frame[lvalid]
        rm = rkey_frame[rvalid]
        if jt in ("leftsemi", "leftanti"):
            rk = rm[keys].drop_duplicates()
            hit = lm.merge(rk, on=keys, how="inner")["_lrow"].to_numpy()
            if jt == "leftsemi":
                keep = np.zeros(nl, np.bool_)
                keep[hit] = True
            else:
                keep = np.ones(nl, np.bool_)
                keep[hit] = False
            return ldf[keep].reset_index(drop=True)

        how = {"inner": "inner", "left": "left", "right": "right",
               "full": "outer"}[jt]
        merged = lm.merge(rm, on=keys, how=how)
        lrow = merged["_lrow"].to_numpy(dtype=np.float64, na_value=-1) \
            .astype(np.int64)
        rrow = merged["_rrow"].to_numpy(dtype=np.float64, na_value=-1) \
            .astype(np.int64)
        # null-keyed rows re-appended for preserved sides (null never
        # matches but outer joins keep the row)
        if jt in ("left", "full") and (~lvalid).any():
            extra = np.flatnonzero(~lvalid).astype(np.int64)
            lrow = np.concatenate([lrow, extra])
            rrow = np.concatenate([rrow, np.full(len(extra), -1, np.int64)])
        if jt in ("right", "full") and (~rvalid).any():
            extra = np.flatnonzero(~rvalid).astype(np.int64)
            lrow = np.concatenate([lrow, np.full(len(extra), -1, np.int64)])
            rrow = np.concatenate([rrow, extra])
        return _assemble_join(ldf, rdf, ls, rs, lrow, rrow)


class CpuBroadcastHashJoinExec(CpuJoinExec):
    """Equi-join whose build side is a broadcast exchange (reference:
    GpuBroadcastHashJoinExec, shims/spark300). Execution is identical to
    CpuJoinExec — the distinct class lets the rewrite engine carry a
    distinct rule/conf key, like the reference's separate exec."""


class CpuCartesianProductExec(CpuJoinExec):
    """Unconditioned cross product (reference: GpuCartesianProductExec,
    disabled by default there too)."""

    def __init__(self, left: PhysicalPlan, right: PhysicalPlan):
        super().__init__(left, right, "cross", [], [])

    def describe(self) -> str:
        return "CpuCartesianProductExec"


class CpuBroadcastNestedLoopJoinExec(PhysicalPlan):
    """Join on an arbitrary boolean condition: every stream row pairs with
    every broadcast-side row, then the condition filters (reference:
    GpuBroadcastNestedLoopJoinExec.scala:258, inner/cross only, disabled by
    default). ``condition`` is bound against the combined left+right
    schema."""

    def __init__(self, left: PhysicalPlan, right: PhysicalPlan,
                 join_type: str, condition: Optional[Expression]):
        super().__init__([left, right])
        assert join_type in ("inner", "cross"), join_type
        self.join_type = join_type
        self.condition = condition

    def output_schema(self) -> Schema:
        ls = self.children[0].output_schema()
        rs = self.children[1].output_schema()
        return Schema(list(ls.names) + list(rs.names),
                      list(ls.dtypes) + list(rs.dtypes))

    def describe(self) -> str:
        return f"CpuBroadcastNestedLoopJoinExec({self.join_type})"

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        left_parts = self.children[0].executed_partitions(ctx)
        right_parts = self.children[1].executed_partitions(ctx)
        assert len(right_parts) == 1, \
            "nested-loop build side must be a broadcast (single partition)"
        right_parts = right_parts * len(left_parts)
        ls = self.children[0].output_schema()
        rs = self.children[1].output_schema()

        def make(lp: Partition, rp: Partition) -> Partition:
            def run():
                ldf = _concat_parts(lp(), ls)
                rdf = _concat_parts(rp(), rs)
                nl, nr = len(ldf), len(rdf)
                lrow = np.repeat(np.arange(nl, dtype=np.int64), nr)
                rrow = np.tile(np.arange(nr, dtype=np.int64), nl)
                out = _assemble_join(ldf, rdf, ls, rs, lrow, rrow)
                if self.condition is not None and len(out):
                    pred = self.condition.eval_host(out)
                    vals, validity, _ = host_unary_values(pred)
                    out = out[vals.astype(np.bool_)
                              & validity].reset_index(drop=True)
                yield out
            return run
        return [make(lp, rp) for lp, rp in zip(left_parts, right_parts)]


class CpuCoalescePartitionsExec(PhysicalPlan):
    """Narrow partition merge, no shuffle (Spark CoalesceExec; reference
    rule GpuOverrides.scala:1611-1615)."""

    def __init__(self, child: PhysicalPlan, n: int):
        super().__init__([child])
        self.n = max(1, int(n))

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def describe(self) -> str:
        return f"CpuCoalescePartitionsExec({self.n})"

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        from spark_rapids_tpu.exec.base import group_contiguous
        child_parts = self.children[0].executed_partitions(ctx)
        groups = group_contiguous(child_parts, self.n)

        def make(group: List[Partition]) -> Partition:
            def run():
                got = False
                for p in group:
                    for df in p():
                        got = True
                        yield df
                if not got:
                    yield _empty_df(self.output_schema())
            return run
        return [make(g) for g in groups]


class CpuCollectLimitExec(PhysicalPlan):
    """Root-position limit: take the first ``limit`` rows across child
    partitions in order (reference: GpuCollectLimitExec)."""

    def __init__(self, child: PhysicalPlan, limit: int):
        super().__init__([child])
        self.limit = int(limit)

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def describe(self) -> str:
        return f"CpuCollectLimitExec({self.limit})"

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        child_parts = self.children[0].executed_partitions(ctx)

        def run():
            remaining = self.limit
            for p in child_parts:
                if remaining <= 0:
                    return
                for df in p():
                    if remaining <= 0:
                        return
                    take = df.head(remaining)
                    remaining -= len(take)
                    yield take
        return [run]
