"""Generate (explode) physical operators (reference: GpuGenerateExec.scala,
194 LoC — explode-style generators; posexplode unsupported cases tagged
there, supported here via the fused device kernel).

The supported generator is ``explode(split(strcol, delim))`` — with a
single-byte literal delimiter it runs fused on device; anything else
(multi-byte delimiters, regex split) stays on the CPU with a readable tag
reason, the reference's fallback taxonomy.
"""

from __future__ import annotations

from typing import Iterator, List

import jax
import numpy as np
import pandas as pd

from spark_rapids_tpu.columnar import dtypes
from spark_rapids_tpu.columnar.batch import (
    DeviceBatch, Schema, bucket_capacity,
)
from spark_rapids_tpu.columnar.column import _char_bucket
from spark_rapids_tpu.exec.base import ExecContext, Partition, PhysicalPlan
from spark_rapids_tpu.ops import generate as gen_ops
from spark_rapids_tpu.utils.kernelcache import cached_jit


def generate_output_schema(child: Schema, with_pos: bool, pos_name: str,
                           out_name: str) -> Schema:
    """Generate output = child columns [+ pos INT32] + token STRING — the
    single definition shared by the logical node and both execs."""
    names = list(child.names)
    dts = list(child.dtypes)
    if with_pos:
        names.append(pos_name)
        dts.append(dtypes.INT32)
    names.append(out_name)
    dts.append(dtypes.STRING)
    return Schema(names, dts)


class CpuGenerateExec(PhysicalPlan):
    """Host explode: pandas str.split + explode. Null strings yield no rows;
    empty strings yield one empty token (Spark split semantics)."""

    def __init__(self, child: PhysicalPlan, col_idx: int, delim: str,
                 out_name: str, with_pos: bool, pos_name: str = "pos"):
        super().__init__([child])
        self.col_idx = col_idx
        self.delim = delim
        self.out_name = out_name
        self.with_pos = with_pos
        self.pos_name = pos_name

    def output_schema(self) -> Schema:
        return generate_output_schema(self.children[0].output_schema(),
                                      self.with_pos, self.pos_name,
                                      self.out_name)

    def describe(self) -> str:
        pos = "pos" if self.with_pos else ""
        return f"CpuGenerateExec({pos}explode(split(c{self.col_idx}, " \
               f"{self.delim!r})) AS {self.out_name})"

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        child_parts = self.children[0].executed_partitions(ctx)
        cs = self.children[0].output_schema()

        def make(part: Partition) -> Partition:
            def run():
                for df in part():
                    src = df.iloc[:, self.col_idx]
                    rows: List[int] = []
                    toks: List[str] = []
                    poss: List[int] = []
                    splitter = _make_splitter(self.delim)
                    for r, v in enumerate(src):
                        if pd.isna(v):
                            continue
                        for p, tok in enumerate(splitter(str(v))):
                            rows.append(r)
                            toks.append(tok)
                            poss.append(p)
                    out = df.iloc[rows].reset_index(drop=True)
                    if self.with_pos:
                        out[self.pos_name] = pd.Series(
                            np.asarray(poss, dtype=np.int32))
                    out[self.out_name] = pd.Series(toks, dtype="str")
                    yield out
            return run
        return [make(p) for p in child_parts]


class TpuGenerateExec(PhysicalPlan):
    columnar_output = True

    def __init__(self, child: PhysicalPlan, col_idx: int, delim: str,
                 out_name: str, with_pos: bool, pos_name: str = "pos"):
        super().__init__([child])
        self.col_idx = col_idx
        self.delim = delim
        self.out_name = out_name
        self.with_pos = with_pos
        self.pos_name = pos_name
        byte = delim.encode("utf-8")
        assert len(byte) == 1, "device split needs a single-byte delimiter"
        self._delim_byte = byte[0]
        sig = (f"generate|{col_idx}|{self._delim_byte}|{with_pos}"
               f"|{out_name}|{pos_name}")
        self._totals = cached_jit(sig + "|totals", lambda: jax.jit(
            lambda b: gen_ops.explode_totals(b, col_idx, self._delim_byte)))
        self._expand = cached_jit(sig + "|expand", lambda: jax.jit(
            lambda b, out_cap, ccaps, tcap: gen_ops.explode_split(
                b, col_idx, self._delim_byte, out_name, out_cap, ccaps,
                tcap, with_pos, pos_name),
            static_argnums=(1, 2, 3)))

    def output_schema(self) -> Schema:
        return generate_output_schema(self.children[0].output_schema(),
                                      self.with_pos, self.pos_name,
                                      self.out_name)

    def describe(self) -> str:
        pos = "pos" if self.with_pos else ""
        return f"TpuGenerateExec({pos}explode(split(c{self.col_idx}, " \
               f"{self.delim!r})) AS {self.out_name})"

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        child_parts = self.children[0].executed_partitions(ctx)
        growth = ctx.conf.capacity_growth
        schema = self.output_schema()

        def make(part: Partition) -> Partition:
            def run() -> Iterator[DeviceBatch]:
                emitted = False
                for batch in part():
                    import jax
                    sizes = [int(x) for x in
                             jax.device_get(self._totals(batch))]
                    total = sizes[0]
                    if total == 0:
                        continue
                    ccaps = tuple(_char_bucket(c) for c in sizes[1:-1])
                    tcap = _char_bucket(sizes[-1])
                    from spark_rapids_tpu.utils.kernelcache import (
                        bucket_dim,
                    )
                    out_cap = bucket_dim(bucket_capacity(total, growth))
                    emitted = True
                    yield self._expand(batch, out_cap, ccaps, tcap)
                if not emitted:
                    yield DeviceBatch.empty(schema)
            return run
        return [make(p) for p in child_parts]


_REGEX_META = set("\\^$.|?*+()[]{}")


def _make_splitter(delim: str):
    """Spark's split() is regex-based: metacharacter patterns go through
    re.split on the host (and are tagged off the device)."""
    if any(ch in _REGEX_META for ch in delim):
        import re
        rx = re.compile(delim)
        return lambda s: rx.split(s)
    return lambda s: s.split(delim)
