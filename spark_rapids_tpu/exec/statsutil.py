"""Advisory scan-statistics resolution shared by the dense-key fast
paths (join direct-index probe, bounded-int composite grouping keys).

The session unions each scanned int column's (min, max) into a
name-keyed registry (exec/transitions.note_scan_stats) and records
rename provenance from the logical plan (session.column_aliases). The
bounds are ADVISORY — every consumer verifies them on device and falls
back to its exact path — so resolution here only needs to be sound
enough to usually hit (the reference's analogue is the cuDF column
min/max the join build reads)."""

from __future__ import annotations

from typing import Optional, Tuple


def int_bounds_for_names(session, names) -> Optional[Tuple[int, int]]:
    """Union advisory (lo, hi) over every stats entry reachable from any
    of ``names`` through the rename-alias map (walk bounded — alias
    chains are shallow). None when nothing resolves."""
    if session is None:
        return None
    reg = session.column_stats
    amap = session.column_aliases
    names = set(names)
    frontier = set(names)
    for _ in range(8):
        nxt = set()
        for n in frontier:
            nxt |= amap.get(n, set()) - names
        if not nxt:
            break
        names |= nxt
        frontier = nxt
    bounds = [reg[n] for n in names if n in reg]
    if not bounds:
        return None
    return (min(b[0] for b in bounds), max(b[1] for b in bounds))


def _pow2_at_least(n: int) -> int:
    size = 1
    while size < n:
        size <<= 1
    return size


def dense_group_plan(session, key_names, key_dtypes,
                     max_bits: int = 62) -> Optional[Tuple[list, tuple]]:
    """(los, sizes) for a bounded-int composite grouping key
    (ops/aggregate.dense_composite), or None. ``key_names``: per key a
    SET of candidate registry names (output name + source name); dtypes
    must all be fixed-width integers. Sizes bucket to powers of two so
    the kernel-cache key is stable under small data drift."""
    import numpy as np
    los, sizes = [], []
    total = 1
    for names, dt in zip(key_names, key_dtypes):
        npdt = np.dtype(dt.np_dtype)
        if dt.is_string or npdt.kind not in ("i", "u"):
            return None
        b = int_bounds_for_names(session, names)
        if b is None:
            return None
        lo, hi = int(b[0]), int(b[1])
        rng = hi - lo + 1
        if rng <= 0:
            return None
        size = _pow2_at_least(rng)
        total *= size + 1
        if total > (1 << max_bits):
            return None
        los.append(lo)
        sizes.append(size)
    # low-cardinality tuples take the dictionary matmul path anyway
    # (ops/aggregate._dict_path_info, DICT_SLOT_MAX): a dense variant
    # would compile a duplicate program and speculate for nothing
    if total <= 4096:
        return None
    return los, tuple(sizes)
