"""Host<->device transition operators.

The analogues of the reference's GpuRowToColumnarExec / GpuColumnarToRowExec
/ HostColumnarToGpu / GpuBringBackToHost (GpuRowToColumnarExec.scala,
GpuColumnarToRowExec.scala, GpuBringBackToHost.scala). The transition
overrides pass (sql/overrides.py) inserts these at every CPU/TPU boundary.
"""

from __future__ import annotations

from typing import Iterator, List

import pandas as pd

from spark_rapids_tpu.columnar.batch import DeviceBatch, Schema
from spark_rapids_tpu.exec.base import ExecContext, Partition, PhysicalPlan


def scan_cache_for(ctx: ExecContext, source, schema: Schema,
                   max_rows: int, pushed_filters=None):
    """Per-source device-batch cache (spark.rapids.sql.cacheDeviceScans),
    or None when disabled. The entry holds a strong reference to the
    source object: keys include id(source), and without the reference a
    GC'd source's id could be reused by a different dataset and serve its
    cached batches. Pushed filters are part of the key: a scan pruned for
    one predicate must not serve a query that needs more row groups.
    Entries live until session.clear_device_cache()."""
    if ctx.session is None or not ctx.conf.get_bool(
            "spark.rapids.sql.cacheDeviceScans", False):
        return None
    store = ctx.session.device_scan_cache
    fkey = tuple(pushed_filters) if pushed_filters else None
    # pruned-column views are fresh objects per query; key on the base
    # source identity so re-executions hit (schema names in the key keep
    # distinct projections apart)
    base = getattr(source, "_base", source)
    key = (id(base), tuple(schema.names), max_rows, fkey)
    if key not in store:
        store[key] = (source, {})
    return store[key][1]


def note_scan_stats(session, df: pd.DataFrame) -> None:
    """Union each scanned int column's (min, max) into the session's
    advisory stats registry (session.column_stats). Called ONLY from scan
    uploads (TpuScanExec / HostToDeviceExec-over-scan), so derived columns
    can never seed it; the dense-key join verifies the bounds on device
    before relying on them (exec/tpujoin.py)."""
    if session is None:
        return
    reg = session.column_stats
    for name in df.columns:
        s = df[name]
        if not (pd.api.types.is_integer_dtype(s.dtype)
                and not pd.api.types.is_bool_dtype(s.dtype)):
            continue
        # min/max skip NA natively; count() avoids the dropna() copy this
        # scan-upload hot path would otherwise pay per column
        if not int(s.count()):
            continue
        lo, hi = int(s.min()), int(s.max())
        prev = reg.get(str(name))
        if prev is not None:
            lo, hi = min(lo, prev[0]), max(hi, prev[1])
        reg[str(name)] = (lo, hi)


def upload_blocked_chars(ctx: ExecContext) -> int:
    """Max byte stride for the blocked char-slab upload layout
    (spark.rapids.sql.dict.blockedChars, docs/gatherfree.md), or 0 when
    disabled — string columns that fail dictionary encoding and fit the
    stride then upload as fixed-stride slabs and move through the whole
    operator stack without 1-D char gathers. Requires dict.enabled owner
    switch too: with the gather-free mode off entirely, uploads are
    byte-identical legacy."""
    if not ctx.conf.get_bool("spark.rapids.sql.dict.enabled", True):
        return 0
    if not ctx.conf.get_bool("spark.rapids.sql.dict.blockedChars", True):
        return 0
    return max(0, ctx.conf.get_int(
        "spark.rapids.sql.dict.blockedChars.maxStride", 64))


def scan_dict_numerics(ctx: ExecContext, source) -> bool:
    """Whether file-scan uploads dictionary-probe NUMERIC columns
    (spark.rapids.sql.scan.dictEncodeNumerics, default off with the
    pipelined reader): the probe + per-batch encode cost an element-wise
    pass per column on the scan upload hot path, integer grouping keys
    already ride the dense-key path, and float dictionary keys are rare.
    In-memory uploads keep full probing — their small-table dictionaries
    pre-seed the aggregation fast path (TpuScanExec) and upload once per
    session. The legacy serial reader (prefetchDepth=0) also keeps full
    probing: the rollback path reproduces pre-pipeline behavior exactly."""
    if source is None or not hasattr(source, "paths"):
        return True
    if int(ctx.conf.get("spark.rapids.sql.scan.prefetchDepth", 2) or 0) \
            <= 0:
        return True
    return ctx.conf.get_bool("spark.rapids.sql.scan.dictEncodeNumerics",
                             False)


def scan_raw_parts(ctx: ExecContext, source, pushed_filters):
    """deviceDecode routing (spark.rapids.sql.scan.deviceDecode):
    RawRowGroup partitions from the source's raw-page reader, or None
    when the conf is off / the source has no raw path — callers then take
    the classic cpu_partitions route, byte-identical to pre-deviceDecode
    behavior."""
    if not ctx.conf.get_bool("spark.rapids.sql.scan.deviceDecode", False):
        return None
    if not hasattr(source, "raw_partitions"):
        return None
    if pushed_filters and hasattr(source, "prune_splits"):
        return source.raw_partitions(ctx, pushed_filters)
    return source.raw_partitions(ctx)


def upload_partition(ctx: ExecContext, part: Partition, schema: Schema,
                     max_rows: int, dict_state: dict, cache, i: int,
                     mesh_devs=None, is_scan: bool = True,
                     dict_numerics: bool = True) -> Iterator[DeviceBatch]:
    """Shared host->device upload runner for TpuScanExec and
    HostToDeviceExec: pandas frames from ``part`` -> chunked, capacity-
    bucketed DeviceBatches, with device-scan-cache replay/fill and HBM
    metering.

    With the scan pipeline on (spark.rapids.sql.scan.prefetchDepth > 0)
    uploads are DOUBLE-BUFFERED: batch i+1's host buffer build +
    ``device_put`` are dispatched before batch i is yielded, so the
    transfer commits while the consumer computes on batch i. Each yielded
    batch re-publishes ITS origin file to the task context right before
    the yield — the read-ahead already moved the thread-local on.
    prefetchDepth=0 keeps the strict pull-driven serial order.
    """
    from spark_rapids_tpu.exec import taskctx
    from spark_rapids_tpu.obs.progress import PROGRESS
    from spark_rapids_tpu.obs.trace import TRACER
    sem = ctx.session.semaphore if ctx.session else None
    if getattr(ctx, "small_query", False) \
            and not getattr(ctx, "small_query_keep_sem", False):
        # tiny-query fast path: a single resident batch of a NON-
        # expanding plan cannot oversubscribe HBM — the admission lock is
        # pure fixed cost here (release on the drain side is a tolerated
        # no-op). Plans with joins/explode keep the semaphore: their
        # working set is not bounded by the leaf row counts.
        sem = None
    if sem is not None:
        sem.acquire_if_necessary()
    if cache is not None and i in cache:
        # replay with each batch's origin file restored so
        # input_file_name() stays correct on cache hits; the catalog
        # faults spilled batches back to the device
        catalog = ctx.session.buffer_catalog
        for fname, bid in cache[i]:
            taskctx.set_input_file(fname)
            yield catalog.acquire_batch(bid)
        taskctx.clear_input_file()
        return
    out = [] if cache is not None else None
    dm = ctx.session.device_manager if ctx.session else None
    double_buffer = int(ctx.conf.get(
        "spark.rapids.sql.scan.prefetchDepth", 2) or 0) > 0
    dict_on = ctx.conf.get_bool("spark.rapids.sql.dict.enabled", True)
    blocked = upload_blocked_chars(ctx)

    def uploads():
        for df in part():
            fname = taskctx.input_file()
            if getattr(df, "is_raw_rowgroup", False):
                # deviceDecode path: the split is a RawRowGroup of
                # encoded-page decode plans, not a pandas frame — decode
                # on device (ops/parquet_decode.py). Owns its own
                # sync_scope / transfer attribution / progress notes.
                from spark_rapids_tpu.ops.parquet_decode import (
                    decode_rowgroup,
                )
                if is_scan and df.fallback_df is not None:
                    note_scan_stats(ctx.session, df.fallback_df)
                dev_gen = decode_rowgroup(
                    ctx, df, schema, max_rows, dict_state, i,
                    device=(mesh_devs[i % len(mesh_devs)]
                            if mesh_devs else None))
                while True:
                    # span scoped to the decode step only, not the
                    # consumer compute between chunk yields
                    with TRACER.span("scan.deviceDecode", partition=i,
                                     rows=df.n):
                        batch = next(dev_gen, None)
                    if batch is None:
                        break
                    yield fname, batch
                continue
            if is_scan:
                note_scan_stats(ctx.session, df)
            for lo in range(0, max(len(df), 1), max_rows):
                if double_buffer and lo == 0 and len(df) <= max_rows:
                    # whole-frame chunk: decode already produced a fresh
                    # RangeIndex frame; the reset_index copy is pure cost
                    # on the upload hot path (legacy reader keeps it —
                    # rollback reproduces the old path exactly)
                    chunk = df
                else:
                    chunk = df.iloc[lo:lo + max_rows].reset_index(drop=True)
                    hints = getattr(df, "attrs", {}).get("srt_dict_fact")
                    if hints:
                        # re-chunked split: slice the worker's factorize
                        # hints positionally so they survive (from_pandas
                        # drops length-mismatched hints)
                        chunk.attrs["srt_dict_fact"] = {
                            nm: (codes[lo:lo + max_rows], u)
                            for nm, (codes, u) in hints.items()}
                with TRACER.span("scan.upload", partition=i,
                                 rows=len(chunk)):
                    import time as _time

                    from spark_rapids_tpu.obs import compileledger
                    from spark_rapids_tpu.obs.syncledger import sync_scope
                    _t0 = _time.perf_counter()
                    with sync_scope("scan.upload",
                                    detail=f"partition={i}") as _sc:
                        batch = DeviceBatch.from_pandas(
                            chunk, schema=schema, dict_state=dict_state,
                            dict_encode=dict_on,
                            dict_numerics=dict_numerics,
                            blocked_chars=blocked,
                            device=(mesh_devs[i % len(mesh_devs)]
                                    if mesh_devs else None))
                        _sc.add_bytes(batch.device_memory_size())
                    # host->device transfer attribution (host buffer
                    # build + device_put dispatch) against the upload
                    # operator — the "transfer" component of its profile
                    # breakdown row (obs/profile.py)
                    compileledger.note_transfer(
                        _time.perf_counter() - _t0, "h2d")
                if PROGRESS.enabled:  # live upload progress
                    PROGRESS.scan_upload(len(chunk))
                yield fname, batch

    def account(fname: str, batch: DeviceBatch) -> None:
        if out is not None:
            # cached batches live in the spillable catalog
            # (budget-metered, evictable)
            from spark_rapids_tpu.memory.spill import SpillPriorities
            bid = ctx.session.buffer_catalog.add_batch(
                batch, SpillPriorities.CACHED_SCAN)
            out.append((fname, bid))
        elif dm is not None:
            dm.meter_batch(batch)

    try:
        gen = uploads()
        if double_buffer:
            # dispatch the NEXT chunk's host build + device_put before
            # handing the current batch downstream: device_put is async,
            # so the transfer commits while the consumer computes, and
            # the decode prefetcher keeps feeding the next splits
            # meanwhile. (An off-thread upload step was measured SLOWER
            # here: host buffer building is GIL/core-bound and a fourth
            # thread just thrashes the decode pool on small boxes.)
            # The CURRENT batch is metered/cataloged BEFORE the next
            # build so the read-ahead never holds more than one
            # unmetered batch — metering can trigger synchronous spill,
            # and budget enforcement must see batch i before i+1's
            # device_put allocates.
            pending = next(gen, None)
            while pending is not None:
                fname, batch = pending
                account(fname, batch)
                nxt = next(gen, None)
                taskctx.set_input_file(fname)
                yield batch
                pending = nxt
        else:
            for fname, batch in gen:
                account(fname, batch)
                taskctx.set_input_file(fname)
                yield batch
        if out is not None:
            if i in cache:  # concurrent filler won the publish
                out, published = None, out
                for _f, bid in published:
                    ctx.session.buffer_catalog.remove(bid)
            else:
                cache[i] = out
    except BaseException:
        # abandoned/failed scan: unpublished bids would leak catalog
        # buffers forever (clear_device_cache only walks published
        # entries)
        if out is not None and cache.get(i) is not out:
            for _f, bid in out:
                ctx.session.buffer_catalog.remove(bid)
        raise
    finally:
        taskctx.clear_input_file()


class HostToDeviceExec(PhysicalPlan):
    """pandas partition chunks -> DeviceBatch, chunked to the conf'd batch
    size and padded to capacity buckets."""

    columnar_output = True

    def __init__(self, child: PhysicalPlan):
        super().__init__([child])

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        child = self.children[0]
        schema = child.output_schema()
        max_rows = ctx.conf.batch_size_rows

        # device-resident scan cache: re-executing a query over the same
        # source skips the re-upload — the HBM analogue of a cached
        # DataFrame, symmetric with the CPU path holding pandas in RAM
        cache = None
        from spark_rapids_tpu.exec.cpu import CpuScanExec
        is_scan = isinstance(child, CpuScanExec)
        child_parts = None
        if is_scan:
            # deviceDecode: build RawRowGroup partitions straight from
            # the source (the child scan node's own wrapper expects
            # pandas frames; decode attribution lands on this node)
            child_parts = scan_raw_parts(ctx, child.source,
                                         child.pushed_filters)
        if child_parts is None:
            child_parts = child.executed_partitions(ctx)
        if is_scan:
            cache = scan_cache_for(ctx, child.source, schema, max_rows,
                                   getattr(child, "pushed_filters", None))

        # shared dictionary registry across every batch of this transition
        # (see TpuScanExec: bounds program-shape churn to one dict/scan)
        dict_state: dict = {}

        dict_numerics = scan_dict_numerics(
            ctx, getattr(child, "source", None)) if is_scan else True

        def make(i: int, part: Partition) -> Partition:
            def run() -> Iterator[DeviceBatch]:
                return upload_partition(ctx, part, schema, max_rows,
                                        dict_state, cache, i,
                                        is_scan=is_scan,
                                        dict_numerics=dict_numerics)
            return run
        return [make(i, p) for i, p in enumerate(child_parts)]


class DeviceToHostExec(PhysicalPlan):
    columnar_output = False

    def __init__(self, child: PhysicalPlan):
        super().__init__([child])

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        child_parts = self.children[0].executed_partitions(ctx)

        def make(part: Partition) -> Partition:
            def run() -> Iterator[pd.DataFrame]:
                import time as _time

                from spark_rapids_tpu.obs import compileledger
                from spark_rapids_tpu.obs.syncledger import sync_scope
                sem = ctx.session.semaphore if ctx.session else None
                try:
                    for batch in part():
                        t0 = _time.perf_counter()
                        with sync_scope("transition.d2h"):
                            df = batch.to_pandas()
                        # device->host fetch seconds against this
                        # transition operator (profile breakdown)
                        compileledger.note_transfer(
                            _time.perf_counter() - t0, "d2h")
                        yield df
                finally:
                    if sem is not None:
                        sem.release()
            return run
        return [make(p) for p in child_parts]
