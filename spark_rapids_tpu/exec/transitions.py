"""Host<->device transition operators.

The analogues of the reference's GpuRowToColumnarExec / GpuColumnarToRowExec
/ HostColumnarToGpu / GpuBringBackToHost (GpuRowToColumnarExec.scala,
GpuColumnarToRowExec.scala, GpuBringBackToHost.scala). The transition
overrides pass (sql/overrides.py) inserts these at every CPU/TPU boundary.
"""

from __future__ import annotations

from typing import Iterator, List

import jax.numpy as jnp
import pandas as pd

from spark_rapids_tpu.columnar.batch import DeviceBatch, Schema, bucket_capacity
from spark_rapids_tpu.exec.base import ExecContext, Partition, PhysicalPlan


class HostToDeviceExec(PhysicalPlan):
    """pandas partition chunks -> DeviceBatch, chunked to the conf'd batch
    size and padded to capacity buckets."""

    columnar_output = True

    def __init__(self, child: PhysicalPlan):
        super().__init__([child])

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        child_parts = self.children[0].executed_partitions(ctx)
        schema = self.children[0].output_schema()
        max_rows = ctx.conf.batch_size_rows

        def make(part: Partition) -> Partition:
            def run() -> Iterator[DeviceBatch]:
                sem = ctx.session.semaphore if ctx.session else None
                for df in part():
                    if sem is not None:
                        sem.acquire_if_necessary()
                    for lo in range(0, max(len(df), 1), max_rows):
                        chunk = df.iloc[lo:lo + max_rows]
                        yield DeviceBatch.from_pandas(
                            chunk.reset_index(drop=True), schema=schema)
            return run
        return [make(p) for p in child_parts]


class DeviceToHostExec(PhysicalPlan):
    columnar_output = False

    def __init__(self, child: PhysicalPlan):
        super().__init__([child])

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        child_parts = self.children[0].executed_partitions(ctx)

        def make(part: Partition) -> Partition:
            def run() -> Iterator[pd.DataFrame]:
                sem = ctx.session.semaphore if ctx.session else None
                try:
                    for batch in part():
                        yield batch.to_pandas()
                finally:
                    if sem is not None:
                        sem.release()
            return run
        return [make(p) for p in child_parts]
