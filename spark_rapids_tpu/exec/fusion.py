"""Operator fusion passes over the converted TPU physical plan.

Filter->Aggregate fusion: a standalone TpuFilterExec compacts its batch
with one gather per column — ~5M rows/s per column on this TPU (indexed
ops lower to scalar-ish loops), which dominated q1/q6-shaped queries.
Aggregation never needs compacted rows: the predicate becomes the
aggregate's live-mask and every gather disappears (dense predicate
evaluation is ~free). Deterministic projections between the aggregate and
the filter are folded in by substituting their expressions into the
aggregate plan. The reference keeps these operators separate because cuDF
gathers are cheap (GpuFilterExec, basicPhysicalOperators.scala:126); on
TPU the fusion IS the fast path.
"""

from __future__ import annotations

from typing import List, Optional

from spark_rapids_tpu.exec.aggutil import AggPlan
from spark_rapids_tpu.exec.base import PhysicalPlan
from spark_rapids_tpu.sql.exprs.core import BoundRef, Col, Expression


class _Unfusable(Exception):
    pass


def _substitute(e: Expression, bindings: List[Expression],
                names: List[str], memo: dict) -> Expression:
    """Replace column references with the producing project's expressions
    (classic projection collapse). Unknown reference forms abort fusion.
    ``memo`` preserves node SHARING: AggPlan id-dedupes aggregate-function
    instances, so a fn object referenced from two result expressions must
    map to ONE substituted object or the partial schema would grow columns
    the final-mode plan does not expect."""
    hit = memo.get(id(e))
    if hit is not None:
        return hit
    if isinstance(e, BoundRef):
        if e.index >= len(bindings):
            raise _Unfusable()
        out = bindings[e.index]
    elif isinstance(e, Col):
        if e.name not in names:
            raise _Unfusable()
        out = bindings[names.index(e.name)]
    else:
        out = e.map_children(lambda c: _substitute(c, bindings, names, memo))
    memo[id(e)] = out
    return out


def fuse_selection_into_filter(plan: PhysicalPlan, conf) -> PhysicalPlan:
    """Rewrite TpuProjectExec(pure column refs)(TpuFilterExec(child)) into
    one TpuFilterExec with an output selection: the filter's row
    compaction then gathers ONLY the selected columns, so predicate-only
    columns (string char slabs especially) are never moved. The
    narrowing projects come from the logical column-pruning pass
    (sql/pushdown.py prune_filter_columns)."""
    from spark_rapids_tpu.exec import tpu as tpuexec
    from spark_rapids_tpu.sql.exprs.core import BoundRef

    def walk(node: PhysicalPlan) -> PhysicalPlan:
        node = node.map_children(walk)
        if not isinstance(node, tpuexec.TpuProjectExec):
            return node
        child = node.children[0]
        if not (isinstance(child, tpuexec.TpuFilterExec)
                and not child._impure and child.out_sel is None):
            return node
        if not all(isinstance(e, BoundRef) for _n, e in node.exprs):
            return node
        names = [n for n, _ in node.exprs]
        idx = [e.index for _n, e in node.exprs]
        return tpuexec.TpuFilterExec(child.children[0], child.condition,
                                     out_sel=(tuple(names), tuple(idx)))

    return walk(plan)


def fuse_filter_into_aggregate(plan: PhysicalPlan, conf) -> PhysicalPlan:
    """Rewrite partial TpuHashAggregateExec(TpuProjectExec* (TpuFilterExec
    (child))) into a fused aggregate with the projects substituted and the
    predicate as the update kernel's live-mask."""
    from spark_rapids_tpu.exec import tpu as tpuexec
    if not conf.get_bool("spark.rapids.sql.agg.fuseFilter", True):
        return plan

    def walk(node: PhysicalPlan) -> PhysicalPlan:
        node = node.map_children(walk)
        if not (isinstance(node, tpuexec.TpuHashAggregateExec)
                and node.mode == "partial" and node.pre_mask is None):
            return node
        projects = []
        c = node.children[0]
        while isinstance(c, tpuexec.TpuProjectExec) and not c._impure:
            projects.append(c)
            c = c.children[0]
        if not (isinstance(c, tpuexec.TpuFilterExec) and not c._impure):
            return node
        new_child = c.children[0]
        try:
            grouping = [(n, e) for n, e in node.plan.grouping]
            results = [(n, e) for n, e in node.plan.results]
            # fold each intervening projection into the aggregate's
            # expressions (innermost project last); a selection fused
            # into the filter (out_sel) acts as one more projection
            # mapping the narrowed ordinals back to the full child schema
            sub_projects = [(list(p.exprs)) for p in projects]
            if c.out_sel is not None:
                names_sel, idx_sel = c.out_sel
                full = new_child.output_schema()
                sub_projects.append(
                    [(n, BoundRef(i, full.dtypes[i], n))
                     for n, i in zip(names_sel, idx_sel)])
            for exprs in sub_projects:
                bindings = [e for _, e in exprs]
                names = [n for n, _ in exprs]
                memo: dict = {}
                grouping = [(n, _substitute(e, bindings, names, memo))
                            for n, e in grouping]
                results = [(n, _substitute(e, bindings, names, memo))
                           for n, e in results]
            new_plan = AggPlan(new_child.output_schema(), grouping, results)
        except _Unfusable:
            return node
        return tpuexec.TpuHashAggregateExec(new_child, new_plan,
                                            "partial", pre_mask=c.condition)

    return walk(plan)
