"""Within-query reuse of identical deterministic subtrees.

Reference analogue: Spark's ReuseExchange / ReuseSubquery rules, which the
reference plugin keeps working by canonicalizing its exchanges
(GpuBroadcastExchangeExec doCanonicalize); TPC-H/TPCxBB lean on it —
q2's min-cost subquery, q11's threshold, q15's revenue view and q17's
per-part average all reference one joined/aggregated intermediate from
two branches. This engine plans those branches as separate physical
subtrees; without reuse each branch re-executes the shared work.

The pass runs on the FINAL physical plan (after overrides+transitions):
identical subtrees are found by the structural plan fingerprint
(exec/base.plan_fingerprint — data-uid-stamped scans, expression-level
signatures), with coordinated column pruning upstream
(sql/pushdown.prune_filter_columns) making shared logical subtrees prune
identically so their physical forms actually match. Matching is gated to
an allowlist of node types whose fingerprints carry their full identity,
and to subtrees whose expressions are deterministic — a rand() branch
must keep re-executing (Spark reuses nondeterministic exchanges only
within one canonicalized stage; staying conservative here costs only the
reuse). A deduped subtree executes ONCE per query; every consumer
replays the materialized batches.
"""

from __future__ import annotations

from typing import Iterator, List

from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.exec.base import (
    ExecContext, Partition, PhysicalPlan, plan_fingerprint,
)

# node types whose describe()+fingerprint_extra() carry their complete
# behavioral identity (anything outside this set disqualifies the subtree)
_PRECISE = {
    "TpuScanExec", "TpuProjectExec", "TpuFilterExec",
    "TpuHashAggregateExec", "TpuShuffledHashJoinExec",
    "TpuBroadcastHashJoinExec", "TpuBroadcastExchangeExec",
    "TpuShuffleExchangeExec", "TpuSortExec", "TpuCoalesceBatchesExec",
    "TpuCoalescePartitionsExec",
    # whole-stage fusion: fingerprint_extra carries every member's full
    # identity (exec/stagecompiler/fusedexec.py), so a fused pipeline is
    # as precise as the chain it replaced
    "TpuFusedStageExec",
}

# a subtree is only worth materializing when it contains real compute
_WORTH = {"TpuShuffledHashJoinExec", "TpuBroadcastHashJoinExec",
          "TpuHashAggregateExec", "TpuSortExec"}


def _node_deterministic(node: PhysicalPlan) -> bool:
    from spark_rapids_tpu.sql.exprs.nondet import has_nondeterministic
    exprs = []
    if hasattr(node, "exprs"):          # project
        exprs.extend(e for _n, e in node.exprs)
    if getattr(node, "condition", None) is not None:   # filter
        exprs.append(node.condition)
    if getattr(node, "pre_mask", None) is not None:    # fused agg filter
        exprs.append(node.pre_mask)
    plan = getattr(node, "plan", None)
    if plan is not None and hasattr(plan, "grouping"):  # aggregate
        exprs.extend(e for _n, e in plan.grouping)
        exprs.extend(e for _n, e in plan.results)
    for o in getattr(node, "orders", ()):               # sort
        exprs.append(o.expr)
    return not any(has_nondeterministic(e) for e in exprs)


class TpuReuseSubtreeExec(PhysicalPlan):
    """Executes its child once per query and replays the materialized
    batches to every consumer. The same INSTANCE appears at every
    occurrence of the deduped subtree; per-query state lives on the
    ExecContext so a speculation re-execution (fresh context,
    session._execute) re-runs the child rather than replaying
    possibly-truncated batches."""

    columnar_output = True

    def __init__(self, child: PhysicalPlan):
        super().__init__([child])

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def describe(self) -> str:
        return "TpuReuseSubtreeExec"

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        state = ctx.reuse_state.setdefault(
            id(self), {"parts": None, "data": {}})
        if state["parts"] is None:
            state["parts"] = self.children[0].executed_partitions(ctx)
        parts = state["parts"]
        data = state["data"]
        session = ctx.session

        def mk(i: int) -> Partition:
            def run() -> Iterator:
                if i not in data:
                    if session is not None:
                        # register in the spillable catalog (same band as
                        # broadcast tables) so a big shared intermediate
                        # can evict under pressure instead of pinning HBM
                        from spark_rapids_tpu.memory.spill import (
                            SpillPriorities,
                        )
                        data[i] = [session.add_transient_batch(
                            b, SpillPriorities.OUTPUT_FOR_WRITE)
                            for b in parts[i]()]
                    else:
                        data[i] = list(parts[i]())
                if session is not None:
                    return iter([session.buffer_catalog.acquire_batch(bid)
                                 for bid in data[i]])
                return iter(data[i])
            return run
        return [mk(i) for i in range(len(parts))]


def subtree_deterministic(node: PhysicalPlan) -> bool:
    """Every expression in the subtree deterministic — the gate shared by
    subtree reuse and capacity speculation (a rand() below a join would
    change sizes every run, making speculation alternate learn/miss and
    double latency; reuse would be outright wrong)."""
    return all(_node_deterministic(n) for n in node.walk())


def _eligible(node: PhysicalPlan, memo: dict) -> bool:
    got = memo.get(id(node))
    if got is None:
        got = (type(node).__name__ in _PRECISE
               and _node_deterministic(node)
               and all(_eligible(c, memo) for c in node.children))
        memo[id(node)] = got
    return got


def _worth(node: PhysicalPlan) -> bool:
    return any(type(n).__name__ in _WORTH for n in node.walk())


def reuse_common_subtrees(plan: PhysicalPlan) -> PhysicalPlan:
    """Replace every group of fingerprint-identical eligible subtrees
    with one shared TpuReuseSubtreeExec instance (outermost match wins;
    nested duplicates collapse automatically because the shared subtree
    executes once)."""
    from collections import Counter

    elig: dict = {}
    fp_memo: dict = {}

    def fp(node: PhysicalPlan) -> str:
        got = fp_memo.get(id(node))
        if got is None:
            got = fp_memo[id(node)] = plan_fingerprint(node)
        return got

    counts: Counter = Counter()

    def collect(node: PhysicalPlan) -> None:
        for c in node.children:
            collect(c)
        if node.columnar_output and _eligible(node, elig):
            counts[fp(node)] += 1
    collect(plan)

    shared: dict = {}

    def rewrite(node: PhysicalPlan) -> PhysicalPlan:
        if (node.columnar_output and _eligible(node, elig)
                and counts[fp(node)] >= 2 and _worth(node)):
            w = shared.get(fp(node))
            if w is None:
                w = shared[fp(node)] = TpuReuseSubtreeExec(node)
            return w
        node.children = [rewrite(c) for c in node.children]
        return node

    return rewrite(plan)
