"""Out-of-core (larger-than-HBM) operator execution.

The second half of the pod-scale data plane (ROADMAP item 4, PAPER.md
L2): when an operator's measured device working set exceeds the
working-set budget (``spark.rapids.tpu.outOfCore.partitionBytes``), its
input is partitioned into fan-out buckets of spillable slices registered
on the 3-tier store (memory/spill.py) — the device store is
synchronously spilled down to the budget as buckets accumulate — and the
operator processes ONE bucket at a time, faulting its pieces back:

  * **grace hash join** — both sides hash-partitioned on the join keys
    (equal keys co-locate, so per-bucket joins union to the exact
    result); a bucket whose build fragment still exceeds the budget is
    recursed with a different hash, up to
    ``spark.rapids.tpu.outOfCore.maxRecursion`` levels (the reference's
    sub-partitioner, GpuShuffledHashJoinExec's spillable build batches);
  * **external merge sort** — sampled range bounds (the
    GpuRangePartitioner sample), range-partitioned spill buckets, one
    in-HBM sort per bucket, buckets emitted in range order = a globally
    sorted stream;
  * **spillable aggregation** — partial-layout batches hash-partitioned
    on the grouping keys; per-bucket merges (disjoint key sets) union to
    the exact aggregate.

Fan-out is chosen from the same MEASURED batch sizes AQE's statistics
collect (``DeviceBatch.device_memory_size`` — host metadata, no device
sync). Everything here is opt-in (``outOfCore.enabled``, default false)
and value-identical: partitioning only changes the order work is done
in, never what is computed.
"""

from __future__ import annotations

from typing import Iterator, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar.batch import (
    DeviceBatch, Schema, bucket_capacity,
)
from spark_rapids_tpu.columnar.column import _char_bucket
from spark_rapids_tpu.memory.spill import SpillPriorities
from spark_rapids_tpu.obs.syncledger import sync_scope
from spark_rapids_tpu.ops import rowops, sortops
from spark_rapids_tpu.ops.groupby import row_hashes
from spark_rapids_tpu.utils.kernelcache import bucket_dim, cached_jit

_MAX_FANOUT = 64


# ---------------------------------------------------------------------------
# policy: enablement, budgets, fan-out
# ---------------------------------------------------------------------------

def enabled_for(ctx) -> bool:
    """Out-of-core applies when opted in, a session (and therefore the
    spill catalog) exists, and no device mesh is configured — mesh
    execution distributes the working set instead (composing the two is
    future work; docs/distributed.md)."""
    if ctx.session is None:
        return False
    if getattr(ctx.session, "mesh", None) is not None:
        return False
    return ctx.conf.get_bool("spark.rapids.tpu.outOfCore.enabled", False)


def working_set_budget(ctx) -> int:
    b = int(ctx.conf.get("spark.rapids.tpu.outOfCore.partitionBytes", 0))
    if b > 0:
        return b
    from spark_rapids_tpu.memory.device import TpuDeviceManager
    dm = TpuDeviceManager.current()
    if dm is not None:
        return max(dm.hbm_budget // 2, 1 << 20)
    return 1 << 30


def total_batch_bytes(batches) -> int:
    """Measured device bytes of a batch list (capacity-based host
    metadata — the same sizes the exchange's MapStatus records)."""
    return sum(b.device_memory_size() for b in batches if b is not None)


def choose_fanout(ctx, total_bytes: int, budget: int) -> int:
    """Bucket count from MEASURED sizes: next power of two of
    total/budget, clamped to [2, 64]; ``outOfCore.fanout`` overrides."""
    f = int(ctx.conf.get("spark.rapids.tpu.outOfCore.fanout", 0))
    if f > 0:
        return max(2, min(f, _MAX_FANOUT))
    need = max(2, -(-int(total_bytes) // max(int(budget), 1)))
    n = 2
    while n < need and n < _MAX_FANOUT:
        n <<= 1
    return n


def _max_recursion(ctx) -> int:
    return int(ctx.conf.get("spark.rapids.tpu.outOfCore.maxRecursion", 3))


def split_stream_on_budget(ctx, it, budget: Optional[int] = None):
    """Consume ``it`` until the accumulated measured bytes EXCEED the
    budget. Returns ``(prefix, rest, engaged)``: on engagement ``rest``
    is the still-unconsumed iterator (the input was never fully
    materialized — the point of out-of-core is that it may not fit);
    otherwise the whole input is in ``prefix`` and the caller keeps the
    fast in-HBM path."""
    if budget is None:
        budget = working_set_budget(ctx)
    prefix: List[DeviceBatch] = []
    total = 0
    for b in it:
        prefix.append(b)
        total += b.device_memory_size()
        if total > budget:
            return prefix, it, True
    return prefix, None, False


def _stage_spillable(session, batches, budget: int, on_batch=None):
    """Register every incoming batch as a transient spillable (spilling
    the device store down to the budget as they arrive) WITHOUT holding
    them live — the staging pass that bounds peak residency to roughly
    budget + one batch while the driver still needs a second look (to
    size the fan-out, or to sample sort bounds). ``on_batch`` runs on
    each live batch before it is staged (the sort driver samples its
    range bounds here). Returns (bids, bytes)."""
    store = session.buffer_catalog.device_store
    bids: List[int] = []
    total = 0
    for b in batches:
        if b is None:
            continue
        if on_batch is not None:
            on_batch(b)
        total += b.device_memory_size()
        bids.append(session.add_transient_batch(
            b, SpillPriorities.OUTPUT_FOR_READ))
        del b
        if store.total_size > budget:
            store.synchronous_spill(budget)
    return bids, total


def _drain_staged(session, bids):
    """Yield staged batches one at a time, freeing each registration."""
    catalog = session.buffer_catalog
    for bid in bids:
        b = catalog.acquire_batch(bid)
        session.consume_transient(bid)
        yield b


def _record(ctx, op: str, fanout: int, total_bytes: int, budget: int,
            level: int = 0) -> None:
    from spark_rapids_tpu.obs.events import EVENTS
    from spark_rapids_tpu.obs.metrics import REGISTRY
    REGISTRY.counter("ooc.operators", op=op).add(1)
    REGISTRY.counter("ooc.fanout", op=op).add(fanout)
    EVENTS.emit("outOfCore", op=op, fanout=fanout, bytes=int(total_bytes),
                budgetBytes=int(budget), level=level)


# ---------------------------------------------------------------------------
# spillable fan-out partitions
# ---------------------------------------------------------------------------

class SpilledPartitions:
    """Fan-out buckets of spillable batch slices.

    ``add_batch`` splits one batch by a per-row partition id (device
    kernel), registers each non-empty slice as a transient spillable in
    the session catalog, and pushes the device store down to the budget
    — partition-and-spill. ``consume_bucket`` faults a bucket's pieces
    back (the acquireBuffer tier walk) and frees them."""

    def __init__(self, session, schema: Schema, n: int, growth: float,
                 budget: int):
        self.session = session
        self.schema = schema
        self.n = n
        self.growth = growth
        self.budget = budget
        self.buckets: List[List[int]] = [[] for _ in range(n)]
        self.bytes = [0] * n
        self.rows = [0] * n

    def add_batch(self, batch: DeviceBatch, split_kernel) -> None:
        """``split_kernel(batch) -> (pid-sorted batch, (n,) counts)``."""
        sorted_b, counts = split_kernel(batch)
        with sync_scope("outofcore.partitionCounts", detail="spill"):
            host_counts = np.asarray(jax.device_get(counts))
        offsets = np.concatenate([[0], np.cumsum(host_counts)])
        for p in range(self.n):
            c = int(host_counts[p])
            if c == 0:
                continue
            out_cap = bucket_capacity(c, self.growth)
            kern = cached_jit(f"slice|{out_cap}", lambda oc=out_cap: jax.jit(
                lambda bb, s, cc: rowops.slice_batch_to(bb, s, cc, oc)))
            piece = kern(sorted_b, jnp.asarray(int(offsets[p]), jnp.int32),
                         jnp.asarray(c, jnp.int32))
            self.bytes[p] += piece.device_memory_size()
            self.rows[p] += c
            self.buckets[p].append(self.session.add_transient_batch(
                piece, SpillPriorities.OUTPUT_FOR_READ))
        self.spill_to_budget()

    def spill_to_budget(self) -> None:
        store = self.session.buffer_catalog.device_store
        if store.total_size > self.budget:
            store.synchronous_spill(self.budget)

    def consume_bucket(self, p: int) -> List[DeviceBatch]:
        out = []
        catalog = self.session.buffer_catalog
        for bid in self.buckets[p]:
            out.append(catalog.acquire_batch(bid))
            self.session.consume_transient(bid)
        self.buckets[p] = []
        return out


def split_batch_by_hash(ctx, key_idx, batch: DeviceBatch, n: int,
                        level: int, growth: float) -> List[DeviceBatch]:
    """In-memory hash fan-out of ONE batch into <= n disjoint-key slices
    (equal keys co-locate; empty buckets are dropped). The light sibling
    of SpilledPartitions.add_batch — same partitioner and slice kernels,
    no spill-store registration — used by the hash-aggregation VMEM
    bound (exec/tpu.py): a batch whose slot table would exceed
    spark.rapids.sql.agg.hash.maxTableSlots splits here and aggregates
    per slice, the disjoint key sets making the slices' partial outputs
    union to exactly the whole batch's groups."""
    split = hash_split_kernel(key_idx, n, level)
    sorted_b, counts = split(batch)
    with sync_scope("outofcore.partitionCounts", detail="hashSplit"):
        host_counts = np.asarray(jax.device_get(counts))
    offsets = np.concatenate([[0], np.cumsum(host_counts)])
    out: List[DeviceBatch] = []
    for p in range(n):
        c = int(host_counts[p])
        if c == 0:
            continue
        out_cap = bucket_capacity(c, growth)
        kern = cached_jit(f"slice|{out_cap}", lambda oc=out_cap: jax.jit(
            lambda bb, s, cc: rowops.slice_batch_to(bb, s, cc, oc)))
        out.append(kern(sorted_b, jnp.asarray(int(offsets[p]), jnp.int32),
                        jnp.asarray(c, jnp.int32)))
    _record(ctx, "hashAggSplit", n, batch.device_memory_size(), 0, level)
    return out


# ---------------------------------------------------------------------------
# partition-id kernels
# ---------------------------------------------------------------------------

def _level_hash(batch: DeviceBatch, key_idx, level: int):
    """Per-row 64-bit key hash for grace level ``level``: level 0 uses
    h1, level 1 the independent h2, deeper levels a mix — so a fragment
    that did not split at one level re-partitions differently at the
    next (identical keys still co-locate at every level)."""
    h1, h2 = row_hashes(batch, list(key_idx))
    if level == 0:
        return h1
    if level == 1:
        return h2
    return h1 ^ (h2 + jnp.uint64(0x9E3779B97F4A7C15) * jnp.uint64(level))


def hash_split_kernel(key_idx, n: int, level: int):
    """Jitted (batch) -> (pid-sorted batch, counts) splitting on the key
    hash — the grace join / spillable agg partitioner."""
    from spark_rapids_tpu.exec.tpu import _split_by_pid
    key_idx = tuple(key_idx)
    sig = f"ooc|hsplit|{key_idx}|{n}|{level}"

    def build():
        def split(b: DeviceBatch):
            pid = (_level_hash(b, key_idx, level)
                   % jnp.uint64(n)).astype(jnp.int32)
            return _split_by_pid(b, pid, n)
        return jax.jit(split)
    return cached_jit(sig, build)


# ---------------------------------------------------------------------------
# grace hash join
# ---------------------------------------------------------------------------

def join_applicable(ctx, exec_) -> bool:
    return (enabled_for(ctx) and exec_.join_type != "cross"
            and bool(exec_._bkey))


def grace_join(ctx, exec_, build_batches, stream_batches, growth: float,
               level: int = 0) -> Iterator[DeviceBatch]:
    """Partition both sides on the join-key hash into spillable buckets,
    then join bucket by bucket; a build fragment still over budget
    recurses with the next hash level. Equal keys co-locate, NULL keys
    land in SOME bucket deterministically (they never match; outer rows
    are preserved wherever they land), so the per-bucket results union
    to exactly the in-HBM join's output.

    Both sides are ITERABLES and are never fully materialized: each
    batch is staged onto the spill store as it arrives (peak residency
    ~ budget + one batch), the fan-out is chosen from the staged
    measured totals, and the staged batches drain back one at a time
    into the fan-out partitioner."""
    session = ctx.session
    budget = working_set_budget(ctx)
    bbids, bbytes = _stage_spillable(session, build_batches, budget)
    sbids, sbytes = _stage_spillable(session, stream_batches, budget)
    n = choose_fanout(ctx, bbytes + sbytes, budget)
    _record(ctx, "join", n, bbytes + sbytes, budget, level)
    si, bi = exec_._sides()
    build_schema = exec_.children[bi].output_schema()
    stream_schema = exec_.children[si].output_schema()
    bsplit = hash_split_kernel(exec_._bkey, n, level)
    ssplit = hash_split_kernel(exec_._skey, n, level)
    bparts = SpilledPartitions(session, build_schema, n, growth, budget)
    sparts = SpilledPartitions(session, stream_schema, n, growth, budget)
    for b in _drain_staged(session, bbids):
        bparts.add_batch(b, bsplit)
    for s in _drain_staged(session, sbids):
        sparts.add_batch(s, ssplit)
    from spark_rapids_tpu.exec.tpu import _concat_device
    emitted = False
    for p in range(n):
        frag_bytes = bparts.bytes[p]
        bpieces = bparts.consume_bucket(p)
        spieces = sparts.consume_bucket(p)
        if not bpieces and not spieces:
            continue
        if (frag_bytes > budget and level + 1 < _max_recursion(ctx)
                and len(bpieces) + len(spieces) > 1):
            for out in grace_join(ctx, exec_, bpieces, spieces, growth,
                                  level + 1):
                emitted = True
                yield out
            continue
        build = _concat_device(bpieces, build_schema, growth, coarse=True) \
            if bpieces else DeviceBatch.empty(build_schema)
        for out in _join_bucket(ctx, exec_, build, spieces):
            emitted = True
            yield out
        bparts.spill_to_budget()
    if not emitted:
        yield DeviceBatch.empty(exec_.output_schema())


def _join_bucket(ctx, exec_, build: DeviceBatch,
                 streams: List[DeviceBatch]) -> Iterator[DeviceBatch]:
    """One bucket's in-HBM join via the exec's cached probe/expand
    kernels — the plain (non-speculating, non-dense) emission loop.

    NB: this is deliberately the SIMPLIFIED twin of
    TpuShuffledHashJoinExec's main emission loop (exec/tpujoin.py run():
    batched one-fetch totals, capacity speculation, dense/Pallas probe
    selection). Changes to join emission semantics there (new join
    types, size/cap layout of _totals, _expand's contract) must be
    mirrored here — the out-of-core tests diff both paths against the
    oracle, which is the drift tripwire."""
    growth = ctx.conf.capacity_growth
    jt = exec_.join_type
    matched_acc = None
    for stream in streams:
        if jt in ("leftsemi", "leftanti"):
            yield exec_._semi(stream, exec_._probe(build, stream)[0])
            continue
        counts, bstart, bperm = exec_._probe(build, stream)
        with sync_scope("outofcore.spillSizes", detail="joinTotals"):
            sizes = [int(x) for x in jax.device_get(
                exec_._totals(build, stream, counts, bstart, bperm))]
        if jt == "full":
            flags = exec_._match_flags(build, counts, bstart, bperm)
            matched_acc = (flags if matched_acc is None
                           else matched_acc | flags)
        total = sizes[0]
        if total == 0:
            continue
        n_s = sum(1 for d in stream.schema.dtypes if d.is_string)
        s_caps = tuple(_char_bucket(c) for c in sizes[1:1 + n_s])
        b_caps = tuple(_char_bucket(c) for c in sizes[1 + n_s:])
        out_cap = bucket_dim(bucket_capacity(total, growth))
        expanded = exec_._expand(build, stream, counts, bstart, bperm,
                                 out_cap, s_caps, b_caps)
        from spark_rapids_tpu.memory.device import TpuDeviceManager
        dm = TpuDeviceManager.current()
        if dm is not None:
            dm.meter_batch(expanded)
        yield expanded
    if jt == "full":
        if matched_acc is None:
            matched_acc = jnp.zeros((build.capacity,), jnp.bool_)
        si, _bi = exec_._sides()
        stream_schema = exec_.children[si].output_schema()
        tail = exec_._unmatched(build, matched_acc, stream_schema)
        if tail.num_rows_host() > 0:
            yield tail


# ---------------------------------------------------------------------------
# external merge sort
# ---------------------------------------------------------------------------

def external_sort(ctx, exec_, batches, schema: Schema,
                  growth: float) -> Iterator[DeviceBatch]:
    """Sampled range bounds -> range-partitioned spill buckets -> one
    in-HBM sort per bucket, emitted in range order: a globally sorted
    stream whose concatenation is byte-identical to the single-batch
    sort (equal keys share a bucket and the per-batch slice order
    preserves the stable tie order).

    ``batches`` is an ITERABLE, never fully materialized: each batch is
    sampled (the GpuRangePartitioner sample — small host fetch) then
    staged onto the spill store; bounds and fan-out come from the staged
    totals, and the staged batches drain back one at a time into the
    range partitioner."""
    session = ctx.session
    budget = working_set_budget(ctx)
    asc = [o.ascending for o in exec_.orders]
    nf = [o.nulls_first for o in exec_.orders]
    base_sig = "ooc|" + exec_.fingerprint_extra()

    def build_sample():
        def samp(b: DeviceBatch):
            work, key_idx = exec_._key_batch(b)
            ops = sortops.sort_key_operands(work, key_idx, asc, nf)
            return b.num_rows, jnp.stack([o.astype(jnp.uint64)
                                          for o in ops])
        return jax.jit(samp)
    sample_kernel = cached_jit(base_sig + "|sample", build_sample)

    samples = []
    kbox = {"k": None}

    def sample(b: DeviceBatch) -> None:
        with sync_scope("outofcore.sample", detail="sortBounds"):
            rows, ops = jax.device_get(sample_kernel(b))
        rows = int(rows)
        ops = np.asarray(ops)
        kbox["k"] = ops.shape[0]
        if rows > 0:
            take = min(rows, 128)
            sel = np.linspace(0, rows - 1, take).astype(np.int64)
            samples.append(ops[:, sel])

    staged, total = _stage_spillable(session, batches, budget,
                                     on_batch=sample)
    k = kbox["k"]
    n = choose_fanout(ctx, total, budget)
    _record(ctx, "sort", n, total, budget)
    from spark_rapids_tpu.parallel.distributed import (
        pick_bounds_from_samples,
    )
    bounds = tuple(jnp.asarray(b) for b in pick_bounds_from_samples(
        samples, k if k is not None else len(asc), n))

    from spark_rapids_tpu.exec.tpu import _concat_device, _split_by_pid
    sig = base_sig + f"|{n}"

    def build_split():
        def split(b: DeviceBatch, *bnds):
            work, key_idx = exec_._key_batch(b)
            pid = sortops.range_partition_ids(work, key_idx, asc, nf,
                                              list(bnds))
            return _split_by_pid(b, pid, n)
        return jax.jit(split)
    split_kernel = cached_jit(sig + "|split", build_split)

    parts = SpilledPartitions(session, schema, n, growth, budget)
    for b in _drain_staged(session, staged):
        parts.add_batch(b, lambda bb: split_kernel(bb, *bounds))
    emitted = False
    for p in range(n):
        pieces = parts.consume_bucket(p)
        if not pieces:
            continue
        merged = _concat_device(pieces, schema, growth)
        emitted = True
        yield exec_._kernel(merged)
        parts.spill_to_budget()
    if not emitted:
        yield exec_._kernel(DeviceBatch.empty(schema))


# ---------------------------------------------------------------------------
# spillable aggregation
# ---------------------------------------------------------------------------

def grace_aggregate(ctx, exec_, batches,
                    growth: float) -> Iterator[DeviceBatch]:
    """Partial-layout batches hash-partitioned on the grouping keys into
    spillable buckets; each bucket merges (and in final mode finalizes)
    independently — key sets are disjoint across buckets, so the union
    of per-bucket outputs IS the aggregate. ``batches`` is an ITERABLE:
    in partial mode the per-batch update pass runs as each batch arrives
    (streaming, bounded by one batch) and its partial is staged onto the
    spill store; fan-out comes from the staged measured totals."""
    session = ctx.session
    plan = exec_.plan
    budget = working_set_budget(ctx)

    def updated():
        for b in batches:
            if b is None:
                continue
            yield exec_._kernel(b) if exec_.mode == "partial" else b
    staged, total = _stage_spillable(session, updated(), budget)
    n = choose_fanout(ctx, total, budget)
    _record(ctx, "aggregate", n, total, budget)
    pschema = plan.partial_schema
    split = hash_split_kernel(range(plan.num_keys), n, 0)
    parts = SpilledPartitions(session, pschema, n, growth, budget)
    for partial in _drain_staged(session, staged):
        parts.add_batch(partial, split)
    from spark_rapids_tpu.exec.tpu import _concat_device
    emitted = False
    for p in range(n):
        pieces = parts.consume_bucket(p)
        if not pieces:
            continue
        merged = exec_._merge_kernel(
            _concat_device(pieces, pschema, growth))
        emitted = True
        yield (merged if exec_.mode == "partial"
               else exec_._final_kernel(merged))
        parts.spill_to_budget()
    if not emitted:
        if exec_.mode == "partial":
            yield exec_._kernel(DeviceBatch.empty(
                exec_.children[0].output_schema()))
        else:
            merged = exec_._merge_kernel(DeviceBatch.empty(pschema))
            yield exec_._final_kernel(merged)
