"""Fused count-distinct execution.

The DataFrame layer (like Spark's RewriteDistinctAggregates) expands
``group_by(G2).agg(count(distinct K))`` — and the hand-written
distinct().group_by().count() spelling — into a two-level aggregation:

    Agg(final G2, count) / Exch / Agg(partial G2, count)
      / Agg(final G1) / Exch / Agg(partial G1) / child      G1 = G2 + K

The reference executes that chain as two full cuDF hash aggregations
(aggregate.scala:40-225 keeps the expansion; each level is a real pass).
On this backend every aggregation pass pays a sort + segment sweep, so
the chain dominates distinct-heavy queries (q16: 1.7s of 2.4s). This
pass recognizes the chain on the FINAL physical plan and replaces it
with one operator running a single sorted pass over the G1 key tuple
(ops/aggregate.count_distinct_reduce): distinct-tuple boundaries and
G2-group boundaries come from the same sorted images.

Gated to: single-chip (no mesh — the chain's exchanges carry real
distribution on a mesh), bare-column keys, a lone count(*) (count(lit 1))
result, and results that are plain key references or the count.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes
from spark_rapids_tpu.columnar.batch import DeviceBatch, Schema
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.exec.base import ExecContext, Partition, PhysicalPlan
from spark_rapids_tpu.utils.kernelcache import cached_jit


class TpuCountDistinctExec(PhysicalPlan):
    """One-pass grouped distinct count (see module docstring).

    ``out_plan``: for each output column, ("key", child_col_idx) or
    ("count", None), in output-schema order."""

    columnar_output = True

    def __init__(self, child: PhysicalPlan, out_schema: Schema,
                 out_plan: List[Tuple[str, Optional[int]]],
                 g2_idx: List[int], rest_idx: List[int]):
        super().__init__([child])
        self._schema = out_schema
        self.out_plan = list(out_plan)
        self.g2_idx = list(g2_idx)
        self.rest_idx = list(rest_idx)
        sig = (f"cdist|{tuple(g2_idx)}|{tuple(rest_idx)}"
               f"|{tuple(out_plan)}|{out_schema!r}")
        self._sig = sig

        def finish(batch: DeviceBatch, rep_rows, counts, n_groups):
            from spark_rapids_tpu.ops.rowops import gather_columns
            cap = batch.capacity
            live = jnp.arange(cap, dtype=jnp.int32) < n_groups
            key_cols = gather_columns(
                [batch.columns[ci] for kind, ci in self.out_plan
                 if kind == "key"], rep_rows, live)
            cols: List[DeviceColumn] = []
            ki = 0
            for kind, _ci in self.out_plan:
                if kind == "key":
                    cols.append(key_cols[ki])
                    ki += 1
                else:
                    cols.append(DeviceColumn(dtypes.INT64, counts, live))
            return DeviceBatch(self._schema, cols,
                               n_groups.astype(jnp.int32))

        def kernel(batch: DeviceBatch) -> DeviceBatch:
            from spark_rapids_tpu.ops.aggregate import count_distinct_reduce
            rep_rows, counts, n_groups = count_distinct_reduce(
                batch, self.g2_idx, self.rest_idx)
            return finish(batch, rep_rows, counts, n_groups)
        self._kernel = cached_jit(sig, lambda: jax.jit(kernel))
        self._finish = finish

    def _hash_kernel(self, mode: str):
        """Hash-table spelling of the fused count-distinct: two
        open-addressing group assignments (ops/pallas_kernels
        .hash_group_ids) — distinct G1 tuples, then G2 groups over the
        tuple representatives — replacing the sorted pass entirely.
        Falls back to the sorted pass at trace time when any key column
        is a plain (non-dictionary) string: only fixed-width values and
        batch-local dictionary codes have exact single-u64 images."""
        from spark_rapids_tpu.ops import pallas_kernels as pk

        def hash_count_distinct(batch: DeviceBatch):
            from spark_rapids_tpu.ops.sortops import u64_key_image

            def images(idx_list):
                imgs = []
                for ci in idx_list:
                    col = batch.columns[ci]
                    per = u64_key_image(col, allow_dict=True)
                    # null keys are their own distinct value: the
                    # sentinel image plus the validity bit as an extra
                    # key column keeps a real value that happens to
                    # equal the sentinel distinct from NULL
                    imgs.extend(jnp.where(col.validity, im, jnp.uint64(0))
                                for im in per)
                    imgs.append(col.validity.astype(jnp.uint64))
                return imgs

            cap = batch.capacity
            valid = batch.row_mask()
            T = pk.hash_table_size(cap)
            rows = jnp.arange(cap, dtype=jnp.int32)
            gid1, _n1, rep1 = pk.hash_group_ids(
                images(self.g2_idx + self.rest_idx), valid, T, mode=mode)
            # representative row of each distinct G1 tuple
            first = (gid1 >= 0) & (
                rows == rep1[jnp.clip(gid1, 0, cap - 1)])
            gid2, n2, rep2 = pk.hash_group_ids(
                images(self.g2_idx), first, T, mode=mode)
            counts = jnp.zeros((cap,), jnp.int64).at[
                jnp.where(first, gid2, cap)].add(1, mode="drop")
            return rep2, counts, n2

        def kernel(batch: DeviceBatch) -> DeviceBatch:
            from spark_rapids_tpu.ops.aggregate import count_distinct_reduce
            plain_string = any(
                batch.columns[ci].dtype.is_string
                and batch.columns[ci].dict_values is None
                for ci in self.g2_idx + self.rest_idx)
            if plain_string:
                rep_rows, counts, n_groups = count_distinct_reduce(
                    batch, self.g2_idx, self.rest_idx)
            else:
                rep_rows, counts, n_groups = hash_count_distinct(batch)
            return self._finish(batch, rep_rows, counts, n_groups)
        return cached_jit(f"{self._sig}|hash|{mode}",
                          lambda: jax.jit(kernel))

    def output_schema(self) -> Schema:
        return self._schema

    def describe(self) -> str:
        return (f"TpuCountDistinctExec(g2={self.g2_idx}, "
                f"distinct={self.rest_idx})")

    def fingerprint_extra(self) -> str:
        return self._sig

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        child_parts = self.children[0].executed_partitions(ctx)
        growth = ctx.conf.capacity_growth
        from spark_rapids_tpu.ops import pallas_kernels as pk
        mode = pk.hash_kernels_mode()
        kernel = self._kernel
        if mode != "off" and ctx.conf.get_bool(
                "spark.rapids.sql.fusion.hashKernels", True):
            kernel = self._hash_kernel(mode)

        def run():
            from spark_rapids_tpu.exec.tpu import _concat_device
            batches = [b for p in child_parts for b in p()]
            if not batches:
                yield DeviceBatch.empty(self._schema)
                return
            # coarse materialization: the fused pass's kernel signature
            # rides the merged capacity — the shape-bucket ladder keeps
            # it stable across input sizes (compile.shapeBuckets)
            merged = _concat_device(
                batches, self.children[0].output_schema(), growth,
                coarse=True)
            yield kernel(merged)
        return [run]


def _strip_alias(e):
    from spark_rapids_tpu.sql.exprs.core import Alias
    while isinstance(e, Alias):
        e = e.children[0]
    return e


def _is_count_star(e) -> bool:
    from spark_rapids_tpu.sql.exprs.aggregates import Count
    from spark_rapids_tpu.sql.exprs.core import Literal
    e = _strip_alias(e)
    return (isinstance(e, Count)
            and isinstance(_strip_alias(e.children[0]), Literal))


def _skip_coalesce(node: PhysicalPlan) -> PhysicalPlan:
    from spark_rapids_tpu.exec.coalesce import TpuCoalesceBatchesExec
    while isinstance(node, TpuCoalesceBatchesExec):
        node = node.children[0]
    return node


def _match_chain(node: PhysicalPlan):
    """Match AggF(G2,count)/Exch/AggP(G2)/AggF(G1)/Exch/AggP(G1)/child
    (TpuCoalesceBatchesExec freely interleaved). Returns the replacement
    exec or None."""
    from spark_rapids_tpu.exec.tpu import (
        TpuHashAggregateExec, TpuShuffleExchangeExec,
    )
    from spark_rapids_tpu.sql.exprs.core import BoundRef, Col

    def agg(n, mode):
        n = _skip_coalesce(n)
        return n if (isinstance(n, TpuHashAggregateExec)
                     and n.mode == mode) else None

    def exch(n):
        n = _skip_coalesce(n)
        return n if isinstance(n, TpuShuffleExchangeExec) else None

    fo = agg(node, "final")
    if fo is None or fo.pre_mask is not None:
        return None
    po = fo
    ex_o = exch(fo.children[0])
    if ex_o is None:
        return None
    po = agg(ex_o.children[0], "partial")
    if po is None or po.plan is not fo.plan or po.pre_mask is not None:
        return None
    fi = agg(po.children[0], "final")
    if fi is None or fi.pre_mask is not None:
        return None
    ex_i = exch(fi.children[0])
    if ex_i is None:
        return None
    pi = agg(ex_i.children[0], "partial")
    if pi is None or pi.plan is not fi.plan or pi.pre_mask is not None:
        return None
    child = _skip_coalesce(pi.children[0])

    plan_o, plan_i = fo.plan, fi.plan
    # inner must be a pure distinct: no aggregate functions, results are
    # exactly the grouping columns
    if plan_i.agg_fns:
        return None
    g1_names = [n for n, _ in plan_i.grouping]
    if [n for n, _ in plan_i.results] != g1_names:
        return None
    # outer: one count(*) and all other results bare G2 key references
    if len(plan_o.agg_fns) != 1 or not _is_count_star(plan_o.agg_fns[0]):
        return None
    g2_names = [n for n, _ in plan_o.grouping]
    # an empty outer grouping (global count-distinct) must NOT fuse: the
    # unfused final aggregate runs force_single_group and returns one
    # row (count 0) on empty input, while the fused kernel would return
    # zero rows — a silent result-shape divergence (ADVICE r4 #1)
    if not g2_names:
        return None
    if not set(g2_names) <= set(g1_names):
        return None
    # the count_distinct_reduce nullsig packs one validity bit per G1 key
    # into a uint32 (ops/aggregate.py count_distinct_reduce); wider
    # tuples would overflow the shift (ADVICE r4 #3)
    if len(g1_names) > 32:
        return None
    # outer grouping exprs must be bare references to the SAME-named
    # inner G1 output — a computed expr aliased to an inner output name
    # (e.g. (col('size')+1).alias('size')) would pass the name-subset
    # check and silently group on the raw child column (ADVICE r4 #2)
    for n, e in plan_o.grouping:
        e = _strip_alias(e)
        if isinstance(e, BoundRef):
            if not (0 <= e.index < len(g1_names)
                    and g1_names[e.index] == n):
                return None
        elif isinstance(e, Col):
            if e.name != n or n not in g1_names:
                return None
        else:
            return None
    # inner grouping exprs must be bare columns of the real child
    child_schema = child.output_schema()
    g1_child_idx = {}
    for n, e in plan_i.grouping:
        e = _strip_alias(e)
        if isinstance(e, BoundRef):
            g1_child_idx[n] = e.index
        elif isinstance(e, Col) and e.name in child_schema.names:
            g1_child_idx[n] = child_schema.index_of(e.name)
        else:
            return None
    # outer results: bare key references or the count
    out_plan: List[Tuple[str, Optional[int]]] = []
    for name, e in plan_o.results:
        e = _strip_alias(e)
        if _is_count_star(e):
            out_plan.append(("count", None))
            continue
        if isinstance(e, Col) and e.name in g2_names:
            out_plan.append(("key", g1_child_idx[e.name]))
            continue
        if isinstance(e, BoundRef) and e.name in g2_names:
            out_plan.append(("key", g1_child_idx[e.name]))
            continue
        return None
    if sum(1 for k, _ in out_plan if k == "count") != 1:
        return None
    g2_idx = [g1_child_idx[n] for n in g2_names]
    rest_idx = [g1_child_idx[n] for n in g1_names if n not in set(g2_names)]
    return TpuCountDistinctExec(child, plan_o.output_schema, out_plan,
                                g2_idx, rest_idx)


def fuse_count_distinct(plan: PhysicalPlan) -> PhysicalPlan:
    """Bottom-up rewrite replacing every matched chain."""
    plan.children = [fuse_count_distinct(c) for c in plan.children]
    replaced = _match_chain(plan)
    return replaced if replaced is not None else plan
