"""Shared aggregation planning machinery.

Decomposes aggregate result expressions into the update/merge/finalize
pipeline both the CPU and TPU hash-aggregate operators execute — the
reference's bound-reference plumbing for partial/final modes
(aggregate.scala:227-509)."""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

from spark_rapids_tpu.columnar import dtypes
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.columnar.dtype import DType
from spark_rapids_tpu.sql.exprs.aggregates import AggregateFunction, find_aggregates
from spark_rapids_tpu.sql.exprs.core import Alias, BoundRef, Expression


class AggPlan:
    """Static description of a grouped aggregation.

    grouping: [(name, expr over child schema)]
    results:  [(name, expr containing AggregateFunction nodes)]
    """

    def __init__(self, child_schema: Schema,
                 grouping: Sequence[Tuple[str, Expression]],
                 results: Sequence[Tuple[str, Expression]]):
        self.child_schema = child_schema
        self.grouping = list(grouping)
        self.results = list(results)

        # distinct aggregate function instances in result order
        self.agg_fns: List[AggregateFunction] = []
        seen = set()
        for _, e in self.results:
            for fn in find_aggregates(e):
                if id(fn) not in seen:
                    seen.add(id(fn))
                    self.agg_fns.append(fn)

        # update inputs: expressions evaluated per input row pre-reduction
        self.update_inputs: List[Expression] = []
        # per agg fn: list of (kind, update_input_index, intermediate dtype)
        self.update_plan: List[List[Tuple[str, int, DType]]] = []
        for fn in self.agg_fns:
            ops = []
            inter_dts = fn.intermediate_dtypes(child_schema)
            for (kind, child_idx), idt in zip(fn.update_ops(), inter_dts):
                inp = fn.children[child_idx]
                self.update_inputs.append(inp)
                ops.append((kind, len(self.update_inputs) - 1, idt))
            self.update_plan.append(ops)

        # intermediate (partial-output) schema: keys then intermediates
        names, dts = [], []
        for name, e in self.grouping:
            names.append(name)
            dts.append(e.dtype(child_schema))
        self.num_keys = len(names)
        i = 0
        for fn, ops in zip(self.agg_fns, self.update_plan):
            for kind, _, idt in ops:
                names.append(f"_agg{i}")
                dts.append(idt)
                i += 1
        self.partial_schema = Schema(names, dts)

        # merge plan over the partial schema: [(kind, partial_col_index)]
        self.merge_plan: List[List[Tuple[str, int, DType]]] = []
        col = self.num_keys
        for fn, ops in zip(self.agg_fns, self.update_plan):
            merged = []
            for kind_merge, (_, _, idt) in zip(fn.merge_ops(), ops):
                merged.append((kind_merge, col, idt))
                col += 1
            self.merge_plan.append(merged)

        # final output schema. Key results are Col(grouping_output_name)
        # references resolved at finalize; their dtype comes from the
        # grouping expr — evaluating the name against the child schema
        # would pick up a shadowing raw column when a computed key is
        # aliased to an existing column name.
        gdt = {n: dt for (n, _), dt
               in zip(self.grouping, dts[:self.num_keys])}
        out_names = [n for n, _ in self.results]
        out_dts = []
        for _, e in self.results:
            from spark_rapids_tpu.sql.exprs.core import Col
            if isinstance(e, Col) and e.name in gdt:
                out_dts.append(gdt[e.name])
            else:
                out_dts.append(e.dtype(child_schema))
        self.output_schema = Schema(out_names, out_dts)

    @property
    def signature(self) -> str:
        """Deterministic structural signature for the kernel cache."""
        from spark_rapids_tpu.utils.kernelcache import expr_signature
        g = ";".join(f"{n}={expr_signature(e)}" for n, e in self.grouping)
        r = ";".join(f"{n}={expr_signature(e)}" for n, e in self.results)
        return f"agg[{self.child_schema!r}][{g}][{r}]"

    def finalize_exprs(self) -> List[Tuple[str, Expression]]:
        """Result expressions over the *merged partial schema*: aggregate
        nodes replaced by finalize() over intermediate BoundRefs; grouping
        expressions replaced by key-column BoundRefs."""
        # map each agg fn -> finalize expression over merged intermediates
        fn_final: Dict[int, Expression] = {}
        col = self.num_keys
        for fn, ops in zip(self.agg_fns, self.update_plan):
            refs = []
            for kind, _, idt in ops:
                refs.append(BoundRef(col, idt, self.partial_schema.names[col]))
                col += 1
            fn_final[id(fn)] = fn.finalize(refs, self.child_schema)

        group_map: Dict[str, int] = {}
        for i, (name, _) in enumerate(self.grouping):
            group_map[name] = i

        def rewrite(e: Expression) -> Expression:
            if isinstance(e, AggregateFunction):
                return fn_final[id(e)]
            # grouping expression by name match (the DataFrame API names
            # grouping output columns)
            from spark_rapids_tpu.sql.exprs.core import Col
            if isinstance(e, Col) and e.name in group_map:
                i = group_map[e.name]
                return BoundRef(i, self.partial_schema.dtypes[i], e.name)
            return e.map_children(rewrite)

        return [(name, rewrite(e)) for name, e in self.results]
