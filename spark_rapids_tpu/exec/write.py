"""Data-writing command execs: Parquet/CSV output with a commit protocol.

reference: GpuDataWritingCommandExec (94), ColumnarOutputWriter (183),
GpuFileFormatWriter (338), GpuParquetFileFormat / GpuOrcFileFormat — the
accelerator writes columnar batches straight to the file format and the
commit protocol (task temp dir -> atomic rename + _SUCCESS) comes from
Spark. Here the device batch's columns convert to one arrow table per
batch (device->host is the only copy) and pyarrow encodes; the TPU-native
delta vs the reference is that encode happens host-side since there is no
device Parquet encoder for TPUs yet (SURVEY.md §7 hard part 2 — staged
plan)."""

from __future__ import annotations

import os
import shutil
import uuid
from typing import Iterator, List, Optional

import numpy as np
import pandas as pd

from spark_rapids_tpu.columnar.batch import DeviceBatch, Schema
from spark_rapids_tpu.exec.base import ExecContext, Partition, PhysicalPlan


def _arrow_table_from_batch(batch: DeviceBatch):
    """Device batch -> pyarrow table (column buffers, no row pivot)."""
    import pyarrow as pa
    n = batch.num_rows_host()
    arrays = []
    for col, dt in zip(batch.columns, batch.schema.dtypes):
        values, validity = col.to_numpy(n)
        mask = ~validity if not validity.all() else None
        arrays.append(pa.array(values, type=dt.pa_type, from_pandas=True,
                               mask=mask))
    return pa.Table.from_arrays(arrays, names=list(batch.schema.names))


def _arrow_table_from_pandas(df: pd.DataFrame, schema: Schema):
    import pyarrow as pa
    arrays = []
    for i, dt in enumerate(schema.dtypes):
        s = df.iloc[:, i]
        arrays.append(pa.Array.from_pandas(s, type=dt.pa_type))
    return pa.Table.from_arrays(arrays, names=list(schema.names))


class WriteCommitProtocol:
    """Task-attempt staging + driver-side commit (reference:
    GpuFileFormatWriter.scala:338 riding Spark's HadoopMapReduceCommitProtocol):
    tasks write under <path>/_temporary/<job>/, commit renames into place,
    abort removes the staging tree. Crash-safe: a reader never sees
    partial files in the target listing."""

    def __init__(self, path: str):
        self.path = path
        self.job_id = uuid.uuid4().hex[:12]
        self.staging = os.path.join(path, "_temporary", self.job_id)

    def setup(self, mode: str) -> None:
        if os.path.isdir(self.path) and mode == "overwrite":
            for entry in os.listdir(self.path):
                full = os.path.join(self.path, entry)
                if entry != "_temporary":
                    (shutil.rmtree if os.path.isdir(full)
                     else os.unlink)(full)
        elif os.path.isdir(self.path) and mode == "error":
            if any(e != "_temporary" for e in os.listdir(self.path)):
                raise FileExistsError(
                    f"path {self.path} already exists (mode=error)")
        os.makedirs(self.staging, exist_ok=True)

    def task_file(self, partition_id: int, ext: str,
                  subdir: str = "") -> str:
        d = os.path.join(self.staging, subdir) if subdir else self.staging
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, f"part-{partition_id:05d}{ext}")

    def commit(self) -> None:
        # move staged files preserving key=value subdirectories
        for root, _dirs, files in os.walk(self.staging):
            rel = os.path.relpath(root, self.staging)
            target = (self.path if rel == "."
                      else os.path.join(self.path, rel))
            os.makedirs(target, exist_ok=True)
            for f in sorted(files):
                os.replace(os.path.join(root, f), os.path.join(target, f))
        shutil.rmtree(os.path.join(self.path, "_temporary"),
                      ignore_errors=True)
        open(os.path.join(self.path, "_SUCCESS"), "w").close()

    def abort(self) -> None:
        shutil.rmtree(os.path.join(self.path, "_temporary"),
                      ignore_errors=True)


def _partition_subdirs(df: pd.DataFrame, pcols: List[str]):
    """Split a frame by its partition-column tuples into
    (key=value/... subdir, frame-without-partition-cols) pairs (Spark's
    dynamic-partition layout; NULL renders as __HIVE_DEFAULT_PARTITION__)."""
    if not pcols:
        yield "", df
        return
    def render(v):
        return "__HIVE_DEFAULT_PARTITION__" if pd.isna(v) else str(v)
    for key, group in df.groupby(pcols, dropna=False, sort=True):
        key = key if isinstance(key, tuple) else (key,)
        subdir = os.path.join(*[f"{c}={render(v)}"
                                for c, v in zip(pcols, key)])
        yield subdir, group.drop(columns=pcols)


def _write_partitioned(tables, schema: Schema, protocol: WriteCommitProtocol,
                       task_id: int, ext: str, fmt: str,
                       pcols: List[str]) -> dict:
    """Write one task's tables; returns the write stats the reference's
    BasicColumnarWriteJobStatsTracker reports (numFiles, numOutputRows,
    numOutputBytes, numParts)."""
    import pyarrow as pa
    table = pa.concat_tables(tables)
    stats = {"numFiles": 0, "numOutputRows": 0, "numOutputBytes": 0}
    part_dirs = set()

    def encode(tbl, path):
        _encode_table(tbl, path, fmt)
        stats["numFiles"] += 1
        stats["numOutputRows"] += tbl.num_rows
        try:
            stats["numOutputBytes"] += os.path.getsize(path)
        except OSError:
            pass

    if not pcols:
        encode(table, protocol.task_file(task_id, ext))
        stats["partDirs"] = part_dirs
        return stats
    keep = Schema([n for n in schema.names if n not in pcols],
                  [d for n, d in zip(schema.names, schema.dtypes)
                   if n not in pcols])
    for subdir, group in _partition_subdirs(table.to_pandas(), pcols):
        encode(_arrow_table_from_pandas(group, keep),
               protocol.task_file(task_id, ext, subdir))
        part_dirs.add(subdir)
    stats["partDirs"] = part_dirs
    return stats




def _record_write_stats(ctx: ExecContext, op: str, st: dict,
                        state: dict) -> None:
    """Per-task write stats -> per-op metrics (the reference's
    BasicColumnarWriteJobStatsTracker). Callers hold state["lock"].
    numParts (distinct dynamic partition dirs across all tasks) is
    recorded by _finish_write_task when the last task completes — tying
    it to stats recording dropped it whenever the final partition was
    empty and never produced tables."""
    if not ctx.metrics_enabled:
        return
    state["parts"] |= st.pop("partDirs", set())
    for k, v in st.items():
        ctx.metric_add(op, k, v)


def _finish_write_task(ctx: ExecContext, op: str, state: dict,
                       protocol) -> None:
    """Last-task bookkeeping: decrement under the lock, then commit and
    emit numParts exactly once, whether or not the final task wrote."""
    with state["lock"]:
        state["remaining"] -= 1
        done = state["remaining"] == 0 and not state["failed"]
        parts = len(state["parts"])
    if done:
        if ctx.metrics_enabled and parts:
            ctx.metric_add(op, "numParts", parts)
        protocol.commit()


class CpuWriteExec(PhysicalPlan):
    """Host path: pandas partition -> arrow -> file."""

    def __init__(self, child: PhysicalPlan, path: str, fmt: str,
                 mode: str = "error", partition_cols: List[str] = ()):
        super().__init__([child])
        self.path = path
        self.fmt = fmt
        self.mode = mode
        self.partition_cols = list(partition_cols)

    def output_schema(self) -> Schema:
        return Schema([], [])

    def describe(self) -> str:
        return f"CpuWriteExec({self.fmt}, {self.path})"

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        child_parts = self.children[0].executed_partitions(ctx)
        schema = self.children[0].output_schema()
        protocol = WriteCommitProtocol(self.path)
        protocol.setup(self.mode)
        ext = _EXTENSIONS[self.fmt]
        import threading
        state = {"remaining": len(child_parts), "failed": False,
                 "parts": set(), "lock": threading.Lock()}

        def make(i: int, part: Partition) -> Partition:
            def run() -> Iterator[pd.DataFrame]:
                try:
                    tables = [_arrow_table_from_pandas(df, schema)
                              for df in part() if len(df)]
                    if tables:
                        st = _write_partitioned(tables, schema, protocol, i,
                                                ext, self.fmt,
                                                self.partition_cols)
                        with state["lock"]:
                            _record_write_stats(ctx, self.describe(), st,
                                                state)
                except Exception:
                    state["failed"] = True
                    protocol.abort()
                    raise
                _finish_write_task(ctx, self.describe(), state, protocol)
                yield pd.DataFrame()
            return run
        return [make(i, p) for i, p in enumerate(child_parts)]


class TpuWriteExec(PhysicalPlan):
    """Columnar path: device batches -> arrow (one D2H copy) -> file
    (reference: ColumnarOutputWriter + GpuParquetFileFormat)."""

    columnar_output = False  # terminal command, produces no batches
    columnar_input = True    # ...but consumes device batches

    def __init__(self, child: PhysicalPlan, path: str, fmt: str,
                 mode: str = "error", partition_cols: List[str] = ()):
        super().__init__([child])
        self.path = path
        self.fmt = fmt
        self.mode = mode
        self.partition_cols = list(partition_cols)

    def output_schema(self) -> Schema:
        return Schema([], [])

    def describe(self) -> str:
        return f"TpuWriteExec({self.fmt}, {self.path})"

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        child_parts = self.children[0].executed_partitions(ctx)
        schema = self.children[0].output_schema()
        protocol = WriteCommitProtocol(self.path)
        protocol.setup(self.mode)
        ext = _EXTENSIONS[self.fmt]
        import threading
        state = {"remaining": len(child_parts), "failed": False,
                 "parts": set(), "lock": threading.Lock()}

        def make(i: int, part: Partition) -> Partition:
            def run() -> Iterator[pd.DataFrame]:
                try:
                    tables = [_arrow_table_from_batch(b)
                              for b in part() if b.num_rows_host()]
                    if tables:
                        st = _write_partitioned(tables, schema, protocol, i,
                                                ext, self.fmt,
                                                self.partition_cols)
                        with state["lock"]:
                            _record_write_stats(ctx, self.describe(), st,
                                                state)
                except Exception:
                    state["failed"] = True
                    protocol.abort()
                    raise
                _finish_write_task(ctx, self.describe(), state, protocol)
                yield pd.DataFrame()
            return run
        return [make(i, p) for i, p in enumerate(child_parts)]


_EXTENSIONS = {"parquet": ".parquet", "csv": ".csv", "orc": ".orc"}


def _encode_table(table, f: str, fmt: str) -> None:
    if fmt == "parquet":
        import pyarrow.parquet as pq
        pq.write_table(table, f)
    elif fmt == "orc":
        import pyarrow.orc as paorc
        paorc.write_table(table, f)
    else:
        import pyarrow.csv as pacsv
        pacsv.write_csv(table, f)
