"""Window physical operators (reference: GpuWindowExec.scala:202 and the
window parts of Spark's WindowExec for the CPU oracle).

Both sides share the descriptor resolution in ``resolve_descriptor`` so
the differential tests compare identical frame semantics. The CPU exec
mirrors the device kernel's sorted-domain math in numpy — positions,
segment starts, prefix sums — rather than pandas rolling, so null
semantics match exactly.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import numpy as np
import pandas as pd

from spark_rapids_tpu.columnar import dtypes
from spark_rapids_tpu.columnar.batch import DeviceBatch, Schema, _numpy_to_pandas
from spark_rapids_tpu.exec.base import ExecContext, Partition, PhysicalPlan
from spark_rapids_tpu.sql.exprs.aggregates import (
    Average, Count, Max, Min, Sum,
)
from spark_rapids_tpu.sql.exprs.core import Expression
from spark_rapids_tpu.sql.exprs.hostutil import host_unary_values
from spark_rapids_tpu.sql.functions import SortOrder
from spark_rapids_tpu.sql.window import (
    CURRENT_ROW, DenseRank, LeadLag, Rank, RowNumber, UNBOUNDED_FOLLOWING,
    UNBOUNDED_PRECEDING, WindowExpression, is_bounded_range,
)

_AGG_KINDS = {Sum: "sum", Count: "count", Min: "min", Max: "max",
              Average: "avg"}
_MICROS_PER_DAY = 86_400_000_000


def resolve_descriptor(wexpr: WindowExpression, schema: Schema):
    """-> (descriptor_without_value_index, value_expr_or_None, tpu_error).
    ``tpu_error`` marks TPU-capability gaps only — the CPU oracle executes
    any non-None descriptor (the fallback path must work, the reference's
    willNotWorkOnGpu contract). A None descriptor is unsupported anywhere.
    The value index is assigned by the exec once it lays out the work
    batch."""
    fn = wexpr.fn
    if isinstance(fn, RowNumber):
        return ("row_number",), None, None
    if isinstance(fn, Rank):
        return ("rank",), None, None
    if isinstance(fn, DenseRank):
        return ("dense_rank",), None, None
    if isinstance(fn, LeadLag):
        off = fn.offset if fn.is_lead else -fn.offset
        child = fn.children[0]
        cdt = child.dtype(schema)
        err = None
        if fn.default is not None and (cdt.is_string or cdt.is_datetime):
            err = (f"lead/lag default values over {cdt.name} are not "
                   "supported on TPU")
        return ("leadlag", None, off, cdt.name, fn.default), child, err
    kind = _AGG_KINDS.get(type(fn))
    if kind is None:
        return None, None, (f"window function {fn.pretty_name} "
                            "is not supported")
    child = fn.children[0]
    frame_kind, lo, hi = wexpr.spec.resolved_frame(is_ranking=False)
    err = None
    if is_bounded_range(frame_kind, lo, hi):
        # the reference's time-range frames
        # (GpuWindowExpression.scala:198 aggregateWindowsOverTimeRanges)
        orders = wexpr.spec.orders
        if len(orders) != 1:
            return None, None, ("a RANGE frame with offsets requires "
                                "exactly one order-by column")
        odt = orders[0].expr.dtype(schema)
        if not (odt.is_numeric or odt.is_datetime):
            return None, None, (f"RANGE frame offsets over {odt.name} "
                                "order are not supported")
        if not orders[0].ascending:
            err = ("bounded RANGE over a descending order is not "
                   "supported on TPU")
        elif not orders[0].nulls_first:
            err = ("bounded RANGE with nulls-last ordering is not "
                   "supported on TPU")
        elif odt.is_floating:
            err = ("bounded RANGE over a floating-point order column is "
                   "not supported on TPU")
    if child.dtype(schema).is_string:
        whole = (lo <= UNBOUNDED_PRECEDING and hi >= UNBOUNDED_FOLLOWING)
        if kind == "count":
            pass  # count only consumes validity — any frame works
        elif kind in ("min", "max") and not whole:
            err = (f"window {kind} over strings supports only "
                   "whole-partition frames on TPU")
        elif kind not in ("min", "max"):
            err = f"window {kind} over strings is not supported on TPU"
        else:
            err = None
    return ("agg", kind, None, frame_kind, lo, hi,
            wexpr.dtype(schema).name), child, err


def _host_bounded_range_extents(ov, om, part_b, lo, hi, asc,
                                seg_start, seg_end):
    """Per-row [f_lo, f_hi] sorted-position extents for a bounded RANGE
    frame (host oracle; also the fallback executor for the device-tagged
    gaps: descending order, nulls-last, float order columns).

    Normalization: w = ov ascending / -ov descending maps both directions
    onto 'order values in [w+lo, w+hi]' over an ascending array. Null rows
    frame over the segment's null run; float NaN rows (sorted greatest)
    over the NaN run — both are peer groups. UNBOUNDED ends widen to the
    segment edge afterwards."""
    n = len(ov)
    lo_unb, hi_unb = lo <= UNBOUNDED_PRECEDING, hi >= UNBOUNDED_FOLLOWING
    w = np.asarray(ov)
    if not asc:
        if w.dtype.kind in "iub":
            w = w.astype(np.int64)
            # -INT64_MIN wraps back to itself and would sort FIRST in the
            # negated (ascending) space instead of last; saturate it to
            # INT64_MAX so it stays the extreme (it collapses with
            # -(INT64_MIN+1), acceptable for a bounded-range frame at the
            # far edge of the domain)
            imin = np.iinfo(np.int64).min
            with np.errstate(over="ignore"):
                w = np.where(w == imin, np.iinfo(np.int64).max, -w)
        else:
            w = -w.astype(np.float64)
    f_lo = np.empty(n, np.int64)
    f_hi = np.empty(n, np.int64)
    starts = np.flatnonzero(part_b)
    ends = np.r_[starts[1:] - 1, n - 1] if len(starts) else np.array([], int)
    for s0, e0 in zip(starts, ends):
        sl = slice(s0, e0 + 1)
        valid = np.asarray(om[sl], bool)
        ww = w[sl]
        isnan = (np.isnan(ww) & valid if ww.dtype.kind == "f"
                 else np.zeros(len(ww), bool))
        normal = valid & ~isnan
        ni = np.flatnonzero(normal)
        for runmask in (~valid, isnan):
            ri = np.flatnonzero(runmask)
            if len(ri):
                f_lo[s0 + ri] = s0 + ri[0]
                f_hi[s0 + ri] = s0 + ri[-1]
        if len(ni):
            vv = ww[ni]

            def sat_add(x, c):
                # saturating add for integer order values (a wrapped
                # target would silently flip the frame empty)
                if x.dtype.kind not in "iu":
                    return x + c
                with np.errstate(over="ignore"):
                    t = x + np.int64(c)
                ii = np.iinfo(np.int64)
                if c > 0:
                    return np.where(t < x, ii.max, t)
                if c < 0:
                    return np.where(t > x, ii.min, t)
                return t

            l = np.searchsorted(vv, sat_add(vv, lo), "left") if not lo_unb \
                else np.zeros(len(ni), np.int64)
            r = (np.searchsorted(vv, sat_add(vv, hi), "right") - 1) \
                if not hi_unb else np.full(len(ni), len(ni) - 1, np.int64)
            lo_rows = np.where(l < len(ni),
                               ni[np.clip(l, 0, len(ni) - 1)],
                               e0 - s0 + 1)  # sentinel: empty frame
            hi_rows = np.where(r >= 0, ni[np.clip(r, 0, len(ni) - 1)], -1)
            f_lo[s0 + ni] = s0 + lo_rows
            f_hi[s0 + ni] = s0 + hi_rows
    if lo_unb:
        f_lo = seg_start.copy()
    if hi_unb:
        f_hi = seg_end.copy()
    return f_lo, f_hi


class CpuWindowExec(PhysicalPlan):
    """CPU oracle: numpy mirror of the device window math."""

    def __init__(self, child: PhysicalPlan,
                 window_exprs: List[Tuple[str, WindowExpression]]):
        super().__init__([child])
        self.window_exprs = list(window_exprs)

    def output_schema(self) -> Schema:
        cs = self.children[0].output_schema()
        names = list(cs.names) + [n for n, _ in self.window_exprs]
        dts = list(cs.dtypes) + [w.dtype(cs) for _, w in self.window_exprs]
        return Schema(names, dts)

    def describe(self) -> str:
        return f"CpuWindowExec([{', '.join(n for n, _ in self.window_exprs)}])"

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        child_parts = self.children[0].executed_partitions(ctx)
        from spark_rapids_tpu.exec.cpu import _concat_parts

        def make(part: Partition) -> Partition:
            def run():
                df = _concat_parts(part(), self.children[0].output_schema())
                yield self._compute(df)
            return run
        return [make(p) for p in child_parts]

    def _compute(self, df: pd.DataFrame) -> pd.DataFrame:
        from spark_rapids_tpu.exec.cpu import host_sort_indices
        cs = self.children[0].output_schema()
        spec = self.window_exprs[0][1].spec
        orders = ([SortOrder(e, True, True) for e in spec.partition_cols]
                  + list(spec.orders))
        idx = host_sort_indices(df, orders)
        sdf = df.iloc[idx].reset_index(drop=True)
        n = len(sdf)
        pos = np.arange(n, dtype=np.int64)

        def key_tuple(exprs):
            cols = []
            for e in exprs:
                vals, validity, _ = host_unary_values(e.eval_host(sdf))
                cols.append((vals, validity))
            return cols

        def boundaries(cols):
            b = np.zeros(n, dtype=bool)
            if n:
                b[0] = True
            for vals, validity in cols:
                if n <= 1:
                    continue
                prev_v, cur_v = vals[:-1], vals[1:]
                prev_m, cur_m = validity[:-1], validity[1:]
                if vals.dtype.kind == "f":
                    eq = (prev_v == cur_v) | (np.isnan(prev_v.astype(float))
                                              & np.isnan(cur_v.astype(float)))
                else:
                    eq = prev_v == cur_v
                same = (prev_m == cur_m) & (eq | ~prev_m)
                b[1:] |= ~same
            return b

        if n == 0:
            from spark_rapids_tpu.exec.cpu import _empty_df
            return _empty_df(self.output_schema())

        part_cols = key_tuple(spec.partition_cols)
        order_cols = key_tuple([o.expr for o in spec.orders])
        part_b = boundaries(part_cols) if spec.partition_cols else \
            (np.arange(n) == 0)
        peer_b = part_b | boundaries(part_cols + order_cols)

        seg = np.cumsum(part_b) - 1
        peer = np.cumsum(peer_b) - 1

        def group_bound(ids, reduce_at, init):
            acc = np.full(ids.max() + 1, init, np.int64)
            reduce_at(acc, ids, pos)
            return acc[ids]

        seg_start = group_bound(seg, np.minimum.at, n)
        seg_end = group_bound(seg, np.maximum.at, -1)
        peer_end = group_bound(peer, np.maximum.at, -1)

        result_series = list(sdf.iloc[:, i] for i in range(len(cs)))
        for name, wexpr in self.window_exprs:
            desc, value_expr, _tpu_err = resolve_descriptor(wexpr, cs)
            if desc is None:
                raise NotImplementedError(_tpu_err)
            dt = wexpr.dtype(cs)
            if value_expr is not None:
                v, m, _ = host_unary_values(value_expr.eval_host(sdf))
                if value_expr.dtype(cs) == dtypes.DATE32 and \
                        v.dtype.kind != "O":
                    # host dates ride as midnight micros; window math and
                    # DATE32 result columns work in days like the device
                    v = v.astype(np.int64) // _MICROS_PER_DAY
            kind = desc[0]
            if kind == "row_number":
                data, validity = pos - seg_start + 1, np.ones(n, bool)
            elif kind == "rank":
                peer_start = group_bound(peer, np.minimum.at, n)
                data = peer_start - seg_start + 1
                validity = np.ones(n, bool)
            elif kind == "dense_rank":
                pb = np.cumsum(peer_b)
                data = pb - pb[seg_start] + 1
                validity = np.ones(n, bool)
            elif kind == "leadlag":
                off, default = desc[2], desc[4]
                src = pos + off
                ok = (src >= seg_start) & (src <= seg_end)
                src_c = np.clip(src, 0, n - 1)
                validity = ok & m[src_c]
                if default is not None:
                    if dt.is_datetime:  # device tags this off; oracle runs it
                        ns = pd.Timestamp(default).value
                        default = (ns // (_MICROS_PER_DAY * 1000)
                                   if dt == dtypes.DATE32 else ns // 1000)
                    data = np.where(ok, v[src_c], default)
                    validity = validity | ~ok
                else:
                    data = np.where(ok, v[src_c], np.zeros_like(v[src_c]))
            else:
                _, agg_kind, _, frame_kind, lo, hi, _ = desc
                mm = m.copy()
                range_bounded = is_bounded_range(frame_kind, lo, hi)
                if range_bounded:
                    ovv, ovm = order_cols[0]
                    if spec.orders[0].expr.dtype(cs) == dtypes.DATE32:
                        # offsets are DAYS for date order columns (device
                        # kernels see int32 days; host dates ride as micros)
                        ovv = ovv.astype(np.int64) // _MICROS_PER_DAY
                    f_lo, f_hi = _host_bounded_range_extents(
                        ovv, ovm, part_b, lo, hi,
                        spec.orders[0].ascending, seg_start, seg_end)
                elif frame_kind == "range":
                    f_lo, f_hi = seg_start, (
                        seg_end if hi >= UNBOUNDED_FOLLOWING else peer_end)
                else:
                    f_lo = (seg_start if lo <= UNBOUNDED_PRECEDING
                            else np.maximum(pos + lo, seg_start))
                    f_hi = (seg_end if hi >= UNBOUNDED_FOLLOWING
                            else np.minimum(pos + hi, seg_end))
                empty = f_hi < f_lo
                f_lo_c = np.clip(f_lo, 0, max(n - 1, 0))
                f_hi_c = np.clip(f_hi, -1, max(n - 1, 0))
                cnt_p = np.concatenate([[0], np.cumsum(mm.astype(np.int64))])
                fcount = np.where(empty, 0, cnt_p[f_hi_c + 1] - cnt_p[f_lo_c])
                if agg_kind == "count":
                    data, validity = fcount, np.ones(n, bool)
                elif agg_kind in ("sum", "avg"):
                    acc = np.where(mm, v, 0).astype(
                        np.float64 if (dt.is_floating or agg_kind == "avg")
                        else np.int64)
                    sp = np.concatenate([[0], np.cumsum(acc)])
                    s = np.where(empty, 0, sp[f_hi_c + 1] - sp[f_lo_c])
                    data = (s / np.maximum(fcount, 1) if agg_kind == "avg"
                            else s)
                    validity = fcount > 0
                elif v.dtype == object:  # string min/max
                    pick = min if agg_kind == "min" else max
                    data = np.empty(n, dtype=object)
                    validity = np.zeros(n, bool)
                    whole_ = (lo <= UNBOUNDED_PRECEDING
                              and hi >= UNBOUNDED_FOLLOWING)
                    if whole_:
                        sts = np.flatnonzero(part_b)
                        eds = np.r_[sts[1:] - 1, n - 1]
                        for s0, e0 in zip(sts, eds):
                            vals = [x for x, ok in
                                    zip(v[s0:e0 + 1], mm[s0:e0 + 1]) if ok]
                            if vals:
                                data[s0:e0 + 1] = pick(vals)
                                validity[s0:e0 + 1] = True
                    else:
                        # fallback-only shape (device handles whole
                        # frames): direct per-row frame reduction
                        for i in range(n):
                            if f_hi[i] >= f_lo[i]:
                                vals = [x for x, ok in zip(
                                    v[f_lo_c[i]:f_hi_c[i] + 1],
                                    mm[f_lo_c[i]:f_hi_c[i] + 1]) if ok]
                                if vals:
                                    data[i] = pick(vals)
                                    validity[i] = True
                else:  # min/max cumulative or whole partition
                    if v.dtype.kind == "f":
                        neutral = np.inf if agg_kind == "min" else -np.inf
                    elif v.dtype == np.bool_:
                        v = v.astype(np.int64)
                        neutral = 1 if agg_kind == "min" else 0
                    else:
                        ii = np.iinfo(v.dtype if v.dtype.kind in "iu"
                                      else np.int64)
                        neutral = ii.max if agg_kind == "min" else ii.min
                    pre = np.where(mm, v, neutral).astype(np.float64
                                                          if v.dtype.kind == "f"
                                                          else np.int64)
                    fn_ = np.minimum if agg_kind == "min" else np.maximum
                    whole = (lo <= UNBOUNDED_PRECEDING
                             and hi >= UNBOUNDED_FOLLOWING)
                    if whole or (frame_kind == "range"
                                 and not range_bounded):
                        scan = pre.copy()
                        for i in range(1, n):
                            if not part_b[i]:
                                scan[i] = fn_(scan[i - 1], scan[i])
                        data = (scan[seg_end] if whole
                                else scan[np.clip(peer_end, 0, n - 1)])
                    else:
                        # bounded ROW/RANGE frame: direct per-row reduction
                        red = np.min if agg_kind == "min" else np.max
                        data = np.full(n, neutral, pre.dtype)
                        for i in range(n):
                            if f_hi[i] >= f_lo[i]:
                                data[i] = red(pre[f_lo_c[i]:f_hi_c[i] + 1])
                    validity = fcount > 0
            if dt.is_string:
                out_arr = np.asarray(data, dtype=object)
                out_arr = np.where(np.asarray(validity), out_arr, None)
            else:
                out_arr = np.asarray(data).astype(dt.np_dtype, copy=False)
            result_series.append(_numpy_to_pandas(out_arr,
                                                  np.asarray(validity), dt))
        out_schema = self.output_schema()
        frame = pd.concat([s.reset_index(drop=True)
                           for s in result_series], axis=1)
        frame.columns = list(out_schema.names)
        return frame


class TpuWindowExec(PhysicalPlan):
    """Device window stage: one fused kernel over a single concatenated
    batch per partition (reference: GpuWindowExec requires the partition's
    batches coalesced the same way)."""

    columnar_output = True

    def __init__(self, child: PhysicalPlan,
                 window_exprs: List[Tuple[str, WindowExpression]]):
        super().__init__([child])
        self.window_exprs = list(window_exprs)

    def output_schema(self) -> Schema:
        cs = self.children[0].output_schema()
        names = list(cs.names) + [n for n, _ in self.window_exprs]
        dts = list(cs.dtypes) + [w.dtype(cs) for _, w in self.window_exprs]
        return Schema(names, dts)

    def describe(self) -> str:
        return f"TpuWindowExec([{', '.join(n for n, _ in self.window_exprs)}])"

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        from spark_rapids_tpu.exec.tpu import _concat_device
        from spark_rapids_tpu.ops.windowops import window_compute
        from spark_rapids_tpu.sql.exprs.evalbridge import (
            make_context, to_device_column,
        )
        from spark_rapids_tpu.utils.kernelcache import cached_jit, expr_signature

        cs = self.children[0].output_schema()
        out_schema = self.output_schema()
        spec = self.window_exprs[0][1].spec
        nc = len(cs)

        # resolve descriptors and collect value expressions
        descs, value_exprs = [], []
        for _, w in self.window_exprs:
            desc, vexpr, err = resolve_descriptor(w, cs)
            assert err is None, err
            if vexpr is not None:
                vidx = nc + len(spec.partition_cols) + len(spec.orders) \
                    + len(value_exprs)
                value_exprs.append(vexpr)
                if desc[0] == "leadlag":
                    desc = (desc[0], vidx) + desc[2:]
                else:
                    desc = desc[:2] + (vidx,) + desc[3:]
            descs.append(desc)
        descs = tuple(descs)
        part_idx = tuple(range(nc, nc + len(spec.partition_cols)))
        order_idx = tuple(range(nc + len(spec.partition_cols),
                                nc + len(spec.partition_cols)
                                + len(spec.orders)))
        order_asc = tuple(o.ascending for o in spec.orders)
        order_nf = tuple(o.nulls_first for o in spec.orders)
        extra = (list(spec.partition_cols)
                 + [o.expr for o in spec.orders] + value_exprs)

        def kernel(batch: DeviceBatch) -> DeviceBatch:
            ctx_ = make_context(batch)
            cols = list(batch.columns)
            names = list(batch.schema.names)
            dts = list(batch.schema.dtypes)
            for i, e in enumerate(extra):
                c = to_device_column(ctx_, e.eval_device(ctx_))
                cols.append(c)
                names.append(f"_w{i}")
                dts.append(c.dtype)
            work = DeviceBatch(Schema(names, dts), cols, batch.num_rows)
            return window_compute(work, nc, part_idx, order_idx, order_asc,
                                  order_nf, descs, out_schema)
        sig = ("window|" + "|".join(map(str, descs)) + "|"
               + "|".join(expr_signature(e) for e in extra))
        kern = cached_jit(sig, lambda: jax.jit(kernel))
        growth = ctx.conf.capacity_growth
        child_parts = self.children[0].executed_partitions(ctx)

        # string min/max columns come back as winner ROW INDICES plus the
        # sorted source column (a per-row string broadcast can exceed any
        # static char buffer) — finish them with a sized gather here
        str_specs = [i for i, d in enumerate(descs)
                     if d[0] == "agg" and d[1] in ("min", "max")
                     and d[-1] == "string"]

        def finalize(raw: DeviceBatch) -> DeviceBatch:
            if not str_specs:
                return raw
            import jax.numpy as jnp
            from spark_rapids_tpu.columnar.column import _char_bucket
            from spark_rapids_tpu.ops.rowops import gather_column
            k = len(str_specs)
            cols = list(raw.columns[:len(raw.columns) - k])
            srcs = raw.columns[len(raw.columns) - k:]
            idx_cols = [cols[nc + si] for si in str_specs]
            totals = jax.device_get([
                jnp.sum(jnp.where(
                    ic.validity,
                    (src.offsets[1:] - src.offsets[:-1])[ic.data], 0))
                for ic, src in zip(idx_cols, srcs)])
            for si, ic, src, tot in zip(str_specs, idx_cols, srcs, totals):
                cc = _char_bucket(int(tot))
                gk = cached_jit(f"wstrgather|{cc}", lambda cc=cc: jax.jit(
                    lambda c, w, vl: gather_column(
                        c, w, vl, out_char_capacity=cc)))
                cols[nc + si] = gk(src, ic.data, ic.validity)
            return DeviceBatch(out_schema, cols, raw.num_rows)

        def make(part: Partition) -> Partition:
            def run() -> Iterator[DeviceBatch]:
                batches = list(part())
                merged = _concat_device(batches, cs, growth)
                yield finalize(kern(merged))
            return run
        return [make(p) for p in child_parts]
