"""TPU columnar physical operators (the Gpu*Exec equivalents, L4).

Each operator's per-batch work is a single ``jax.jit``-compiled function
(cached per capacity bucket via pytree static aux data), so XLA fuses the
whole expression tree — and for aggregation the whole
hash/sort/segment-reduce pipeline — into one device executable. This is the
TPU-first improvement over the reference's one-cuDF-kernel-per-expression
dispatch (GpuExpressions.scala:98-149).
"""

from __future__ import annotations

import functools
from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import DeviceBatch, Schema, bucket_capacity
from spark_rapids_tpu.columnar.column import (
    DICT_MAX_CARD_SMALL, DICT_SMALL_TABLE_ROWS, _char_bucket,
)
from spark_rapids_tpu.exec.aggutil import AggPlan
from spark_rapids_tpu.exec.base import ExecContext, Partition, PhysicalPlan
from spark_rapids_tpu.ops import aggregate as agg_ops
from spark_rapids_tpu.ops import rowops, sortops
from spark_rapids_tpu.ops.groupby import row_hashes
from spark_rapids_tpu.utils.kernelcache import cached_jit, expr_signature
from spark_rapids_tpu.sql.exprs.core import Expression
from spark_rapids_tpu.sql.exprs.evalbridge import (
    eval_projection, make_context, to_device_column,
)
from spark_rapids_tpu.sql.functions import SortOrder


class TpuExec(PhysicalPlan):
    columnar_output = True

    def output_schema(self) -> Schema:
        raise NotImplementedError


def _concat_device(batches: List[DeviceBatch], schema: Schema,
                   growth: float, keep_masks=None,
                   coarse: bool = False) -> DeviceBatch:
    """Concatenate device batches (GpuCoalesceBatches / ConcatAndConsumeAll,
    GpuCoalesceBatches.scala:38-165). ``keep_masks``: per-batch keep
    vectors of a fused Filter (see _fused_filter_source). ``coarse``:
    pad the output capacity up the shape-bucket ladder
    (utils/kernelcache.bucket_dim) — used for SECONDARY-dimension
    materializations (join build tables, broadcast tables, fused
    count-distinct inputs) so one downstream compile serves a capacity
    range; identity while spark.rapids.tpu.compile.shapeBuckets is off."""
    if len(batches) == 1 and keep_masks is None:
        if coarse:
            from spark_rapids_tpu.utils.kernelcache import bucket_dim
            if bucket_dim(batches[0].capacity) == batches[0].capacity:
                return batches[0]
            # single-batch build tables still re-pad to the coarse
            # bucket: the point is a STABLE downstream capacity
        else:
            return batches[0]
    if not batches:
        return DeviceBatch.empty(schema)
    # mesh execution commits batches to their shard device; a concat that
    # spans shards (single-partition exchange, broadcast materialization)
    # must colocate first or the jit below rejects the device mix
    # colocation check via validity — NEVER .data: a lazy (codes-only)
    # string column would materialize its chars eagerly right here,
    # measured as 6 spurious device round trips per q1 run
    devs = {b.columns[0].validity.device for b in batches if b.columns}
    if len(devs) > 1:
        target = batches[0].columns[0].validity.device
        batches = [jax.device_put(b, target) for b in batches]
        if keep_masks is not None:
            keep_masks = [jax.device_put(k, target) for k in keep_masks]
    total_cap = sum(b.capacity for b in batches)
    out_cap = bucket_capacity(total_cap, growth)
    if coarse:
        from spark_rapids_tpu.utils.kernelcache import bucket_dim
        out_cap = bucket_dim(out_cap)
    # one generic jitted concat kernel; jax re-specializes per pytree shape.
    # char capacity 0 = per-column sum computed inside concat_batches.
    # dict-merge (union+remap at the boundary) changes the OUTPUT
    # representation for mixed-dictionary inputs, so the flag is part of
    # the kernel-cache signature — flipping
    # spark.rapids.sql.dict.mergeOnExchange mid-process cannot serve a
    # stale trace.
    from spark_rapids_tpu.columnar.dictionary import merge_exchange_enabled
    # NB: bind the flag as a default arg, not a closure — this frame
    # reuses the name ``dm`` below for the device manager, and a closure
    # over a reassigned local would silently flip the merge behavior on
    # every re-trace of the cached kernel
    dmerge = merge_exchange_enabled()
    if keep_masks is None:
        kernel = cached_jit(f"concat|dm{int(dmerge)}", lambda: jax.jit(
            lambda bs, oc, cc, _dm=dmerge: rowops.concat_batches(
                bs, oc, cc, dict_merge=_dm), static_argnums=(1, 2)))
        out = kernel(batches, out_cap, 0)
    else:
        kernel = cached_jit(f"concatmask|dm{int(dmerge)}", lambda: jax.jit(
            lambda bs, ks, oc, cc, _dm=dmerge: rowops.concat_batches(
                bs, oc, cc, keep_masks=ks, dict_merge=_dm),
            static_argnums=(2, 3)))
        out = kernel(batches, list(keep_masks), out_cap, 0)
    from spark_rapids_tpu.memory.device import TpuDeviceManager
    dm = TpuDeviceManager.current()
    if dm is not None:
        dm.meter_batch(out)
    return out


def _fused_filter_source(node: PhysicalPlan, ctx: ExecContext):
    """(source node, mask kernel, out_sel) for the exchange/broadcast
    collapse concat: a deterministic TpuFilterExec directly below folds
    its predicate into the concat's single compaction gather instead of
    paying per-batch per-column compaction gathers (~5M rows/s on TPU) —
    the exchange-side sibling of fuse_filter_into_aggregate
    (exec/fusion.py). ``out_sel`` is the filter's fused output selection
    (fuse_selection_into_filter); the caller applies it as a zero-copy
    column view before the concat. Returns (node, None, None) when
    nothing fuses. NB the whole-stage cutter mirrors this claim
    (exec/stagecompiler/cutter._parent_claims_filter) and leaves the
    claimed filter out of fused pipelines — changes to the conditions
    here must be reflected there."""
    from spark_rapids_tpu.exec.coalesce import TpuCoalesceBatchesExec
    if isinstance(node, TpuCoalesceBatchesExec):
        # the collapse concat coalesces everything anyway — a TargetSize
        # re-batching between the filter and the exchange is a no-op on
        # this path, and looking through it is what lets the filter fuse
        # (the planner inserts Coalesce above every filter; without this
        # q12's 3M-row filter pays its own per-column compaction gather,
        # measured 1.16s exclusive vs the fused concat's single gather)
        node = node.children[0]
    if (isinstance(node, TpuFilterExec) and not node._impure
            and ctx.conf.get_bool(
                "spark.rapids.sql.exchange.fuseFilter", True)):
        cond = node.condition
        sig = "filtermask|" + expr_signature(cond)

        def build():
            def mask(batch: DeviceBatch):
                ectx = make_context(batch)
                pred = to_device_column(ectx, cond.eval_device(ectx))
                return pred.data & pred.validity & batch.row_mask()
            return jax.jit(mask)
        return node.children[0], cached_jit(sig, build), node.out_sel
    return node, None, None


def _select_view(batch: DeviceBatch, out_sel) -> DeviceBatch:
    """Zero-copy column selection (no device op)."""
    if out_sel is None:
        return batch
    names, idx = out_sel
    return DeviceBatch(
        Schema(list(names), [batch.schema.dtypes[i] for i in idx]),
        [batch.columns[i] for i in idx], batch.num_rows)


def _split_by_pid(batch: DeviceBatch, pid: jnp.ndarray, n: int):
    """Sort rows by partition id (dead rows to the back) and count per-pid
    rows — the contiguous-split analogue (GpuPartitioning.scala:41-75)."""
    pid = jnp.where(batch.row_mask(), pid, n)
    perm = jnp.argsort(pid, stable=True).astype(jnp.int32)
    sorted_batch = rowops.gather_batch(batch, perm, batch.num_rows)
    counts = jnp.zeros((n,), jnp.int32).at[
        jnp.clip(pid, 0, n - 1)].add(jnp.where(pid < n, 1, 0))
    return sorted_batch, counts


class TpuProjectExec(TpuExec):
    """reference: GpuProjectExec (basicPhysicalOperators.scala:65)."""

    def __init__(self, child: PhysicalPlan,
                 exprs: Sequence[Tuple[str, Expression]]):
        super().__init__([child])
        self.exprs = list(exprs)
        names = [n for n, _ in self.exprs]
        bound = [e for _, e in self.exprs]
        from spark_rapids_tpu.sql.exprs.nondet import has_nondeterministic
        self._impure = any(has_nondeterministic(e) for e in bound)
        from spark_rapids_tpu.sql.exprs.core import Alias, BoundRef

        def as_ref(e):
            """The BoundRef behind (possibly aliased) e, else None."""
            while isinstance(e, Alias):
                e = e.children[0]
            return e if isinstance(e, BoundRef) else None

        self._pure_selection = (not self._impure and all(
            as_ref(e) is not None for e in bound))
        if self._pure_selection:
            # selection/rename-only projection: re-arrange the COLUMN
            # OBJECTS, no device work at all. A jitted identity kernel
            # would copy every buffer (jit outputs are fresh buffers
            # unless donated) — measured 0.39s PER narrowing project on a
            # 2M-row join chain (q7 carries three of them).
            sel = (tuple(names), tuple(as_ref(e).index for e in bound))
            self._kernel = lambda batch: _select_view(batch, sel)
        elif self._impure:
            # nondeterministic exprs read task-local state (partition id,
            # row offset, input file) that must be current at call time, so
            # the projection is traced eagerly per batch instead of through
            # the process-wide kernel cache (the reference similarly special
            # cases these, GpuTransitionOverrides.scala:110-123).
            self._kernel = lambda batch: eval_projection(batch, bound, names)
        elif any(as_ref(e) is not None for e in bound):
            # mixed projection: jit computes ONLY the derived outputs;
            # bare-reference outputs pass their column objects through
            # untouched (the jitted identity would copy their buffers)
            comp = [(n, e) for n, e in self.exprs if as_ref(e) is None]
            sig = "projectmix|" + "|".join(
                f"{n}={expr_signature(e)}" for n, e in comp)
            ckern = cached_jit(sig, lambda: jax.jit(
                lambda batch: eval_projection(
                    batch, [e for _n, e in comp],
                    [n for n, _e in comp])))

            def mixed_kernel(batch: DeviceBatch) -> DeviceBatch:
                computed = ckern(batch)
                out_cols = []
                ci = 0
                for _n, e in self.exprs:
                    ref = as_ref(e)
                    if ref is not None:
                        out_cols.append(batch.columns[ref.index])
                    else:
                        out_cols.append(computed.columns[ci])
                        ci += 1
                return DeviceBatch(
                    Schema(names, [c.dtype for c in out_cols]),
                    out_cols, batch.num_rows)
            self._kernel = mixed_kernel
        else:
            sig = "project|" + "|".join(
                f"{n}={expr_signature(e)}" for n, e in self.exprs)
            self._kernel = cached_jit(sig, lambda: jax.jit(
                lambda batch: eval_projection(batch, bound, names)))

    def output_schema(self) -> Schema:
        cs = self.children[0].output_schema()
        return Schema([n for n, _ in self.exprs],
                      [e.dtype(cs) for _, e in self.exprs])

    def describe(self) -> str:
        return f"TpuProjectExec([{', '.join(n for n, _ in self.exprs)}])"

    def fingerprint_extra(self) -> str:
        from spark_rapids_tpu.utils.kernelcache import expr_signature
        return ";".join(expr_signature(e) for _, e in self.exprs)

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        from spark_rapids_tpu.exec import taskctx
        child_parts = self.children[0].executed_partitions(ctx)

        def make(index: int, part: Partition) -> Partition:
            def run() -> Iterator[DeviceBatch]:
                seen = 0
                for batch in part():
                    if self._impure:
                        taskctx.set_partition(index)
                        taskctx.set_row_base(seen)
                        seen += batch.num_rows_host()
                    yield self._kernel(batch)
            return run
        return [make(i, p) for i, p in enumerate(child_parts)]


class TpuFilterExec(TpuExec):
    """reference: GpuFilterExec (basicPhysicalOperators.scala:126).

    ``out_sel``: optional (names, indices) output selection fused from a
    pure-column Project above (exec/fusion.py fuse_selection_into_filter):
    the predicate evaluates over the FULL input, but the row compaction
    gathers ONLY the selected columns — predicate-only columns (string
    slabs especially) are never moved."""

    def __init__(self, child: PhysicalPlan, condition: Expression,
                 out_sel=None):
        super().__init__([child])
        self.condition = condition
        self.out_sel = out_sel

        def kernel(batch: DeviceBatch) -> DeviceBatch:
            ctx = make_context(batch)
            pred = to_device_column(ctx, condition.eval_device(ctx))
            keep = pred.data & pred.validity
            return rowops.filter_batch(_select_view(batch, out_sel), keep)
        # the un-jitted closure: whole-stage fusion traces it INSIDE the
        # fused program (exec/stagecompiler/fusedexec.member_fn), so the
        # fused and standalone spellings can never diverge
        self._raw_kernel = kernel
        from spark_rapids_tpu.sql.exprs.nondet import has_nondeterministic
        self._impure = has_nondeterministic(condition)
        if self._impure:
            # see TpuProjectExec: task-local state must be read at call time
            self._kernel = kernel
        else:
            # names participate in the cache key: the closure bakes the
            # output Schema, so an aliased selection must not hit a
            # same-ordinal kernel compiled under different names
            sel_sig = ("" if out_sel is None
                       else f"|sel={tuple(out_sel[1])}"
                            f":{','.join(out_sel[0])}")
            sig = "filter|" + expr_signature(condition) + sel_sig
            self._kernel = cached_jit(sig, lambda: jax.jit(kernel))

    def output_schema(self) -> Schema:
        cs = self.children[0].output_schema()
        if self.out_sel is None:
            return cs
        names, idx = self.out_sel
        return Schema(list(names), [cs.dtypes[i] for i in idx])

    def describe(self) -> str:
        sel = ("" if self.out_sel is None
               else f", sel={list(self.out_sel[0])}")
        return f"TpuFilterExec({self.condition!r}{sel})"

    def fingerprint_extra(self) -> str:
        # expr repr prints only class name + children for many nodes
        # (startswith('a') vs startswith('b') collide); the signature
        # serializes every instance attribute
        from spark_rapids_tpu.utils.kernelcache import expr_signature
        return expr_signature(self.condition)

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        from spark_rapids_tpu.exec import taskctx
        child_parts = self.children[0].executed_partitions(ctx)

        def make(index: int, part: Partition) -> Partition:
            def run() -> Iterator[DeviceBatch]:
                seen = 0
                for batch in part():
                    if self._impure:
                        taskctx.set_partition(index)
                        taskctx.set_row_base(seen)
                        seen += batch.num_rows_host()
                    yield self._kernel(batch)
            return run
        return [make(i, p) for i, p in enumerate(child_parts)]


class TpuHashAggregateExec(TpuExec):
    """reference: GpuHashAggregateExec (aggregate.scala:227-509). Streaming
    per-batch update, then concat + merge of the (small) partial results —
    the reference's exact loop shape, each step one fused XLA program."""

    def __init__(self, child: PhysicalPlan, plan: AggPlan, mode: str,
                 pre_mask: Optional[Expression] = None):
        super().__init__([child])
        self.plan = plan
        self.mode = mode
        # fused pre-filter predicate (exec/fusion.py): evaluated inside the
        # update kernel, replacing a standalone Filter's compaction gathers
        self.pre_mask = pre_mask
        p = self.plan
        if mode == "partial":
            key_exprs = [e for _, e in p.grouping]
            reductions = []
            for ops in p.update_plan:
                for kind, input_idx, idt in ops:
                    reductions.append((kind, input_idx, idt))
            mask_sig = ("|mask=" + expr_signature(pre_mask)
                        if pre_mask is not None else "")
            self._kernel = cached_jit(
                "aggupd|" + p.signature + mask_sig,
                lambda: jax.jit(lambda b: agg_ops.aggregate_update(
                    b, key_exprs, p.update_inputs, reductions,
                    p.partial_schema, mask_expr=pre_mask)))
            # bounded-int composite grouping key variant (advisory scan
            # stats resolved at partitions() time; the ONLY compiled
            # grouping path — a miss re-executes via the deferred
            # speculation verification, ops/aggregate.dense_composite)
            self._dense_update = lambda sizes: cached_jit(
                f"aggupd|{p.signature}{mask_sig}|dense{sizes}",
                lambda: jax.jit(lambda b, los: agg_ops.aggregate_update(
                    b, key_exprs, p.update_inputs, reductions,
                    p.partial_schema, mask_expr=pre_mask,
                    dense=(los, sizes))))
            # one-pass hash-aggregation variant (spark.rapids.sql.agg.
            # hashAggEnabled): same program, the slot-table branch armed
            # with its slot budget — _hash_payload_reduce declines at
            # TRACE time where inapplicable, so this kernel is safe for
            # any batch
            self._hash_update = lambda mt: cached_jit(
                f"aggupd|{p.signature}{mask_sig}|hash{mt}",
                lambda: jax.jit(lambda b: agg_ops.aggregate_update(
                    b, key_exprs, p.update_inputs, reductions,
                    p.partial_schema, mask_expr=pre_mask, hash_table=mt)))
            # adaptive low-reduction skip: rows projected straight into the
            # partial layout (spark.rapids.sql.agg.skipAggPassReductionRatio)
            self._passthrough_kernel = cached_jit(
                "aggpass|" + p.signature + mask_sig,
                lambda: jax.jit(lambda b: agg_ops.aggregate_passthrough(
                    b, key_exprs, p.update_inputs, reductions,
                    p.partial_schema, mask_expr=pre_mask)))
            # merging partials within the partition uses merge kinds
            self._merge_kernel = self._make_merge_kernel()
        else:
            self._merge_kernel = self._make_merge_kernel()
            final_exprs = p.finalize_exprs()
            names = [n for n, _ in final_exprs]
            bound = [e for _, e in final_exprs]
            self._final_kernel = cached_jit(
                "aggfin|" + p.signature,
                lambda: jax.jit(lambda b: eval_projection(b, bound, names)))

    def _make_merge_kernel(self):
        p = self.plan
        reductions = []
        for merged in p.merge_plan:
            for kind, col, idt in merged:
                reductions.append((kind, col, idt))
        self._dense_merge = lambda sizes: cached_jit(
            f"aggmrg|{p.signature}|dense{sizes}",
            lambda: jax.jit(lambda b, los: agg_ops.aggregate_merge(
                b, p.num_keys, reductions, p.partial_schema,
                dense=(los, sizes))))
        self._hash_merge = lambda mt: cached_jit(
            f"aggmrg|{p.signature}|hash{mt}",
            lambda: jax.jit(lambda b: agg_ops.aggregate_merge(
                b, p.num_keys, reductions, p.partial_schema,
                hash_table=mt)))
        return cached_jit(
            "aggmrg|" + p.signature,
            lambda: jax.jit(lambda b: agg_ops.aggregate_merge(
                b, p.num_keys, reductions, p.partial_schema)))

    def _dense_group_plan(self, ctx: ExecContext):
        """(los list, sizes tuple, spec_key) for the bounded-int composite
        grouping key, or None (non-int keys, unresolvable stats, >62
        bits, speculation off, or blocklisted after a verification miss).
        The dense program is the ONLY compiled grouping path; the
        device-computed ok flag joins the deferred speculation
        verification and a miss re-executes without dense (and
        blocklists this plan so chronically-stale stats do not re-run
        every execution)."""
        if (ctx.session is None or not getattr(ctx, "speculate", False)
                or not ctx.conf.get_bool(
                    "spark.rapids.sql.agg.denseKeys", True)):
            return None
        p = self.plan
        if p.num_keys == 0:
            return None
        from spark_rapids_tpu.exec.statsutil import dense_group_plan
        from spark_rapids_tpu.sql.exprs.core import BoundRef
        key_names, key_dts = [], []
        if self.mode == "partial":
            cs = p.child_schema
            for name, e in p.grouping:
                if not isinstance(e, BoundRef):
                    return None
                names = {name}
                if 0 <= e.index < len(cs.names):
                    names.add(cs.names[e.index])
                key_names.append(names)
                key_dts.append(cs.dtypes[e.index])
        else:
            ps = p.partial_schema
            for j in range(p.num_keys):
                key_names.append({ps.names[j]})
                key_dts.append(ps.dtypes[j])
        from spark_rapids_tpu.exec.base import plan_fingerprint
        fp = plan_fingerprint(self)
        # dense only engages for a plan the session has EXECUTED before:
        # on a first execution the scan stats may not cover this upload
        # yet (they record as batches stream, after planning), and a
        # guaranteed-stale speculation would re-execute the query
        seen = ctx.session.dense_plans_seen
        if fp not in seen:
            seen.add(fp)
            return None
        got = dense_group_plan(ctx.session, key_names, key_dts)
        if got is None:
            return None
        skey = f"nocache|densegroup|{fp}|{got[1]}"
        if skey in ctx.session.capacity_spec_blocklist:
            return None
        return got[0], got[1], skey

    def output_schema(self) -> Schema:
        return (self.plan.partial_schema if self.mode == "partial"
                else self.plan.output_schema)

    def describe(self) -> str:
        keys = ", ".join(n for n, _ in self.plan.grouping)
        fused = (f", fused_filter={self.pre_mask!r}"
                 if self.pre_mask is not None else "")
        return f"TpuHashAggregateExec(mode={self.mode}, keys=[{keys}]{fused})"

    def fingerprint_extra(self) -> str:
        extra = ""
        if self.pre_mask is not None:
            from spark_rapids_tpu.utils.kernelcache import expr_signature
            extra = "|mask:" + expr_signature(self.pre_mask)
        return self.plan.signature + extra

    # batches sampled before an undecided signature commits to the
    # update path: bounds the row-count syncs a first execution pays
    _SKIP_SAMPLE_BATCHES = 3

    def _runtime_partial(self, ctx, it, first, update_kernel, merge_kernel,
                         cache, sig, adaptive, prior, skip_ratio, growth):
        """Runtime partial-aggregation skip (spark.rapids.sql.agg.
        runtimeSkip): the partial pass measures output_groups/input_rows
        as batches stream and flips to passthrough MID-STREAM once the
        cumulative ratio exceeds the threshold — already-updated partials
        flush as-is (the final aggregate reduces any mix of grouped and
        passthrough layouts). Decisions are journaled (aggSkipDecision,
        carrying the measured rate) and recorded in the session ratio
        cache either way, so later executions decide from batch 0 with
        zero syncs; capacity-shrunk outputs prove strong reduction
        without any sync and are never recorded (the bounded-cardinality
        paths, matching the legacy heuristic)."""
        from spark_rapids_tpu.obs.events import EVENTS
        partials = []
        # a recorded good ratio short-circuits measurement entirely
        decided = "update" if (not adaptive or prior is not None) else None
        in_rows = out_rows = sampled = 0
        b = first
        while b is not None:
            if decided == "skip":
                yield self._passthrough_kernel(b)
                b = next(it, None)
                continue
            p = update_kernel(b)
            partials.append(p)
            if decided is None:
                if p.capacity < b.capacity:
                    decided = "update"
                else:
                    from spark_rapids_tpu.obs.syncledger import sync_scope
                    with sync_scope("agg.runtimeSkip",
                                    detail=f"batch={sampled}"):
                        out_rows += p.num_rows_host()
                    in_rows += b.num_rows_hint()
                    sampled += 1
                    measured = out_rows / max(in_rows, 1)
                    if measured > skip_ratio:
                        decided = "skip"
                        cache[sig] = [measured, 0]
                        ctx.ratio_writes.append(sig)
                        EVENTS.emit("aggSkipDecision", decision="skip",
                                    source="measured",
                                    measuredRatio=float(measured),
                                    batches=sampled, threshold=skip_ratio)
                        for pp in partials:
                            yield pp
                        partials = []
                    elif sampled >= self._SKIP_SAMPLE_BATCHES:
                        decided = "update"
                        cache[sig] = [measured, 0]
                        ctx.ratio_writes.append(sig)
                        EVENTS.emit("aggSkipDecision", decision="update",
                                    source="measured",
                                    measuredRatio=float(measured),
                                    batches=sampled, threshold=skip_ratio)
            b = next(it, None)
        if decided is None and sampled > 0:
            # stream ended while still sampling (short partitions): the
            # cumulative measurement is the signature's decision —
            # recorded so later executions decide from batch 0 with no
            # syncs (the legacy heuristic's single-batch learning)
            measured = out_rows / max(in_rows, 1)
            cache[sig] = [measured, 0]
            ctx.ratio_writes.append(sig)
            EVENTS.emit("aggSkipDecision", decision="update",
                        source="measured", measuredRatio=float(measured),
                        batches=sampled, threshold=skip_ratio)
        if len(partials) == 1:
            yield partials[0]
        elif partials:
            merged = _concat_device(partials, self.plan.partial_schema,
                                    growth)
            yield merge_kernel(merged)

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        child_parts = self.children[0].executed_partitions(ctx)
        growth = ctx.conf.capacity_growth

        from spark_rapids_tpu.config.conf import (
            AGG_HASH_ENABLED, AGG_HASH_MAX_SLOTS, AGG_RUNTIME_SKIP,
            AGG_SKIP_RATIO,
        )
        skip_ratio = float(ctx.conf.get(AGG_SKIP_RATIO.key))
        runtime_skip = ctx.conf.get_bool(AGG_RUNTIME_SKIP.key, True)
        hash_on = ctx.conf.get_bool(AGG_HASH_ENABLED.key, False)
        max_slots = int(ctx.conf.get(AGG_HASH_MAX_SLOTS.key))

        dense = self._dense_group_plan(ctx)
        # dense keys outrank the hash table (exact composite key, fewer
        # sort operands); hash engages exactly where dense cannot
        use_hash = hash_on and self.plan.num_keys > 0 and dense is None
        if dense is not None:
            los_arr = jnp.asarray(dense[0], jnp.int64)
            sizes, skey = dense[1], dense[2]

            def _register(ok) -> None:
                from spark_rapids_tpu.exec.tpujoin import _start_host_copies
                _start_host_copies([ok])
                ctx.spec_pending.append((skey, [], [], [ok], None))

            dmerge = self._dense_merge(sizes)

            def merge_kernel(b):
                out, ok = dmerge(b, los_arr)
                _register(ok)
                return out
            if self.mode == "partial":
                dupd = self._dense_update(sizes)

                def update_kernel(b):
                    out, ok = dupd(b, los_arr)
                    _register(ok)
                    return out
            else:
                update_kernel = None
        elif use_hash:
            merge_kernel = self._hash_merge(max_slots)
            update_kernel = (self._hash_update(max_slots)
                             if self.mode == "partial" else None)
        else:
            merge_kernel = self._merge_kernel
            update_kernel = self._kernel if self.mode == "partial" else None

        # VMEM-bound recursed bucketing: a batch whose slot table would
        # exceed maxTableSlots splits by key hash into in-budget slices
        # (disjoint key sets), each aggregates in-VMEM, and the slices'
        # partial outputs concatenate back into ONE valid partial batch
        # (no cross-slice merge needed — no key spans two slices). Only
        # column-reference grouping keys can drive the input-batch
        # partitioner; expression keys keep the in-trace sorted fallback.
        hash_split_idx = None
        if use_hash and self.mode == "partial":
            from spark_rapids_tpu.sql.exprs.core import BoundRef
            if all(isinstance(e, BoundRef) for _, e in self.plan.grouping):
                hash_split_idx = [e.index for _, e in self.plan.grouping]

        if hash_split_idx is not None and update_kernel is not None:
            from spark_rapids_tpu.exec import outofcore as ooc
            from spark_rapids_tpu.ops import pallas_kernels as pk
            base_update = update_kernel

            def _bucketed_update(b, level=0):
                if (level >= 3
                        or pk.hash_table_size(b.capacity) <= max_slots):
                    return base_update(b)
                need = -(-pk.hash_table_size(b.capacity) // max_slots)
                n = 2
                while n < 2 * need and n < 64:
                    n <<= 1
                parts = [_bucketed_update(s, level + 1)
                         for s in ooc.split_batch_by_hash(
                             ctx, hash_split_idx, b, n, level, growth)]
                if not parts:
                    return base_update(b)
                return _concat_device(parts, self.plan.partial_schema,
                                      growth)
            update_kernel = _bucketed_update

        def make(part: Partition) -> Partition:
            def run() -> Iterator[DeviceBatch]:
                # out-of-core: a grouped aggregate whose input exceeds
                # the budget hash-partitions its partial-layout batches
                # onto the spill store and merges bucket by bucket
                # (disjoint key sets; exec/outofcore.py)
                from spark_rapids_tpu.exec import outofcore as ooc
                src = part
                if ooc.enabled_for(ctx) and self.plan.num_keys > 0:
                    # streaming probe: never materializes past the
                    # budget — on engagement the unconsumed tail flows
                    # straight into the grace driver's staging pass
                    prefix, rest, engaged = ooc.split_stream_on_budget(
                        ctx, iter(part()))
                    if engaged:
                        import itertools
                        yield from ooc.grace_aggregate(
                            ctx, self, itertools.chain(prefix, rest),
                            growth)
                        return
                    src = lambda ob=prefix: iter(ob)  # noqa: E731
                if self.mode == "partial":
                    it = iter(src())
                    first = next(it, None)
                    if first is None:
                        yield self._kernel(DeviceBatch.empty(
                            self.children[0].output_schema()))
                        return
                    # adaptive statistics (Spark-AQE-style): the session
                    # remembers each aggregate's observed reduction
                    # ratio; a known-poor reducer skips its partial pass
                    # from batch 0 — including single-batch partitions,
                    # where the ratio is otherwise only learnable AFTER
                    # paying the full pass. Keyed on the PLAN FINGERPRINT
                    # (data-uid-stamped, exec/base.py): a different data
                    # source mints a different key, so entries never need
                    # a use-count expiry — the old structural-signature
                    # key's periodic expiry flipped the skip decision in
                    # steady state, changing batch shapes downstream and
                    # forcing a retrace in the bench's timed window.
                    cache = getattr(ctx.session, "agg_ratio_cache", None) \
                        if ctx.session else None
                    from spark_rapids_tpu.exec.base import plan_fingerprint
                    sig = plan_fingerprint(self) + "|ratio"
                    adaptive = (skip_ratio < 1.0 and cache is not None
                                and self.plan.num_keys > 0)
                    prior = None
                    if adaptive and sig in cache:
                        ratio_known, uses = cache[sig]
                        prior = ratio_known
                        if ratio_known > skip_ratio:
                            cache[sig][1] = uses + 1
                            if runtime_skip:
                                from spark_rapids_tpu.obs.events import (
                                    EVENTS,
                                )
                                EVENTS.emit(
                                    "aggSkipDecision", decision="skip",
                                    source="cache",
                                    measuredRatio=float(ratio_known),
                                    threshold=skip_ratio)
                            yield self._passthrough_kernel(first)
                            for b in it:
                                yield self._passthrough_kernel(b)
                            return
                    if runtime_skip:
                        # AQE-style runtime decision from measured
                        # per-batch reduction rates (spark.rapids.sql.
                        # agg.runtimeSkip); false restores the legacy
                        # first-batch-only heuristic below
                        yield from self._runtime_partial(
                            ctx, it, first, update_kernel, merge_kernel,
                            cache, sig, adaptive, prior, skip_ratio,
                            growth)
                        return
                    p0 = update_kernel(first)
                    second = next(it, None)
                    # learn the ratio (one row-count sync, first execution
                    # of a signature only) whenever the partial kept its
                    # input capacity — the bounded-cardinality paths
                    # shrink it, proving heavy reduction without a round
                    # trip
                    ratio = None
                    if (adaptive and sig not in cache
                            and p0.capacity >= first.capacity):
                        ratio = (p0.num_rows_host()
                                 / max(first.num_rows_hint(), 1))
                        cache[sig] = [ratio, 0]
                        ctx.ratio_writes.append(sig)
                    if second is None:
                        yield p0
                        return
                    # adaptive skip: the first batch's pass barely reduced
                    # -> project the remaining batches straight into the
                    # partial layout and let the final aggregate reduce
                    # once; on a single chip the exchange is a local
                    # concat, so a low-reduction partial pass is pure cost
                    if ratio is not None and ratio > skip_ratio:
                        yield p0
                        while second is not None:
                            yield self._passthrough_kernel(second)
                            second = next(it, None)
                        return
                    partials = [p0, update_kernel(second)]
                    partials.extend(update_kernel(b) for b in it)
                    merged = _concat_device(partials, self.plan.partial_schema,
                                            growth)
                    yield merge_kernel(merged)
                    return
                batches = list(src())
                merged_in = _concat_device(batches, self.plan.partial_schema,
                                           growth)
                merged = merge_kernel(merged_in)
                yield self._final_kernel(merged)
            return run
        return [make(p) for p in child_parts]


class TpuSortExec(TpuExec):
    """reference: GpuSortExec (GpuSortExec.scala:50-253) — RequireSingleBatch
    global sort: concat partition batches, one fused device sort."""

    def __init__(self, child: PhysicalPlan, orders: Sequence[SortOrder]):
        super().__init__([child])
        self.orders = list(orders)

        def kernel(batch: DeviceBatch) -> DeviceBatch:
            work, key_idx = self._key_batch(batch)
            sorted_b = sortops.sort_batch(
                work, key_idx,
                [o.ascending for o in self.orders],
                [o.nulls_first for o in self.orders])
            # drop appended key columns
            ncols = len(batch.schema.names)
            return DeviceBatch(batch.schema, sorted_b.columns[:ncols],
                               sorted_b.num_rows)
        sig = "sort|" + "|".join(
            f"{expr_signature(o.expr)}:{o.ascending}:{o.nulls_first}"
            for o in self.orders)
        self._kernel = cached_jit(sig, lambda: jax.jit(kernel))

    def _key_batch(self, batch: DeviceBatch):
        """Append evaluated sort-key expressions as extra columns."""
        ctx = make_context(batch)
        cols = list(batch.columns)
        names = list(batch.schema.names)
        dts = list(batch.schema.dtypes)
        key_idx = []
        for i, o in enumerate(self.orders):
            c = to_device_column(ctx, o.expr.eval_device(ctx))
            cols.append(c)
            names.append(f"_sk{i}")
            dts.append(c.dtype)
            key_idx.append(len(cols) - 1)
        return DeviceBatch(Schema(names, dts), cols, batch.num_rows), key_idx

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def describe(self) -> str:
        return f"TpuSortExec({self.orders})"

    def fingerprint_extra(self) -> str:
        from spark_rapids_tpu.utils.kernelcache import expr_signature
        return ";".join(
            f"{expr_signature(o.expr)}|a{int(o.ascending)}"
            f"|n{int(o.nulls_first)}" for o in self.orders)

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        child_parts = self.children[0].executed_partitions(ctx)
        growth = ctx.conf.capacity_growth
        schema = self.output_schema()

        def make(part: Partition) -> Partition:
            def run() -> Iterator[DeviceBatch]:
                # out-of-core: a working set past the budget range-
                # partitions onto the spill store and sorts bucket by
                # bucket (external merge sort, exec/outofcore.py). The
                # probe streams — the input is never fully materialized
                # past the budget.
                from spark_rapids_tpu.exec import outofcore as ooc
                if ooc.enabled_for(ctx):
                    prefix, rest, engaged = ooc.split_stream_on_budget(
                        ctx, iter(part()))
                    if engaged:
                        import itertools
                        yield from ooc.external_sort(
                            ctx, self, itertools.chain(prefix, rest),
                            schema, growth)
                        return
                    batches = prefix
                else:
                    batches = list(part())
                merged = _concat_device(batches, schema, growth)
                yield self._kernel(merged)
            return run
        return [make(p) for p in child_parts]


class TpuLocalLimitExec(TpuExec):
    """reference: GpuLocalLimitExec / GpuGlobalLimitExec (limit.scala).

    ``remaining`` stays a device scalar threaded through one fused
    slice-and-decrement kernel per batch — the per-batch row-count readback
    the round-1 version paid (a full device->host round trip each) is gone.
    Later batches past the limit yield empty slices instead of breaking
    the loop; on a high-latency attachment the extra enqueues are far
    cheaper than one sync."""

    def __init__(self, child: PhysicalPlan, limit: int):
        super().__init__([child])
        self.limit = limit

        def step(b, remaining):
            out = rowops.slice_batch(b, jnp.asarray(0, jnp.int32), remaining)
            return out, remaining - out.num_rows
        self._kernel = cached_jit("limitstep", lambda: jax.jit(step))

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        child_parts = self.children[0].executed_partitions(ctx)

        def make(part: Partition) -> Partition:
            def run() -> Iterator[DeviceBatch]:
                import numpy as np
                remaining = np.asarray(self.limit, np.int32)
                # early-exit check every 8 batches: one round trip per 8
                # upstream batches at most, instead of either one per batch
                # (round 1) or none at all (which would drain an unbounded
                # upstream under LIMIT k)
                for i, batch in enumerate(part()):
                    if (i + 1) % 8 == 0 and int(remaining) <= 0:
                        break
                    out, remaining = self._kernel(batch, remaining)
                    yield out
            return run
        return [make(p) for p in child_parts]


class TpuGlobalLimitExec(TpuLocalLimitExec):
    pass


class TpuCollectLimitExec(TpuLocalLimitExec):
    """Root-position limit (reference: GpuCollectLimitExec,
    GpuOverrides.scala:1641-1643): one output partition draining children
    in order with the device-scalar remaining count."""

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        child_parts = self.children[0].executed_partitions(ctx)

        def run() -> Iterator[DeviceBatch]:
            import numpy as np
            remaining = np.asarray(self.limit, np.int32)
            i = 0
            for part in child_parts:
                for batch in part():
                    if (i + 1) % 8 == 0 and int(remaining) <= 0:
                        return
                    i += 1
                    out, remaining = self._kernel(batch, remaining)
                    yield out
        return [run]


class TpuCoalescePartitionsExec(TpuExec):
    """Narrow partition merge (Spark CoalesceExec; reference rule
    GpuOverrides.scala:1611-1615): group child partitions contiguously,
    no device work at all."""

    def __init__(self, child: PhysicalPlan, n: int):
        super().__init__([child])
        self.n = max(1, int(n))

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def describe(self) -> str:
        return f"TpuCoalescePartitionsExec({self.n})"

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        from spark_rapids_tpu.exec.base import group_contiguous
        child_parts = self.children[0].executed_partitions(ctx)
        groups = group_contiguous(child_parts, self.n)
        schema = self.output_schema()

        def make(group: List[Partition]) -> Partition:
            def run() -> Iterator[DeviceBatch]:
                got = False
                for p in group:
                    for b in p():
                        got = True
                        yield b
                if not got:
                    yield DeviceBatch.empty(schema)
            return run
        return [make(g) for g in groups]


class TpuUnionExec(TpuExec):
    """reference: GpuUnionExec."""

    def __init__(self, children: Sequence[PhysicalPlan]):
        super().__init__(children)

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        out: List[Partition] = []
        for c in self.children:
            out.extend(c.executed_partitions(ctx))
        return out


class TpuRangeExec(TpuExec):
    """reference: GpuRangeExec — generates the sequence directly on device."""

    def __init__(self, start: int, end: int, step: int, num_partitions: int,
                 name: str = "id"):
        super().__init__()
        self.start, self.end, self.step = start, end, step
        self.num_partitions = num_partitions
        self.col_name = name

    def output_schema(self) -> Schema:
        from spark_rapids_tpu.columnar import dtypes
        return Schema([self.col_name], [dtypes.INT64])

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        total = max(0, -(-(self.end - self.start) // self.step))
        per = -(-total // self.num_partitions) if total else 0
        growth = ctx.conf.capacity_growth
        schema = self.output_schema()

        @functools.partial(jax.jit, static_argnums=(2,))
        def kernel(lo, n, capacity):
            from spark_rapids_tpu.columnar.column import DeviceColumn
            from spark_rapids_tpu.columnar import dtypes
            idx = jnp.arange(capacity, dtype=jnp.int64)
            data = self.start + (lo + idx) * self.step
            validity = idx < n
            col = DeviceColumn(dtypes.INT64, data, validity)
            return DeviceBatch(schema, [col], n.astype(jnp.int32))

        def make(i: int) -> Partition:
            def run() -> Iterator[DeviceBatch]:
                lo = i * per
                hi = min(total, (i + 1) * per)
                n = max(hi - lo, 0)
                cap = bucket_capacity(max(per, 1), growth)
                yield kernel(jnp.asarray(lo, jnp.int64),
                             jnp.asarray(n, jnp.int32), cap)
            return run
        return [make(i) for i in range(self.num_partitions)]


class TpuExpandExec(TpuExec):
    """reference: GpuExpandExec (GpuExpandExec.scala:202) — one jitted
    projection kernel per set, each input batch replayed through all of
    them."""

    def __init__(self, child: PhysicalPlan, projections):
        super().__init__([child])
        self.projections = [list(p) for p in projections]
        self._kernels = []
        for pi, proj in enumerate(self.projections):
            names = [n for n, _ in proj]
            bound = [e for _, e in proj]
            sig = f"expand{pi}|" + "|".join(
                f"{n}={expr_signature(e)}" for n, e in proj)
            self._kernels.append(cached_jit(sig, lambda bound=bound,
                                            names=names: jax.jit(
                lambda batch: eval_projection(batch, bound, names))))

    def output_schema(self) -> Schema:
        cs = self.children[0].output_schema()
        first = self.projections[0]
        return Schema([n for n, _ in first],
                      [e.dtype(cs) for _, e in first])

    def describe(self) -> str:
        return f"TpuExpandExec({len(self.projections)} sets)"

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        child_parts = self.children[0].executed_partitions(ctx)

        def make(part: Partition) -> Partition:
            def run() -> Iterator[DeviceBatch]:
                for batch in part():
                    for kern in self._kernels:
                        yield kern(batch)
            return run
        return [make(p) for p in child_parts]


class TpuScanExec(TpuExec):
    """Columnar scan: host-side decode (pyarrow/pandas — the reference also
    parses footers and rebuilds file buffers on the CPU,
    GpuParquetScan.scala:316-373) + device upload per batch."""

    def __init__(self, source, schema: Schema, pushed_filters=None):
        super().__init__()
        self.source = source
        self._schema = schema
        self.pushed_filters = pushed_filters

    def output_schema(self) -> Schema:
        return self._schema

    def describe(self) -> str:
        return f"TpuScanExec({self.source.describe()})"

    def fingerprint_extra(self) -> str:
        # pushed filters are (name, op, value) tuples (sql/pushdown.py
        # extract_pushable_filters), with repr-stable literal values
        pushed = ",".join(repr(f) for f in (self.pushed_filters or ()))
        return (f"{self.source.data_uid()}|{pushed}"
                f"|{','.join(self._schema.names)}")

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        from spark_rapids_tpu.exec.transitions import scan_raw_parts
        cpu_parts = scan_raw_parts(ctx, self.source, self.pushed_filters)
        if cpu_parts is None:
            if self.pushed_filters and hasattr(self.source,
                                               "prune_splits"):
                cpu_parts = self.source.cpu_partitions(
                    ctx, self.pushed_filters)
            else:
                cpu_parts = self.source.cpu_partitions(ctx)
        max_rows = ctx.conf.batch_size_rows
        schema = self._schema

        # device-resident scan cache (spark.rapids.sql.cacheDeviceScans):
        # skip the re-upload when the same source is scanned again — the
        # HBM analogue of a cached DataFrame
        from spark_rapids_tpu.exec.transitions import scan_cache_for
        cache = scan_cache_for(ctx, self.source, schema, max_rows,
                               self.pushed_filters)
        # one dictionary registry per scan: every batch of this scan
        # encodes against the first batch's dictionaries, so the
        # aggregation fast path compiles ONE program per scan (a racing
        # concurrent partition at worst costs one extra retrace).
        # Small in-memory tables PRE-SEED the registry from the whole
        # column: a dimension table split across partitions would
        # otherwise disable encoding the moment partition 2 shows a
        # value outside partition 1's dictionary — exactly the natural-
        # key columns (all-distinct) whose codes joins fan out to fact
        # scale.
        dict_state: dict = {}
        src_df = getattr(self.source, "df", None)
        if src_df is not None and 0 < len(src_df) <= DICT_SMALL_TABLE_ROWS:
            for ci, dt in enumerate(schema.dtypes):
                if not dt.is_string:
                    continue
                vals = src_df.iloc[:, ci].dropna().unique()
                if (0 < len(vals) <= DICT_MAX_CARD_SMALL
                        and all(isinstance(v, str) for v in vals)):
                    dict_state[ci] = tuple(sorted(vals))

        # mesh execution: partition i uploads to mesh device i so scan data
        # is born distributed (reference map tasks produce data already
        # spread over executors) — the downstream exchange's device_put is
        # then a no-op placement
        mesh = getattr(ctx.session, "mesh", None) if ctx.session else None
        mesh_devs = list(mesh.devices.flat) if mesh is not None else None

        from spark_rapids_tpu.exec.transitions import (
            scan_dict_numerics, upload_partition,
        )
        dict_numerics = scan_dict_numerics(ctx, self.source)

        def make(i: int, part: Partition) -> Partition:
            def run() -> Iterator[DeviceBatch]:
                return upload_partition(ctx, part, schema, max_rows,
                                        dict_state, cache, i,
                                        mesh_devs=mesh_devs,
                                        dict_numerics=dict_numerics)
            return run
        return [make(i, p) for i, p in enumerate(cpu_parts)]


class TpuShuffleExchangeExec(TpuExec):
    """reference: GpuShuffleExchangeExec + GpuPartitioning
    (GpuShuffleExchangeExec.scala:60-215, GpuPartitioning.scala:41-75).

    Device-side partitioning: hash rows, sort by partition id (one fused
    kernel — the contiguous-split analogue), then slice per output
    partition. In-process exchange; the distributed path rides the mesh
    transport (shuffle/)."""

    def __init__(self, child: PhysicalPlan, partitioning):
        super().__init__([child])
        self.partitioning = partitioning

        kind = partitioning[0]
        if kind == "roundrobin":
            n = partitioning[-1]

            def rr_kernel(batch: DeviceBatch):
                # row-level round robin like Spark's repartition(n) —
                # every output partition receives an even share of each
                # batch's rows
                pid = (jnp.arange(batch.capacity, dtype=jnp.int32)
                       % jnp.int32(n))
                return _split_by_pid(batch, pid, n)
            self._pkernel = cached_jit(
                f"exchrr|{n}", lambda: jax.jit(rr_kernel))
        elif kind == "hash":
            key_idx = tuple(partitioning[1])
            n = partitioning[2]

            def pkernel(batch: DeviceBatch):
                h1, h2 = row_hashes(batch, key_idx)
                pid = (h1 % jnp.uint64(n)).astype(jnp.int32)
                return _split_by_pid(batch, pid, n)
            self._pkernel = cached_jit(
                f"exchhash|{key_idx}|{n}", lambda: jax.jit(pkernel))
        elif kind == "range":
            key_idx = tuple(partitioning[1])
            asc = tuple(partitioning[2])
            nf = tuple(partitioning[3])
            n = partitioning[4]
            sig = f"exchrange|{key_idx}|{asc}|{nf}|{n}"

            def sample_kernel(batch: DeviceBatch):
                ops = sortops.sort_key_operands(batch, key_idx, asc, nf)
                return jnp.stack([o.astype(jnp.uint64) for o in ops])
            self._sample_kernel = cached_jit(
                sig + "|sample", lambda: jax.jit(sample_kernel))

            def range_pkernel(batch: DeviceBatch, bounds):
                pid = sortops.range_partition_ids(batch, key_idx, asc, nf,
                                                  list(bounds))
                return _split_by_pid(batch, pid, n)
            self._pkernel = cached_jit(
                sig + "|part", lambda: jax.jit(range_pkernel))

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    @staticmethod
    def _padded_producer(node: PhysicalPlan) -> bool:
        """Does the subtree below (up to the next exchange) contain an
        operator whose batches systematically carry far more capacity than
        rows? Aggregates always do; limits and semi/anti joins compact
        hard within unchanged capacity. Plain filters are deliberately NOT
        counted: at moderate selectivity the shrink's count-fetch sync +
        gathers measured slower than just concatenating (a very selective
        filter below a join is the accepted trade-off)."""
        from spark_rapids_tpu.exec.tpujoin import TpuShuffledHashJoinExec
        if isinstance(node, TpuHashAggregateExec):
            return True
        if isinstance(node, TpuLocalLimitExec):
            return True
        if (isinstance(node, TpuShuffledHashJoinExec)
                and node.join_type in ("leftsemi", "leftanti")):
            return True
        if isinstance(node, TpuShuffleExchangeExec):
            return False  # already shrunk at that boundary
        return any(TpuShuffleExchangeExec._padded_producer(c)
                   for c in node.children)

    def describe(self) -> str:
        return f"TpuShuffleExchangeExec({self.partitioning[0]})"

    def fingerprint_extra(self) -> str:
        return repr(self.partitioning)

    def materialize_stage(self, ctx: ExecContext):
        """AQE query-stage materialization (sql/adaptive/): run the map
        side on device, bring the batches to the host in one fused fetch
        (DeviceBatch.to_pandas_many — two round trips for the whole
        stage), split each map partition with the canonical host hash
        and report per-(map, partition) sizes. AQE is a statistics
        barrier by design: the map output must become host-addressable
        for the runtime to measure and re-partition it — the role the
        reference's shuffle catalog registration plays
        (RapidsCachingWriter -> MapStatus.partition_sizes)."""
        from spark_rapids_tpu.exec.cpu import concat_host_frames
        from spark_rapids_tpu.sql.adaptive import stats as aqestats
        assert self.partitioning[0] == "hash", self.partitioning
        key_idx = list(self.partitioning[1])
        n = self.partitioning[-1]
        schema = self.output_schema()
        sess = ctx.session
        per_map: List[List[DeviceBatch]] = []
        for part in self.children[0].executed_partitions(ctx):
            try:
                per_map.append(list(part()))
            finally:
                if sess is not None and sess.semaphore is not None:
                    sess.semaphore.release()
        flat = [b for bs in per_map for b in bs]
        # stage-barrier fetch under this exchange's operator scope: the
        # fused-fetch slice/pack kernels it compiles attribute HERE, and
        # the device->host seconds land in this node's transfer component
        import time as _time

        from spark_rapids_tpu.obs import compileledger
        from spark_rapids_tpu.obs.syncledger import sync_scope
        with compileledger.op_context(self.describe(), id(self), ctx):
            t0 = _time.perf_counter()
            with sync_scope("aqe.stageFetch",
                            detail=f"batches={len(flat)}"):
                frames = DeviceBatch.to_pandas_many(
                    flat, fused_fetch_bytes=int(ctx.conf.get(
                        "spark.rapids.sql.collect.fusedFetchBytes",
                        4 << 20)))
            compileledger.note_transfer(_time.perf_counter() - t0, "d2h")
        map_outputs = []
        pos = 0
        for bs in per_map:
            dfs = frames[pos:pos + len(bs)]
            pos += len(bs)
            df = concat_host_frames(dfs, schema)
            map_outputs.append(aqestats.split_frame(df, key_idx, n))
        return map_outputs, aqestats.stats_from_map_outputs(map_outputs)

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        child_parts = self.children[0].executed_partitions(ctx)
        schema = self.output_schema()
        growth = ctx.conf.capacity_growth
        kind = self.partitioning[0]

        # per-edge transport selection (shuffle/manager.py
        # ShuffleTransportKind): ICI = in-slice mesh collective, MANAGER =
        # catalog + transport wire (inprocess/socket — the cross-host /
        # DCN path), LOCAL = single-process collapse or bucket
        # materialization. The default mode ('legacy') reproduces the
        # historical inline selection byte-identically.
        from spark_rapids_tpu.shuffle.manager import (
            ShuffleTransportKind, select_transport_kind,
        )
        mesh = getattr(ctx.session, "mesh", None) if ctx.session else None
        n_req = self.partitioning[-1] if kind != "single" else 1
        tkind = select_transport_kind(ctx.conf, ctx.session, kind, n_req)
        manager_on = tkind is ShuffleTransportKind.MANAGER
        # roundrobin is exempt from collapse: it IS the user-visible
        # repartition(n) shape (output partition/file count of a
        # following write)
        collapse = (tkind is ShuffleTransportKind.LOCAL
                    and kind in ("hash", "range")
                    and ctx.conf.get_bool(
                        "spark.rapids.sql.shuffle.localCollapse", True))

        if tkind is ShuffleTransportKind.ICI:
            # distributed exchange: one fused shard_map program whose core
            # is an ICI all_to_all (shuffle/ici.py over
            # parallel/distributed.py), replacing the reference's UCX
            # transfers (RapidsShuffleInternalManager.scala) for EVERY
            # exchange kind (GpuShuffleExchangeExec.scala:60-215): hash
            # (joins/aggregates), range (distributed global sort:
            # per-shard sample -> host bounds -> all_to_all), roundrobin.
            # Each upstream partition stays resident on its own mesh
            # device end-to-end — no single-device funnel — and the
            # backend folds device-side send counts into
            # MapOutputStatistics + skew/journal/ledger surfaces.
            from spark_rapids_tpu.shuffle.ici import IciMeshExchange
            backend = IciMeshExchange(self, mesh, schema, growth)
            return backend.partitions(ctx, child_parts)

        if kind == "single" or collapse:
            from spark_rapids_tpu.exec import outofcore as ooc
            if ooc.enabled_for(ctx):
                # out-of-core mode: the collapse concat IS the whole-
                # dataset funnel array larger-than-HBM execution must
                # avoid — stream the pieces through individually and let
                # the downstream grace operators partition-and-spill them
                def stream_pieces() -> Iterator[DeviceBatch]:
                    got = False
                    for p in child_parts:
                        for b in p():
                            got = True
                            yield b
                    if not got:
                        yield DeviceBatch.empty(schema)
                return [stream_pieces]
            # sync-free collapse: when no aggregate feeds this exchange,
            # the producer batches are NOT systematically over-padded, so
            # the count-fetch sync + per-batch shrink gathers cost more
            # than they save — ONE capacity-based concat (zero round
            # trips) hands the consumer a single big batch, keeping joins
            # and aggregates on one wide kernel instead of per-fragment
            # dispatches. Aggregate producers keep the shrink (their
            # outputs carry pre-agg padding worth removing before the
            # merge/sort).
            if not self._padded_producer(self.children[0]):
                # a deterministic Filter directly below folds into the
                # concat's compaction gather (_fused_filter_source)
                src_node, mask_kernel, out_sel = _fused_filter_source(
                    self.children[0], ctx)
                fused_parts = (src_node.executed_partitions(ctx)
                               if mask_kernel is not None else child_parts)

                def nosync_concat() -> Iterator[DeviceBatch]:
                    batches = [b for p in fused_parts for b in p()]
                    if not batches:
                        yield DeviceBatch.empty(schema)
                        return
                    masks = ([mask_kernel(b) for b in batches]
                             if mask_kernel is not None else None)
                    if masks is not None and out_sel is not None:
                        batches = [_select_view(b, out_sel)
                                   for b in batches]
                    yield _concat_device(batches, schema, growth, masks)
                return [nosync_concat]

            def single() -> Iterator[DeviceBatch]:
                import jax as _jax
                batches = [b for p in child_parts for b in p()]
                if not batches:
                    yield DeviceBatch.empty(schema)
                    return
                if getattr(ctx, "small_query", False):
                    # tiny-query fast path: the shrink exists to drop
                    # pre-aggregation padding before heavy downstream
                    # kernels — at single-resident-batch scale the
                    # count-fetch round trip costs more than the padding
                    # it would remove
                    yield _concat_device(batches, schema, growth)
                    return
                # capacity shrink: post-aggregate partials carry their
                # pre-aggregate input capacity as padding; ONE batched
                # row-count fetch lets each piece drop to its true bucket
                # so every downstream kernel compiles and runs at the
                # real scale instead of the padded one. Speculation
                # (spark.rapids.sql.adaptiveCapacity.enabled): later
                # executions reuse the remembered counts as host
                # metadata and defer an EXACT-equality check to query
                # end (session._verify_speculation) — the slice kernel
                # clamps liveness by the device-side row count, so a
                # covered speculation emits identical data
                cache = entry = None
                if getattr(ctx, "speculate", False):
                    from spark_rapids_tpu.exec.base import (
                        plan_fingerprint,
                    )
                    from spark_rapids_tpu.exec.reuse import (
                        subtree_deterministic,
                    )
                    if subtree_deterministic(self):
                        skey = plan_fingerprint(self) + "|shrink"
                        cache = ctx.session.capacity_cache
                        entry = cache.get(skey)
                # under speculation the cache entry must key on an
                # execution-invariant batch set: which batches already
                # carry _host_rows differs between run 1 (one-time
                # agg-ratio learning syncs set some) and run 2, so
                # filtering to the unknown ones made entry['n'] mismatch
                # and wasted the first speculation window (ADVICE r4 #5).
                # Counts only speculate once they have proven STABLE
                # across two consecutive runs: adaptive strategy shifts
                # (dense grouping / partial-skip engage from a plan's
                # second execution) legitimately change the counts between
                # run 1 and run 2 under an identical structural
                # fingerprint, and speculating unstable counts forces a
                # full re-execution at verify time.
                need = (list(batches) if cache is not None
                        else [b for b in batches if b._host_rows is None])
                # per-batch stats: row count + each plain (non-dict)
                # string column's live char total — shrinking the char
                # slab alongside the rows stops every downstream string
                # kernel from paying the pre-aggregation char padding.
                # Layout is computed PER BATCH (a scan can close a
                # dictionary mid-stream, so batches of one exchange may
                # disagree on which string columns are plain); the
                # speculation entry keys on the layout so a mismatch can
                # never mis-assign a char total as a row count.
                def batch_stats(b):
                    vals = [b.num_rows]
                    for col in b.columns:
                        if (col.dtype.is_string
                                and col.dict_values is None
                                and not col.has_slab):
                            # slab columns carry a STATIC stride — no
                            # char total to fetch (and reading offsets
                            # here would materialize their packed chars)
                            vals.append(col.offsets[jnp.minimum(
                                b.num_rows.astype(jnp.int32),
                                jnp.int32(col.offsets.shape[0] - 1))])
                    return vals

                if need:
                    per_batch = [batch_stats(b) for b in need]
                    layout = tuple(len(v) for v in per_batch)
                    counts_d = [v for vals in per_batch for v in vals]
                    if (entry is not None
                            and entry.get("layout") == layout
                            and entry.get("stable")):
                        from spark_rapids_tpu.exec.tpujoin import (
                            _start_host_copies,
                        )
                        _start_host_copies(counts_d)
                        ctx.session.capacity_spec_hits += 1
                        ctx.spec_pending.append(
                            (skey, counts_d, [], [], entry["counts"]))
                        stats = entry["counts"]
                    else:
                        from spark_rapids_tpu.obs.syncledger import (
                            sync_scope,
                        )
                        with sync_scope("exchange.shrink",
                                        detail=f"counts={len(counts_d)}"):
                            stats = [int(c)
                                     for c in _jax.device_get(counts_d)]
                        if cache is not None:
                            if (entry is not None
                                    and entry.get("layout") == layout
                                    and entry["counts"] == stats):
                                entry["stable"] = True
                            else:
                                cache[skey] = {"layout": layout,
                                               "counts": stats}
                    pos = 0
                    for b, vals in zip(need, per_batch):
                        b._host_rows = int(stats[pos])
                        b._host_chars = [int(c) for c in
                                         stats[pos + 1:pos + len(vals)]]
                        pos += len(vals)
                shrunk = []
                for b in batches:
                    target = bucket_capacity(max(b._host_rows, 1), growth)
                    # full char_caps tuple: one entry per string column
                    # (0 = keep; dict-backed strings move codes only)
                    ccaps = []
                    hc = list(getattr(b, "_host_chars", []) or [])
                    for col in b.columns:
                        if not col.dtype.is_string:
                            continue
                        if (col.dict_values is None and not col.has_slab
                                and hc):
                            ccaps.append(_char_bucket(max(hc.pop(0), 1)))
                        else:
                            ccaps.append(0)
                    char_shrink = any(
                        cc and col.dtype.is_string
                        and col.dict_values is None and not col.is_lazy
                        and cc < col.data.shape[0]
                        for cc, col in zip(
                            ccaps, [c for c in b.columns
                                    if c.dtype.is_string]))
                    if target < b.capacity or char_shrink:
                        ccaps_t = tuple(ccaps)
                        kern = cached_jit(
                            f"shrink|{target}|{ccaps_t}",
                            lambda t=target, cc=ccaps_t: jax.jit(
                                lambda bb, c: rowops.slice_batch_to(
                                    bb, jnp.asarray(0, jnp.int32), c, t,
                                    cc)))
                        sb = kern(b, jnp.asarray(b._host_rows, jnp.int32))
                        sb._host_rows = b._host_rows
                        shrunk.append(sb)
                    else:
                        shrunk.append(b)
                yield _concat_device(shrunk, schema, growth)
            return [single]

        assert kind in ("hash", "range", "roundrobin")
        n = self.partitioning[-1]

        def slice_kernel(b: DeviceBatch, start, count, rows: int):
            # shrink to the bucket of the KNOWN row count: post-aggregate
            # pieces stop inheriting the pre-aggregate capacity, so the
            # downstream merge/sort kernels run at the output's true scale
            out_cap = bucket_capacity(max(rows, 1), growth)
            kern = cached_jit(f"slice|{out_cap}", lambda: jax.jit(
                lambda bb, s, c: rowops.slice_batch_to(bb, s, c, out_cap)))
            return kern(b, start, count)

        # materialization barrier: partition every child batch once,
        # bucket the slices
        state = {"buckets": None}

        def compute_range_bounds(batches: List[DeviceBatch]):
            """Reservoir-style sample of sort-key operand vectors -> n-1
            lexicographic upper bounds (GpuRangePartitioner.scala:42-120)."""
            import jax
            import numpy as np
            # one batched fetch of every batch's (row count, key operands)
            from spark_rapids_tpu.obs.syncledger import sync_scope
            with sync_scope("exchange.rangeBounds",
                            detail=f"batches={len(batches)}"):
                fetched = jax.device_get([(b.num_rows,
                                           self._sample_kernel(b))
                                          for b in batches])
            from spark_rapids_tpu.parallel.distributed import (
                pick_bounds_from_samples,
            )
            samples = []
            k = None
            for batch, (rows, ops) in zip(batches, fetched):
                rows = int(rows)
                batch._host_rows = rows
                ops = np.asarray(ops)  # (k, capacity)
                k = ops.shape[0]
                if rows == 0:
                    continue
                take = min(rows, 128)
                sel = np.linspace(0, rows - 1, take).astype(np.int64)
                samples.append(ops[:, sel])
            if k is None:
                # no batches at all: operand count from an empty probe
                k = np.asarray(self._sample_kernel(
                    DeviceBatch.empty(schema))).shape[0]
            bounds = pick_bounds_from_samples(samples, k, n)
            return tuple(jnp.asarray(b) for b in bounds)

        def split_to_slices(batches, bounds):
            """Split each batch by partition id and yield
            (batch_index, pid, piece) — the shared core of both exchange
            materializations. Bucket counts are fetched in windows: one
            device->host round trip per WINDOW batches (per-batch scalar
            syncs each pay a full round trip; one giant window would pin
            every split output in device memory at once)."""
            import itertools
            import jax
            import numpy as np
            split_iter = ((bi, (self._pkernel(b, bounds) if kind == "range"
                                else self._pkernel(b)))
                          for bi, b in enumerate(batches))
            WINDOW = 16
            windowed = iter(lambda: list(itertools.islice(split_iter,
                                                          WINDOW)), [])
            from spark_rapids_tpu.obs.syncledger import sync_scope
            for window in windowed:
                with sync_scope("exchange.split",
                                detail=f"window={len(window)}"):
                    window_counts = jax.device_get(
                        [c for _, (_s, c) in window])
                for (bi, (sorted_batch, _c)), host_counts in zip(
                        window, window_counts):
                    host_counts = np.asarray(host_counts)
                    offsets = np.concatenate([[0], np.cumsum(host_counts)])
                    for pid in range(n):
                        if host_counts[pid] == 0:
                            continue
                        yield bi, pid, slice_kernel(
                            sorted_batch,
                            jnp.asarray(offsets[pid], jnp.int32),
                            jnp.asarray(host_counts[pid], jnp.int32),
                            int(host_counts[pid]))

        # map-side output registers in the spillable BufferCatalog at the
        # shuffle-output band (spills FIRST under pressure,
        # SpillPriorities.scala:26-50 / RapidsShuffleInternalManager.scala:
        # 92-141 route all shuffle data through the catalog); the reduce
        # side acquires (faulting spilled pieces back) and frees on
        # consumption
        use_catalog = ctx.session is not None

        def materialize():
            if state["buckets"] is not None:
                return state["buckets"]
            from spark_rapids_tpu.memory.spill import SpillPriorities
            buckets: List[List] = [[] for _ in range(n)]
            all_batches = [b for p in child_parts for b in p()]
            bounds = (compute_range_bounds(all_batches)
                      if kind == "range" else None)
            for _bi, pid, piece in split_to_slices(all_batches, bounds):
                if use_catalog:
                    buckets[pid].append(ctx.session.add_transient_batch(
                        piece, SpillPriorities.OUTPUT_FOR_READ))
                else:
                    buckets[pid].append(piece)
            state["buckets"] = buckets
            return buckets

        if manager_on:
            # accelerated shuffle manager path: map-side slices register
            # as spillable shuffle blocks via CachingShuffleWriter; the
            # reduce side reads them back through CachingShuffleReader
            # over the (in-process) transport — the engine-integrated
            # RapidsShuffleInternalManager.scala:74-362 flow
            from spark_rapids_tpu.shuffle.manager import (
                CachingShuffleReader, CachingShuffleWriter,
            )
            mstate = {"statuses": None}

            def materialize_manager():
                if mstate["statuses"] is not None:
                    return mstate["statuses"]
                # map tasks stripe across the executor pool
                # (spark.rapids.shuffle.executors); with >1, reduce-side
                # fetches of other executors' blocks traverse the real
                # transport wire (socket: serializer -> server -> client)
                envs = ctx.session.shuffle_envs
                shuffle_id = ctx.session.next_shuffle_id()
                per_map_batches = [list(p()) for p in child_parts]
                bounds = (compute_range_bounds(
                    [b for bs in per_map_batches for b in bs])
                    if kind == "range" else None)
                statuses = []
                for mi, batches in enumerate(per_map_batches):
                    per_pid: List[List[DeviceBatch]] = [[] for _ in range(n)]
                    for _bi, pid, piece in split_to_slices(batches, bounds):
                        per_pid[pid].append(piece)
                    writer = CachingShuffleWriter(envs[mi % len(envs)],
                                                  shuffle_id, mi)
                    statuses.append(writer.write(per_pid))
                if statuses and ctx.metrics_enabled:
                    # per-shuffle skew from the EXACT device byte sizes
                    # the writer recorded (MapStatus.partition_sizes) —
                    # the satellite observability AQE's stage stats also
                    # report on the host path (obs/shuffleobs.py)
                    from spark_rapids_tpu.obs.shuffleobs import (
                        record_shuffle_skew,
                    )
                    from spark_rapids_tpu.shuffle.manager import (
                        aggregate_map_statistics,
                    )
                    record_shuffle_skew(
                        aggregate_map_statistics(statuses)
                        .bytes_by_partition,
                        source=f"tpu:manager-{shuffle_id}")
                mstate["statuses"] = (shuffle_id, statuses)
                return mstate["statuses"]

            def make_manager(pid: int) -> Partition:
                def run() -> Iterator[DeviceBatch]:
                    from spark_rapids_tpu.shuffle.client import (
                        ShuffleFetchFailedError,
                    )
                    shuffle_id, statuses = materialize_manager()
                    # reduce with bounded PER-PEER retry — the in-process
                    # analogue of mapping transport errors into Spark's
                    # stage-retry path (RapidsShuffleClient.scala:409-418
                    # -> RapidsShuffleFetchFailedException). Each peer
                    # group moves in ONE metadata/transfer round trip
                    # (RapidsCachingReader groups per BlockManagerId) and
                    # a failure re-fetches only that peer's blocks (they
                    # live in the spillable map-side catalog), never data
                    # already fetched. The pieces still concatenate into
                    # ONE wide batch before yielding — deliberate:
                    # downstream joins/aggregates run one wide kernel
                    # instead of per-fragment dispatches (same trade as
                    # the collapse path).
                    max_retries = ctx.conf.get_int(
                        "spark.rapids.shuffle.maxFetchRetries", 3)
                    reader = CachingShuffleReader(ctx.session.shuffle_env)
                    batches = []
                    for peer, group in reader.peer_groups(statuses):
                        attempt = 0
                        while True:
                            try:
                                got = reader.read_group(
                                    shuffle_id, pid, peer, group)
                                break
                            except ShuffleFetchFailedError as e:
                                attempt += 1
                                if attempt > max_retries:
                                    raise
                                from spark_rapids_tpu.obs.metrics import (
                                    REGISTRY,
                                )
                                from spark_rapids_tpu.obs.trace import (
                                    TRACER,
                                )
                                REGISTRY.counter(
                                    "shuffle.fetch.retries").add(1)
                                TRACER.instant(
                                    "shuffle.fetch.retry",
                                    peer=str(peer), attempt=attempt)
                                from spark_rapids_tpu.obs.events import (
                                    EVENTS,
                                )
                                EVENTS.emit("fetchRetry", peer=str(peer),
                                            attempt=attempt,
                                            error=str(e)[:200])
                                from spark_rapids_tpu.obs.progress import (
                                    PROGRESS,
                                )
                                if PROGRESS.enabled:
                                    PROGRESS.shuffle_retry()
                                import logging
                                logging.getLogger(__name__).warning(
                                    "shuffle fetch failed (%s); retrying "
                                    "%d/%d", e, attempt, max_retries)
                        batches.extend(got)
                    if not batches:
                        yield DeviceBatch.empty(schema)
                        return
                    yield _concat_device(batches, schema, growth)
                return run
            return [make_manager(i) for i in range(n)]

        def make(pid: int) -> Partition:
            def run() -> Iterator[DeviceBatch]:
                buckets = materialize()
                if buckets[pid] is None:
                    raise RuntimeError(
                        f"shuffle partition {pid} already consumed "
                        "(freed on use)")
                if not buckets[pid]:
                    yield DeviceBatch.empty(schema)
                    return
                if use_catalog:
                    catalog = ctx.session.buffer_catalog
                    pieces = []
                    for bid in buckets[pid]:
                        pieces.append(catalog.acquire_batch(bid))
                        ctx.session.consume_transient(bid)  # free on use
                    buckets[pid] = None
                else:
                    pieces = buckets[pid]
                yield _concat_device(pieces, schema, growth)
            return run
        return [make(i) for i in range(n)]
