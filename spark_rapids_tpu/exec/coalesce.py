"""Batch coalescing (reference: GpuCoalesceBatches + CoalesceGoal,
GpuCoalesceBatches.scala:38-165, inserted by
GpuTransitionOverrides.scala:64-147).

Fragmenting producers (scans with many small row groups, filters, joins)
emit batches far below the target size; every downstream operator then pays
one kernel dispatch per fragment, and each distinct capacity bucket compiles
its own XLA program. ``TpuCoalesceBatchesExec`` accumulates child batches to
the ``spark.rapids.sql.batchSizeRows`` target (or everything, for
``RequireSingleBatch``) and concatenates them in one fused device kernel.
"""

from __future__ import annotations

from typing import Iterator, List

from spark_rapids_tpu.columnar.batch import DeviceBatch, Schema
from spark_rapids_tpu.exec.base import ExecContext, Partition, PhysicalPlan


class CoalesceGoal:
    """Target for coalescing (reference: CoalesceGoal/TargetSize/
    RequireSingleBatch, GpuCoalesceBatches.scala)."""


class TargetSize(CoalesceGoal):
    def __init__(self, rows: int):
        self.rows = rows

    def __repr__(self) -> str:
        return f"TargetSize({self.rows})"


class RequireSingleBatch(CoalesceGoal):
    def __repr__(self) -> str:
        return "RequireSingleBatch"


def coalesce_iter(batches, goal: CoalesceGoal, schema: Schema,
                  growth: float, coarse: bool = False
                  ) -> Iterator[DeviceBatch]:
    """Accumulate a batch stream to ``goal`` and concatenate — the one
    coalescing loop, shared by TpuCoalesceBatchesExec and the fused
    stage's input re-batching (exec/stagecompiler/fusedexec.py).

    Capacity-based accounting: an exact count would cost a device->host
    scalar sync per batch (~hundreds of ms through remote attachments);
    the bucketed capacity over-estimates by at most 2x, which only makes
    coalesced outputs slightly smaller than the goal.

    ``coarse``: pad the concatenated capacity up the shape-bucket ladder
    (spark.rapids.tpu.compile.shapeBuckets; identity when off) — the
    fused-stage re-batching uses it so small tail fragments land on the
    same compiled capacity as each other instead of one program per
    tail size."""
    from spark_rapids_tpu.exec.tpu import _concat_device
    single = isinstance(goal, RequireSingleBatch)
    target = 0 if single else goal.rows
    pending: List[DeviceBatch] = []
    pending_rows = 0
    for batch in batches:
        rows = batch.num_rows_hint()
        if rows == 0 and pending:
            continue  # drop known-empty fragments
        pending.append(batch)
        pending_rows += rows
        if not single and pending_rows >= target:
            yield _concat_device(pending, schema, growth, coarse=coarse)
            pending, pending_rows = [], 0
    if pending:
        yield _concat_device(pending, schema, growth, coarse=coarse)


class TpuCoalesceBatchesExec(PhysicalPlan):
    columnar_output = True

    def __init__(self, child: PhysicalPlan, goal: CoalesceGoal):
        super().__init__([child])
        self.goal = goal

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def describe(self) -> str:
        return f"TpuCoalesceBatchesExec({self.goal!r})"

    def partitions(self, ctx: ExecContext) -> List[Partition]:
        child_parts = self.children[0].executed_partitions(ctx)
        schema = self.output_schema()
        growth = ctx.conf.capacity_growth

        def make(part: Partition) -> Partition:
            def run() -> Iterator[DeviceBatch]:
                yield from coalesce_iter(part(), self.goal, schema,
                                         growth)
            return run
        return [make(p) for p in child_parts]


# producers whose output batches can be much smaller than the target
# (the reference's insertCoalesce walks goals the same way)
def is_fragmenting(plan: PhysicalPlan) -> bool:
    from spark_rapids_tpu.exec import tpu, tpujoin
    return isinstance(plan, (tpu.TpuScanExec, tpu.TpuFilterExec,
                             tpujoin.TpuShuffledHashJoinExec,
                             tpujoin.TpuBroadcastNestedLoopJoinExec,
                             tpu.TpuExpandExec))


def _reads_input_file(plan: PhysicalPlan) -> bool:
    """Does this operator evaluate input_file_name()? Coalescing would drain
    the scan past the file boundary before evaluation, so such consumers
    must see uncoalesced batches (the reference disables coalesce the same
    way, GpuTransitionOverrides.scala:110-123)."""
    from spark_rapids_tpu.sql.exprs.core import walk
    from spark_rapids_tpu.sql.exprs.nondet import InputFileName
    exprs = []
    if hasattr(plan, "exprs"):
        exprs.extend(e for _, e in plan.exprs)
    if getattr(plan, "condition", None) is not None:
        exprs.append(plan.condition)
    return any(isinstance(n, InputFileName) for e in exprs for n in walk(e))


def insert_coalesce(plan: PhysicalPlan, conf) -> PhysicalPlan:
    """Insert TpuCoalesceBatchesExec above fragmenting producers feeding
    TPU consumers (GpuTransitionOverrides.scala:64-147). Disabled for the
    whole query when any operator evaluates input_file_name(): coalescing
    drains a scan past its file boundary before any ancestor evaluates,
    so even a distant consumer would read a cleared/stale path."""
    if any(_reads_input_file(node) for node in plan.walk()):
        return plan
    return _insert(plan, conf)


def _insert(plan: PhysicalPlan, conf) -> PhysicalPlan:
    new_children = []
    for c in plan.children:
        c2 = _insert(c, conf)
        if (getattr(plan, "columnar_output", False)
                and not isinstance(plan, TpuCoalesceBatchesExec)
                and is_fragmenting(c2)):
            c2 = TpuCoalesceBatchesExec(c2, TargetSize(conf.batch_size_rows))
        new_children.append(c2)
    out = plan.map_children(lambda x: x)
    out.children = new_children
    return out
