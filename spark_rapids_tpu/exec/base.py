"""Physical plan base classes.

Execution model: a physical operator produces a list of *partitions*, each a
zero-arg callable returning an iterator of batches (the Spark
``RDD.mapPartitions`` shape the reference's operators use, e.g.
aggregate.scala:259-286). Two payload kinds flow through a mixed plan:

  * CPU operators:   pandas DataFrames          (the fallback path)
  * TPU operators:   columnar DeviceBatch       (the accelerated path)

Explicit transition operators convert between them
(exec/transitions.py — the analogue of GpuRowToColumnarExec /
GpuColumnarToRowExec / HostColumnarToGpu).
"""

from __future__ import annotations

from typing import Callable, Iterator, List, Optional, Sequence

from spark_rapids_tpu.columnar.batch import Schema

Partition = Callable[[], Iterator]  # yields pd.DataFrame or DeviceBatch


def group_contiguous(parts: Sequence[Partition],
                     n: int) -> List[List[Partition]]:
    """Contiguous partition grouping for CoalesceExec (like Spark's
    DefaultPartitionCoalescer), shared by the CPU and TPU operators."""
    n = min(max(1, int(n)), max(len(parts), 1))
    per = -(-len(parts) // n) if parts else 0
    groups: List[List[Partition]] = [[] for _ in range(n)]
    for i, p in enumerate(parts):
        groups[min(i // max(per, 1), n - 1)].append(p)
    return groups


class PhysicalPlan:
    """Base physical operator."""

    # True if this operator's output is device columnar (TPU path)
    columnar_output = False

    def __init__(self, children: Sequence["PhysicalPlan"] = ()):  # noqa: D401
        self.children: List[PhysicalPlan] = list(children)

    @property
    def name(self) -> str:
        return type(self).__name__

    def output_schema(self) -> Schema:
        raise NotImplementedError

    def partitions(self, ctx: "ExecContext") -> List[Partition]:
        raise NotImplementedError

    def executed_partitions(self, ctx: "ExecContext") -> List[Partition]:
        """``partitions`` wrapped with per-operator SQL metrics and tracer
        spans (reference: GpuMetricNames per-exec Spark metrics,
        GpuExec.scala:24-41, + NvtxWithMetrics.scala:17-44). Consumers call
        this; operators implement ``partitions``. With metrics AND tracing
        disabled the partitions pass through untouched — no timers on the
        hot path."""
        parts = self.partitions(ctx)
        from spark_rapids_tpu.obs import compileledger
        from spark_rapids_tpu.obs.trace import TRACER
        prog = ctx.progress  # live monitoring (obs/progress.py)
        cancel = ctx.cancel  # cooperative cancellation (serving/)
        if not ctx.metrics_enabled and not TRACER.enabled \
                and prog is None and not compileledger.LEDGER.enabled \
                and cancel is None:
            return parts
        import time
        # tiny-query lite bookkeeping
        # (spark.rapids.sql.smallQuery.liteBookkeeping): one record per
        # operator per partition instead of per-batch timers + ledger
        # scopes + tracer spans — a pure fixed-cost removal for queries
        # whose wall time is Python dispatch. Anything that genuinely
        # needs batch granularity (tracing, profile sync, live progress,
        # cancellation scopes) forces the full wrapper back on.
        if (ctx.small_query and ctx.small_query_lite
                and not TRACER.enabled and prog is None
                and cancel is None and not ctx.profile_sync):
            record_lite = ctx.metrics_enabled
            lite_op = self.describe()
            lite_id = id(self)
            members = getattr(self, "member_ops", None)

            def lite_wrap(part: Partition) -> Partition:
                def run():
                    t0 = time.perf_counter()
                    rows = 0
                    it = part()
                    while True:
                        # ledger scope around the pull only (a thread-
                        # local set/unset): compile attribution — and a
                        # fused stage's member pipeline — survive, while
                        # the per-batch timers, tracer spans and
                        # progress heartbeats are elided
                        prev_op = compileledger.push_op(
                            lite_op, lite_id, ctx, members)
                        try:
                            batch = next(it)
                        except StopIteration:
                            break
                        finally:
                            compileledger.pop_op(prev_op)
                        r = getattr(batch, "_host_rows", None)
                        if r is None and not hasattr(batch, "num_rows"):
                            r = len(batch)
                        rows += r or 0
                        yield batch
                    if record_lite:
                        ctx.record_op(lite_op, lite_id,
                                      time.perf_counter() - t0, rows)
                return run
            return [lite_wrap(p) for p in parts]
        op = self.describe()
        record = ctx.metrics_enabled
        node_id = id(self)
        # profile mode: force a device sync after every operator's batch
        # so totalTime is ATTRIBUTABLE per kernel — without it dispatch is
        # async and all queued compute lands on whichever operator first
        # syncs (the first device_get carries ~85% of wall time). NB on
        # the tunneled attachment block_until_ready does not reliably
        # block; fetching the num_rows device scalar does.
        sync_each = ctx.profile_sync

        def _force_sync(batch):
            nr = getattr(batch, "num_rows", None)
            if nr is not None:
                import jax

                from spark_rapids_tpu.obs.syncledger import sync_scope
                with sync_scope("profile.syncEachOp", nbytes=4):
                    jax.device_get(nr)

        def wrap(part: Partition, pidx: int) -> Partition:
            def run():
                it = part()
                while True:
                    if cancel is not None:
                        # batch-pull boundary: a cancelled or past-
                        # deadline query raises here instead of being
                        # killed mid-kernel, so the session's normal
                        # failure path releases its buffers/shuffles
                        cancel.check()
                    t0 = time.perf_counter()
                    with TRACER.span(self.name, op=op,
                                     partition=pidx) as sp:
                        # operator scope: a backend compile fired by a
                        # kernel call inside this pull attributes to
                        # THIS operator (obs/compileledger.py), and
                        # transfer sites report their seconds against it.
                        # Fused stages publish their member pipeline too.
                        prev_op = compileledger.push_op(
                            op, node_id, ctx,
                            getattr(self, "member_ops", None))
                        try:
                            batch = next(it)
                        except StopIteration:
                            return
                        finally:
                            compileledger.pop_op(prev_op)
                        rows = (batch._host_rows
                                if hasattr(batch, "_host_rows")
                                else len(batch))
                        if sp is not None:
                            sp.set(batch_rows=rows)
                    if sync_each:
                        t1 = time.perf_counter()
                        _force_sync(batch)
                        t2 = time.perf_counter()
                        # pull vs sync split: the pull is python dispatch
                        # (+ children + transfers), the sync is the
                        # device draining THIS operator's queued kernels
                        # (children already synced before yielding) —
                        # the profile's device/transfer/dispatch rows
                        compileledger.note_breakdown(
                            ctx, node_id, pull_s=t1 - t0, sync_s=t2 - t1)
                        # per-node-identity inclusive time: the profiler
                        # subtracts children to get exclusive per-kernel
                        # attribution (describe() keys merge same-shaped
                        # operators, which hides where time goes)
                        with ctx._stats_lock:
                            ctx.node_times[node_id] = ctx.node_times.get(
                                node_id, 0.0) + (time.perf_counter() - t0)
                    if record:
                        ctx.record_op(op, node_id,
                                      time.perf_counter() - t0, rows)
                    if prog is not None:
                        # per-batch heartbeat: per-operator rows/batches/
                        # time so far, served live at /api/query/<id>
                        prog.op_batch(node_id, op, rows,
                                      time.perf_counter() - t0)
                    yield batch
            return run
        return [wrap(p, i) for i, p in enumerate(parts)]

    def map_children(self, fn) -> "PhysicalPlan":
        import copy
        new = copy.copy(self)
        new.children = [fn(c) for c in self.children]
        return new

    def tree_string(self, indent: int = 0) -> str:
        lines = ["  " * indent + f"{self.describe()}"]
        for c in self.children:
            lines.append(c.tree_string(indent + 1))
        return "\n".join(lines)

    def describe(self) -> str:
        return self.name

    def fingerprint_extra(self) -> str:
        """Extra identity beyond ``describe()`` for the structural plan
        fingerprint (plan_fingerprint): scans add their data identity,
        projects their expression signatures. Collisions are safe — every
        consumer of the fingerprint (the adaptive capacity cache) device-
        verifies what it speculates — they only cost cache churn."""
        return ""

    def walk(self):
        yield self
        for c in self.children:
            yield from c.walk()


def plan_fingerprint(node: "PhysicalPlan") -> str:
    """Structural identity of a plan subtree, stable across executions of
    the same query over the same data (plan objects are rebuilt per
    execution; this string is not). Keys the session's adaptive capacity
    cache (reference analogue: AQE's per-stage runtime statistics reuse,
    which also keys on the canonicalized plan subtree)."""
    import hashlib
    parts: List[str] = []

    def rec(n: "PhysicalPlan") -> None:
        parts.append(n.describe())
        parts.append(n.fingerprint_extra())
        parts.append("(")
        for c in n.children:
            rec(c)
        parts.append(")")
    rec(node)
    return hashlib.md5("|".join(parts).encode()).hexdigest()


class ExecContext:
    """Per-query execution context: conf, session services, metrics."""

    def __init__(self, conf, session=None, speculate: bool = True):
        from spark_rapids_tpu.obs.metrics import MetricsRegistry
        self.conf = conf
        self.session = session
        # per-query metrics registry: per-op counters carry an op= label
        # and render back into the legacy {op: {metric: value}} dict via
        # the ``metrics`` property (session.last_query_metrics shape).
        # Thread-safe — the shuffle server and partition executor threads
        # accumulate concurrently.
        self.registry = MetricsRegistry()
        self.metrics_enabled = conf.get_bool(
            "spark.rapids.sql.metrics.enabled", True)
        # per-plan-node (identity-keyed) inclusive time/rows/batches for
        # the profile report (obs/profile.py)
        import threading
        self.node_stats: dict = {}
        self._stats_lock = threading.Lock()
        # per-operator sync for kernel attribution (tools/profile_query.py)
        self.profile_sync = conf.get_bool(
            "spark.rapids.sql.profile.syncEachOp", False)
        self.node_times: dict = {}
        # per-plan-node wall-time components (obs/compileledger.py
        # note_breakdown): pull_s/sync_s under profile_sync, transfer_s
        # from the host<->device transfer sites — the profile report
        # renders these as device/transfer/dispatch rows (obs/profile.py)
        self.node_breakdown: dict = {}
        # adaptive capacity speculation (spark.rapids.sql.adaptiveCapacity.
        # enabled): operators that speculated a device->host size fetch
        # from the session cache append (key, totals_device, caps_used,
        # ok_flags_device) here; the session verifies the whole list in
        # ONE fetch at query end and re-executes without speculation on
        # any miss (session._execute). ``speculate=False`` is that exact
        # re-execution.
        self.speculate = (
            speculate and session is not None
            and conf.get_bool("spark.rapids.sql.adaptiveCapacity.enabled",
                              True))
        self.spec_pending: list = []
        # adaptive-ratio cache entries written during this execution:
        # a speculative run that later fails verification learned its
        # ratios from possibly-garbage group counts — the session clears
        # exactly these before re-executing (session._execute)
        self.ratio_writes: list = []
        # per-query materialization state of deduped shared subtrees
        # (exec/reuse.TpuReuseSubtreeExec) — context-scoped so a fresh
        # context (speculation re-execution) re-runs the subtree
        self.reuse_state: dict = {}
        # live QueryProgress record (obs/progress.py), set by the session
        # only when the monitoring UI is enabled; None (the default)
        # keeps every heartbeat site a single is-None check
        self.progress = None
        # cooperative cancellation scope (serving/cancellation.py): the
        # scheduler installs it thread-locally before running a job;
        # executed_partitions checks it at every batch-pull boundary.
        # None (the default) keeps the hot path untouched.
        from spark_rapids_tpu.serving.cancellation import current_scope
        self.cancel = current_scope()
        # tiny-query overhead-floor fast path (sql/planner.py
        # note_input_size): the session sets this after planning when the
        # measured input is a single resident batch under the threshold.
        # Exchanges skip their shrink sync, uploads skip the semaphore,
        # and executed_partitions swaps the per-batch-pull bookkeeping
        # for one per-partition record (liteBookkeeping).
        self.small_query = False
        # expanding plans (joins/explode) keep the admission semaphore
        # even under the fast path — leaf row counts do not bound THEIR
        # working set (sql/planner.note_input_size)
        self.small_query_keep_sem = False
        self.small_query_lite = conf.get_bool(
            "spark.rapids.sql.smallQuery.liteBookkeeping", True)
        # per-QUERY resource tracking (shuffle ids registered, transient
        # spillable buffer ids): concurrent queries must each release
        # exactly their own at query end — a shared session-level list
        # would free a neighbor's live buffers (session.py routes its
        # register/release calls through the executing query's context)
        self.active_shuffles: list = []
        self.transient_bids: set = set()

    def metric_add(self, op: str, name: str, value):
        self.registry.counter(name, op=op).add(value)

    def record_op(self, op: str, node_id: int, seconds: float, rows):
        """One executed batch of one operator: per-op SQL metrics plus the
        per-node-identity stats the profile report attributes time with."""
        self.metric_add(op, "totalTime", seconds)
        self.metric_add(op, "numOutputBatches", 1)
        if rows is not None:
            self.metric_add(op, "numOutputRows", rows)
        with self._stats_lock:
            st = self.node_stats.get(node_id)
            if st is None:
                st = self.node_stats[node_id] = {
                    "time": 0.0, "rows": 0, "batches": 0}
            st["time"] += seconds
            st["batches"] += 1
            if rows is not None:
                st["rows"] += rows

    def op_metrics(self) -> dict:
        """Legacy nested-dict render of the registry: {op: {metric:
        value}} (the session.last_query_metrics shape)."""
        out: dict = {}
        for m in self.registry.metrics():
            op = m.labels.get("op")
            if op is not None:
                out.setdefault(op, {})[m.name] = m.value
        return out

    @property
    def metrics(self) -> dict:
        return self.op_metrics()
