"""Host-side grouped reductions (the CPU fallback aggregate).

Implemented with factorize + stable sort + ``np.ufunc.reduceat`` segments —
the same sort-segment shape as the device kernel (ops/groupby.py) so the two
paths share null/NaN semantics exactly (pandas' skipna conventions would
silently diverge)."""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np
import pandas as pd

from spark_rapids_tpu.columnar import dtypes
from spark_rapids_tpu.columnar.dtype import DType
from spark_rapids_tpu.sql.exprs.hostutil import host_unary_values, rebuild_series


def group_codes(keys: Sequence[Tuple[np.ndarray, np.ndarray]]) -> Tuple[np.ndarray, int]:
    """Combine (values, validity) key columns into dense group codes.

    NULL is its own group; float NaN is its own group; -0.0 == 0.0."""
    n = len(keys[0][0]) if keys else 0
    combined = np.zeros(n, dtype=np.int64)
    for values, validity in keys:
        if values.dtype == object:
            # NUL-exact string factorization via arrow: pandas 3.x
            # factorize hashes object strings through a NUL-terminated
            # path and merges 'a' with 'a\x00'
            import pyarrow as pa
            vals = np.where(validity, values, "")
            codes = (pa.array(vals, type=pa.string(), from_pandas=True)
                     .dictionary_encode().indices
                     .to_numpy(zero_copy_only=False).astype(np.int64))
        elif values.dtype.kind == "f":
            vals = np.where(validity, np.where(values == 0.0, 0.0, values), 0.0)
            codes, _ = pd.factorize(vals)
            codes = codes.astype(np.int64)
        else:
            vals = np.where(validity, values, np.zeros(1, dtype=values.dtype))
            codes, _ = pd.factorize(vals)
            codes = codes.astype(np.int64)
        nan_code = codes.max(initial=-1) + 1
        codes = np.where(codes == -1, nan_code, codes)  # NaN group
        codes = np.where(validity, codes + 1, 0)        # NULL group = 0
        combined = combined * (codes.max(initial=0) + 1) + codes
        combined, _ = pd.factorize(combined)
        combined = combined.astype(np.int64)
    return combined, int(combined.max(initial=-1)) + 1


def segment_reduce_host(kind: str, values: np.ndarray, validity: np.ndarray,
                        order: np.ndarray, starts: np.ndarray,
                        ends: np.ndarray,
                        out_dt: DType) -> Tuple[np.ndarray, np.ndarray]:
    """Reduce one column over sorted segments. ``order`` sorts rows by group,
    ``starts``/``ends`` delimit segments in sorted space."""
    n = len(values)
    num_groups = len(starts)
    vs = values[order]
    val_s = validity[order]
    has_valid = (np.add.reduceat(val_s.astype(np.int64), starts) > 0
                 if n else np.zeros(0, np.bool_))

    if kind == "count_valid":
        data = np.add.reduceat(val_s.astype(np.int64), starts)
        return data.astype(out_dt.np_dtype), np.ones(num_groups, np.bool_)
    if kind == "sum":
        x = np.where(val_s, vs, np.zeros(1, dtype=vs.dtype)).astype(out_dt.np_dtype)
        data = np.add.reduceat(x, starts)
        return data, has_valid
    if kind in ("min", "max"):
        if vs.dtype == object:
            # lexicographic min/max over strings; python str comparison is
            # code-point order == UTF-8 byte order, matching Spark/cuDF
            out = np.empty(num_groups, dtype=object)
            pick = min if kind == "min" else max
            for g in range(num_groups):
                seg_valid = val_s[starts[g]:ends[g]]
                seg = vs[starts[g]:ends[g]][seg_valid]
                out[g] = pick(seg) if len(seg) else None
            return out, has_valid
        if vs.dtype.kind == "f":
            neutral = np.inf if kind == "min" else -np.inf
        elif vs.dtype.kind == "b":
            vs = vs.astype(np.int64)
            neutral = 1 if kind == "min" else 0
        else:
            ii = np.iinfo(vs.dtype)
            neutral = ii.max if kind == "min" else ii.min
        x = np.where(val_s, vs, np.asarray(neutral, dtype=vs.dtype))
        op = np.minimum if kind == "min" else np.maximum
        data = op.reduceat(x, starts)
        return data.astype(out_dt.np_dtype), has_valid
    if kind in ("first", "last", "first_valid", "last_valid"):
        pos = np.arange(n, dtype=np.int64)
        if kind.endswith("_valid"):
            if kind.startswith("first"):
                p = np.where(val_s, pos, n)
                sel = np.minimum.reduceat(p, starts)
            else:
                p = np.where(val_s, pos, -1)
                sel = np.maximum.reduceat(p, starts)
            has = (sel >= 0) & (sel < n)
            sel_c = np.clip(sel, 0, max(n - 1, 0))
        else:
            sel_c = starts if kind == "first" else (ends - 1)
            has = np.ones(num_groups, np.bool_)
        if vs.dtype == object:
            data = vs[sel_c]
        else:
            data = vs[sel_c].astype(out_dt.np_dtype)
        validity = np.where(has, val_s[sel_c], False)
        return data, validity
    raise ValueError(f"unknown reduction kind: {kind}")


def grouped_aggregate(keys: List[Tuple[np.ndarray, np.ndarray]],
                      reductions: List[Tuple[str, np.ndarray, np.ndarray, DType]],
                      ) -> Tuple[List[Tuple[np.ndarray, np.ndarray]],
                                 List[Tuple[np.ndarray, np.ndarray]]]:
    """Group rows by ``keys`` and apply ``reductions`` (kind, values,
    validity, out_dtype). Returns (key outputs, reduction outputs), one row
    per group in first-occurrence order of the sorted codes."""
    if keys:
        codes, num_groups = group_codes(keys)
    else:
        n = len(reductions[0][1]) if reductions else 0
        codes = np.zeros(n, dtype=np.int64)
        num_groups = 1 if n else 1  # global agg: always one group (even empty)
    n = len(codes)
    if n == 0:
        order = np.zeros(0, np.int64)
        if keys:
            starts = np.zeros(0, np.int64)
            ends = np.zeros(0, np.int64)
            num_groups = 0
        else:
            # global aggregate over empty input still yields one group
            key_out = []
            red_out = []
            for kind, values, validity, out_dt in reductions:
                if kind == "count_valid":
                    red_out.append((np.zeros(1, out_dt.np_dtype),
                                    np.ones(1, np.bool_)))
                else:
                    fill = dtypes.null_fill_value(out_dt) if not out_dt.is_string else None
                    arr = (np.array([fill], dtype=out_dt.np_dtype)
                           if not out_dt.is_string else np.array([None], dtype=object))
                    red_out.append((arr, np.zeros(1, np.bool_)))
            return [], red_out
    order = np.argsort(codes, kind="stable")
    sorted_codes = codes[order]
    if n:
        boundary = np.concatenate([[True], sorted_codes[1:] != sorted_codes[:-1]])
        starts = np.flatnonzero(boundary)
        ends = np.concatenate([starts[1:], [n]])
        num_groups = len(starts)
    else:
        starts = np.zeros(0, np.int64)
        ends = np.zeros(0, np.int64)
        num_groups = 0

    key_out = []
    for values, validity in keys:
        rep = order[starts] if n else np.zeros(0, np.int64)
        key_out.append((values[rep], validity[rep]))
    red_out = []
    for kind, values, validity, out_dt in reductions:
        red_out.append(segment_reduce_host(kind, values, validity, order,
                                           starts, ends, out_dt))
    return key_out, red_out
