"""API-parity validation tool.

The reference ships ``api_validation``: a reflection tool that diffs each
Spark exec's constructor signature against its Gpu* counterpart across
Spark versions, so API drift shows up as a report instead of a runtime
crash (api_validation/.../ApiValidation.scala:27-60). Same job here,
introspecting the Python exec classes: every CPU (fallback-path) operator
must have a TPU operator registered, and where parameter names overlap
they must agree in order — the contract the plan rewriter's
convert-to-device step depends on.

Run: ``python -m spark_rapids_tpu.tools.api_validation`` — prints a
report and exits nonzero on missing counterparts (CI-able).
"""

from __future__ import annotations

import inspect
import sys
from typing import Dict, List, Tuple, Type

# CPU execs with no device counterpart by design, with the reason
ALLOWED_CPU_ONLY = {
    "CpuScanExec": "leaf ingestion: host file/memory scan feeds the "
                   "HostToDevice transition",
}

# device-only operators (no CPU twin needed): transitions and coalesce
# exist only on the accelerated plan (reference: GpuCoalesceBatches /
# GpuRowToColumnarExec have no CPU-side equivalents either)
ALLOWED_TPU_ONLY = {
    "TpuCoalesceBatchesExec", "TpuExec",
}

# CPU exec base -> differently-named TPU counterpart (the reference's
# SortMergeJoin -> GpuShuffledHashJoinExec replacement is the same shape)
RENAMED = {
    "JoinExec": "ShuffledHashJoinExec",
}


def _exec_classes() -> Tuple[Dict[str, Type], Dict[str, Type]]:
    from spark_rapids_tpu.exec import (  # noqa: F401
        coalesce, cpu, generate, tpu, tpujoin, windowexec, write,
    )
    mods = [cpu, tpu, tpujoin, coalesce, windowexec, generate, write]
    cpus: Dict[str, Type] = {}
    tpus: Dict[str, Type] = {}
    for m in mods:
        for name, obj in vars(m).items():
            if not inspect.isclass(obj) or not name.endswith("Exec"):
                continue
            if name.startswith("Cpu"):
                cpus[name[3:]] = obj
            elif name.startswith("Tpu"):
                tpus[name[3:]] = obj
    return cpus, tpus


def _params(cls: Type) -> List[str]:
    sig = inspect.signature(cls.__init__)
    return [p for p in sig.parameters if p != "self"]


def validate() -> Tuple[List[str], List[str]]:
    """Returns (errors, report_lines)."""
    cpus, tpus = _exec_classes()
    errors: List[str] = []
    lines: List[str] = []
    for base in sorted(cpus):
        cpu_cls = cpus[base]
        tpu_cls = tpus.get(RENAMED.get(base, base))
        if tpu_cls is None:
            if f"Cpu{base}" in ALLOWED_CPU_ONLY:
                lines.append(f"  Cpu{base}: cpu-only (allowed: "
                             f"{ALLOWED_CPU_ONLY[f'Cpu{base}']})")
                continue
            errors.append(f"Cpu{base} has no Tpu{base} counterpart")
            continue
        cp, tp = _params(cpu_cls), _params(tpu_cls)
        shared = [p for p in cp if p in tp]
        cpu_order = [p for p in cp if p in shared]
        tpu_order = [p for p in tp if p in shared]
        if cpu_order != tpu_order:
            errors.append(
                f"{base}: shared ctor params disagree in order: "
                f"Cpu{base}{tuple(cp)} vs Tpu{base}{tuple(tp)}")
        else:
            lines.append(f"  {base}: Cpu{tuple(cp)} ~ Tpu{tuple(tp)} OK")
    for base in sorted(set(tpus) - set(cpus)):
        if f"Tpu{base}" not in ALLOWED_TPU_ONLY:
            lines.append(f"  Tpu{base}: device-only operator")
    return errors, lines


def main() -> int:
    errors, lines = validate()
    print("exec API parity report (CPU fallback vs TPU operators):")
    for line in lines:
        print(line)
    if errors:
        print("\nERRORS:")
        for e in errors:
            print(f"  {e}")
        return 1
    print("\nall operators validated")
    return 0


if __name__ == "__main__":
    sys.exit(main())
