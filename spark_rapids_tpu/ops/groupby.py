"""Hash-group-by kernel, sort-segment style (reference: cuDF
groupBy().aggregate() called from aggregate.scala:728-810).

TPU-first design: cuDF builds a device hash table (data-dependent memory),
which XLA cannot express efficiently. Instead:

  1. hash each key column to 64 bits (x2 independent hashes for strings and
     for collision immunity -> 128 bits total);
  2. one fused ``lax.sort`` of (h1, h2, row-index);
  3. group boundaries where the hash pair changes; group ids by prefix sum;
  4. ``jax.ops.segment_*`` reductions per aggregate.

Everything is O(n log n) sort + O(n) segment ops — shapes static, output
capacity = input capacity, real group count carried as data. This is also
the standard recipe for groupby on SIMD/vector machines.

Null keys form their own group (SQL GROUP BY semantics); float keys are
normalized (-0.0 == 0.0, canonical NaN) before hashing to match CPU
grouping (reference: NormalizeFloatingNumbers.scala).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes
from spark_rapids_tpu.columnar.batch import DeviceBatch, Schema
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.ops import hashing
from spark_rapids_tpu.ops.rowops import gather_batch, gather_column


def row_hashes(batch: DeviceBatch, key_indices: Sequence[int],
               batch_local: bool = False
               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Two independent 64-bit row hashes over the key columns.

    ``batch_local``: the caller only needs consistency WITHIN this batch
    (grouping), not across batches or tables (exchange partitioning) —
    dictionary-encoded string columns then hash their int32 codes (exact
    per batch by construction, zero char reads) instead of running the
    char-scanning poly hashes. NEVER set for exchange/join partitioning:
    two tables' dictionaries assign different codes to equal values.
    Cross-batch string hashing is still gather-free for encoded layouts:
    dictionary columns gather per-VALUE hash tables by code and slab
    columns hash densely from their words (string_poly_hashes_col) —
    bit-identical to the char-scanning hashes, so partition assignment
    is unchanged."""
    h1s, h2s = [], []
    for ki in key_indices:
        col = batch.columns[ki]
        if col.dtype.is_string and not (
                batch_local and col.dict_values is not None):
            h1, h2 = hashing.string_poly_hashes_col(col)
        else:
            data = (col.dict_codes
                    if col.dtype.is_string else col.data)
            h = hashing.hash_fixed_width(data, col.validity)
            h1 = h
            h2 = hashing.splitmix64(h ^ jnp.uint64(hashing.SALT2))
        h1s.append(h1)
        h2s.append(h2)
    return hashing.combine_hashes(h1s), hashing.combine_hashes(h2s)


class GroupInfo:
    """Result of the grouping phase, all device-resident."""

    def __init__(self, perm, group_id_sorted, boundary, num_groups, rep_rows):
        self.perm = perm                    # sorted row order (capacity,)
        self.group_id_sorted = group_id_sorted  # group id per sorted slot
        self.boundary = boundary            # bool: first row of its group
        self.num_groups = num_groups        # int32 scalar
        self.rep_rows = rep_rows            # original row index of each
                                            # group's first row (capacity,)


def group_rows(batch: DeviceBatch, key_indices: Sequence[int],
               compute_rep: bool = True, live=None) -> GroupInfo:
    capacity = batch.capacity
    if live is None:
        live = batch.row_mask()
    # grouping is batch-local: dictionary codes may stand in for string
    # poly hashes (see row_hashes)
    h1, h2 = row_hashes(batch, key_indices, batch_local=True)
    # dead rows sort last
    dead = (~live).astype(jnp.uint8)
    idx = jnp.arange(capacity, dtype=jnp.int32)
    dead_s, h1_s, h2_s, perm = jax.lax.sort((dead, h1, h2, idx), num_keys=3,
                                            is_stable=True)
    live_s = dead_s == 0
    prev_h1 = jnp.concatenate([h1_s[:1] ^ jnp.uint64(1), h1_s[:-1]])
    prev_h2 = jnp.concatenate([h2_s[:1], h2_s[:-1]])
    boundary = ((h1_s != prev_h1) | (h2_s != prev_h2)) & live_s
    boundary = boundary.at[0].set(live_s[0])
    group_id = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    group_id = jnp.where(live_s, group_id, capacity - 1)  # park dead rows
    num_groups = boundary.sum().astype(jnp.int32)
    rep_rows = None
    if compute_rep:
        # original row of each group's first sorted row (capacity-wide
        # scatter: the row-space reduce path skips this, computing reps
        # at group-slot width instead — ops/aggregate.py)
        rep_rows = jax.ops.segment_sum(
            jnp.where(boundary, perm, 0), group_id, num_segments=capacity)
    return GroupInfo(perm, group_id, boundary, num_groups, rep_rows)


def gather_keys(batch: DeviceBatch, key_indices: Sequence[int],
                info: GroupInfo) -> List[DeviceColumn]:
    """Key columns with one row per group (group's first occurrence)."""
    live = jnp.arange(batch.capacity, dtype=jnp.int32) < info.num_groups
    from spark_rapids_tpu.ops.rowops import gather_columns
    return gather_columns([batch.columns[ki] for ki in key_indices],
                          info.rep_rows, live)


def minmax_operands(vs, kind: str):
    """Shared (values, neutral) selection for min/max reductions — one
    definition consumed by the sorted-space, row-space/slot, and
    single-group aggregation paths so their semantics cannot diverge."""
    if jnp.issubdtype(vs.dtype, jnp.floating):
        return vs, (jnp.inf if kind == "min" else -jnp.inf)
    if vs.dtype == jnp.bool_:
        return vs.astype(jnp.int32), (1 if kind == "min" else 0)
    info_ = jnp.iinfo(vs.dtype)
    return vs, (info_.max if kind == "min" else info_.min)


def segment_reduce(kind: str, values: jnp.ndarray, validity: jnp.ndarray,
                   info: GroupInfo, out_dtype) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One reduction over groups. Returns (data, validity) of capacity size
    with the first num_groups entries real.

    kinds: sum, min, max, count_valid, first, last, first_valid, last_valid,
    any.
    """
    capacity = values.shape[0]
    vs = values[info.perm]
    val_s = validity[info.perm]
    gid = info.group_id_sorted
    seg = lambda op, x: op(x, gid, num_segments=capacity)  # noqa: E731
    group_has_valid = seg(jax.ops.segment_max, val_s.astype(jnp.int32)) > 0

    if kind == "count_valid":
        data = seg(jax.ops.segment_sum, val_s.astype(jnp.int64))
        return data.astype(out_dtype), jnp.ones((capacity,), jnp.bool_)
    if kind == "sum":
        x = jnp.where(val_s, vs, 0).astype(out_dtype)
        data = seg(jax.ops.segment_sum, x)
        return data, group_has_valid
    if kind in ("min", "max"):
        vs, neutral = minmax_operands(vs, kind)
        x = jnp.where(val_s, vs, neutral)
        op = jax.ops.segment_min if kind == "min" else jax.ops.segment_max
        data = seg(op, x)
        if out_dtype == jnp.bool_:
            data = data.astype(jnp.bool_)
        return data.astype(out_dtype), group_has_valid
    if kind in ("first", "last", "first_valid", "last_valid"):
        sel_c, picked = _segment_pick_pos(kind, val_s, gid, capacity)
        data = vs[sel_c].astype(out_dtype)
        validity = picked & val_s[sel_c]
        return data, validity
    if kind == "any":
        data = seg(jax.ops.segment_max, (vs & val_s).astype(jnp.int32)) > 0
        return data.astype(out_dtype), jnp.ones((capacity,), jnp.bool_)
    raise ValueError(f"unknown reduction kind: {kind}")


def _segment_pick_pos(kind: str, val_s: jnp.ndarray, gid: jnp.ndarray,
                      capacity: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Shared first/last position selection in sorted-slot space. Returns
    (sel_c clipped sorted-slot index per group, picked bool per group)."""
    pos = jnp.arange(capacity, dtype=jnp.int32)
    eligible = val_s if kind.endswith("_valid") else jnp.ones(
        (capacity,), jnp.bool_)
    if kind.startswith("first"):
        sel = jax.ops.segment_min(jnp.where(eligible, pos, capacity + 1),
                                  gid, num_segments=capacity)
    else:
        sel = jax.ops.segment_max(jnp.where(eligible, pos, -1),
                                  gid, num_segments=capacity)
    picked = (sel >= 0) & (sel < capacity)
    return jnp.clip(sel, 0, capacity - 1), picked


def segment_select_string(kind: str, col, info: GroupInfo
                          ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Winning ORIGINAL row index per group for string reductions (the value
    itself is materialized later with one string gather). Returns
    (rows int32 (capacity,), has_valid bool (capacity,)).

    min/max results are EXACT lexicographic byte order: the prefix-image
    sort decides within the sort kernel's 64-byte images (+ length key),
    and any group whose winning slot ties its neighbour on the whole
    prefix is re-decided by the cond-gated full-length refinement below.
    first/last are positional."""
    from spark_rapids_tpu.ops.sortops import _string_prefix_chunks
    capacity = col.validity.shape[0]
    gid = info.group_id_sorted
    val_s = col.validity[info.perm]
    seg = lambda op, x: op(x, gid, num_segments=capacity)  # noqa: E731
    has = seg(jax.ops.segment_max, val_s.astype(jnp.int32)) > 0

    if kind in ("min", "max"):
        want_max = kind == "max"
        imgs = [c[info.perm] for c in _string_prefix_chunks(col)]
        if want_max:
            imgs = [~img for img in imgs]
        allones = ~jnp.uint64(0)
        imgs = [jnp.where(val_s, img, allones) for img in imgs]
        # invalid rows must sort strictly last within the group: the image
        # sentinel alone cannot guarantee it for max, where a valid empty
        # string's inverted image is also all-ones and an earlier null row
        # would stably win the boundary slot. Wide string keys (9 image
        # operands) take the LSD path inside lexsort_permutation — a
        # direct multi-operand sort compiles pathologically at large
        # capacities on TPU.
        from spark_rapids_tpu.ops.rowops import packed_gather_vectors
        from spark_rapids_tpu.ops.sortops import lexsort_permutation
        invalid_key = (~val_s).astype(jnp.uint8)
        keys = [gid, invalid_key] + list(imgs)
        p2 = lexsort_permutation(keys)
        gathered = packed_gather_vectors(
            list(imgs) + [info.perm, val_s], p2)
        imgs_s = gathered[:len(imgs)]
        orig_new = gathered[len(imgs)]
        val_new = gathered[len(imgs) + 1] != 0
        # gid sequence is unchanged by the re-sort, so the original group
        # boundaries still mark each group's first (= winning) slot
        rows = seg(jax.ops.segment_sum,
                   jnp.where(info.boundary, orig_new, 0))
        # Exactness: the prefix images only order the first 64 bytes. If a
        # group's winning slot ties its neighbour on the whole prefix, the
        # true winner needs full-length compares — run a segmented doubling
        # reduce with the exact comparator, skipped entirely (lax.cond) in
        # the common no-tie case.
        pos = jnp.arange(capacity, dtype=jnp.int32)
        same_g = jnp.concatenate(
            [jnp.zeros((1,), jnp.bool_), gid[1:] == gid[:-1]])
        tie_prev = same_g
        # scan the 8 byte-prefix images only — NOT the trailing length
        # image: candidates sharing the 64-byte prefix but differing in
        # length are length-ordered by the sort, which is wrong whenever
        # bytes past the prefix disagree with length order, so they MUST
        # refine (the exact comparator settles prefix-of cases too)
        for img in imgs_s[:-1]:
            tie_prev = tie_prev & jnp.concatenate(
                [jnp.zeros((1,), jnp.bool_), img[1:] == img[:-1]])
        both_valid = val_new & jnp.concatenate(
            [jnp.zeros((1,), jnp.bool_), val_new[:-1]])
        tie_prev = tie_prev & both_valid
        tie_next = jnp.concatenate([tie_prev[1:],
                                    jnp.zeros((1,), jnp.bool_)])
        need_refine = jnp.any(info.boundary & tie_next)

        def refine(_):
            from spark_rapids_tpu.ops import strings as string_ops
            cand, cval = orig_new, val_new
            s = 1
            while s < capacity:
                prev_c = jnp.where(pos >= s, jnp.roll(cand, s), cand)
                prev_v = jnp.where(pos >= s, jnp.roll(cval, s), False)
                same = (pos >= s) & (gid == jnp.roll(gid, s))
                cmp = string_ops.compare_rows(col, prev_c, cand)
                better = (cmp > 0) if want_max else (cmp < 0)
                take = same & prev_v & ((~cval) | better)
                cand = jnp.where(take, prev_c, cand)
                cval = cval | (same & prev_v)
                s <<= 1
            last = jnp.concatenate([gid[1:] != gid[:-1],
                                    jnp.ones((1,), jnp.bool_)])
            return seg(jax.ops.segment_sum, jnp.where(last, cand, 0))

        rows = jax.lax.cond(need_refine, refine, lambda _: rows, None)
        return rows, has

    if kind in ("first", "last", "first_valid", "last_valid"):
        sel_c, picked = _segment_pick_pos(kind, val_s, gid, capacity)
        rows = info.perm[sel_c]
        return rows, picked & val_s[sel_c]
    raise ValueError(f"unknown string reduction kind: {kind}")
