"""Arithmetic float64 -> IEEE-754 bits (no 64-bit float bitcast).

The TPU AOT compile helper on this attachment rejects any program that
bitcasts a float64 operand (``f64.view(uint64)``, ``bitcast_convert_type``
to uint64 *or* 2x uint32, ``frexp``, ``ldexp`` all fail with a compiler
crash), while 64-bit integer bitcasts and arithmetic compile fine. Sort key
images (ops/sortops.py) and row hashes (ops/hashing.py) need the exact IEEE
bit pattern of float columns, so this module reconstructs it with exact
floating-point arithmetic only:

  * binary normalization: scale |x| into [1, 2) by a fixed unrolled ladder
    of exact power-of-two multiplies, accumulating the unbiased exponent;
  * mantissa: ``x1 * 2^52`` is then an exact 53-bit integer;
  * zero/inf/NaN patch in as constants. Denormals flush to +0.0 bits: TPU
    float arithmetic is flush-to-zero on read, so their true bits are
    unrecoverable on device — and they already behave as 0.0 in every
    other traced op.

Matches ``np.float64.view(np.uint64)`` bit-for-bit (denormals aside) after
the engine's standard normalizations (-0.0 -> +0.0, NaN -> canonical quiet
NaN), which this function applies itself — so it is also the device twin of
the normalize-then-view sequence in ops/hashing.py's numpy path.

Measured TPU v5e caveat: float64 there is emulated as a double-float32
pair (~49-bit mantissa, float32 exponent range) and even a device_put/
device_get roundtrip is lossy. Bit-exactness with the host is therefore
impossible on hardware for ANY implementation; the contract this module
ships is (a) bit-exact on CPU (the differential-test mesh), (b) on TPU,
strictly monotone w.r.t. device float ordering and equality-consistent
with device float equality (verified empirically across exponent bands),
so sorts, joins and group-bys agree with what the device's own float
semantics say. The ladder steps above 2^128 are unreachable there (their
constants saturate to inf, making the compares trivially false), which is
harmless: no representable value needs them.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

_U64 = jnp.uint64

# descending ladder; after processing step k the magnitude lies in
# [2^(1-2k'), 2^k') for the next k' — ten exact steps land in [1, 2)
_EXP_STEPS = (512, 256, 128, 64, 32, 16, 8, 4, 2, 1)

_CANONICAL_NAN_BITS = np.uint64(0x7FF8) << np.uint64(48)
_INF_BITS = np.uint64(0x7FF) << np.uint64(52)


def f64_bits(f: jnp.ndarray) -> jnp.ndarray:
    """uint64 IEEE bits of a float64 array, with -0.0 normalized to +0.0,
    every NaN mapped to the canonical quiet NaN pattern, and denormals
    flushed to +0.0 bits.

    One code path on every backend, so the CPU differential-test mesh
    exercises exactly what runs on TPU. The denormal flush is not a choice:
    XLA float arithmetic (including the ``== 0.0`` comparison the previous
    normalize-then-view used) reads denormals as zero on both backends, so
    their true bits are unrecoverable in any traced op."""
    return f64_bits_arith(f)


def f64_bits_arith(f: jnp.ndarray) -> jnp.ndarray:
    """The arithmetic reconstruction (no 64-bit float bitcast)."""
    f = f.astype(jnp.float64)
    ax = jnp.abs(f)
    neg = f < 0  # False for -0.0: normalized to +0.0 by construction
    nan = jnp.isnan(f)
    inf = jnp.isinf(ax)
    # denormals bucket with zero: FTZ hardware reads them as 0.0, and a
    # comparison cannot even distinguish them reliably under FTZ
    zero = ax < 2.0 ** -1022
    special = zero | inf | nan

    x1 = jnp.where(special, 1.0, ax)
    e = jnp.zeros(f.shape, jnp.int64)
    for k in _EXP_STEPS:
        big = x1 >= 2.0 ** k
        x1 = jnp.where(big, x1 * 2.0 ** -k, x1)
        e = e + jnp.where(big, k, 0)
        lift = x1 < 2.0 ** (1 - k)
        x1 = jnp.where(lift, x1 * 2.0 ** k, x1)
        e = e - jnp.where(lift, k, 0)
    # value == x1 * 2^e with x1 in [1, 2), e in [-1022, 1023]
    scaled = (x1 * 2.0 ** 52).astype(_U64)  # exact integer in [2^52, 2^53)
    mant = scaled - (_U64(1) << _U64(52))
    biased = jnp.clip(e + 1023, 1, 2046).astype(_U64)
    bits = (biased << _U64(52)) | mant
    bits = jnp.where(zero, _U64(0), bits)
    bits = jnp.where(inf, _U64(_INF_BITS), bits)
    bits = jnp.where(nan, _U64(_CANONICAL_NAN_BITS), bits)
    sign = jnp.where(neg & ~nan & ~zero, _U64(1) << _U64(63), _U64(0))
    return bits | sign


def np_f64_bits(f: np.ndarray) -> np.ndarray:
    """Numpy twin: normalize (-0.0 and denormals -> +0.0, NaN -> canonical)
    then view — the reference result f64_bits must match bit-for-bit."""
    f64 = np.asarray(f, dtype=np.float64).copy()
    f64[np.abs(f64) < 2.0 ** -1022] = 0.0
    f64[np.isnan(f64)] = np.nan
    return f64.view(np.uint64)
