"""Device-resident Parquet decode: raw pages -> DeviceBatch.

The deviceDecode scan mode (spark.rapids.sql.scan.deviceDecode) splits a
row-group's decode across the two sides of the scan pipeline:

  * ``prepare_rowgroup`` runs ON THE DECODE WORKER (sql/scan_pipeline.py
    pool): reads raw column-chunk bytes (sql/parquet_raw.py), splits and
    decompresses pages, and builds per-column DECODE PLANS — small numpy
    run tables plus the encoded streams viewed as u32 word buffers. Host
    work is byte shuffling plus O(#runs) header parsing; no value is
    decoded on the host. Columns the device path cannot take fall back to
    the classic pyarrow host decode per column (journaled as
    ``scanDeviceFallback`` with a reason, ranked by tools/qualification).
  * ``decode_rowgroup`` runs ON THE CONSUMER THREAD: ships every plan's
    buffers in ONE ``jax.device_put`` (plus the fallback columns' classic
    host buffers) and expands them with the ops/pallas_kernels decode
    family (jnp twins by default, =interpret for kernel-body CI, =1 for
    attached TPUs) straight into PR 11's native column forms — dictionary
    codes-only, (cap, stride/8) u64 char slabs, dense fixed-width arrays.

Pages are cached encoded (memory/spill.py EncodedPageCache): a warm
re-scan re-decodes from cached pages — device-resident ones skip even the
upload — and performs zero host file reads.

Encoding coverage and the fallback-reason vocabulary live in
docs/scan_device.md.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from spark_rapids_tpu.obs.events import EVENTS
from spark_rapids_tpu.obs.metrics import REGISTRY
from spark_rapids_tpu.sql import parquet_raw as praw

_DEV_BYTES = REGISTRY.counter("scan.device.bytesDevice")
_HOST_BYTES = REGISTRY.counter("scan.device.bytesHost")
_DEV_COLS = REGISTRY.counter("scan.device.columns")
_FB_COLS = REGISTRY.counter("scan.device.fallbackColumns")
_DEV_SPLITS = REGISTRY.counter("scan.device.splits")
_HOST_READS = REGISTRY.counter("scan.device.hostReads")
_DEC_TIME = REGISTRY.timer("scan.device.decodeTime")
_HOST_DEC_TIME = REGISTRY.timer("scan.device.hostDecodeTime")
_PREP_TIME = REGISTRY.timer("scan.device.prepTime")

# journal dedup: one scanDeviceFallback event per (path, column, reason)
# — a thousand-row-group scan must not flood the flight ring (the
# per-column counters carry the exact aggregates)
_EMITTED: Dict[Tuple[str, str, str], bool] = {}
_EMITTED_CAP = 1024

_FIXED_KINDS = {"INT32": ("i32", 4), "INT64": ("i64", 8),
                "FLOAT": ("f32", 4), "DOUBLE": ("f64", 8)}

_DICT_ENCODINGS = (praw.ENC_PLAIN_DICTIONARY, praw.ENC_RLE_DICTIONARY)


def _note_fallback(path: str, column: str, reason: str, rg: int) -> None:
    _FB_COLS.add(1)
    key = (path, column, reason)
    if key in _EMITTED:
        return
    if len(_EMITTED) >= _EMITTED_CAP:
        _EMITTED.clear()
    _EMITTED[key] = True
    EVENTS.emit("scanDeviceFallback", column=column, reason=reason,
                path=path, rowGroup=rg)


def _words_u8(parts: List[bytes]) -> Tuple[np.ndarray, List[int]]:
    """Concatenate byte streams into one u32 word buffer (8 pad bytes so
    every u64 window load lands in bounds). Returns (words, per-part
    byte offsets)."""
    offs, total = [], 0
    for p in parts:
        offs.append(total)
        total += len(p)
    buf = b"".join(parts) + b"\0" * (((-total) % 4) + 8)
    return np.frombuffer(buf, np.uint32).copy(), offs


def _pad1(arr: np.ndarray, cap: int, fill=0) -> np.ndarray:
    out = np.full(cap, fill, arr.dtype)
    out[:len(arr)] = arr
    return out


def _pad_run_table(tbl: dict) -> dict:
    """Guard row past the real runs: decode runs at output length = the
    CAPACITY bucket, so the cursor / searchsorted must have somewhere
    sane to land for padding rows (values there are masked anyway)."""
    r = len(tbl["kind"])
    big = np.iinfo(np.int32).max
    return {
        "out_start": np.concatenate(
            [tbl["out_start"], np.asarray([big], np.int32)]),
        "kind": _pad1(tbl["kind"], r + 1),
        "value": _pad1(tbl["value"], r + 1),
        "bit_start": _pad1(tbl["bit_start"], r + 1),
        "bw": _pad1(tbl["bw"], r + 1),
    }


def _count_level_ones(levels: bytes, num_values: int) -> int:
    """Non-null count of a max_def=1 page from its def-level hybrid
    stream, O(#runs) + popcount over bit-packed spans (the format
    zero-pads partial groups, so popcount is exact)."""
    pos = 0
    out = 0
    ones = 0
    while out < num_values and pos < len(levels):
        header, pos = praw._uvarint(levels, pos)
        if header & 1:
            groups = header >> 1
            span = levels[pos:pos + groups]
            pos += groups
            take = min(groups * 8, num_values - out)
            ones += int(np.unpackbits(
                np.frombuffer(span, np.uint8)).sum())
            out += take
        else:
            count = header >> 1
            v = levels[pos] if pos < len(levels) else 0
            pos += 1
            take = min(count, num_values - out)
            if v & 1:
                ones += take
            out += take
    return min(ones, num_values)


class _Unsupported(Exception):
    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


def _split_page(chunk, page) -> Tuple[Optional[bytes], bytes]:
    if chunk.max_def == 0:
        return None, page.payload
    n = int.from_bytes(page.payload[:4], "little")
    return page.payload[4:4 + n], page.payload[4 + n:]


def _plan_levels(chunk) -> Tuple[dict, List[int], List[bytes]]:
    """(levels plan-part, per-page non-null counts, per-page value
    streams)."""
    nns: List[int] = []
    streams: List[bytes] = []
    if chunk.max_def == 0:
        for pg in chunk.pages:
            nns.append(pg.num_values)
            streams.append(pg.payload)
        return {}, nns, streams
    lv_parts: List[bytes] = []
    tables: List[dict] = []
    for pg in chunk.pages:
        lv, rest = _split_page(chunk, pg)
        streams.append(rest)
        nns.append(_count_level_ones(lv, pg.num_values))
        lv_parts.append(lv)
    words, offs = _words_u8(lv_parts)
    for lv, off, pg in zip(lv_parts, offs, chunk.pages):
        tables.append(praw.hybrid_run_table(lv, 1, pg.num_values,
                                            base_bit=off * 8))
    tbl = _pad_run_table(praw.merge_run_tables(tables))
    return {"lv_words": words, **{f"lv_{k}": v for k, v in tbl.items()}}, \
        nns, streams


def _plan_codes(streams: List[bytes], nns: List[int]) -> dict:
    """Dictionary-index streams ([bw byte][hybrid]) -> merged run
    table + word buffer (cd_*)."""
    bodies = [s[1:] for s in streams]
    words, offs = _words_u8(bodies)
    tables = []
    for s, off, nn in zip(streams, offs, nns):
        bw = s[0] if s else 0
        if bw > 32:
            raise _Unsupported("dictWide")
        t = praw.hybrid_run_table(s[1:], bw, nn, base_bit=off * 8)
        tables.append(t)
    tbl = _pad_run_table(praw.merge_run_tables(tables))
    return {"cd_words": words, **{f"cd_{k}": v for k, v in tbl.items()}}


def plan_column(chunk: "praw.RawColumnChunk", dt, arrow_type,
                blocked: int) -> dict:
    """One column chunk -> decode plan: {"kind", "upload": {name: np
    array}, "meta": {...}}. Raises _Unsupported(reason) when the chunk
    must ride the host path."""
    from spark_rapids_tpu.columnar.batch import bucket_capacity

    if chunk.unsupported:
        raise _Unsupported(chunk.unsupported)
    if chunk.max_rep > 0:
        raise _Unsupported("nested")
    if chunk.max_def > 1:
        raise _Unsupported("defLevels")
    if not chunk.pages:
        raise _Unsupported("empty")
    pt = chunk.physical_type
    encs = {pg.encoding for pg in chunk.pages}
    is_dict = bool(encs & set(_DICT_ENCODINGS))
    if is_dict and not encs <= set(_DICT_ENCODINGS):
        # writer overflowed its dictionary mid-chunk and switched the
        # remaining pages to PLAIN — decodable only column-at-a-time on
        # the host
        raise _Unsupported("mixedEncoding")
    if is_dict and chunk.dict_page is None:
        raise _Unsupported("noDictPage")
    if not is_dict and len(encs) > 1:
        raise _Unsupported("mixedEncoding")
    enc = next(iter(encs))
    lv, nns, streams = _plan_levels(chunk)
    nn_total = sum(nns)
    nv_cap = bucket_capacity(max(nn_total, 1))
    meta = {"n": chunk.num_values, "nn": nn_total,
            "max_def": chunk.max_def, "ts": None, "cast": None}
    upload = dict(lv)
    import pyarrow as pa
    if pa.types.is_timestamp(arrow_type):
        meta["ts"] = arrow_type.unit

    if pt == "BOOLEAN":
        if enc != praw.ENC_PLAIN:
            raise _Unsupported(f"enc:{praw.ENCODING_NAMES.get(enc, enc)}")
        # PLAIN booleans ARE a bit-packed stream: spell each page as one
        # bw=1 bit-packed run and ride the hybrid expander
        words, offs = _words_u8(streams)
        tbl = _pad_run_table({
            "out_start": np.concatenate(
                [np.zeros(1, np.int64), np.cumsum(nns)]).astype(np.int32),
            "kind": np.ones(len(nns), np.uint8),
            "value": np.zeros(len(nns), np.int32),
            "bit_start": np.asarray([o * 8 for o in offs], np.int64),
            "bw": np.ones(len(nns), np.int32),
        })
        upload.update({"cd_words": words,
                       **{f"cd_{k}": v for k, v in tbl.items()}})
        meta["kind"] = "bool"
        return {"kind": "bool", "upload": upload, "meta": meta}

    if pt == "BYTE_ARRAY":
        if not dt.is_string:
            raise _Unsupported("binary")
        if is_dict:
            dvals = praw.parse_plain_byte_array(chunk.dict_page.payload,
                                                chunk.dict_page.num_values)
            return _plan_str_dict(upload, meta, streams, nns, dvals,
                                  blocked)
        if enc != praw.ENC_PLAIN:
            raise _Unsupported(f"enc:{praw.ENCODING_NAMES.get(enc, enc)}")
        return _plan_str_plain(upload, meta, streams, nn_total, nv_cap,
                               blocked)

    if pt not in _FIXED_KINDS:
        raise _Unsupported(f"type:{pt}")  # INT96, FLBA
    pkind, isize = _FIXED_KINDS[pt]
    meta["pkind"] = pkind
    if dt.np_dtype is not None and pkind in ("i32", "i64") \
            and dt.np_dtype.itemsize < isize:
        meta["cast"] = dt.np_dtype.str  # int8/int16 stored as INT32

    if is_dict:
        # dictionary page is a PLAIN fixed stream of `card` values:
        # upload it raw, decode it device-side, gather by codes
        card = chunk.dict_page.num_values
        dw, _ = _words_u8([chunk.dict_page.payload])
        if len(chunk.dict_page.payload) < card * isize:
            raise _Unsupported("dictShort")
        upload.update({"dv_words": dw})
        upload.update(_plan_codes(streams, nns))
        meta["card"] = card
        return {"kind": "fixed_dict", "upload": upload, "meta": meta}

    if enc == praw.ENC_DELTA_BINARY_PACKED:
        if pkind not in ("i32", "i64"):
            raise _Unsupported("deltaFloat")
        words, offs = _words_u8(streams)
        pages = []
        for j, (s, off, nn) in enumerate(zip(streams, offs, nns)):
            res = praw.delta_header_table(s, base_bit=off * 8)
            if res is None:
                raise _Unsupported("deltaWide")
            first, _vpm, total, tbl = res
            if total != nn:
                raise _Unsupported("deltaCount")
            guard = {"out_start": np.concatenate(
                [tbl["out_start"],
                 np.asarray([np.iinfo(np.int32).max], np.int32)]),
                "bit_width": _pad1(tbl["bit_width"],
                                   len(tbl["bit_width"]) + 1),
                "min_delta": _pad1(tbl["min_delta"],
                                   len(tbl["min_delta"]) + 1),
                "bit_start": _pad1(tbl["bit_start"],
                                   len(tbl["bit_start"]) + 1)}
            for k, v in guard.items():
                upload[f"d{j}_{k}"] = v
            upload[f"d{j}_first"] = np.asarray([first], np.int64)
            pages.append((j, total))
        upload["dl_words"] = words
        meta["delta_pages"] = pages
        return {"kind": "fixed_delta", "upload": upload, "meta": meta}

    if enc != praw.ENC_PLAIN:
        raise _Unsupported(f"enc:{praw.ENCODING_NAMES.get(enc, enc)}")
    # PLAIN fixed width: the value streams concatenate into one aligned
    # buffer (each page's stream is exactly nn_p * itemsize bytes)
    clipped = [s[:nn * isize] for s, nn in zip(streams, nns)]
    for s, nn in zip(clipped, nns):
        if len(s) != nn * isize:
            raise _Unsupported("levelMismatch")
    words, _ = _words_u8(clipped)
    upload["vals"] = words
    return {"kind": "fixed_plain", "upload": upload, "meta": meta}


def _plan_str_plain(upload: dict, meta: dict, streams: List[bytes],
                    nn_total: int, nv_cap: int, blocked: int) -> dict:
    from spark_rapids_tpu.columnar.column import slab_stride_for
    if blocked <= 0:
        raise _Unsupported("slabOff")
    chars = b"".join(streams)
    starts, lens = praw.plain_byte_array_starts(chars, nn_total)
    max_len = int(lens.max()) if nn_total else 0
    stride = slab_stride_for(max_len, blocked)
    if not stride:
        raise _Unsupported("slabStride")
    pad = np.zeros(((-len(chars)) % 4) + max(stride, 8), np.uint8)
    upload["chars"] = np.concatenate(
        [np.frombuffer(chars, np.uint8), pad])
    upload["st"] = _pad1(starts, nv_cap)
    upload["ln"] = _pad1(lens, nv_cap)
    meta["stride"] = stride
    return {"kind": "str_plain", "upload": upload, "meta": meta}


def _plan_str_dict(upload: dict, meta: dict, streams: List[bytes],
                   nns: List[int], dvals: List[bytes],
                   blocked: int) -> dict:
    """Dictionary string column: codes ride the hybrid expander; the
    page dictionary (canonically sorted, matching host_dict_encode's
    compile-key contract) becomes either the batch dictionary (codes-
    only column) or a host-built char slab the device gathers rows from
    (large-cardinality / NUL-bearing dictionaries)."""
    from spark_rapids_tpu.columnar.column import (
        DICT_MAX_CARD, np_build_slab, slab_stride_for,
    )
    card = len(dvals)
    order = sorted(range(card), key=lambda i: dvals[i])
    remap = np.empty(card + 1, np.int32)
    for rank, i in enumerate(order):
        remap[i] = rank
    remap[card] = card
    svals = [dvals[i] for i in order]
    has_nul = any(b"\0" in v for v in svals)
    try:
        vals_tuple = tuple(v.decode("utf-8") for v in svals)
    except UnicodeDecodeError:
        raise _Unsupported("dictUtf8")
    if sorted(vals_tuple) != list(vals_tuple):
        # bytewise and str sort orders diverge past the BMP; keep the
        # canonical contract by re-sorting in str space
        order2 = sorted(range(card), key=lambda i: vals_tuple[i])
        inv = np.empty(card + 1, np.int32)
        for rank, i in enumerate(order2):
            inv[i] = rank
        inv[card] = card
        remap = inv[remap]
        svals = [svals[i] for i in order2]
        vals_tuple = tuple(vals_tuple[i] for i in order2)
    max_len = max((len(v) for v in svals), default=0)
    stride = slab_stride_for(max_len, blocked) if blocked > 0 else 0
    dict_ok = card <= DICT_MAX_CARD and card > 0 and not has_nul
    if not dict_ok and not stride:
        raise _Unsupported("dictStride")
    if stride:
        dchars = b"".join(svals)
        offs = np.zeros(card + 2, np.int32)
        offs[1:card + 1] = np.cumsum([len(v) for v in svals])
        offs[card + 1] = offs[card]  # zero-length null row at index card
        slab, slens = np_build_slab(
            np.frombuffer(dchars or b"\0", np.uint8), offs, card + 1,
            stride)
        upload["slab"] = slab
        upload["slens"] = slens.astype(np.int32)
        meta["stride"] = stride
    else:
        meta["stride"] = 0
    upload["rm"] = remap
    upload.update(_plan_codes(streams, nns))
    meta["card"] = card
    meta["dict_ok"] = dict_ok
    meta["vals"] = vals_tuple if dict_ok else None
    return {"kind": "str_dict", "upload": upload, "meta": meta}


# ---------------------------------------------------------------------------
# Worker side: RawRowGroup assembly
# ---------------------------------------------------------------------------

class RawRowGroup:
    """Worker-side product of the deviceDecode path: per-column decode
    plans + the host-decoded fallback frame. Flows through the scan
    prefetcher like a DataFrame (``nbytes`` feeds its budget)."""

    is_raw_rowgroup = True

    def __init__(self, path: str, rg: int, pvals: dict, n: int,
                 mtime: Optional[float]):
        self.path = path
        self.rg = rg
        self.pvals = pvals
        self.n = n
        self.mtime = mtime
        self.plans: Dict[str, dict] = {}       # column -> decode plan
        self.cached: Dict[str, bool] = {}      # column -> page-cache hit
        self.fallback: List[Tuple[str, str]] = []
        self.fallback_df = None
        self.stats: Dict[str, Tuple[int, int]] = {}
        self.nbytes = 0

    # generic operator wrappers count split rows through either of these
    @property
    def _host_rows(self) -> int:
        return self.n

    def __len__(self) -> int:
        return self.n


def prepare_rowgroup(path: str, rg: int, pvals: dict, columns: List[str],
                     dtypes_by_name: dict, blocked: int, page_cache=None,
                     direct: bool = True):
    """Build a RawRowGroup on the decode worker. Returns a plain pandas
    DataFrame instead when NO column can ride the device path (the
    consumer then treats the split exactly like a legacy one)."""
    md = praw.file_metadata(path)
    mtime = praw.file_mtime(path)
    rg_meta = md.row_group(rg)
    arrow_schema = md.schema.to_arrow_schema()
    ci_by_name = {rg_meta.column(ci).path_in_schema: ci
                  for ci in range(rg_meta.num_columns)}
    raw = RawRowGroup(path, rg, pvals, int(rg_meta.num_rows), mtime)
    with _PREP_TIME.time():
        for name in columns:
            ci = ci_by_name.get(name)
            if ci is None:
                raw.fallback.append((name, "missing"))
                _note_fallback(path, name, "missing", rg)
                continue
            cache_key = (path, mtime, rg, name)
            hit = page_cache.get(cache_key) if page_cache is not None \
                else None
            if hit is not None:
                raw.plans[name] = hit
                raw.cached[name] = True
                raw.nbytes += hit.get("nbytes", 0)
                continue
            dt = dtypes_by_name[name]
            try:
                chunk = praw.read_column_chunk(path, rg, ci, md=md,
                                               mtime=mtime)
                plan = plan_column(chunk, dt,
                                   arrow_schema.field(name).type, blocked)
            except _Unsupported as e:
                raw.fallback.append((name, e.reason))
                _note_fallback(path, name, e.reason, rg)
                continue
            except Exception:  # noqa: BLE001 — never fail the scan here
                raw.fallback.append((name, "parseError"))
                _note_fallback(path, name, "parseError", rg)
                continue
            plan["nbytes"] = sum(a.nbytes for a in plan["upload"].values())
            raw.plans[name] = plan
            raw.cached[name] = False
            raw.nbytes += plan["nbytes"]
            if page_cache is not None:
                page_cache.put(cache_key, plan, plan["nbytes"])
            # footer min/max seed the advisory stats registry (consumers
            # verify on device before relying on them) — the analogue of
            # note_scan_stats on the pandas path
            if dt.is_integral:
                col = rg_meta.column(ci)
                s = col.statistics
                if s is not None and s.has_min_max \
                        and isinstance(s.min, int) \
                        and isinstance(s.max, int):
                    raw.stats[name] = (int(s.min), int(s.max))
    if raw.fallback:
        fb_cols = [name for name, _ in raw.fallback]
        import pyarrow.parquet as pq

        from spark_rapids_tpu.sql.sources import (
            _arrow_decode, _attach_dict_hints,
        )
        with _HOST_DEC_TIME.time():
            table = pq.ParquetFile(path).read_row_group(rg,
                                                        columns=fb_cols)
            df = _arrow_decode(table, direct)
            df = _attach_dict_hints(df)
        _HOST_READS.add(1)
        _HOST_BYTES.add(int(df.memory_usage(deep=False).sum()))
        raw.fallback_df = df
        raw.nbytes += int(df.memory_usage(deep=False).sum())
    if not raw.plans and columns:
        # nothing rides the device path: hand back the classic frame
        return raw.fallback_df if raw.fallback_df is not None else None
    return raw


# ---------------------------------------------------------------------------
# Consumer side: plans -> DeviceBatch
# ---------------------------------------------------------------------------

def _decode_levels(up, meta, cap: int, n: int):
    import jax.numpy as jnp

    from spark_rapids_tpu.ops import pallas_kernels as pk
    row_mask = jnp.arange(cap, dtype=jnp.int32) < n
    if meta["max_def"] == 0 or "lv_words" not in up:
        return row_mask
    levels = pk.hybrid_expand(up["lv_words"], up["lv_out_start"],
                              up["lv_kind"], up["lv_value"],
                              up["lv_bit_start"], up["lv_bw"], cap)
    return (levels == meta["max_def"]) & row_mask


def _value_positions(validity):
    import jax.numpy as jnp
    pos = jnp.cumsum(validity.astype(jnp.int32)) - 1
    return jnp.maximum(pos, 0)


def _gather_rows(vals_v, validity, fill):
    """Value-space stream -> row space: non-null row k takes value
    cumsum(validity)[k]-1, null rows take the canonical fill."""
    import jax.numpy as jnp
    idx = jnp.clip(_value_positions(validity), 0,
                   max(vals_v.shape[0] - 1, 0))
    return jnp.where(validity, vals_v[idx], fill)


def _apply_ts(vals, unit):
    import jax.numpy as jnp
    if unit in (None, "us"):
        return vals
    if unit == "ms":
        return vals * jnp.int64(1000)
    if unit == "s":
        return vals * jnp.int64(1000000)
    return vals // jnp.int64(1000)  # ns


def _decode_codes(up, cap_or_n: int):
    from spark_rapids_tpu.ops import pallas_kernels as pk
    return pk.hybrid_expand(up["cd_words"], up["cd_out_start"],
                            up["cd_kind"], up["cd_value"],
                            up["cd_bit_start"], up["cd_bw"], cap_or_n)


def _decode_column(name: str, plan: dict, up: dict, dt, cap: int,
                   dict_state: Optional[dict], i: int):
    """One uploaded plan -> DeviceColumn (eager jnp/pallas dispatch)."""
    import jax.numpy as jnp

    from spark_rapids_tpu.columnar import dtype as dtypes
    from spark_rapids_tpu.columnar.batch import bucket_capacity
    from spark_rapids_tpu.columnar.column import DeviceColumn
    from spark_rapids_tpu.ops import pallas_kernels as pk
    meta = plan["meta"]
    kind = plan["kind"]
    n = meta["n"]
    validity = _decode_levels(up, meta, cap, n)
    fill = dtypes.null_fill_value(dt)

    if kind == "bool":
        nv = bucket_capacity(max(meta["nn"], 1))
        vals_v = _decode_codes(up, nv) != 0
        out = _gather_rows(vals_v, validity, jnp.bool_(False))
        return DeviceColumn(dt, out, validity)

    if kind == "fixed_plain":
        nv = bucket_capacity(max(meta["nn"], 1))
        vals_v = pk.plain_fixed(up["vals"], meta["pkind"], nv)
        return _finish_fixed(dt, vals_v, validity, meta, fill)

    if kind == "fixed_delta":
        parts = []
        for j, total in meta["delta_pages"]:
            parts.append(pk.delta_unpack(
                up["dl_words"], up[f"d{j}_out_start"],
                up[f"d{j}_bit_width"], up[f"d{j}_min_delta"],
                up[f"d{j}_bit_start"], up[f"d{j}_first"], total))
        vals_v = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        if meta["pkind"] == "i32":
            vals_v = vals_v.astype(jnp.int32)
        return _finish_fixed(dt, vals_v, validity, meta, fill)

    if kind == "fixed_dict":
        nv = bucket_capacity(max(meta["nn"], 1))
        codes_v = _decode_codes(up, nv)
        dvals = pk.plain_fixed(up["dv_words"], meta["pkind"],
                               max(meta["card"], 1))
        vals_v = dvals[jnp.clip(codes_v, 0, max(meta["card"] - 1, 0))]
        return _finish_fixed(dt, vals_v, validity, meta, fill)

    if kind == "str_plain":
        nv = up["st"].shape[0]
        slab_v = pk.slab_pack(up["chars"], up["st"], up["ln"],
                              nv, meta["stride"])
        idx = jnp.clip(_value_positions(validity), 0, nv - 1)
        slab = jnp.where(validity[:, None], slab_v[idx], jnp.uint64(0))
        lens = jnp.where(validity, up["ln"][idx], 0).astype(jnp.int32)
        return _widen_slab(DeviceColumn, dt, slab, lens, validity,
                           meta["stride"], dict_state, i)

    # str_dict: canonical codes in row space first
    nv = bucket_capacity(max(meta["nn"], 1))
    codes_v = _decode_codes(up, nv)
    canon_v = up["rm"][jnp.clip(codes_v, 0, meta["card"])]
    card = meta["card"]
    idx = jnp.clip(_value_positions(validity), 0, nv - 1)
    codes_row = jnp.where(validity, canon_v[idx], card).astype(jnp.int32)
    use_dict = meta["dict_ok"]
    if use_dict and dict_state is not None:
        st = dict_state.get(i)
        if st is False:
            use_dict = False
        elif st is None:
            dict_state[i] = meta["vals"]
        elif tuple(st) != meta["vals"]:
            # remap into the established dictionary when this page dict
            # is a subset; otherwise close the column for the scan
            held = {v: k for k, v in enumerate(st)}
            if all(v in held for v in meta["vals"]):
                tbl = np.asarray(
                    [held[v] for v in meta["vals"]] + [len(st)], np.int32)
                codes_row = jnp.asarray(tbl)[
                    jnp.clip(codes_row, 0, card)]
                card = len(st)
                return DeviceColumn(dt, None, validity,
                                    dict_codes=codes_row,
                                    dict_values=tuple(st))
            dict_state[i] = False
            use_dict = False
    if use_dict:
        return DeviceColumn(dt, None, validity, dict_codes=codes_row,
                            dict_values=meta["vals"])
    if meta["stride"]:
        rows = jnp.clip(codes_row, 0, card)  # card = the zero null row
        slab = up["slab"][rows]
        lens = jnp.where(validity, up["slens"][rows], 0).astype(jnp.int32)
        return _widen_slab(DeviceColumn, dt, slab, lens, validity,
                           meta["stride"], dict_state, i)
    # dict_ok guaranteed stride>0 when not dict-eligible; reaching here
    # means the scan closed the dictionary and no slab was built — decode
    # through the dictionary host constants (card is small by dict_ok)
    import jax

    from spark_rapids_tpu.columnar.column import np_build_slab
    svals = [v.encode("utf-8") for v in meta["vals"]]
    offs = np.zeros(card + 2, np.int32)
    offs[1:card + 1] = np.cumsum([len(v) for v in svals])
    offs[card + 1] = offs[card]
    stride = 8
    while stride < max((len(v) for v in svals), default=1):
        stride <<= 1
    slab_h, lens_h = np_build_slab(
        np.frombuffer(b"".join(svals) or b"\0", np.uint8), offs,
        card + 1, stride)
    slab_d, lens_d = jax.device_put((slab_h, lens_h))
    rows = jnp.clip(codes_row, 0, card)
    slab = slab_d[rows]
    lens = jnp.where(validity, lens_d[rows], 0).astype(jnp.int32)
    return _widen_slab(DeviceColumn, dt, slab, lens, validity, stride,
                       dict_state, i)


def _finish_fixed(dt, vals_v, validity, meta, fill):
    import jax.numpy as jnp

    from spark_rapids_tpu.columnar.column import DeviceColumn
    out = _gather_rows(vals_v, validity, fill)
    if meta.get("cast"):
        out = out.astype(np.dtype(meta["cast"]))
    if meta.get("ts"):
        out = _apply_ts(out, meta["ts"])
    if dt.np_dtype is not None and out.dtype != dt.np_dtype:
        out = out.astype(dt.np_dtype)
    out = jnp.where(validity, out,
                    jnp.asarray(fill, out.dtype))  # canonical null fill
    return DeviceColumn(dt, out, validity)


def _widen_slab(DeviceColumn, dt, slab, lens, validity, stride: int,
                dict_state: Optional[dict], i: int):
    """Honor the per-scan widen-only stride registry (the from_pandas
    slab contract): later batches pad to the widest stride seen so a
    scan compiles one program shape per widening, not per batch."""
    import jax.numpy as jnp
    if dict_state is not None:
        prev = int(dict_state.get(("slab", i), 0) or 0)
        if prev > stride:
            pad = (prev - stride) // 8
            slab = jnp.pad(slab, ((0, 0), (0, pad)))
            stride = prev
        if prev >= 0:
            dict_state[("slab", i)] = stride
    return DeviceColumn(dt, None, validity, slab64=slab, lens=lens)


def _pkey_buffers(pvals: dict, pkeys, pkey_dtypes, n: int, cap: int):
    """Partition-value scalar columns as classic host buffers."""
    from spark_rapids_tpu.columnar.column import DeviceColumn
    from spark_rapids_tpu.sql.sources import _infer_partition_value
    out = []
    for k in pkeys:
        dt = pkey_dtypes[k]
        v = _infer_partition_value(pvals[k]) if k in pvals else None
        if v is None:
            vals = (np.empty(n, object) if dt.is_string
                    else np.zeros(n, dt.np_dtype))
            validity = np.zeros(n, np.bool_)
        elif dt.is_string:
            vals = np.full(n, str(v), object)
            validity = np.ones(n, np.bool_)
        else:
            vals = np.full(n, dt.np_dtype.type(v))
            validity = np.ones(n, np.bool_)
        out.append((k, dt,
                    DeviceColumn.build_host_buffers(vals, validity, dt,
                                                    cap)))
    return out


def _fallback_buffers(df, name: str, dt, cap: int):
    from spark_rapids_tpu.columnar.batch import _pandas_to_numpy
    from spark_rapids_tpu.columnar.column import DeviceColumn
    values, validity = _pandas_to_numpy(df[name], dt)
    return DeviceColumn.build_host_buffers(values, validity, dt, cap)


def _slice_col(col, dt, lo: int, m: int, cap2: int):
    """Static device slice of one decoded column into a chunk batch."""
    import jax.numpy as jnp

    from spark_rapids_tpu.columnar.column import DeviceColumn
    def cut(a, fill=0):
        if a is None:
            return None
        part = a[lo:lo + m]
        if part.shape[0] == cap2:
            return part
        pad_shape = (cap2 - part.shape[0],) + part.shape[1:]
        return jnp.concatenate(
            [part, jnp.full(pad_shape, fill, part.dtype)])
    validity = cut(col.validity, False)
    if col.dict_values is not None and col._data is None:
        return DeviceColumn(dt, None, validity,
                            dict_codes=cut(col.dict_codes,
                                           len(col.dict_values)),
                            dict_values=col.dict_values)
    if col.has_slab:
        return DeviceColumn(dt, None, validity, slab64=cut(col._slab64),
                            lens=cut(col._lens))
    if dt.is_string:
        # packed strings only arise from fallback columns; re-slice via
        # offsets is host work we avoid — keep whole-chars with shifted
        # offsets (chars stay shared, extents stay correct)
        offs = col.offsets[lo:lo + m + 1]
        base = offs[0]
        offs = jnp.concatenate(
            [offs - base,
             jnp.full((cap2 - m,), offs[-1] - base, offs.dtype)])
        return DeviceColumn(dt, col.data, validity, offsets=offs,
                            prefix8=cut(col.prefix8))
    return DeviceColumn(dt, cut(col.data), validity)


def decode_rowgroup(ctx, raw: RawRowGroup, schema, max_rows: int,
                    dict_state: Optional[dict], part_index: int,
                    device=None):
    """Consumer-side: RawRowGroup -> DeviceBatch(es). One device_put for
    every plan buffer + fallback/pkey host buffers, then eager kernel
    decode; row groups larger than ``max_rows`` yield device-sliced
    chunk batches (no extra host work, no syncs)."""
    import jax
    import jax.numpy as jnp

    from spark_rapids_tpu.columnar.batch import (
        DeviceBatch, bucket_capacity,
    )
    from spark_rapids_tpu.columnar.column import DeviceColumn
    from spark_rapids_tpu.obs import compileledger
    from spark_rapids_tpu.obs.progress import PROGRESS
    from spark_rapids_tpu.obs.syncledger import sync_scope

    session = ctx.session
    page_cache = getattr(session, "page_cache", None) if session else None
    n = raw.n
    cap = bucket_capacity(max(n, 1))
    if session is not None:
        reg = session.column_stats
        for name, (lo, hi) in raw.stats.items():
            prev = reg.get(name)
            if prev is not None:
                lo, hi = min(lo, prev[0]), max(hi, prev[1])
            reg[name] = (lo, hi)

    dt_by_name = dict(zip(schema.names, schema.dtypes))
    fb_names = {name for name, _ in raw.fallback}
    pkeys = [nm for nm in schema.names
             if nm not in raw.plans and nm not in fb_names]

    # assemble the single-upload tree: cached-on-device plans are reused
    # as-is; everything else (plan buffers, fallback columns' classic
    # buffers, partition-value scalars) rides ONE device_put
    tree = {}
    reused = {}
    all_cached = bool(raw.plans)
    for name, plan in raw.plans.items():
        key = (raw.path, raw.mtime, raw.rg, name)
        dev = page_cache.get_device(key) if page_cache is not None \
            else None
        if dev is not None:
            reused[name] = dev
            continue
        if not raw.cached.get(name):
            all_cached = False
        tree[name] = plan["upload"]
    fb_tree = {}
    if raw.fallback_df is not None:
        for name, _reason in raw.fallback:
            if name in raw.fallback_df.columns:
                fb_tree[name] = _fallback_buffers(raw.fallback_df, name,
                                                  dt_by_name[name], cap)
    pk_bufs = _pkey_buffers(raw.pvals, pkeys,
                            {k: dt_by_name[k] for k in pkeys}, n, cap) \
        if pkeys else []

    chunk_ms = [] if n <= max_rows else \
        [min(max_rows, n - lo) for lo in range(0, n, max_rows)]
    t0 = time.perf_counter()
    scope_kind = "scan.pagecache" if (all_cached and not fb_tree) \
        else "scan.upload"
    with sync_scope(scope_kind, detail=f"partition={part_index}") as sc:
        dev_tree, dev_fb, dev_pk, num_rows, dev_ms = jax.device_put(
            (tree, fb_tree, [b for _k, _d, b in pk_bufs],
             np.asarray(n, np.int32),
             [np.asarray(m, np.int32) for m in chunk_ms]), device=device)
        up_bytes = sum(
            a.nbytes for up in tree.values() for a in up.values())
        sc.add_bytes(up_bytes)
    compileledger.note_transfer(time.perf_counter() - t0, "h2d")

    # promote freshly uploaded plan buffers into the cache's device tier
    if page_cache is not None:
        for name, up in dev_tree.items():
            key = (raw.path, raw.mtime, raw.rg, name)
            page_cache.promote(key, up, raw.plans[name].get("nbytes", 0))

    enc_bytes = sum(p.get("nbytes", 0) for p in raw.plans.values())
    _DEV_BYTES.add(enc_bytes)
    _DEV_COLS.add(len(raw.plans))
    _DEV_SPLITS.add(1)
    if PROGRESS.enabled:
        PROGRESS.note("scan", deviceColumns=len(raw.plans),
                      hostColumns=len(raw.fallback),
                      deviceBytes=enc_bytes)

    with _DEC_TIME.time():
        cols = []
        for i, name in enumerate(schema.names):
            dt = dt_by_name[name]
            if name in raw.plans:
                up = reused.get(name) or dev_tree[name]
                cols.append(_decode_column(name, raw.plans[name], up, dt,
                                           cap, dict_state, i))
            elif name in fb_tree:
                bufs = dev_fb[name]
                cols.append(DeviceColumn(dt, *bufs))
            else:
                j = [nm for nm, _d, _b in pk_bufs].index(name)
                cols.append(DeviceColumn(dt, *dev_pk[j]))

    if n <= max_rows:
        batch = DeviceBatch(schema, cols, num_rows)
        batch._host_rows = n
        if PROGRESS.enabled:
            PROGRESS.scan_upload(n)
        yield batch
        return
    for j, lo in enumerate(range(0, n, max_rows)):
        m = chunk_ms[j]
        cap2 = bucket_capacity(m)
        ccols = [_slice_col(c, dt, lo, m, cap2)
                 for c, dt in zip(cols, schema.dtypes)]
        batch = DeviceBatch(schema, ccols, dev_ms[j])
        batch._host_rows = m
        if PROGRESS.enabled:
            PROGRESS.scan_upload(m)
        yield batch
