"""Row-level batch kernels: gather, compaction (filter), concatenation.

These replace cuDF's Table.filter / Table.concatenate / gather calls
(reference call sites: basicPhysicalOperators.scala GpuFilterExec:126,
GpuCoalesceBatches.scala:52). All shape-static: outputs share the input
capacity (or a target bucket) and carry a new num_rows scalar.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import DeviceBatch, Schema
from spark_rapids_tpu.columnar.column import DeviceColumn


def rank_of_iota(sorted_vals: jnp.ndarray, out_len: int) -> jnp.ndarray:
    """``searchsorted(sorted_vals, arange(out_len), side='right')`` as a
    histogram + cumsum: two dense-ish passes instead of a per-element
    binary search (searchsorted at 2^22 costs ~0.8s on this TPU; this
    form ~0.2s). Values below 0 count toward every position, values above
    out_len toward none — exactly searchsorted's clip behavior for an
    iota query vector."""
    hist = jnp.zeros((out_len + 1,), jnp.int32).at[
        jnp.clip(sorted_vals.astype(jnp.int32), 0, out_len)].add(1)
    return jnp.cumsum(hist[:out_len]).astype(jnp.int32)


def gather_columns(cols: Sequence[DeviceColumn], perm: jnp.ndarray,
                   live: jnp.ndarray,
                   char_caps: Sequence[int] = ()) -> List[DeviceColumn]:
    """Gather MANY columns by one index vector with PACKED row gathers.

    A 1-D gather lowers to a scalar-ish loop on TPU (~5M elem/s); gathering
    a stacked (n, k) matrix along rows moves k lane-contiguous elements per
    index and measures ~4-6x faster for typical column counts. So all
    fixed-width payloads sharing a dtype ride ONE stacked gather (data,
    validity, string lengths/starts, prefix images, dictionary codes), and
    only the string char slabs keep their per-column char-space gather.
    ``char_caps``: optional per-STRING-column output char capacities (same
    contract as the old per-column gather)."""
    out_cap = perm.shape[0]
    plans: dict = {}   # dtype key -> list of (array, col_index, field)
    parts: List[dict] = [dict() for _ in cols]

    def add(arr, ci, field):
        # bool matrices hit a pathological gather lowering on TPU
        # (measured ~100x slower than int8); ride as int8 lanes instead
        if arr.dtype == jnp.bool_:
            arr = arr.astype(jnp.int8)
        plans.setdefault(str(arr.dtype), []).append((arr, ci, field))

    for i, c in enumerate(cols):
        add(c.validity, i, "validity")
        if c.dtype.is_string:
            lens = (c.offsets[1:] - c.offsets[:-1]).astype(jnp.int32)
            add(lens, i, "lens")
            add(c.offsets[:-1].astype(jnp.int32), i, "starts")
            if c.prefix8 is not None:
                add(c.prefix8, i, "prefix8")
        else:
            add(c.data, i, "data")
        if c.dict_values is not None:
            add(c.dict_codes, i, "codes")

    for _key, entries in plans.items():
        if len(entries) == 1:
            arr, ci, field = entries[0]
            parts[ci][field] = arr[perm]
            continue
        m = jnp.stack([a for a, _, _ in entries], axis=1)[perm, :]
        for j, (_a, ci, field) in enumerate(entries):
            parts[ci][field] = m[:, j]

    out: List[DeviceColumn] = []
    si = 0
    for i, c in enumerate(cols):
        p = parts[i]
        validity = (p["validity"] != 0) & live
        codes = None
        if c.dict_values is not None:
            codes = jnp.where(live, p["codes"],
                              jnp.asarray(c.dict_card, jnp.int32))
        if not c.dtype.is_string:
            data = p["data"]
            if data.dtype != c.data.dtype:
                # bool payloads rode the packed gather as int8 (see add());
                # restore the column's physical dtype
                data = data.astype(c.data.dtype)
            out.append(DeviceColumn(c.dtype, data, validity,
                                    dict_codes=codes,
                                    dict_values=c.dict_values))
            continue
        occ = char_caps[si] if si < len(char_caps) else 0
        si += 1
        new_len = jnp.where(live, p["lens"], 0)
        new_offsets = jnp.concatenate([
            jnp.zeros((1,), jnp.int32),
            jnp.cumsum(new_len).astype(jnp.int32)])
        nchars = c.data.shape[0]
        out_chars_n = occ if occ > 0 else nchars
        total_new = new_offsets[out_cap]
        k = jnp.arange(out_chars_n, dtype=jnp.int32)
        out_row = jnp.clip(rank_of_iota(new_offsets, out_chars_n) - 1,
                           0, out_cap - 1)
        src_idx = p["starts"][out_row] + (k - new_offsets[out_row])
        gathered = c.data[jnp.clip(src_idx, 0, nchars - 1)]
        new_chars = jnp.where(k < total_new, gathered, 0).astype(jnp.uint8)
        prefix8 = None
        if c.prefix8 is not None:
            prefix8 = jnp.where(live, p["prefix8"], jnp.uint64(0))
        out.append(DeviceColumn(c.dtype, new_chars, validity, new_offsets,
                                prefix8, codes, c.dict_values))
    return out


def gather_column(col: DeviceColumn, perm: jnp.ndarray,
                  live: jnp.ndarray,
                  out_char_capacity: int = 0) -> DeviceColumn:
    """Gather rows of a column by index vector ``perm`` (len = out capacity).
    ``live`` marks which output slots are real rows; dead slots become
    invalid/empty. ``out_char_capacity`` sizes the output char buffer for
    string columns (default: same as the source — callers that *expand*
    rows, like joins, must pass the synced total). Multi-column callers
    should use gather_columns (packed row gathers)."""
    caps = (out_char_capacity,) if col.dtype.is_string else ()
    return gather_columns([col], perm, live, caps)[0]


def _shared_dict(parts: Sequence[DeviceColumn]):
    """The dictionary all ``parts`` share, or None: a concat result keeps
    codes only when every input encodes against the SAME static values."""
    if parts[0].dict_values is None or any(
            p.dict_values != parts[0].dict_values for p in parts):
        return None
    return parts[0].dict_values


def gather_batch(batch: DeviceBatch, perm: jnp.ndarray,
                 num_rows: jnp.ndarray) -> DeviceBatch:
    out_cap = perm.shape[0]
    live = jnp.arange(out_cap, dtype=jnp.int32) < num_rows
    cols = gather_columns(batch.columns, perm, live)
    return DeviceBatch(batch.schema, cols, num_rows.astype(jnp.int32))


def filter_batch(batch: DeviceBatch, keep: jnp.ndarray) -> DeviceBatch:
    """Compact rows where ``keep`` (bool capacity-vector) is True to the
    front. keep is pre-masked to live rows by the caller or here."""
    keep = keep & batch.row_mask()
    # stable partition via the O(n) prefix-count kernel (pallas on TPU)
    from spark_rapids_tpu.ops.pallas_kernels import compact_permutation
    perm, new_rows = compact_permutation(keep)
    return gather_batch(batch, perm, new_rows)


def concat_batches(batches: Sequence[DeviceBatch],
                   out_capacity: int,
                   out_char_capacity: int = 0) -> DeviceBatch:
    """Concatenate batches into one of ``out_capacity`` (device analogue of
    cuDF Table.concatenate under GpuCoalesceBatches)."""
    schema = batches[0].schema
    total = batches[0].num_rows
    for b in batches[1:]:
        total = total + b.num_rows
    cols: List[DeviceColumn] = []
    for ci, dt in enumerate(schema.dtypes):
        parts = [b.columns[ci] for b in batches]
        if dt.is_string:
            cols.append(_concat_string_cols(parts, [b.num_rows for b in batches],
                                            out_capacity, out_char_capacity))
        else:
            offset = jnp.asarray(0, jnp.int32)
            out_data = jnp.zeros((out_capacity,), dtype=parts[0].data.dtype)
            out_val = jnp.zeros((out_capacity,), dtype=jnp.bool_)
            shared = _shared_dict(parts)
            out_codes = (jnp.full((out_capacity,), len(shared), jnp.int32)
                         if shared is not None else None)
            idx = jnp.arange(out_capacity, dtype=jnp.int32)
            for part, b in zip(parts, batches):
                n = b.num_rows
                # place part rows [0, n) at [offset, offset+n)
                src = jnp.clip(idx - offset, 0, part.data.shape[0] - 1)
                in_range = (idx >= offset) & (idx < offset + n)
                out_data = jnp.where(in_range, part.data[src], out_data)
                out_val = jnp.where(in_range, part.validity[src], out_val)
                if shared is not None:
                    out_codes = jnp.where(in_range, part.dict_codes[src],
                                          out_codes)
                offset = offset + n
            cols.append(DeviceColumn(dt, out_data, out_val,
                                     dict_codes=out_codes,
                                     dict_values=shared))
    return DeviceBatch(schema, cols, total.astype(jnp.int32))


def _concat_string_cols(parts: List[DeviceColumn], counts,
                        out_capacity: int,
                        out_char_capacity: int) -> DeviceColumn:
    if out_char_capacity <= 0:
        out_char_capacity = sum(int(p.data.shape[0]) for p in parts)
    idx = jnp.arange(out_capacity, dtype=jnp.int32)
    out_len = jnp.zeros((out_capacity,), jnp.int32)
    out_val = jnp.zeros((out_capacity,), jnp.bool_)
    has_prefix = all(p.prefix8 is not None for p in parts)
    prefix8 = jnp.zeros((out_capacity,), jnp.uint64) if has_prefix else None
    shared = _shared_dict(parts)
    out_codes = (jnp.full((out_capacity,), len(shared), jnp.int32)
                 if shared is not None else None)
    row_offset = jnp.asarray(0, jnp.int32)
    # first pass: lengths, validity (and the prefix image / dictionary
    # codes, which share the same masks)
    for part, n in zip(parts, counts):
        lens = (part.offsets[1:] - part.offsets[:-1]).astype(jnp.int32)
        src = jnp.clip(idx - row_offset, 0, part.capacity - 1)
        in_range = (idx >= row_offset) & (idx < row_offset + n)
        out_len = jnp.where(in_range, lens[src], out_len)
        out_val = jnp.where(in_range, part.validity[src], out_val)
        if has_prefix:
            prefix8 = jnp.where(in_range, part.prefix8[src], prefix8)
        if shared is not None:
            out_codes = jnp.where(in_range, part.dict_codes[src], out_codes)
        row_offset = row_offset + n
    new_offsets = jnp.concatenate([
        jnp.zeros((1,), jnp.int32), jnp.cumsum(out_len).astype(jnp.int32)])
    # second pass: chars
    k = jnp.arange(out_char_capacity, dtype=jnp.int32)
    out_row = jnp.clip(rank_of_iota(new_offsets, out_char_capacity) - 1,
                       0, out_capacity - 1)
    rel = k - new_offsets[out_row]
    out_chars = jnp.zeros((out_char_capacity,), jnp.uint8)
    row_offset = jnp.asarray(0, jnp.int32)
    for part, n in zip(parts, counts):
        src_row = jnp.clip(out_row - row_offset, 0, part.capacity - 1)
        in_range = (out_row >= row_offset) & (out_row < row_offset + n)
        src_idx = part.offsets[:-1][src_row].astype(jnp.int32) + rel
        nc = part.data.shape[0]
        vals = part.data[jnp.clip(src_idx, 0, nc - 1)]
        out_chars = jnp.where(in_range, vals, out_chars)
        row_offset = row_offset + n
    total_chars = new_offsets[out_capacity]
    out_chars = jnp.where(k < total_chars, out_chars, 0).astype(jnp.uint8)
    return DeviceColumn(parts[0].dtype, out_chars, out_val, new_offsets,
                        prefix8, out_codes, shared)


def slice_batch(batch: DeviceBatch, start: jnp.ndarray,
                count: jnp.ndarray) -> DeviceBatch:
    """Rows [start, start+count) compacted to the front (zero-copy-ish slice,
    the analogue of SlicedGpuColumnVector)."""
    return slice_batch_to(batch, start, count, batch.capacity)


def slice_batch_to(batch: DeviceBatch, start: jnp.ndarray,
                   count: jnp.ndarray, out_capacity: int) -> DeviceBatch:
    """slice_batch gathering into an ``out_capacity``-row batch. Callers
    that learn row counts on the host (the exchange's bucket split) use
    this to SHRINK capacity, so downstream kernels stop paying for the
    pre-aggregation padding (a 4-group result inheriting a 32k-row input
    bucket would otherwise keep every later sort/agg at 32k)."""
    idx = jnp.arange(out_capacity, dtype=jnp.int32)
    perm = jnp.clip(idx + start.astype(jnp.int32), 0, batch.capacity - 1)
    n = jnp.minimum(count.astype(jnp.int32),
                    jnp.maximum(batch.num_rows - start.astype(jnp.int32), 0))
    live = idx < n
    cols = gather_columns(batch.columns, perm, live)
    return DeviceBatch(batch.schema, cols, n.astype(jnp.int32))
