"""Row-level batch kernels: gather, compaction (filter), concatenation.

These replace cuDF's Table.filter / Table.concatenate / gather calls
(reference call sites: basicPhysicalOperators.scala GpuFilterExec:126,
GpuCoalesceBatches.scala:52). All shape-static: outputs share the input
capacity (or a target bucket) and carry a new num_rows scalar.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import DeviceBatch, Schema
from spark_rapids_tpu.columnar.column import DeviceColumn


def rank_of_iota(sorted_vals: jnp.ndarray, out_len: int) -> jnp.ndarray:
    """``searchsorted(sorted_vals, arange(out_len), side='right')`` as a
    histogram + cumsum: two dense-ish passes instead of a per-element
    binary search (searchsorted at 2^22 costs ~0.8s on this TPU; this
    form ~0.2s). Values below 0 count toward every position, values above
    out_len toward none — exactly searchsorted's clip behavior for an
    iota query vector."""
    hist = jnp.zeros((out_len + 1,), jnp.int32).at[
        jnp.clip(sorted_vals.astype(jnp.int32), 0, out_len)].add(1)
    return jnp.cumsum(hist[:out_len]).astype(jnp.int32)


def packed_gather_vectors(vectors: Sequence[jnp.ndarray],
                          perm: jnp.ndarray) -> List[jnp.ndarray]:
    """Gather many same-length raw vectors by one index vector with
    dtype-grouped STACKED gathers (the gather_columns trick without the
    column wrapper): a (n, k) row gather moves k lane-contiguous elements
    per index — 4-6x cheaper than k separate 1-D gathers on TPU. Bool
    inputs ride as int8 (callers convert back)."""
    groups: dict = {}
    for i, v in enumerate(vectors):
        if v.dtype == jnp.bool_:
            v = v.astype(jnp.int8)
        groups.setdefault(str(v.dtype), []).append((i, v))
    out: List[jnp.ndarray] = [None] * len(vectors)
    for _dt, items in groups.items():
        if len(items) == 1:
            i, v = items[0]
            out[i] = v[perm]
        else:
            m = jnp.stack([v for _i, v in items], axis=1)[perm, :]
            for j, (i, _v) in enumerate(items):
                out[i] = m[:, j]
    return out


def gather_columns(cols: Sequence[DeviceColumn], perm: jnp.ndarray,
                   live: jnp.ndarray,
                   char_caps: Sequence[int] = ()) -> List[DeviceColumn]:
    """Gather MANY columns by one index vector with PACKED row gathers.

    A 1-D gather lowers to a scalar-ish loop on TPU (~5M elem/s); gathering
    a stacked (n, k) matrix along rows moves k lane-contiguous elements per
    index and measures ~4-6x faster for typical column counts. So all
    fixed-width payloads sharing a dtype ride ONE stacked gather (data,
    validity, string lengths/starts, prefix images, dictionary codes), and
    only the string char slabs keep their per-column char-space gather.
    ``char_caps``: optional per-STRING-column output char capacities (same
    contract as the old per-column gather)."""
    out_cap = perm.shape[0]
    plans: dict = {}   # dtype key -> list of (array, col_index, field)
    parts: List[dict] = [dict() for _ in cols]

    def add(arr, ci, field):
        # bool matrices hit a pathological gather lowering on TPU
        # (measured ~100x slower than int8); ride as int8 lanes instead
        if arr.dtype == jnp.bool_:
            arr = arr.astype(jnp.int8)
        plans.setdefault(str(arr.dtype), []).append((arr, ci, field))

    slabs: dict = {}
    for i, c in enumerate(cols):
        add(c.validity, i, "validity")
        if c.dtype.is_string:
            if c.dict_values is not None:
                # dictionary strings move ONLY their codes; the output is
                # a codes-only (lazy) column — chars rebuild from the
                # static dictionary if a consumer ever reads them. Char
                # space (tens of MB at fact scale) is never touched here.
                add(c.dict_codes, i, "codes")
                continue
            if c.has_slab:
                # blocked chars: the fixed-stride slab moves with ONE 2-D
                # row gather (k lane-contiguous words per index — the
                # stacked-gather form), lens ride the packed int32 group.
                # No char-index gather happens at all; packed chars only
                # materialize if a downstream consumer reads them.
                add(c.lens_(), i, "slens")
                slabs[i] = c._slab64
                continue
            # _ExtentColumn (concat's flat view) carries explicit extents;
            # plain columns derive them from the offsets vector
            lens = getattr(c, "ext_lens", None)
            if lens is None:
                lens = (c.offsets[1:] - c.offsets[:-1]).astype(jnp.int32)
            starts = getattr(c, "ext_starts", None)
            if starts is None:
                starts = c.offsets[:-1].astype(jnp.int32)
            add(lens, i, "lens")
            add(starts, i, "starts")
            if c.prefix8 is not None:
                add(c.prefix8, i, "prefix8")
        else:
            add(c.data, i, "data")
        if c.dict_values is not None:
            add(c.dict_codes, i, "codes")

    for _key, entries in plans.items():
        if len(entries) == 1:
            arr, ci, field = entries[0]
            parts[ci][field] = arr[perm]
            continue
        m = jnp.stack([a for a, _, _ in entries], axis=1)[perm, :]
        for j, (_a, ci, field) in enumerate(entries):
            parts[ci][field] = m[:, j]

    out: List[DeviceColumn] = []
    si = 0
    for i, c in enumerate(cols):
        p = parts[i]
        validity = (p["validity"] != 0) & live
        codes = None
        if c.dict_values is not None:
            codes = jnp.where(live, p["codes"],
                              jnp.asarray(c.dict_card, jnp.int32))
        if not c.dtype.is_string:
            data = p["data"]
            if data.dtype != c.data.dtype:
                # bool payloads rode the packed gather as int8 (see add());
                # restore the column's physical dtype
                data = data.astype(c.data.dtype)
            out.append(DeviceColumn(c.dtype, data, validity,
                                    dict_codes=codes,
                                    dict_values=c.dict_values))
            continue
        occ = char_caps[si] if si < len(char_caps) else 0
        si += 1
        if i in slabs:
            slab_out = slabs[i][perm]
            slab_out = jnp.where(live[:, None], slab_out,
                                 jnp.uint64(0))
            lens_out = jnp.where(live, p["slens"], 0).astype(jnp.int32)
            out.append(DeviceColumn(c.dtype, None, validity,
                                    slab64=slab_out, lens=lens_out))
            continue
        if codes is not None:
            # codes-only output: chars never move (see the add() loop) —
            # the column materializes from its static dictionary only if
            # some consumer actually reads chars
            out.append(DeviceColumn(c.dtype, None, validity,
                                    dict_codes=codes,
                                    dict_values=c.dict_values))
            continue
        nchars = c.data.shape[0]
        new_len = jnp.where(live, p["lens"], 0)
        new_offsets = jnp.concatenate([
            jnp.zeros((1,), jnp.int32),
            jnp.cumsum(new_len).astype(jnp.int32)])
        out_chars_n = occ if occ > 0 else nchars
        total_new = new_offsets[out_cap]
        k = jnp.arange(out_chars_n, dtype=jnp.int32)
        out_row = jnp.clip(rank_of_iota(new_offsets, out_chars_n) - 1,
                           0, out_cap - 1)
        src_idx = p["starts"][out_row] + (k - new_offsets[out_row])
        gathered = c.data[jnp.clip(src_idx, 0, nchars - 1)]
        new_chars = jnp.where(k < total_new, gathered, 0).astype(jnp.uint8)
        prefix8 = None
        if c.prefix8 is not None:
            prefix8 = jnp.where(live, p["prefix8"], jnp.uint64(0))
        out.append(DeviceColumn(c.dtype, new_chars, validity, new_offsets,
                                prefix8, codes, c.dict_values))
    return out


def gather_column(col: DeviceColumn, perm: jnp.ndarray,
                  live: jnp.ndarray,
                  out_char_capacity: int = 0) -> DeviceColumn:
    """Gather rows of a column by index vector ``perm`` (len = out capacity).
    ``live`` marks which output slots are real rows; dead slots become
    invalid/empty. ``out_char_capacity`` sizes the output char buffer for
    string columns (default: same as the source — callers that *expand*
    rows, like joins, must pass the synced total). Multi-column callers
    should use gather_columns (packed row gathers)."""
    caps = (out_char_capacity,) if col.dtype.is_string else ()
    return gather_columns([col], perm, live, caps)[0]


def _shared_dict(parts: Sequence[DeviceColumn]):
    """The dictionary all ``parts`` share, or None: a concat result keeps
    codes only when every input encodes against the SAME static values."""
    if parts[0].dict_values is None or any(
            p.dict_values != parts[0].dict_values for p in parts):
        return None
    return parts[0].dict_values


# union-dictionary cardinality ceiling for the exchange-boundary merge:
# beyond it the merged dictionary would stop being "small host constant"
# material (it rides jit cache keys as aux data), so the concat decodes
# instead — the same bound the small-table pre-seed uses.
DICT_MERGE_MAX_CARD = 1 << 14


def _concat_dict_info(parts: Sequence[DeviceColumn], dict_merge: bool):
    """(values, effective per-part codes) for a concat keeping codes:
    identical dictionaries pass through; DIFFERENT dictionaries merge by
    union + an O(cardinality) static remap per part (the exchange-
    boundary merge, docs/gatherfree.md) when ``dict_merge`` is on.
    (None, None) -> the caller must decode (legacy char path)."""
    shared = _shared_dict(parts)
    if shared is not None:
        return shared, [p.dict_codes for p in parts]
    if not dict_merge:
        return None, None
    if any(p.dict_values is None or p.dict_codes is None for p in parts):
        return None, None
    from spark_rapids_tpu.columnar.dictionary import (
        union_dictionaries_cached,
    )
    vals, remaps = union_dictionaries_cached(
        [p.dict_values for p in parts])
    if len(vals) > DICT_MERGE_MAX_CARD:
        return None, None
    eff = []
    for p, r in zip(parts, remaps):
        card_p = len(p.dict_values)
        eff.append(jnp.asarray(r)[jnp.clip(p.dict_codes, 0, card_p)])
    return vals, eff


def _concat_slabs(parts: Sequence[DeviceColumn]):
    """Per-part slabs re-padded to the widest word count, or None when
    some part is not slab-backed (the caller then takes the char path,
    which transparently materializes slab parts)."""
    if any(not p.has_slab for p in parts):
        return None
    w_out = max(int(p._slab64.shape[1]) for p in parts)
    out = []
    for p in parts:
        s = p._slab64
        w = int(s.shape[1])
        if w < w_out:
            s = jnp.pad(s, ((0, 0), (0, w_out - w)))
        out.append(s)
    return out


def gather_batch(batch: DeviceBatch, perm: jnp.ndarray,
                 num_rows: jnp.ndarray) -> DeviceBatch:
    out_cap = perm.shape[0]
    live = jnp.arange(out_cap, dtype=jnp.int32) < num_rows
    cols = gather_columns(batch.columns, perm, live)
    return DeviceBatch(batch.schema, cols, num_rows.astype(jnp.int32))


def filter_batch(batch: DeviceBatch, keep: jnp.ndarray) -> DeviceBatch:
    """Compact rows where ``keep`` (bool capacity-vector) is True to the
    front. keep is pre-masked to live rows by the caller or here."""
    keep = keep & batch.row_mask()
    # stable partition via the O(n) prefix-count kernel (pallas on TPU)
    from spark_rapids_tpu.ops.pallas_kernels import compact_permutation
    perm, new_rows = compact_permutation(keep)
    return gather_batch(batch, perm, new_rows)


def concat_batches(batches: Sequence[DeviceBatch],
                   out_capacity: int,
                   out_char_capacity: int = 0,
                   keep_masks: Optional[Sequence[jnp.ndarray]] = None,
                   dict_merge: bool = True
                   ) -> DeviceBatch:
    """Concatenate batches into one of ``out_capacity`` (device analogue of
    cuDF Table.concatenate under GpuCoalesceBatches).

    TPU shape: part row counts are device scalars (dynamic), so a static
    concatenation is impossible — but the compaction source index is pure
    arithmetic over the per-part bases (P dense passes, no gathers), and
    the payload move is ONE packed gather per dtype group from the
    statically concatenated flat buffers (gather_columns). The previous
    spelling gathered per part per column at out_capacity width and
    measured ~770ms for a 4-part 5-column concat at 4M rows; this one
    runs the same shape in ~1/3 of that.

    ``keep_masks``: optional per-part bool keep vectors (a fused Filter
    below the exchange collapse): kept rows compact to the front in part
    order via ONE O(n) compact_permutation — the standalone filter's
    per-batch compaction gathers disappear into the concat's single
    gather."""
    schema = batches[0].schema
    idx = jnp.arange(out_capacity, dtype=jnp.int32)
    if keep_masks is not None:
        from spark_rapids_tpu.ops.pallas_kernels import compact_permutation
        flat_keep = jnp.concatenate(
            [k & b.row_mask() for k, b in zip(keep_masks, batches)])
        perm, total = compact_permutation(flat_keep)
        total = total.astype(jnp.int32)
        flat_n = perm.shape[0]
        if flat_n >= out_capacity:
            src = perm[:out_capacity]
        else:
            src = jnp.concatenate(
                [perm, jnp.zeros((out_capacity - flat_n,), jnp.int32)])
        live_out = idx < total
    else:
        total = batches[0].num_rows
        for b in batches[1:]:
            total = total + b.num_rows
        total = total.astype(jnp.int32)
        live_out = idx < total

        # source flat index per output slot: part p's rows [0, n_p) land
        # at [base_p, base_p + n_p), reading flat slots [static_off_p+rel)
        src = jnp.zeros((out_capacity,), jnp.int32)
        base = jnp.asarray(0, jnp.int32)
        static_off = 0
        for b in batches:
            rel = idx - base
            in_p = (idx >= base) & (rel < b.num_rows)
            src = jnp.where(in_p, jnp.int32(static_off) + rel, src)
            base = base + b.num_rows
            static_off += b.capacity

    # fast path (no keep masks): fixed-width and codes-only columns move
    # with CONTIGUOUS dynamic_update_slice block copies instead of a
    # row gather — batch i's full padded buffer lands at its dynamic
    # base and batch i+1's copy overwrites i's padding (bases advance by
    # LIVE counts). Measured ~8x faster than the packed gather for the
    # same move on v5e (XLA's gather lowering is the engine's ceiling,
    # docs/roofline_r5.md). Plain string columns (dynamic char extents)
    # stay on the gather path below.
    def _block_copy(arrs, fill=None):
        dt0 = arrs[0].dtype
        out = jnp.zeros((out_capacity,), dt0) if fill is None else \
            jnp.full((out_capacity,), fill, dt0)
        base = jnp.asarray(0, jnp.int32)
        for arr, b in zip(arrs, batches):
            out = jax.lax.dynamic_update_slice(out, arr, (base,))
            base = base + b.num_rows.astype(jnp.int32)
        return out

    def _block_copy2d(arrs):
        # slab rows: same contiguous block-copy trick, one word-matrix
        # per part landing at its dynamic row base
        w = int(arrs[0].shape[1])
        out = jnp.zeros((out_capacity, w), arrs[0].dtype)
        base = jnp.asarray(0, jnp.int32)
        for arr, b in zip(arrs, batches):
            out = jax.lax.dynamic_update_slice(
                out, arr, (base, jnp.asarray(0, jnp.int32)))
            base = base + b.num_rows.astype(jnp.int32)
        return out

    blockable = keep_masks is None and all(
        b.capacity <= out_capacity for b in batches)

    # flat columns: static dense concatenation of every part buffer;
    # string offsets get static per-part char bases (the flat array is
    # NOT a valid offsets vector at part boundaries, but gather_columns
    # only reads per-row starts and lens, and dead rows' lens are masked
    # by ``live``)
    flat_cols: List[DeviceColumn] = []
    char_caps: List[int] = []
    block_out: dict = {}
    for ci, dt in enumerate(schema.dtypes):
        parts = [b.columns[ci] for b in batches]
        shared, eff_codes = _concat_dict_info(parts, dict_merge)
        slab_parts = (_concat_slabs(parts)
                      if dt.is_string and shared is None else None)
        if blockable and dt.is_string and slab_parts is not None:
            # blocked chars: slab rows block-copy exactly like fixed-
            # width payloads — 2-D contiguous copies, no char gather
            validity = _block_copy([p.validity for p in parts]) & live_out
            lens_b = jnp.where(live_out,
                               _block_copy([p.lens_() for p in parts]),
                               0).astype(jnp.int32)
            slab_b = jnp.where(live_out[:, None],
                               _block_copy2d(slab_parts), jnp.uint64(0))
            block_out[ci] = DeviceColumn(dt, None, validity,
                                         slab64=slab_b, lens=lens_b)
            continue
        if blockable and (not dt.is_string or shared is not None):
            validity = _block_copy([p.validity for p in parts]) & live_out
            if dt.is_string:
                card = len(shared)
                codes_b = jnp.where(live_out, _block_copy(
                    eff_codes, fill=jnp.int32(card)), jnp.int32(card))
                block_out[ci] = DeviceColumn(
                    dt, None, validity, dict_codes=codes_b,
                    dict_values=shared)
            else:
                codes_b = None
                if shared is not None:
                    card = len(shared)
                    codes_b = jnp.where(live_out, _block_copy(
                        eff_codes, fill=jnp.int32(card)), jnp.int32(card))
                block_out[ci] = DeviceColumn(
                    dt, _block_copy([p.data for p in parts]), validity,
                    dict_codes=codes_b, dict_values=shared)
            continue
        codes = (jnp.concatenate(eff_codes)
                 if shared is not None else None)
        if dt.is_string and shared is not None:
            # dictionary strings concat as codes only — no char extents,
            # no char slab reads (and lazy inputs stay unmaterialized);
            # differing dictionaries merged by union+remap above
            flat_cols.append(DeviceColumn(
                dt, None, jnp.concatenate([p.validity for p in parts]),
                dict_codes=codes, dict_values=shared))
            char_caps.append(0)
            continue
        if dt.is_string and slab_parts is not None:
            # slab flat view: rows are self-contained (no cross-part
            # offset bases), so the compaction gather moves slab rows
            # directly — including under keep_masks
            flat_cols.append(DeviceColumn(
                dt, None, jnp.concatenate([p.validity for p in parts]),
                slab64=jnp.concatenate(slab_parts),
                lens=jnp.concatenate([p.lens_() for p in parts])))
            char_caps.append(0)
            continue
        if dt.is_string:
            char_base = 0
            starts_parts = []
            for p in parts:
                starts_parts.append(p.offsets[:-1].astype(jnp.int32)
                                    + jnp.int32(char_base))
                char_base += p.data.shape[0]
            # trailing entry only closes the last row's length; boundary
            # rows are dead and masked in the gather
            lens_flat = jnp.concatenate(
                [(p.offsets[1:] - p.offsets[:-1]).astype(jnp.int32)
                 for p in parts])
            starts_flat = jnp.concatenate(starts_parts)
            offsets_flat = jnp.concatenate(
                [starts_flat, jnp.asarray([char_base], jnp.int32)])
            # rebuild a consistent offsets vector from starts+lens is
            # unnecessary: gather_columns derives lens as adjacent
            # differences, which would be wrong at part boundaries — so
            # hand it explicit extents via a shim column whose offsets
            # encode starts and whose boundary rows are masked dead
            chars_flat = jnp.concatenate([p.data for p in parts])
            has_prefix = all(p.prefix8 is not None for p in parts)
            prefix8 = (jnp.concatenate([p.prefix8 for p in parts])
                       if has_prefix else None)
            flat_cols.append(_ExtentColumn(
                dt, chars_flat, jnp.concatenate(
                    [p.validity for p in parts]),
                offsets_flat, prefix8, codes, shared,
                starts=starts_flat, lens=lens_flat))
            char_caps.append(out_char_capacity if out_char_capacity > 0
                             else char_base)
        else:
            flat_cols.append(DeviceColumn(
                dt, jnp.concatenate([p.data for p in parts]),
                jnp.concatenate([p.validity for p in parts]),
                dict_codes=codes, dict_values=shared))
    gathered = (gather_columns(flat_cols, src, live_out, tuple(char_caps))
                if flat_cols else [])
    cols: List[DeviceColumn] = []
    gi = 0
    for ci in range(len(schema.dtypes)):
        if ci in block_out:
            cols.append(block_out[ci])
        else:
            cols.append(gathered[gi])
            gi += 1
    return DeviceBatch(schema, cols, total)


class _ExtentColumn(DeviceColumn):
    """String column whose per-row (start, len) extents are explicit —
    concat's flat view has inter-part gaps no offsets vector can encode.
    Only consumed by gather_columns."""

    def __init__(self, dtype, data, validity, offsets, prefix8, dict_codes,
                 dict_values, starts, lens):
        super().__init__(dtype, data, validity, offsets, prefix8,
                         dict_codes, dict_values)
        self.ext_starts = starts
        self.ext_lens = lens


def slice_batch(batch: DeviceBatch, start: jnp.ndarray,
                count: jnp.ndarray) -> DeviceBatch:
    """Rows [start, start+count) compacted to the front (zero-copy-ish slice,
    the analogue of SlicedGpuColumnVector)."""
    return slice_batch_to(batch, start, count, batch.capacity)


def slice_batch_to(batch: DeviceBatch, start: jnp.ndarray,
                   count: jnp.ndarray, out_capacity: int,
                   char_caps=()) -> DeviceBatch:
    """slice_batch gathering into an ``out_capacity``-row batch. Callers
    that learn row counts on the host (the exchange's bucket split) use
    this to SHRINK capacity, so downstream kernels stop paying for the
    pre-aggregation padding (a 4-group result inheriting a 32k-row input
    bucket would otherwise keep every later sort/agg at 32k).
    ``char_caps``: optional per-STRING-column output char capacities —
    shrinking the char slab too stops downstream string kernels (poly
    hashes, char gathers, the result fetch) from paying the
    pre-aggregation CHAR padding, which dwarfs the row padding for
    string-keyed aggregates."""
    idx = jnp.arange(out_capacity, dtype=jnp.int32)
    perm = jnp.clip(idx + start.astype(jnp.int32), 0, batch.capacity - 1)
    n = jnp.minimum(count.astype(jnp.int32),
                    jnp.maximum(batch.num_rows - start.astype(jnp.int32), 0))
    live = idx < n
    cols = gather_columns(batch.columns, perm, live, char_caps)
    return DeviceBatch(batch.schema, cols, n.astype(jnp.int32))
