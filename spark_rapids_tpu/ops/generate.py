"""Device generator kernels: explode(split(str, delim)) (reference:
GpuGenerateExec.scala:194 runs explode-style generators through cuDF; here
the fused split+explode is one segmentation kernel over the char buffer).

Two-phase like joins: a totals kernel syncs the output row count and char
totals to the host (the one device->host sync dynamic cardinality costs),
then the expand kernel builds the output batch at a bucketed capacity.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes
from spark_rapids_tpu.columnar.batch import DeviceBatch, Schema
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.ops.rowops import gather_column


def _token_layout(batch: DeviceBatch, col_idx: int, delim: int):
    """Per-row token counts and the global ascending delimiter-position
    list (segmented by row via a cumulative offset table)."""
    col = batch.columns[col_idx]
    capacity = batch.capacity
    nchars = col.data.shape[0]
    i = jnp.arange(nchars, dtype=jnp.int32)
    row_ids = jnp.clip(
        jnp.searchsorted(col.offsets, i, side="right").astype(jnp.int32) - 1,
        0, capacity - 1)
    live_char = i < col.offsets[capacity]
    is_delim = (col.data == jnp.uint8(delim)) & live_char
    delims_per_row = jax.ops.segment_sum(
        is_delim.astype(jnp.int32), row_ids, num_segments=capacity)
    valid = col.validity & batch.row_mask()
    tokens = jnp.where(valid, delims_per_row + 1, 0)
    # compact delimiter positions (ascending) to the front
    perm_d = jnp.argsort(~is_delim, stable=True).astype(jnp.int32)
    delim_pos = i[perm_d]
    delim_offsets = jnp.concatenate([
        jnp.zeros((1,), jnp.int32),
        jnp.cumsum(delims_per_row).astype(jnp.int32)])
    return col, tokens, delim_pos, delim_offsets


def explode_totals(batch: DeviceBatch, col_idx: int, delim: int):
    """(total output rows, replicated char total per string column, token
    char total) — the host sync before expansion."""
    col, tokens, _, _ = _token_layout(batch, col_idx, delim)
    totals = [tokens.sum()]
    for ci, dt in enumerate(batch.schema.dtypes):
        if not dt.is_string:
            continue
        c = batch.columns[ci]
        lens = (c.offsets[1:] - c.offsets[:-1]).astype(jnp.int32)
        totals.append((lens * tokens).sum())
    # token column chars never exceed the source column's chars
    totals.append(col.offsets[batch.capacity])
    return jnp.stack([t.astype(jnp.int32) for t in totals])


def explode_split(batch: DeviceBatch, col_idx: int, delim: int,
                  out_name: str, out_cap: int, char_caps: Tuple[int, ...],
                  tok_char_cap: int, with_pos: bool,
                  pos_name: str = "pos") -> DeviceBatch:
    """Output: child columns (replicated per token) + [pos] + token column.
    Null input strings produce no rows (Spark explode drops nulls)."""
    col, tokens, delim_pos, delim_offsets = _token_layout(batch, col_idx,
                                                          delim)
    capacity = batch.capacity
    nchars = max(col.data.shape[0], 1)
    tok_offsets = jnp.concatenate([
        jnp.zeros((1,), jnp.int32), jnp.cumsum(tokens).astype(jnp.int32)])
    total = tok_offsets[capacity]
    t = jnp.arange(out_cap, dtype=jnp.int32)
    out_live = t < total
    out_row = jnp.clip(
        jnp.searchsorted(tok_offsets, t, side="right").astype(jnp.int32) - 1,
        0, capacity - 1)
    k = t - tok_offsets[out_row]                       # token ordinal in row
    d_base = delim_offsets[out_row]
    starts = jnp.where(
        k == 0, col.offsets[:-1][out_row].astype(jnp.int32),
        delim_pos[jnp.clip(d_base + k - 1, 0, delim_pos.shape[0] - 1)] + 1)
    ends = jnp.where(
        k == tokens[out_row] - 1, col.offsets[1:][out_row].astype(jnp.int32),
        delim_pos[jnp.clip(d_base + k, 0, delim_pos.shape[0] - 1)])
    tok_len = jnp.where(out_live, jnp.maximum(ends - starts, 0), 0)

    # replicated child columns (the source column stays, like Spark's
    # requiredChildOutput keeps it)
    out_cols = []
    names = []
    dts = []
    si = 0
    for ci, (name, dt) in enumerate(zip(batch.schema.names,
                                        batch.schema.dtypes)):
        ccap = 0
        if dt.is_string:
            ccap = char_caps[si]
            si += 1
        out_cols.append(gather_column(batch.columns[ci], out_row, out_live,
                                      out_char_capacity=ccap))
        names.append(name)
        dts.append(dt)
    if with_pos:
        out_cols.append(DeviceColumn(dtypes.INT32, k.astype(jnp.int32),
                                     out_live))
        names.append(pos_name)
        dts.append(dtypes.INT32)
    # token string column
    new_offsets = jnp.concatenate([
        jnp.zeros((1,), jnp.int32), jnp.cumsum(tok_len).astype(jnp.int32)])
    cchars = jnp.arange(tok_char_cap, dtype=jnp.int32)
    c_row = jnp.clip(
        jnp.searchsorted(new_offsets, cchars,
                         side="right").astype(jnp.int32) - 1, 0, out_cap - 1)
    src_idx = starts[c_row] + (cchars - new_offsets[c_row])
    gathered = col.data[jnp.clip(src_idx, 0, nchars - 1)]
    total_chars = new_offsets[out_cap]
    tok_chars = jnp.where(cchars < total_chars, gathered, 0).astype(jnp.uint8)
    out_cols.append(DeviceColumn(dtypes.STRING, tok_chars, out_live,
                                 new_offsets))
    names.append(out_name)
    dts.append(dtypes.STRING)
    return DeviceBatch(Schema(names, dts), out_cols, total)
